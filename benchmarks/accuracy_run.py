"""Accuracy-loop experiment: train to convergence, report a learning curve.

VERDICT round-1 item 4: nothing in the repo had ever trained toward a real
ranking number. Real MIND needs the raw tsv download (zero egress here —
the preprocessing pipeline for it exists in ``fedrec_tpu/data/preprocess.py``),
so this trains on the largest corpus obtainable offline: the topic-structured
synthetic generator (``make_synthetic_mind_topics``) whose Bayes-optimal
full-pool AUC is known by construction (~0.90 at defaults) and empirically
bounded by an oracle scorer. Metrics use the deterministic full-pool protocol
(the one behind the reference's published table, reference
``evaluation_functions.py:33-47``; published numbers ``README.md:70-80``).

Legs (each a subprocess with its own platform env, like ``bench.py``):

  * ``central``  — flagship single-chip run at reference scale (768-d trunk
    states, 50-token titles, 50k impressions) on the TPU if live, else CPU.
  * ``fed``      — 8-client federation on a fake CPU mesh (small corpus):
    local vs param_avg vs grad_avg vs param_avg+DP(eps=10), plus a
    32-client cohort run (4 clients per device) — shows federation/DP
    cost on accuracy. Direct ``--leg fed/adressa/finetune`` invocations
    self-re-exec onto the 8-device CPU mesh; set ``FEDREC_ACC_INNER=1``
    to keep your own environment (e.g. a live multi-device accelerator).
  * ``adressa``  — second dataset family (reference published Adressa AUC
    72.04, ``README.md:76-80``): synthetic event LOG with a lexical topic
    signal, run through the real Adressa pipeline (parse -> tokenize ->
    chronological split) + frozen-random-trunk token states.
  * ``finetune`` — BASELINE config 5: the FULL text trunk trains in-loop
    from raw tokens (no cached states) on the lexical Adressa corpus.
  * ``report``   — collect ``benchmarks/accuracy_*.json`` into RESULTS.md.

Usage:  python benchmarks/accuracy_run.py --all
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np

HERE = Path(__file__).resolve().parent
REPO = HERE.parent
if str(REPO) not in sys.path:  # runnable as `python benchmarks/accuracy_run.py`
    sys.path.insert(0, str(REPO))


def _prov() -> dict:
    from fedrec_tpu.utils.provenance import provenance

    return provenance()


# --------------------------------------------------------------------- data
def _central_corpus():
    from fedrec_tpu.data import make_synthetic_mind_topics

    if os.environ.get("FEDREC_ACC_SMOKE"):  # fast correctness pass of the glue
        return make_synthetic_mind_topics(
            num_news=256, num_train=400, num_valid=100, title_len=8,
            bert_hidden=768, his_len_range=(3, 10), seed=7,
        )
    if os.environ.get("FEDREC_ACC_CPU"):
        # CPU-feasible fallback scale for when the TPU tunnel is wedged a
        # whole session; the report records the actual dims used
        return make_synthetic_mind_topics(
            num_news=2048, num_train=12_000, num_valid=2_000, title_len=16,
            bert_hidden=192, his_len_range=(5, 30), seed=7,
        )
    return make_synthetic_mind_topics(
        num_news=4096,
        num_train=50_000,
        num_valid=5_000,
        title_len=50,
        bert_hidden=768,
        seed=7,
    )


def _small_corpus():
    from fedrec_tpu.data import make_synthetic_mind_topics

    return make_synthetic_mind_topics(
        num_news=1024,
        num_train=8_000,
        num_valid=2_000,
        title_len=12,
        bert_hidden=96,
        his_len_range=(5, 20),
        seed=11,
    )


def oracle_auc(data, states) -> float:
    """Full-pool AUC of a cheating reference scorer: cosine(candidate
    centroid, mean history centroid) on the raw trunk states. A strong
    baseline the model should approach; a LEARNED pooling can legitimately
    exceed it (uniform token averaging is not optimal)."""
    cent = np.asarray(states, np.float32).mean(axis=1)
    cent /= np.linalg.norm(cent, axis=1, keepdims=True) + 1e-9
    n2i = data.nid2index
    aucs = []
    for _, pos, negs, his, _ in data.valid_samples:
        hv = cent[[n2i[h] for h in his]].mean(0)
        s_pos = float(hv @ cent[n2i[pos]])
        s_neg = cent[[n2i[x] for x in negs]] @ hv
        aucs.append(
            (np.sum(s_pos > s_neg) + 0.5 * np.sum(s_pos == s_neg)) / len(s_neg)
        )
    return float(np.mean(aucs))


def _adressa_corpus(num_users: int, num_news: int, event_seed: int, prep_seed: int):
    """Synthetic Adressa event log -> artifacts through the REAL adapter
    (shared by the adressa and finetune legs)."""
    import tempfile

    from fedrec_tpu.data import make_synthetic_adressa_events, preprocess_adressa

    events = make_synthetic_adressa_events(
        num_users=num_users, num_news=num_news, seed=event_seed
    )
    with tempfile.TemporaryDirectory() as tmpdir:
        tmp = Path(tmpdir) / "events.jsonl"
        with open(tmp, "w") as fh:
            for ev in events:
                fh.write(json.dumps(ev) + "\n")
        data = preprocess_adressa(
            [tmp], out_dir=None, max_title_len=12, neg_pool_size=20,
            valid_frac=0.15, seed=prep_seed,
        )
    return events, data


# --------------------------------------------------------------------- legs
def _train(cfg, data, states, on_round=None):
    """Round loop with an optional per-round callback — the TPU tunnel can
    wedge mid-run, so callers persist partial curves instead of losing a
    20-minute run to a stall at round N-1."""
    from fedrec_tpu.train.trainer import Trainer

    t0 = time.time()
    trainer = Trainer(cfg, data, states, snapshot_dir=None)
    out = {"wall_s": 0.0, "curve": []}
    for round_idx in range(cfg.fed.rounds):
        r = trainer.train_round(round_idx)
        out["curve"].append(
            {
                "round": r.round_idx,
                "train_loss": round(r.train_loss, 5),
                **{k: round(v, 5) for k, v in r.val_metrics.items()},
            }
        )
        out["wall_s"] = round(time.time() - t0, 1)
        print(json.dumps(out["curve"][-1]), flush=True)
        if on_round is not None:
            on_round(out)
    trainer.logger.finish()
    return out


def leg_central(rounds: int) -> None:
    import jax

    from fedrec_tpu.config import ExperimentConfig

    platform = jax.devices()[0].platform
    data, states = _central_corpus()
    hidden = states.shape[-1]

    cfg = ExperimentConfig()
    cfg.model.text_encoder_mode = "head"
    cfg.model.bert_hidden = hidden
    if hidden < 768:  # CPU-scale corpus -> proportionally scaled model
        cfg.model.news_dim = 128
        cfg.model.num_heads = 16
        cfg.model.head_dim = 8
        cfg.model.query_dim = 64
    cfg.data.max_title_len = data.title_len
    if platform != "cpu":
        cfg.model.dtype = "bfloat16"
    cfg.fed.strategy = "local"
    cfg.fed.num_clients = 1
    cfg.fed.rounds = rounds
    # the reference's lr 5e-5 assumes ~8 h of training; this demo runs a
    # bounded number of rounds, so use a proportionally larger Adam lr
    # (recorded in the output JSON — an accuracy-loop choice, not parity)
    cfg.optim.user_lr = cfg.optim.news_lr = 5e-4
    cfg.train.eval_protocol = "full"
    cfg.train.eval_every = 1
    cfg.train.snapshot_dir = ""
    cfg.train.resume = False

    out = {
        "leg": "central",
        "platform": platform,
        "device": getattr(jax.devices()[0], "device_kind", platform),
        "corpus": {
            "num_news": data.num_news,
            "train": len(data.train_samples),
            "valid": len(data.valid_samples),
            "bert_hidden": hidden,
        },
        "oracle_auc": round(oracle_auc(data, states), 4),
        "rounds_requested": rounds,
        "config": {"mode": "head", "dtype": cfg.model.dtype,
                   "lr": cfg.optim.user_lr, "batch": cfg.data.batch_size},
    }

    out["provenance"] = _prov()

    def persist(partial):
        (HERE / "accuracy_central.json").write_text(
            json.dumps({**out, **partial}, indent=2)
        )

    result = _train(cfg, data, states, on_round=persist)
    persist(result)
    print(json.dumps({"leg": "central", "platform": platform,
                      "oracle_auc": out["oracle_auc"],
                      "wall_s": result["wall_s"]}))


def leg_bf16(rounds: int) -> None:
    """Dtype-tolerance leg (VERDICT r2 item 9): the SAME corpus and config
    trained twice — float32 vs bfloat16 (params/opt stay f32; compute and
    the token-state table take the dtype, exactly like the TPU bench) —
    asserting the final full-pool AUC agrees within a stated tolerance.
    The TPU bench advertises bfloat16; this leg is the accuracy proof for
    that dtype. CPU runs use the small corpus (XLA:CPU bf16 is slow)."""
    import jax

    from fedrec_tpu.config import ExperimentConfig

    platform = jax.devices()[0].platform
    if os.environ.get("FEDREC_ACC_SMOKE"):
        from fedrec_tpu.data import make_synthetic_mind_topics

        data, states = make_synthetic_mind_topics(
            num_news=256, num_train=400, num_valid=100, title_len=8,
            bert_hidden=96, his_len_range=(3, 10), seed=7,
        )
    elif platform == "cpu":
        data, states = _small_corpus()
    else:
        data, states = _central_corpus()
    hidden = states.shape[-1]

    def cfg_for(dtype: str) -> ExperimentConfig:
        cfg = ExperimentConfig()
        cfg.model.text_encoder_mode = "head"
        cfg.model.bert_hidden = hidden
        if hidden < 768:  # CPU-scale corpus -> proportionally scaled model
            cfg.model.news_dim = 128
            cfg.model.num_heads = 16
            cfg.model.head_dim = 8
            cfg.model.query_dim = 64
        cfg.data.max_title_len = data.title_len
        cfg.model.dtype = dtype
        cfg.fed.strategy = "local"
        cfg.fed.num_clients = 1
        cfg.fed.rounds = rounds
        cfg.optim.user_lr = cfg.optim.news_lr = 5e-4
        cfg.train.eval_protocol = "full"
        cfg.train.eval_every = 1
        cfg.train.snapshot_dir = ""
        cfg.train.resume = False
        return cfg

    tolerance = 0.02
    out = {
        "leg": "bf16",
        "platform": platform,
        "device": getattr(jax.devices()[0], "device_kind", platform),
        "corpus": {
            "num_news": data.num_news,
            "train": len(data.train_samples),
            "valid": len(data.valid_samples),
            "bert_hidden": hidden,
        },
        "oracle_auc": round(oracle_auc(data, states), 4),
        "rounds_requested": rounds,
        "tolerance_auc": tolerance,
        "runs": {},
    }
    out["provenance"] = _prov()

    from fedrec_tpu.utils.provenance import write_artifact

    def persist(final: bool = False) -> None:
        # incremental, but write_artifact stages non-final stamps in an
        # .inprogress sidecar until BOTH dtypes finished and the tolerance
        # verdict is in — the watcher must not bank a half-trained
        # comparison as the dtype-safety proof, and a wedged re-run must
        # not clobber previously banked complete evidence
        write_artifact(HERE / "accuracy_bf16.json", out, not final)

    for dtype in ("float32", "bfloat16"):
        print(f"[bf16-leg] training dtype={dtype}", flush=True)
        res = _train(cfg_for(dtype), data, states, on_round=lambda p: persist())
        out["runs"][dtype] = res
        persist()

    f32_auc = out["runs"]["float32"]["curve"][-1]["auc"]
    bf16_auc = out["runs"]["bfloat16"]["curve"][-1]["auc"]
    out["final_auc"] = {"float32": f32_auc, "bfloat16": bf16_auc}
    out["auc_delta"] = round(abs(f32_auc - bf16_auc), 5)
    out["within_tolerance"] = out["auc_delta"] <= tolerance
    persist(final=True)
    print(json.dumps({"leg": "bf16", "auc_f32": f32_auc, "auc_bf16": bf16_auc,
                      "delta": out["auc_delta"],
                      "within_tolerance": out["within_tolerance"]}))
    if not out["within_tolerance"]:
        raise SystemExit(
            f"bf16 final AUC diverged from f32 by {out['auc_delta']} "
            f"(> {tolerance}) — the bench dtype is not accuracy-safe"
        )


def _small_corpus_base_cfg():
    """The tuned harness recipe shared by the fed and dp legs: the
    `_small_corpus` model geometry + the full-pool eval tail. ONE
    definition, so the dp leg's anchor can never silently drift from the
    fed leg's operating point (they are compared against each other in
    the report)."""
    from fedrec_tpu.config import ExperimentConfig

    cfg = ExperimentConfig()
    cfg.model.news_dim = 64
    cfg.model.num_heads = 8
    cfg.model.head_dim = 8
    cfg.model.query_dim = 32
    cfg.model.bert_hidden = 96
    cfg.data.max_title_len = 12
    cfg.data.max_his_len = 20
    cfg.train.eval_protocol = "full"
    cfg.train.eval_every = 1
    cfg.train.snapshot_dir = ""
    cfg.train.resume = False
    return cfg


# Row spec: name -> (strategy[+server_opt], clients, text_encoder_mode[+tower]).
# DP rows live in the dedicated dp leg (leg_dp -> accuracy_dp.json): the r3
# rows here trained the DP estimator with the non-DP hyperparameters and were
# noise-crushed to ~random (VERDICT r3 #4).
FED_ROWS = {
    "local_1client": ("local", 1, "head"),
    # the reference's actual epoch structure: user tower trains on a
    # precomputed news-vec table, text head updates from accumulated
    # embedding grads at epoch end (reference model.py:66-90)
    "decoupled_1client": ("local", 1, "table"),
    "param_avg_8": ("param_avg", 8, "head"),
    # FedAvgM (server momentum over round deltas, Reddi et al. 2021) —
    # beyond-parity: the reference only has the plain mean
    "param_avg_8_fedavgm": ("param_avg+fedavgm", 8, "head"),
    "grad_avg_8": ("grad_avg", 8, "head"),
    # BASELINE north-star client count via cohorts (32 clients on the
    # 8-device rig -> 4 per device; packing-independent semantics
    # pinned by tests/test_cohorts.py)
    "param_avg_32_cohort": ("param_avg", 32, "head"),
    # second model family: recurrent (LSTUR-style) user tower
    "gru_tower_8": ("param_avg", 8, "head+gru"),
    # third model family: CNN text head (NAML-style, Wu et al. 2019).
    # Shared lr 1e-2 is also its own sweep optimum (5e-3 -> 0.759,
    # 2e-2 diverges); it trails the additive head (~0.77 vs 0.80) on this
    # corpus BY CONSTRUCTION — the synthetic token states carry no
    # token-order signal for the conv window to read
    "cnn_head_8": ("param_avg", 8, "head+cnn"),
}


def fed_row_cfg(name: str, rounds: int):
    """Pure per-row config construction for the fed leg.

    Extracted so routing regressions are caught by asserting on the
    RETURNED config values (tests/test_accuracy_harness.py) instead of
    grepping leg_fed's source — a reordered assignment that keeps the
    literal strings must still fail the tests.
    """
    strategy, clients, mode = FED_ROWS[name]
    cfg = _small_corpus_base_cfg()
    if strategy.endswith("+fedavgm"):
        strategy = strategy.split("+")[0]
        cfg.fed.server_opt = "sgd"
        cfg.fed.server_lr = 1.0
        # momentum 0.5 at the SHARED local lr: the best point of the r5
        # (server_lr x momentum x local lr) sweep — 0.797 vs 0.800 plain.
        # m=0.9 needs crippled locals (5e-4 -> 0.721) or a shrunk server
        # step (s0.3 -> 0.755); FedAdam peaks at 0.768; nothing BEATS the
        # plain mean on this corpus, so PARITY.md marks the feature
        # "available, not recommended at this scale" (VERDICT r4 #4)
        cfg.fed.server_momentum = 0.5
    if mode.endswith("+gru"):
        mode = mode.split("+")[0]
        cfg.model.user_tower = "gru"
    if mode.endswith("+cnn"):
        mode = mode.split("+")[0]
        cfg.model.text_head_arch = "cnn"
    cfg.model.text_encoder_mode = mode
    cfg.fed.strategy = strategy
    cfg.fed.num_clients = clients
    cfg.fed.rounds = rounds
    # lr 1e-2: the r4 sweep optimum on this corpus (5e-4 -> 0.667,
    # 1e-2 -> 0.80 for the 8-client row); one shared lr keeps the
    # federation-mode comparison fair. One row runs at its own measured
    # operating point (noted in the report): local_1client takes 8x the
    # optimizer steps per round of the federated rows, and lr 1e-2
    # collapses it after round 2 (AUC 0.72 -> 0.50); its sweep optimum
    # is 2e-3. (The fedavgm row ran conservative 5e-4 locals through r4;
    # the r5 sweep found momentum 0.5 at the SHARED lr strictly better —
    # see the fedavgm block above.)
    cfg.optim.user_lr = cfg.optim.news_lr = 1e-2
    if name == "local_1client":
        cfg.optim.user_lr = cfg.optim.news_lr = 2e-3
    if clients == 32:
        # step equalization (VERDICT r3 #5): a 32-client split leaves
        # each client 1/4 the per-round local steps of the 8-client
        # rows (250 samples -> 3 steps/epoch vs 15); 4 local epochs
        # restores the update count, closing the gap to the 8-client
        # row from 0.17 to ~0.006 AUC on this corpus
        cfg.fed.local_epochs = 4
    return cfg


def leg_fed(rounds: int) -> None:
    import jax

    data, states = _small_corpus()
    runs = {}
    for name in FED_ROWS:
        cfg = fed_row_cfg(name, rounds)
        runs[name] = _train(cfg, data, states)
        print(f"[fed] {name}: final "
              f"{runs[name]['curve'][-1] if runs[name]['curve'] else '?'}")

    out = {
        "leg": "fed",
        "platform": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        "corpus": {
            "num_news": data.num_news,
            "train": len(data.train_samples),
            "valid": len(data.valid_samples),
            "bert_hidden": 96,
        },
        "oracle_auc": round(oracle_auc(data, states), 4),
        "runs": runs,
    }
    out["provenance"] = _prov()
    (HERE / "accuracy_fed.json").write_text(json.dumps(out, indent=2))


# DP leg rows: eps=None is a non-private anchor; scope/batch default to the
# tuned recipe's ("all", 64). Finalized from the round-5 probe sweep
# (/tmp/dp_tune_r5.py pattern — see docs/DP.md for the measured outcomes).
DP_ROWS: dict[str, dict] = {
    "nodp_tuned": {"eps": None},
    "dp_eps50": {"eps": 50.0},
    "dp_eps10": {"eps": 10.0},
    "dp_eps3": {"eps": 3.0},
    # dp_scope='user' lever + its honest ceiling: non-private training with
    # the text head frozen — the scope's utility can never exceed this
    "nodp_user_frozen": {"eps": None, "scope": "user"},
    "dp_eps10_user": {"eps": 10.0, "scope": "user"},
    # batch lever: sigma*C/B per-step noise shrinks 2.5x at B=256, but the
    # accountant's sigma grows with q and the step count falls 4x — the
    # probe measured a net LOSS at every B tried (docs/DP.md section 4)
    "dp_eps10_b256": {"eps": 10.0, "batch": 256},
}


def dp_row_cfg(name: str, rounds: int, n_train: int):
    """Pure per-row config for the dp leg (same testable-construction
    pattern as :func:`fed_row_cfg`)."""
    from fedrec_tpu.privacy import calibrate_from_config

    spec = DP_ROWS[name]
    eps = spec.get("eps")
    cfg = _small_corpus_base_cfg()
    cfg.model.text_encoder_mode = "head"
    cfg.data.batch_size = spec.get("batch", 64)
    cfg.fed.strategy = "grad_avg"
    cfg.fed.num_clients = 8
    cfg.fed.rounds = rounds
    cfg.fed.local_epochs = 2
    cfg.optim.user_lr = cfg.optim.news_lr = 1e-2
    per_client = n_train // cfg.fed.num_clients
    steps_per_epoch = max(per_client // cfg.data.batch_size, 1)
    cfg.optim.lr_schedule = "cosine"
    cfg.optim.decay_steps = steps_per_epoch * rounds * cfg.fed.local_epochs
    scope = spec.get("scope", "all")
    if eps is not None:
        cfg.privacy.enabled = True
        cfg.privacy.epsilon = eps
        cfg.privacy.clip_norm = 1.0
        cfg.privacy.dp_scope = scope
        # budget the accountant for the steps this run actually takes
        cfg.privacy.accountant_epochs = rounds * cfg.fed.local_epochs
        cfg.privacy.sigma = calibrate_from_config(cfg, n_train)
    elif scope == "user":
        # frozen-head ceiling: the DP machinery with sigma ~ 0 and an
        # inactive clip IS the non-private user-only trainer
        # (tests/test_privacy.py pins the sigma->0 equivalence)
        cfg.privacy.enabled = True
        cfg.privacy.mechanism = "dpsgd"
        cfg.privacy.dp_scope = "user"
        cfg.privacy.clip_norm = 1e6
        cfg.privacy.sigma = 1e-12
    return cfg


def leg_dp(rounds: int) -> None:
    """Privacy-utility sweep with DP-TUNED hyperparameters (VERDICT r3 #4).

    The r3 DP rows trained the DP-SGD estimator with the non-DP recipe
    (Adam lr 5e-4, param_avg, C=2) and landed at ~random AUC. The failure
    mode was measured, not guessed (see docs/DP.md): per-step noise-vector
    norm ~20x the mean-gradient norm, and Adam's second moment normalizes
    by the NOISE scale, shrinking the per-parameter update to
    lr * (per-param SNR) — so at lr 5e-4 the model barely moves in the
    budgeted steps. The tuned recipe measured here:

      * ``grad_avg``: the per-step pmean over 8 clients averages 8
        INDEPENDENT noise draws — sqrt(8) noise reduction at the SAME
        local-DP guarantee (each client noises before the collective).
      * clip C=1.0 (just under the observed per-example norm median).
      * Adam lr 1e-2 (the empirical optimum of the lr sweep; 2e-2
        diverges), cosine-decayed over the full step budget — injected
        noise variance scales with lr^2, so the small late lr averages
        the noise out (worth +0.03 AUC at eps=50 over constant lr).
      * 32 rounds x 2 local epochs (DP gains from more steps under decay
        where the constant-lr run plateaus), accountant budgeting exactly
        the steps trained.

    Rows: non-private anchor at the SAME tuned recipe (the honest
    comparison bar — non-DP also improves under it) + eps in {50, 10, 3},
    plus the round-5 levers (VERDICT r4 #3): ``dp_scope='user'`` with its
    frozen-head non-private ceiling row, and large-batch rows (sigma*C/B
    noise-on-the-mean shrinks faster than the accountant's sigma grows
    with the sampling rate q). Writes ``accuracy_dp.json``.
    """
    import jax

    data, states = _small_corpus()
    runs = {}
    # FEDREC_DP_ROWS subset (chip watcher: the on-TPU proof runs only the
    # tuned anchor + eps=10 row; the full sweep is the CPU artifact's job).
    # Validated UP FRONT — a typo must fail before training, not after an
    # hour of chip window; the anchor row is required (every downstream
    # field is relative to it) and auto-included.
    row_filter = [
        r for r in os.environ.get("FEDREC_DP_ROWS", "").split(",") if r
    ]
    unknown = [r for r in row_filter if r not in DP_ROWS]
    if unknown:
        raise SystemExit(
            f"FEDREC_DP_ROWS names unknown rows {unknown}; known: "
            f"{sorted(DP_ROWS)}"
        )
    if row_filter and "nodp_tuned" not in row_filter:
        row_filter.insert(0, "nodp_tuned")
    rows = (
        {n: DP_ROWS[n] for n in row_filter} if row_filter else DP_ROWS
    )

    from fedrec_tpu.utils.provenance import write_artifact

    # only the FULL sweep on the cpu rig may update the canonical artifact
    # the report reads. A chip run (VERDICT r4 #7) — and equally a wedge
    # CPU-fallback of the chip queue item, which still carries the row
    # subset — goes to its own file; the watcher banks it only when its
    # provenance proves a tpu backend AND the run completed (no "partial").
    full_cpu = not row_filter and jax.devices()[0].platform == "cpu"
    name = "accuracy_dp.json" if full_cpu else "accuracy_dp_tpu.json"

    out = {
        "leg": "dp",
        "platform": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        "corpus": {
            "num_news": data.num_news,
            "train": len(data.train_samples),
            "valid": len(data.valid_samples),
            "bert_hidden": 96,
        },
        "recipe": {
            "strategy": "grad_avg", "clients": 8, "clip_norm": 1.0,
            "lr": 1e-2, "lr_schedule": "cosine", "local_epochs": 2,
            "rounds": rounds, "delta": 1e-5,
        },
        "oracle_auc": round(oracle_auc(data, states), 4),
        "runs": runs,
    }

    def persist(partial: bool) -> None:
        # per-row incremental banking: a ~20-min tunnel window cannot fit
        # the whole leg; a wedge mid-leg must keep the rows already trained
        # as labeled evidence. write_artifact stages partial stamps in an
        # .inprogress sidecar, so a wedged RE-run can never destroy
        # previously banked complete evidence; the watcher retries until
        # the canonical artifact completes.
        out["provenance"] = _prov()
        write_artifact(HERE / name, out, partial)

    for row_name, spec in rows.items():
        cfg = dp_row_cfg(row_name, rounds, len(data.train_samples))
        runs[row_name] = _train(cfg, data, states)
        runs[row_name]["epsilon"] = spec.get("eps")
        runs[row_name]["sigma"] = (
            round(cfg.privacy.sigma, 4) if spec.get("eps") else 0.0
        )
        runs[row_name]["dp_scope"] = cfg.privacy.dp_scope
        runs[row_name]["batch_size"] = cfg.data.batch_size
        print(f"[dp] {row_name}: final "
              f"{runs[row_name]['curve'][-1] if runs[row_name]['curve'] else '?'}")
        persist(partial=True)

    anchor = runs["nodp_tuned"]["curve"][-1]["auc"]
    out["nodp_anchor_auc"] = anchor
    out["gap_to_anchor"] = {
        n: round(anchor - r["curve"][-1]["auc"], 4)
        for n, r in runs.items()
        if DP_ROWS[n].get("eps") is not None and r["curve"]
    }
    if "nodp_user_frozen" in runs and runs["nodp_user_frozen"]["curve"]:
        # the scope lever's hard ceiling, stated next to the rows it bounds
        out["user_frozen_ceiling_auc"] = (
            runs["nodp_user_frozen"]["curve"][-1]["auc"]
        )
    persist(partial=False)


def leg_adressa(rounds: int) -> None:
    """Second dataset family, end-to-end through the REAL adapter: synthetic
    JSON-lines event log -> ``preprocess_adressa`` (tokenizer, news index,
    chronological per-user split, corpus-sampled negative pools) ->
    token-derived trunk states -> train -> full-pool metrics."""
    import jax

    from fedrec_tpu.config import ExperimentConfig
    from fedrec_tpu.data import token_states_from_tokens

    smoke = bool(os.environ.get("FEDREC_ACC_SMOKE"))
    events, data = _adressa_corpus(
        num_users=200 if smoke else 3_000,
        num_news=400 if smoke else 2_000,
        event_seed=1, prep_seed=2,
    )
    states = token_states_from_tokens(data.news_tokens, bert_hidden=96, seed=3)

    cfg = ExperimentConfig()
    cfg.model.text_encoder_mode = "head"
    cfg.model.bert_hidden = 96
    cfg.model.news_dim = 128
    cfg.model.num_heads = 16
    cfg.model.head_dim = 8
    cfg.model.query_dim = 64
    cfg.data.max_title_len = data.title_len
    cfg.data.max_his_len = 30
    cfg.fed.strategy = "local"
    cfg.fed.num_clients = 1
    cfg.fed.rounds = rounds
    cfg.optim.user_lr = cfg.optim.news_lr = 5e-4  # see leg_central
    cfg.train.eval_protocol = "full"
    cfg.train.eval_every = 1
    cfg.train.snapshot_dir = ""
    cfg.train.resume = False

    out = {
        "leg": "adressa",
        "platform": jax.devices()[0].platform,
        "corpus": {
            "num_news": data.num_news,
            "train": len(data.train_samples),
            "valid": len(data.valid_samples),
            "events": len(events),
            "bert_hidden": 96,
        },
        "oracle_auc": round(oracle_auc(data, states), 4),
        "rounds_requested": rounds,
        "config": {"mode": "head", "dtype": cfg.model.dtype,
                   "lr": cfg.optim.user_lr, "batch": cfg.data.batch_size},
    }

    out["provenance"] = _prov()

    def persist(partial):
        (HERE / "accuracy_adressa.json").write_text(
            json.dumps({**out, **partial}, indent=2)
        )

    result = _train(cfg, data, states, on_round=persist)
    persist(result)
    print(json.dumps({"leg": "adressa", "oracle_auc": out["oracle_auc"],
                      "wall_s": result["wall_s"]}))


def leg_finetune(rounds: int) -> None:
    """BASELINE config 5 at benchmark scale: the FULL text trunk trains
    in-loop from raw tokens (no cached states anywhere). The lexical topic
    corpus carries its signal in the tokens, so a from-scratch tiny trunk
    must learn the topical structure end-to-end — embeddings, transformer
    block, pooling head, and user tower together."""
    import jax

    from fedrec_tpu.config import ExperimentConfig
    from fedrec_tpu.data import token_states_from_tokens

    smoke = bool(os.environ.get("FEDREC_ACC_SMOKE"))
    _, data = _adressa_corpus(
        num_users=150 if smoke else 1_200,
        num_news=300 if smoke else 800,
        event_seed=21, prep_seed=22,
    )

    cfg = ExperimentConfig()
    cfg.model.text_encoder_mode = "finetune"
    cfg.model.bert_hidden = 64
    cfg.model.trunk_layers = 2
    cfg.model.trunk_heads = 4
    cfg.model.trunk_ffn = 128
    cfg.model.trunk_vocab = 30_522       # hashing-tokenizer id space
    cfg.model.news_dim = 64
    cfg.model.num_heads = 8
    cfg.model.head_dim = 8
    cfg.model.query_dim = 32
    cfg.data.max_title_len = data.title_len
    cfg.data.max_his_len = 20
    cfg.fed.strategy = "local"
    cfg.fed.num_clients = 1
    cfg.fed.rounds = rounds
    cfg.optim.user_lr = cfg.optim.news_lr = 1e-3
    # standard logit CE: the reference's CE-over-sigmoid quirk
    # (model.py:123-126, kept as the parity default) compresses logits into
    # [0,1] and starves a from-scratch trunk of gradient — it never escapes
    # the ln(5) plateau in a bounded-round demo
    cfg.model.sigmoid_before_ce = False
    cfg.train.eval_protocol = "full"
    cfg.train.eval_every = 1
    cfg.train.snapshot_dir = ""
    cfg.train.resume = False

    # oracle on token-derived states: same lexical ceiling the trunk chases
    states = token_states_from_tokens(data.news_tokens, bert_hidden=64, seed=23)
    out = {
        "leg": "finetune",
        "platform": jax.devices()[0].platform,
        "corpus": {
            "num_news": data.num_news,
            "train": len(data.train_samples),
            "valid": len(data.valid_samples),
            "trunk": f"{cfg.model.trunk_layers}x{cfg.model.bert_hidden}",
        },
        "oracle_auc": round(oracle_auc(data, states), 4),
        "rounds_requested": rounds,
        "config": {"mode": "finetune", "dtype": cfg.model.dtype,
                   "lr": cfg.optim.user_lr, "batch": cfg.data.batch_size},
    }

    out["provenance"] = _prov()

    def persist(partial):
        (HERE / "accuracy_finetune.json").write_text(
            json.dumps({**out, **partial}, indent=2)
        )

    result = _train(cfg, data, None, on_round=persist)
    persist(result)
    print(json.dumps({"leg": "finetune", "oracle_auc": out["oracle_auc"],
                      "wall_s": result["wall_s"]}))


# ------------------------------------------------------------------- report
_CURVE_HEADER = [
    "| round | train loss | AUC | MRR | NDCG@5 | NDCG@10 |",
    "|---|---|---|---|---|---|",
]


def _curve_rows(curve: list[dict]) -> list[str]:
    return [
        f"| {row['round']} | {row['train_loss']:.4f} | {row.get('auc', float('nan')):.4f} "
        f"| {row.get('mrr', float('nan')):.4f} | {row.get('ndcg5', float('nan')):.4f} "
        f"| {row.get('ndcg10', float('nan')):.4f} |"
        for row in curve
    ]


def _partial_note(leg: dict) -> str:
    """'(PARTIAL: ...)' when a persisted curve is shorter than requested —
    a wedged tunnel truncates runs mid-leg and the report must say so."""
    requested = leg.get("rounds_requested", len(leg["curve"]))
    if len(leg["curve"]) >= requested:
        return ""
    return (
        f" (PARTIAL: run truncated at round {leg['curve'][-1]['round']} "
        f"of {requested} — tunnel stall)"
    )


def write_report() -> None:
    """Collect whichever leg JSONs exist into RESULTS.md (a wedged TPU
    tunnel can leave one leg missing — report the evidence that exists)."""
    def _load_complete(fname: str):
        # an artifact flagged "partial" (incremental stamp of a run that
        # never finished) lacks the leg's summary fields — reporting it
        # would KeyError mid-report or publish a half-trained comparison
        path = HERE / fname
        if not path.exists():
            return None
        d = json.loads(path.read_text())
        if d.get("partial"):
            print(f"[report] skipping {fname}: partial (run never "
                  "completed); re-run the leg", file=sys.stderr)
            return None
        return d

    central = _load_complete("accuracy_central.json")
    fed = _load_complete("accuracy_fed.json")
    dp = _load_complete("accuracy_dp.json")
    adressa = _load_complete("accuracy_adressa.json")
    finetune = _load_complete("accuracy_finetune.json")
    bf16 = _load_complete("accuracy_bf16.json")
    if all(x is None for x in (central, fed, dp, adressa, finetune, bf16)):
        raise SystemExit("no accuracy_*.json found; run the legs first")

    lines = [
        "# RESULTS — end-to-end accuracy loop",
        "",
        "Deterministic **full-negative-pool** evaluation (the protocol behind",
        "the reference's published MIND table, reference",
        "`evaluation_functions.py:33-47`): AUC / MRR / NDCG@5 / NDCG@10 averaged",
        "over every validation impression's entire pool. Data is the",
        "topic-structured synthetic corpus (`make_synthetic_mind_topics`) — the",
        "largest corpus obtainable offline (real MIND needs the tsv download;",
        "the preprocessing for it is `fedrec_tpu/data/preprocess.py`). The",
        "corpus has a *known* recoverable signal, quantified by an oracle",
        "cosine scorer on the raw trunk states.",
    ]
    if central is not None:
        lines += [
            "",
            "## 1. Flagship centralized run",
            "",
            f"Platform **{central['platform']}** ({central['device']}), mode",
            "`head` (trainable text head over cached trunk states), dtype",
            f"`{central['config']['dtype']}`, lr {central['config']['lr']},",
            f"batch {central['config']['batch']}. Corpus: {central['corpus']['train']:,}",
            f"train / {central['corpus']['valid']:,} valid impressions over",
            f"{central['corpus']['num_news']:,} news,",
            f"{central['corpus']['bert_hidden']}-d trunk states.",
            f"Oracle reference scorer AUC: **{central['oracle_auc']:.4f}**.",
            f"Wall-clock: {central['wall_s']}s.",
            "",
            *_CURVE_HEADER,
        ]
        lines += _curve_rows(central["curve"])
        last = central["curve"][-1]
        frac = last.get("auc", 0.0) / max(central["oracle_auc"], 1e-9)
        lines += [
            "",
            f"Final AUC {last.get('auc', float('nan')):.4f} = "
            f"**{100 * frac:.1f}% of the oracle reference scorer** "
            f"(random = 0.5; a learned pooling can exceed the oracle's "
            f"uniform token average).{_partial_note(central)}",
        ]
    if fed is not None:
        lines += [
            "",
            "## 2. Federation and privacy cost (8-client CPU mesh)",
            "",
            f"Same protocol on a small corpus ({fed['corpus']['train']:,} train /",
            f"{fed['corpus']['valid']:,} valid, {fed['corpus']['num_news']:,} news,",
            f"96-d states), {fed['n_devices']}-device fake mesh. Oracle AUC:",
            f"**{fed['oracle_auc']:.4f}**.",
            "",
            "| run | final AUC | final MRR | final NDCG@10 | wall s |",
            "|---|---|---|---|---|",
        ]
        for name, run in fed["runs"].items():
            c = run["curve"][-1]
            lines.append(
                f"| {name} | {c.get('auc', float('nan')):.4f} | {c.get('mrr', float('nan')):.4f} "
                f"| {c.get('ndcg10', float('nan')):.4f} | {run['wall_s']} |"
            )
        if any(n.endswith("_cohort") for n in fed["runs"]):
            lines += [
                "",
                "`param_avg_32_cohort` runs the BASELINE north-star client",
                "count via in-device cohorts (32 clients on the 8-device",
                "mesh, 4 per device; `tests/test_cohorts.py` pins the",
                "packing-independence). It trains 4 local epochs per round:",
                "a 32-way split leaves each client 1/4 the per-round local",
                "steps of the 8-client rows, and equalizing the update",
                "count closes the r3 gap (0.55 vs 0.67 then) to within",
                "~0.006 AUC of the 8-client row — standard FedAvg data",
                "scaling, not a cohort artifact: the same 32-client run on",
                "32 devices computes bit-equal collectives.",
                "",
                "`local_1client` runs at its own measured operating point",
                "(lr 2e-3): one client takes 8x the optimizer steps per",
                "round and collapses at the shared lr.",
                "`param_avg_8_fedavgm` runs server momentum 0.5 at the",
                "SHARED lr — the best point of the r5 (server_lr x",
                "momentum x local lr) sweep; no FedOpt point beat the",
                "plain mean once local lrs were tuned, so the feature is",
                "marked available-not-recommended at this scale",
                "(PARITY.md; m=0.9 needs crippled 5e-4 locals -> 0.721).",
            ]
    if dp is not None:
        r = dp["recipe"]
        lines += [
            "",
            "## 2b. Privacy-utility tradeoff (DP-tuned recipe)",
            "",
            "DP-SGD sweep with hyperparameters tuned FOR the DP estimator",
            f"(`{r['strategy']}`, {r['clients']} clients, clip C={r['clip_norm']},",
            f"Adam lr {r['lr']}, {r['rounds']} rounds; accountant budgets the",
            f"steps actually trained, delta={r['delta']}). The non-private",
            "anchor uses the SAME tuned lr — the honest bar, since non-DP",
            "training also improves under the lr sweep. Why the r3 rows were",
            "~random and what changed: docs/DP.md.",
            "",
            "| run | epsilon | scope | B | sigma | final AUC | gap to non-DP |",
            "|---|---|---|---|---|---|---|",
        ]
        for name, run in dp["runs"].items():
            c = run["curve"][-1] if run["curve"] else {}
            gap = dp["gap_to_anchor"].get(name)
            lines.append(
                f"| {name} | {run.get('epsilon') or '—'} "
                f"| {run.get('dp_scope', 'all')} | {run.get('batch_size', 64)} "
                f"| {run.get('sigma', 0)} "
                f"| {c.get('auc', float('nan')):.4f} "
                f"| {f'{gap:+.4f}' if gap is not None else '—'} |"
            )
        lines += [
            "",
            f"Oracle AUC {dp['oracle_auc']:.4f}; non-DP tuned anchor "
            f"{dp['nodp_anchor_auc']:.4f}.",
        ]
        ceil = dp.get("user_frozen_ceiling_auc")
        eps10 = dp["runs"].get("dp_eps10", {}).get("curve") or []
        if ceil is not None and eps10:
            floor = eps10[-1]["auc"]
            lines += [
                "",
                "The round-5 levers (noise-dimension shrink via "
                "`privacy.dp_scope='user'`, batch scaling) are measured "
                "and both LOSE at this per-client data scale — "
                f"`nodp_user_frozen` ({ceil:.4f}) is the non-private "
                "ceiling of any user-tower-only scheme, and full-model DP "
                f"at eps=10 ({floor:.4f}) sits {ceil - floor:+.4f} from "
                "it. That eps=10 number is the measured floor here; the "
                "full argument is in docs/DP.md.",
            ]
    if adressa is not None:
        lines += [
            "",
            "## 3. Second dataset family: Adressa pipeline",
            "",
            "Synthetic Adressa-format event log (lexical topic signal) run",
            "through the REAL adapter — `parse_adressa_events` →",
            "tokenizer → `build_news_index` → chronological per-user split →",
            "corpus-sampled negative pools (`fedrec_tpu/data/adressa.py`) —",
            "then trained on token-derived frozen-random-trunk states",
            f"(`token_states_from_tokens`). Corpus: {adressa['corpus']['events']:,}",
            f"events → {adressa['corpus']['train']:,} train /",
            f"{adressa['corpus']['valid']:,} valid samples over",
            f"{adressa['corpus']['num_news']:,} news. Oracle AUC:",
            f"**{adressa['oracle_auc']:.4f}**. Wall-clock: {adressa['wall_s']}s.",
            "",
            *_CURVE_HEADER,
        ]
        lines += _curve_rows(adressa["curve"])
        last_a = adressa["curve"][-1]
        lines += [
            "",
            f"Final AUC {last_a.get('auc', float('nan')):.4f} "
            f"({100 * last_a.get('auc', 0.0) / max(adressa['oracle_auc'], 1e-9):.1f}% "
            "of the oracle; reference published Adressa AUC 72.04 on the real "
            f"corpus, `README.md:78`).{_partial_note(adressa)}",
        ]
    if finetune is not None:
        lines += [
            "",
            "## 4. In-loop trunk fine-tuning (BASELINE config 5)",
            "",
            "The FULL text trunk",
            f"({finetune['corpus']['trunk']} transformer, from scratch) trains",
            "in-loop from raw tokens — no cached states anywhere — on the",
            f"lexical Adressa corpus ({finetune['corpus']['train']:,} train /",
            f"{finetune['corpus']['valid']:,} valid over",
            f"{finetune['corpus']['num_news']:,} news). Oracle (token-derived",
            f"states): **{finetune['oracle_auc']:.4f}**. Wall-clock:",
            f"{finetune['wall_s']}s.",
            "",
            *_CURVE_HEADER,
        ]
        lines += _curve_rows(finetune["curve"])
        last_f = finetune["curve"][-1]
        lines += [
            "",
            f"Final AUC {last_f.get('auc', float('nan')):.4f} "
            f"({100 * last_f.get('auc', 0.0) / max(finetune['oracle_auc'], 1e-9):.1f}% "
            f"of the oracle).{_partial_note(finetune)}",
        ]
    lines += [
        "",
        *([
            "",
            "## Dtype tolerance (bfloat16 vs float32)",
            "",
            f"Same corpus/config trained in both dtypes on "
            f"**{bf16['platform']}** ({bf16['device']}); final full-pool "
            f"AUC — f32 **{bf16['final_auc']['float32']:.4f}** vs bf16 "
            f"**{bf16['final_auc']['bfloat16']:.4f}** "
            f"(delta {bf16['auc_delta']:.4f}, tolerance "
            f"{bf16['tolerance_auc']}): "
            + ("**within tolerance** — the dtype the TPU bench advertises "
               "is accuracy-safe." if bf16.get("within_tolerance")
               else "**OUT OF TOLERANCE** — investigate before trusting "
                    "bf16 numbers."),
        ] if bf16 is not None and "final_auc" in bf16 else []),
        "Full per-round curves: `benchmarks/accuracy_central.json`,",
        "`benchmarks/accuracy_fed.json`, `benchmarks/accuracy_adressa.json`,",
        "`benchmarks/accuracy_finetune.json`.",
        "Reproduce: `python benchmarks/accuracy_run.py --all`.",
        "",
    ]
    (REPO / "RESULTS.md").write_text("\n".join(lines))
    print(f"wrote {REPO / 'RESULTS.md'}")


# --------------------------------------------------------------------- main
def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--leg", choices=["central", "fed", "dp", "adressa",
                                     "finetune", "bf16", "report"])
    p.add_argument("--all", action="store_true")
    p.add_argument("--rounds", type=int, default=16)
    p.add_argument("--fed-rounds", type=int, default=10)
    p.add_argument("--dp-rounds", type=int, default=32)
    p.add_argument("--adressa-rounds", type=int, default=10)
    p.add_argument("--finetune-rounds", type=int, default=12)
    p.add_argument("--bf16-rounds", type=int, default=8)
    args = p.parse_args()

    if args.all:
        from fedrec_tpu.hostenv import cpu_host_env

        # the central leg wants the real chip, but launching it with the
        # ambient env while the tunnel is wedged hangs forever (the wedge
        # passes a device listing and stalls at first compile) — probe with
        # a real compile first, exactly like bench.py, and fall back to the
        # CPU-scaled corpus when the chip can't actually run ops
        import bench

        if bench._probe_accelerator():
            env_central = dict(os.environ)
        else:
            print("[accuracy] accelerator unusable; central leg on CPU "
                  "(FEDREC_ACC_CPU scale)", file=sys.stderr)
            env_central = cpu_host_env()
            env_central["FEDREC_ACC_CPU"] = "1"

        env_fed = cpu_host_env(8)
        env_fed["FEDREC_ACC_INNER"] = "1"  # children skip the self-harden re-exec
        # an ambient row filter (watcher debugging) must not turn the
        # canonical full-sweep artifacts into subsets
        env_fed.pop("FEDREC_DP_ROWS", None)
        me = str(HERE / "accuracy_run.py")
        central_cmd = [
            sys.executable, me, "--leg", "central", "--rounds", str(args.rounds)
        ]
        # the probe only closes the wedged-at-launch case; a POST-probe wedge
        # would hang the leg at its first compile, so the accelerator attempt
        # also runs under a watchdog with the same CPU fallback (per-round
        # persist means a mid-run wedge still leaves a PARTIAL curve)
        try:
            rc = subprocess.run(
                central_cmd, env=env_central, cwd=REPO, timeout=2400
            ).returncode
        except subprocess.TimeoutExpired:
            print("[accuracy] central leg timed out (tunnel wedge?); "
                  "retrying on CPU", file=sys.stderr)
            rc = 1
        if rc != 0 and "FEDREC_ACC_CPU" not in env_central:
            env_cpu = cpu_host_env()
            env_cpu["FEDREC_ACC_CPU"] = "1"
            rc = subprocess.run(
                central_cmd, env=env_cpu, cwd=REPO, timeout=7200
            ).returncode
        if rc != 0:
            return rc
        for cmd, env in (
            ([sys.executable, me, "--leg", "fed", "--rounds", str(args.fed_rounds)],
             env_fed),
            ([sys.executable, me, "--leg", "dp",
              "--dp-rounds", str(args.dp_rounds)], env_fed),
            ([sys.executable, me, "--leg", "adressa",
              "--rounds", str(args.adressa_rounds)], env_fed),
            ([sys.executable, me, "--leg", "finetune",
              "--rounds", str(args.finetune_rounds)], env_fed),
            ([sys.executable, me, "--leg", "report"], dict(os.environ)),
        ):
            rc = subprocess.run(cmd, env=env, cwd=REPO).returncode
            if rc != 0:
                return rc

        # dtype-tolerance leg AFTER the report chain: prefer the chip (it
        # is the dtype's native home) but under the same watchdog + CPU
        # fallback discipline as the central leg — a post-probe wedge must
        # not hang --all at the bf16 leg's first compile
        bf16_cmd = [
            sys.executable, me, "--leg", "bf16",
            "--bf16-rounds", str(args.bf16_rounds),
        ]
        try:
            rc = subprocess.run(
                bf16_cmd, env=env_central, cwd=REPO, timeout=2400
            ).returncode
        except subprocess.TimeoutExpired:
            print("[accuracy] bf16 leg timed out (tunnel wedge?); retrying "
                  "on CPU", file=sys.stderr)
            rc = 1
        if rc != 0 and "FEDREC_ACC_CPU" not in env_central:
            env_cpu = cpu_host_env()
            env_cpu["FEDREC_ACC_CPU"] = "1"
            rc = subprocess.run(
                bf16_cmd, env=env_cpu, cwd=REPO, timeout=7200
            ).returncode
        if rc != 0:
            return rc
        # regenerate the report so it includes the bf16 section
        return subprocess.run(
            [sys.executable, me, "--leg", "report"],
            env=dict(os.environ), cwd=REPO,
        ).returncode

    if (
        args.leg in ("fed", "dp", "adressa", "finetune")
        and os.environ.get("FEDREC_ACC_INNER") != "1"
    ):
        # These legs are DESIGNED for the 8-device fake CPU mesh (the
        # multi-client simulation rig); launched with the ambient env they
        # instead try the axon backend and crash at init when the tunnel is
        # wedged (observed 2026-07-31). Self-harden exactly like --all does
        # for its children. Operators who really want a leg on a live
        # multi-device accelerator can set FEDREC_ACC_INNER=1 to skip the
        # re-exec and keep their own environment.
        from fedrec_tpu.hostenv import cpu_host_env

        env = cpu_host_env(8)
        env["FEDREC_ACC_INNER"] = "1"
        os.execve(sys.executable, [sys.executable, *sys.argv], env)

    if args.leg == "central":
        leg_central(args.rounds)
    elif args.leg == "bf16":
        leg_bf16(args.bf16_rounds)
    elif args.leg == "fed":
        leg_fed(args.rounds)
    elif args.leg == "dp":
        leg_dp(args.dp_rounds)
    elif args.leg == "adressa":
        leg_adressa(args.rounds)
    elif args.leg == "finetune":
        leg_finetune(args.rounds)
    elif args.leg == "report":
        write_report()
    else:
        p.error("pass --leg or --all")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
