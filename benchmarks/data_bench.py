"""Host data-pipeline benchmark: Python batcher vs native C++ engine.

The reference feeds training through torch ``DataLoader`` workers
(``main.py:166``); our equivalent host-side hot loop — epoch shuffle,
round-robin client sharding, negative sampling, static-shape batch packing —
has two implementations: the numpy ``TrainBatcher`` and the threaded C++
engine (``native/fedrec_data.cpp`` via ``NativeTrainBatcher``). This
benchmark records what the native engine buys on a MIND-scale epoch, since
on TPU the host pipeline is what must keep the chip fed.

Writes ``benchmarks/data_bench.json`` and prints one JSON line.
Usage: python benchmarks/data_bench.py [--samples 200000]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

HERE = Path(__file__).resolve().parent
REPO = HERE.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def make_indexed(n_samples: int, num_news: int, pool: int, his: int, seed: int = 0):
    from fedrec_tpu.data.batcher import IndexedSamples

    rng = np.random.default_rng(seed)
    neg_lens = rng.integers(4, pool + 1, size=n_samples).astype(np.int32)
    pools = rng.integers(1, num_news, size=(n_samples, pool)).astype(np.int32)
    pools[np.arange(pool)[None, :] >= neg_lens[:, None]] = 0
    his_len = rng.integers(1, his + 1, size=n_samples).astype(np.int32)
    hist = rng.integers(1, num_news, size=(n_samples, his)).astype(np.int32)
    hist[np.arange(his)[None, :] >= his_len[:, None]] = 0
    return IndexedSamples(
        pos=rng.integers(1, num_news, size=n_samples).astype(np.int32),
        neg_pools=pools,
        neg_lens=neg_lens,
        history=hist,
        his_len=his_len,
    )


def time_call(fn, repeats: int = 3) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--samples", type=int, default=200_000)
    p.add_argument("--num-news", type=int, default=65_000)  # MIND-small scale
    p.add_argument("--pool", type=int, default=40)
    p.add_argument("--his", type=int, default=50)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--clients", type=int, default=8)
    p.add_argument("--threads", type=int, default=8)
    args = p.parse_args()

    from fedrec_tpu.data.batcher import TrainBatcher
    from fedrec_tpu.data.native_batcher import NativeTrainBatcher, is_available

    indexed = make_indexed(args.samples, args.num_news, args.pool, args.his)
    n_eff = (args.samples // args.batch) * args.batch  # drop_remainder parity

    py = TrainBatcher(indexed, batch_size=args.batch, seed=1)
    t_py = time_call(lambda: sum(1 for _ in py.epoch_batches(0)))

    out = {
        "metric": "data_pipeline_epoch_assembly",
        "unit": "samples/sec",
        "samples": args.samples,
        "batch": args.batch,
        "pool": args.pool,
        "his": args.his,
        "python_batcher": round(n_eff / t_py, 1),
    }

    if is_available():
        nb = NativeTrainBatcher(indexed, batch_size=args.batch, seed=1)
        t_n1 = time_call(lambda: sum(1 for _ in nb.epoch_batches(0)))
        out["native_batcher"] = round(n_eff / t_n1, 1)

        nb_s = NativeTrainBatcher(
            indexed, batch_size=args.batch, seed=1, num_threads=args.threads
        )
        n_shard = (
            nb_s._steps(args.clients) * args.clients * args.batch
        )  # samples packed per sharded epoch
        t_ep = time_call(lambda: nb_s.epoch_arrays_sharded(args.clients, 0))
        out["native_epoch_threaded"] = round(n_shard / t_ep, 1)
        # same bulk-epoch call pinned to ONE thread: separates the bulk-
        # packing gain (one FFI call, no per-batch Python) from actual
        # thread parallelism — on a 1-core host these two rates should
        # match, and the threaded/python ratio is NOT a parallelism claim
        nb_1 = NativeTrainBatcher(
            indexed, batch_size=args.batch, seed=1, num_threads=1
        )
        t_ep1 = time_call(lambda: nb_1.epoch_arrays_sharded(args.clients, 0))
        out["native_epoch_1thread"] = round(n_shard / t_ep1, 1)
        out["clients"] = args.clients
        out["threads"] = args.threads
        out["speedup_native"] = round(out["native_batcher"] / out["python_batcher"], 2)
        out["speedup_threaded"] = round(
            out["native_epoch_threaded"] / out["python_batcher"], 2
        )
        out["speedup_threads_only"] = round(
            out["native_epoch_threaded"] / out["native_epoch_1thread"], 2
        )
    else:
        out["native_batcher"] = None

    from fedrec_tpu.utils.provenance import provenance

    out["provenance"] = provenance()
    (HERE / "data_bench.json").write_text(json.dumps(out, indent=2))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
