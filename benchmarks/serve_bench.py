"""Serving-path throughput: users/sec for full-catalog top-k scoring.

The serving subsystem (``fedrec_tpu.serve``, beyond-parity: the reference
stops at validation, reference ``client.py:149-171``) had tests but no perf
artifact. This measures the jitted ``recommend`` program — user encode over
the history, one (B, D) x (D, N) full-catalog matmul, masked ``top_k`` — at
MIND-small catalog scale (N=65k news, D=400) across user-batch sizes.

On TPU the tunnel-honest chain timer applies (``pallas_bench._time``); on
CPU plain local timing is trustworthy, and the number contextualizes the
CPU-fallback deployment. Writes ``benchmarks/serve_bench[_cpu].json``.

Usage: python benchmarks/serve_bench.py [--cpu] [--num-news 65000]
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

import numpy as np

_REPO = str(Path(__file__).resolve().parent.parent)
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from pallas_bench import _time  # noqa: E402  (same honest timer on TPU)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--cpu", action="store_true",
                   help="allow running on the CPU backend (local timing)")
    p.add_argument("--num-news", type=int, default=65_000)  # MIND-small scale
    p.add_argument("--top-k", type=int, default=10)
    p.add_argument("--his-len", type=int, default=50)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from fedrec_tpu.config import ExperimentConfig
    from fedrec_tpu.models import NewsRecommender
    from fedrec_tpu.serve import build_recommend_fn

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu and not args.cpu:
        print("needs the TPU (honest timing assumptions); pass --cpu for a "
              "local CPU measurement", file=sys.stderr)
        return 1

    cfg = ExperimentConfig()
    cfg.model.dtype = "float32" if on_cpu else "bfloat16"
    N, D, H = args.num_news, cfg.model.news_dim, args.his_len

    rng = np.random.default_rng(0)
    table = jnp.asarray(
        rng.standard_normal((N, D)), dtype=jnp.dtype(cfg.model.dtype)
    )
    model = NewsRecommender(cfg.model)
    dummy = jnp.zeros((1, H, D), jnp.dtype(cfg.model.dtype))
    user_params = model.init(
        jax.random.PRNGKey(0), dummy, method=NewsRecommender.encode_user
    )["params"]["user_encoder"]
    fn = build_recommend_fn(model, top_k=args.top_k)
    jfn = jax.jit(fn)

    def cpu_best_of_3(fn2, *a):
        # plain local timing: warm, then best-of-3 with host sync
        np.asarray(fn2(*a)[0])
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(fn2(*a)[0])
            best = min(best, time.perf_counter() - t0)
        return best

    from fedrec_tpu.utils.provenance import provenance, write_artifact

    name = "serve_bench_cpu.json" if on_cpu else "serve_bench.json"
    out_rows = {}
    sharded_rows = {"batches": {}}

    def _stamp(partial: bool) -> None:
        # incremental banking: a tunnel wedge mid-run must not discard the
        # rows already measured (windows last ~20 min). The watcher banks
        # the queue item only when "partial" is absent.
        write_artifact(Path(__file__).with_name(name), {
            "metric": "recommend_throughput",
            "unit": "users/sec",
            "num_news": N,
            "news_dim": D,
            "top_k": args.top_k,
            "his_len": H,
            "dtype": cfg.model.dtype,
            "batches": out_rows,
            "sharded": sharded_rows,
            "provenance": provenance(),
        }, partial)

    for B in (1, 64, 256, 1024):
        history = jnp.asarray(
            rng.integers(1, N, (B, H)).astype(np.int32)
        )
        if on_cpu:
            dt = cpu_best_of_3(jfn, user_params, table, history)
        else:
            # the chain timer perturbs the FIRST argument; wrap so that is
            # the float table (histories stay fixed ids)
            dt = _time(
                jax.jit(lambda t, h: fn(user_params, t, h)[1]),
                table, history,
            )
        out_rows[str(B)] = {
            "users_per_sec": round(B / dt, 2),
            "ms_per_batch": round(dt * 1e3, 3),
        }
        print(f"B={B:5d}  {B/dt:12.1f} users/s  ({dt*1e3:.3f} ms)", flush=True)
        _stamp(partial=True)

    # mesh-sharded scorer (serve.build_recommend_fn_sharded): catalog +
    # score matrix split over every device, local top-k + gather merge.
    # Runs even on ONE device (size-1 mesh): on the single-chip TPU rig
    # that is the only available on-hardware execution proof for the
    # sharded program — the WIN is a multi-chip property (see verdict).
    from fedrec_tpu.parallel import client_mesh
    from fedrec_tpu.serve import build_recommend_fn_sharded

    mesh = client_mesh(len(jax.devices()))
    sfn = build_recommend_fn_sharded(model, mesh, top_k=args.top_k)
    sharded_rows["n_devices"] = mesh.size
    if on_cpu and mesh.size > 1:
        sharded_rows["note"] = (
            f"{mesh.size} FAKE devices on 1 physical core: this row "
            "proves the sharded program executes at catalog scale; "
            f"wall time measures the core running {mesh.size} device "
            "programs serially + collective overhead, NOT the sharding "
            "win, which is a multi-chip property"
        )
    if mesh.size == 1:
        sharded_rows["note"] = (
            "size-1 mesh: proves the shard_map serving program (local "
            "top-k + all_gather merge) executes on this hardware; its "
            "throughput should track the dense rows"
        )
    for B in (256, 1024):
        history = jnp.asarray(rng.integers(1, N, (B, H)).astype(np.int32))
        if on_cpu:
            dt = cpu_best_of_3(sfn, user_params, table, history)
        else:
            dt = _time(
                jax.jit(lambda t, h: sfn(user_params, t, h)[1]),
                table, history,
            )
        sharded_rows["batches"][str(B)] = {
            "users_per_sec": round(B / dt, 2),
            "ms_per_batch": round(dt * 1e3, 3),
        }
        print(f"B={B:5d} sharded x{mesh.size}  {B/dt:10.1f} users/s",
              flush=True)
        _stamp(partial=True)

    # when does sharded win? One (B, k) all_gather per query vs splitting
    # the (N, D) table + (B, N) scores — a CHIP-sizing question, so the
    # cutoff is computed for the chip serving dtype (bf16 table; the
    # scorer always keeps scores f32) even when this run is the f32 CPU
    # fallback. The artifact carries its own one-line verdict (r4 #6).
    chip_itemsize = 2  # bfloat16 table on the chip path
    hbm_budget = 12e9  # ~16 GB chip, leave compiler/program headroom
    bmax = 1024
    n_single_chip = int(hbm_budget / (D * chip_itemsize + bmax * 4))
    side = (
        f"this run's N={N:,} is below that cutoff, where dense on one "
        "chip avoids the all_gather merge entirely and a "
        f"size-{mesh.size} mesh adds capacity, not speed"
        if N <= n_single_chip
        else f"this run's N={N:,} EXCEEDS the cutoff: the sharded scorer "
        "is the only single-program option at this catalog size"
    )
    verdict = (
        f"sharded wins when the catalog stops fitting one device: at "
        f"D={D}/bfloat16-table/B={bmax} one ~16 GB chip holds "
        f"N ~= {n_single_chip:,} news (table + f32 scores); {side}"
    )
    sharded_rows["verdict"] = verdict
    print(f"[serve] {verdict}", flush=True)

    _stamp(partial=False)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
