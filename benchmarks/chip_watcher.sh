#!/bin/bash
# Chip-measurement watcher: re-probe the axon TPU tunnel every 10 minutes and,
# at the next live window, run the outstanding measurement queue serially.
#
# Why this exists: the tunnel wedges transiently (sometimes for hours) and the
# windows are short, so measurements must be queued and banked incrementally.
# Each queue item stamps its artifact with provenance (commit, host, time) as
# soon as it lands. A done-marker under benchmarks/.chipqueue/ is touched ONLY
# when the item's output proves a real-chip measurement (see verify_*): every
# queue item exits 0 on its CPU-fallback path too, so exit status alone would
# let a wedge between the probe and the item's own run consume the item with
# no chip number banked. Run detached:
#
#   nohup benchmarks/chip_watcher.sh > /tmp/chip_watcher.log 2>&1 &
#
# The markers live in the working tree (gitignored) — a fresh checkout starts
# a fresh queue, which is correct: a new tree needs new measurements.
set -u
cd "$(dirname "$0")/.."
MARK=benchmarks/.chipqueue
mkdir -p "$MARK"

# single source of tunnel-health truth: bench.py's _probe_accelerator
# (DEVOK wedge/stall disambiguation, retry/backoff) — do not fork the policy
probe() {
  python -c 'import sys; sys.path.insert(0, "."); import bench; \
sys.exit(0 if bench._probe_accelerator() else 1)'
}

verify_bench() { # fresh real-chip primary: platform tpu, not a cached replay
  grep -q '"platform": "tpu"' /tmp/chipq_bench.out \
    && ! grep -q '"cached": true' /tmp/chipq_bench.out
}
verify_pallas() { # refuses to run off-TPU, so its table implies the chip
  grep -q 'on tpu' /tmp/chipq_pallas.out
}
# shared JSON-artifact check: artifact newer than THIS run's start sentinel
# (a stale tpu-stamped artifact from an earlier window must not bank a run
# that produced no fresh chip evidence) and stamped with a real chip backend.
# CPU fallbacks write *_cpu.json siblings, leaving these untouched.
verify_json_artifact() { # artifact_path item_name
  # "partial": the harness stamps incrementally so a mid-run wedge keeps
  # its completed rows as labeled evidence — but the item banks (stops
  # retrying) only on a COMPLETE run
  [ "$1" -nt "$MARK/.start_$2" ] 2>/dev/null \
    && grep -q '"jax_backend": "tpu"' "$1" \
    && ! grep -q '"partial": true' "$1"
}
verify_step_profile() {
  verify_json_artifact benchmarks/step_profile.json step_profile
}
verify_acc_bf16() {
  verify_json_artifact benchmarks/accuracy_bf16.json acc_bf16
}
verify_serve() {
  verify_json_artifact benchmarks/serve_bench.json serve
}
verify_acc_dp() { # tuned anchor + eps=10 DP row proven on-chip (r4 #7)
  verify_json_artifact benchmarks/accuracy_dp_tpu.json acc_dp
}
verify_agg_scale() { # on-device flat-mean reduce leg of the agg frontier
  verify_json_artifact benchmarks/agg_scale_tpu.json agg_scale
}

run_item() { # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  [ -e "$MARK/$name" ] && return 0
  echo "[watcher] $(date -u +%FT%TZ) running $name"
  touch "$MARK/.start_$name"
  timeout "$tmo" "$@" > "/tmp/chipq_$name.out" 2>&1
  local rc=$?
  if [ "$rc" -eq 0 ] && "verify_$name"; then
    touch "$MARK/$name"
    echo "[watcher] $name DONE (real-chip evidence verified)"
  else
    echo "[watcher] $name not banked (rc=$rc or no chip evidence); will retry"
  fi
}

while :; do
  remaining=0
  for n in bench step_profile serve pallas acc_bf16 acc_dp agg_scale; do
    [ -e "$MARK/$n" ] || remaining=$((remaining + 1))
  done
  if [ "$remaining" -eq 0 ]; then
    echo "[watcher] queue drained; exiting"
    exit 0
  fi
  if probe; then
    echo "[watcher] $(date -u +%FT%TZ) chip live; draining queue ($remaining left)"
    # short, high-information items first: windows have measured ~20 min
    # (2026-08-01 08:28-08:48Z window closed mid-bench), so the roofline
    # verdict and the serving row must not queue behind an accuracy leg.
    # ISSUE 8: the bench item now also banks the fused-hot-path B=1024
    # leg (fused_b1024_samples_per_sec / fused_vs_unfused_b1024 /
    # fused_mfu_b1024) and the pallas item the fused-kernel micro legs
    # (B in {256,1024} + gather+encode) — a fresh tree queues both
    # automatically (markers are per-checkout).
    run_item bench 2400 python bench.py
    run_item step_profile 1800 python benchmarks/step_profile.py
    run_item serve 1800 python benchmarks/serve_bench.py
    run_item pallas 2400 python benchmarks/pallas_bench.py
    run_item acc_bf16 3600 python benchmarks/accuracy_run.py --leg bf16
    # FEDREC_ACC_INNER=1: without it accuracy_run.py self-hardens by
    # re-exec'ing under JAX_PLATFORMS=cpu and the on-chip proof could
    # never bank (it would burn every window on a CPU run)
    run_item acc_dp 3600 env FEDREC_ACC_INNER=1 \
      FEDREC_DP_ROWS=nodp_tuned,dp_eps10 \
      python benchmarks/accuracy_run.py --leg dp --dp-rounds 32
    # on-device flat-mean reduce over the 100k-client stack: the
    # DCN-free upper bound the host agg kernels compare against
    run_item agg_scale 1200 python benchmarks/agg_scale.py --chip --check
  else
    echo "[watcher] $(date -u +%FT%TZ) chip unreachable; sleeping"
  fi
  # 5-min probe cadence: windows last ~20 min, a 10-min cadence can burn
  # half a window before noticing it opened
  sleep 300
done
