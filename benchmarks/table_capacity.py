"""Catalog capacity: rows-per-device x devices under the sharded table.

The replicated token-state table caps catalog size at single-device HBM
(ROADMAP item 2: MIND-small fits, a production million-item catalog does
not). ``shard.table`` row-shards it over the mesh, so capacity scales
linearly with devices. This benchmark banks that frontier:

1. **Modeled frontier** — max catalog rows per HBM budget x device
   count, replicated vs sharded, at the flagship row shape
   (``max_title_len x bert_hidden``, bf16 and f32) — plain arithmetic,
   labeled as such, so the sizing runbook (docs/OPERATIONS.md §3e) has
   numbers to point at.
2. **Measured leg** — on the LOCAL backend (8 fake CPU devices when no
   accelerator; the real slice otherwise): a :class:`ShardedNewsTable`
   is committed, per-device resident rows are asserted equal to
   ``padded_rows / devices`` from the actual addressable shards, the
   owner-bucketed ``all_to_all`` gather is checked BIT-IDENTICAL to the
   dense ``table[ids]``, and both gathers are timed (warm, readback-
   synchronized). CPU timings say nothing about chip speed — the row is
   labeled — but the exactness and residency claims are backend-exact.

Writes ``benchmarks/table_capacity.json`` (provenance-stamped) and
prints one JSON line.

    python benchmarks/table_capacity.py       # or: make table-capacity
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial
from pathlib import Path

import numpy as np

HERE = Path(__file__).resolve().parent
REPO = HERE.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

_INNER = "FEDREC_TABLE_CAPACITY_INNER"

GIB = 1024**3
# flagship row shape (DataConfig.max_title_len x ModelConfig.bert_hidden)
ROW_SHAPE = (50, 768)
HBM_BUDGETS_GIB = (16, 32)
DEVICE_COUNTS = (1, 4, 8, 32, 64, 256)


def modeled_frontier() -> dict:
    out: dict = {"row_shape": list(ROW_SHAPE), "rows": []}
    for dtype, itemsize in (("bfloat16", 2), ("float32", 4)):
        row_bytes = int(np.prod(ROW_SHAPE)) * itemsize
        for budget in HBM_BUDGETS_GIB:
            per_dev = (budget * GIB) // row_bytes
            for n_dev in DEVICE_COUNTS:
                out["rows"].append({
                    "dtype": dtype,
                    "row_bytes": row_bytes,
                    "hbm_gib_per_device": budget,
                    "devices": n_dev,
                    "max_rows_replicated": int(per_dev),
                    "max_rows_sharded": int(per_dev * n_dev),
                })
    return out


def measured_leg() -> dict:
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from fedrec_tpu.compat import shard_map
    from fedrec_tpu.shard.table import (
        ShardedNewsTable, a2a_bytes_per_gather, owner_bucketed_gather,
    )

    devices = jax.devices()
    s = len(devices)
    mesh = Mesh(np.array(devices).reshape(s), ("clients",))
    rng = np.random.default_rng(0)
    # small rows on CPU sim; the claim being measured is exactness +
    # residency + relative exchange cost, not chip throughput
    n, l, d = 4096 + 3, 12, 64  # +3: non-divisible (padding path)
    u = 256
    full = rng.standard_normal((n, l, d)).astype(np.float32)
    tab = ShardedNewsTable.create(full, mesh, "clients")

    resident = sorted({sh.data.shape[0] for sh in tab.rows.addressable_shards})
    assert resident == [tab.spec.rows_per_shard], resident
    assert tab.spec.rows_per_shard == tab.spec.padded_rows // s

    ids = rng.integers(0, n, (s, u)).astype(np.int32)
    ids_sharded = jax.device_put(ids, NamedSharding(mesh, P("clients")))

    @partial(
        shard_map, mesh=mesh,
        in_specs=(P("clients"), P("clients")), out_specs=P("clients"),
        check_vma=False,
    )
    def sharded_gather(rows, ids_blk):
        return owner_bucketed_gather(rows, ids_blk[0], tab.spec)[None]

    @partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P("clients")), out_specs=P("clients"),
        check_vma=False,
    )
    def dense_gather(rows, ids_blk):
        return rows[ids_blk[0]][None]

    g_sharded = jax.jit(sharded_gather)
    g_dense = jax.jit(dense_gather)
    table_rep = jnp.asarray(full)

    out_s = np.asarray(g_sharded(tab.rows, ids_sharded))
    out_d = np.asarray(g_dense(table_rep, ids_sharded))
    np.testing.assert_array_equal(out_s, full[ids])
    np.testing.assert_array_equal(out_d, full[ids])

    def timed(fn, *args, iters=20) -> float:
        fn(*args)  # warm (compile)
        t0 = time.perf_counter()
        last = None
        for _ in range(iters):
            last = fn(*args)
        jax.block_until_ready(last)
        return (time.perf_counter() - t0) / iters

    dt_sharded = timed(g_sharded, tab.rows, ids_sharded)
    dt_dense = timed(g_dense, table_rep, ids_sharded)
    platform = devices[0].platform
    return {
        "platform": platform,
        "devices": s,
        "catalog_rows": n,
        "row_shape": [l, d],
        "unique_ids_per_client": u,
        "rows_per_device_sharded": tab.spec.rows_per_shard,
        "rows_per_device_replicated": n,
        "table_occupancy": round(n / tab.spec.padded_rows, 6),
        "gather_exact_vs_dense": True,  # assert above raised otherwise
        "sharded_gather_ms": round(dt_sharded * 1e3, 3),
        "dense_gather_ms": round(dt_dense * 1e3, 3),
        "a2a_bytes_per_gather": a2a_bytes_per_gather(
            u, (l, d), np.float32, tab.spec
        ),
        "timing_note": (
            "exactness/residency are backend-exact; the ms rows are "
            f"{platform} timings of the exchange vs the dense gather at "
            "toy shapes — never quote them as chip numbers"
        ),
    }


def main() -> int:
    from fedrec_tpu.hostenv import fake_device_count

    if (
        os.environ.get(_INNER) is None
        and os.environ.get("JAX_PLATFORMS", "cpu") == "cpu"
        and (fake_device_count() or 1) < 2
    ):
        # CPU backend with a single device: re-exec with an 8-device fake
        # mesh so the measured leg exercises a real multi-shard exchange
        from fedrec_tpu.hostenv import cpu_host_env

        env = cpu_host_env(8)
        env[_INNER] = "1"
        os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)

    out = {
        "metric": "fedrec_table_capacity",
        "modeled_frontier": modeled_frontier(),
        "measured": measured_leg(),
    }
    from fedrec_tpu.utils.provenance import provenance

    out["provenance"] = provenance()
    (HERE / "table_capacity.json").write_text(json.dumps(out, indent=2))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
