"""Aggregation-scale frontier: round time vs cohort size, flat vs
hierarchical vs async, on the REAL ``fedrec_tpu.agg`` reduce kernels.

The round-barrier cost model at pod scale has three regimes:

* **flat**    — every logical client reports to one reducer; the round
                waits for the SLOWEST report (max of the chaos lognormal
                latency draw) and then pays one robust reduce over the
                full (C, D) contribution stack.
* **hier**    — clients pre-aggregate per host (groups of
                ``HOST_GROUP``, concurrent across hosts → wall cost is
                the slowest GROUP, not the sum), then a fanout-2 sparse
                tree reduces the per-host stack over DCN
                (``agg.hierarchy.tree_reduce_np``; wall cost is the tree
                CRITICAL PATH — per level, groups run concurrently).
                Still barriered on the slowest report, but the reduce
                leaves the linear regime: round time goes sub-linear in
                cohort size.
* **async**   — the commit fires at quorum K = ceil(QUORUM_FRAC x C)
                (``agg.commit.fold_commit`` over the K on-time entries):
                the round pays the K-quantile of the latency draw, not
                the max. The banked ``gate_saved_ms`` lane is the
                straggler tail the quorum cut off.

The async lanes also bank an **uplink-bytes column**: the wire cost of
the K on-time contributions dense vs countsketch-encoded, priced from
REAL ``fedrec_tpu.comms.encode_leaf`` payload buffers (payload size is
shape-deterministic, so one encode per leaf prices every contribution).
The structural check requires async+sketch < async-dense at 10k+.

Latency draws ride the production population engine
(``fed.chaos.population_report``: seeded lognormal, median
``chaos.pop_straggle_ms``) so the tail shape matches what the trainer's
deadline/quorum machinery actually sees. Reduce/fold times are measured
on synthetic (C, D) stacks with the real kernels; latency lanes are
bit-deterministic (seeded), timing lanes carry a measured spread.

Structural checks — run EVERY time, bank or check (they are the
acceptance criteria, not regression guards):

* hierarchical round time is SUB-LINEAR in cohort size at 10k+ clients
  (growing the cohort 10x must grow the round < 10x);
* async round time beats flat at every cohort size (the quorum cut is
  real).

Usage:
    python benchmarks/agg_scale.py            # bank if absent, else check
    python benchmarks/agg_scale.py --bank     # (re)bank the baseline
    python benchmarks/agg_scale.py --check    # check only (exit 2 if no baseline)
    python benchmarks/agg_scale.py --chip     # also time the on-device flat
                                              # mean; writes agg_scale_tpu.json

Writes ``benchmarks/agg_scale.json`` (provenance-stamped); exit 0 =
pass/banked, 1 = regression/structural failure, 2 = usage/missing-baseline.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np

HERE = Path(__file__).resolve().parent
REPO = HERE.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

COHORTS = (1_000, 10_000, 100_000)   # logical clients
HOST_GROUP = 256                     # clients pre-aggregated per host
FANOUT = 2                           # cross-host DCN tree fanout
QUORUM_FRAC = 0.8                    # async commit quorum fraction
LEAF_DIMS = ((48,), (16,))           # synthetic per-client contribution
STRAGGLE_MS = 200.0                  # lognormal median report latency
STRAGGLE_SIGMA = 0.7
SKETCH_WIDTH = 0.1                   # fed.dcn_sketch_width for the uplink lane
SKETCH_CODEC = "countsketch"
SUBLINEAR_FROM = 10_000              # the acceptance bound applies at 10k+
REL_FLOOR = 1.0                      # timing lanes may regress 2x (they are
                                     # µs..ms host reduces on a shared rig)
ABS_FLOOR_MS = 0.5


def _latencies(cohort: int) -> np.ndarray:
    """The production latency draw: chaos population engine, seeded."""
    from fedrec_tpu.config import ChaosConfig
    from fedrec_tpu.fed.chaos import FaultPlan, population_report

    ccfg = ChaosConfig()
    ccfg.enabled = True
    ccfg.seed = 0
    ccfg.pop_straggle_ms = STRAGGLE_MS
    ccfg.pop_straggle_sigma = STRAGGLE_SIGMA
    plan = FaultPlan(ccfg, cohort)
    _, latency = population_report(plan, 0, np.arange(cohort))
    return latency


def _stacks(cohort: int) -> list[np.ndarray]:
    rng = np.random.default_rng([1, cohort])
    return [
        rng.standard_normal((cohort,) + d).astype(np.float32)
        for d in LEAF_DIMS
    ]


def _timed(fn, repeats: int) -> tuple[float, float]:
    """(best_ms, spread_ms) over ``repeats`` calls."""
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append((time.perf_counter() - t0) * 1e3)
    return min(times), max(times) - min(times)


def measure_cohort(cohort: int, repeats: int) -> dict:
    """One frontier row: flat/hier/async round-time model + components."""
    from fedrec_tpu.agg.buffer import BufferEntry
    from fedrec_tpu.agg.commit import CommitPolicy, fold_commit
    from fedrec_tpu.agg.hierarchy import tree_critical_path_ms, tree_reduce_np
    from fedrec_tpu.fed.robust import robust_reduce_tree_np

    lat = _latencies(cohort)
    stacks = _stacks(cohort)
    w = np.ones(cohort)
    fallback = [np.zeros(d, np.float32) for d in LEAF_DIMS]
    max_lat = float(lat.max())

    # ---- flat: one robust reduce over the full contribution stack
    flat_ms, flat_spread = _timed(
        lambda: robust_reduce_tree_np(
            stacks, w, "trimmed_mean", trim_k=1, fallback_tree=fallback
        ),
        repeats,
    )

    # ---- hierarchical: per-host pre-aggregate (concurrent across hosts
    # -> wall = slowest group) + cross-host critical-path tree
    hosts = list(range(0, cohort, HOST_GROUP))
    host_ms = 0.0
    host_leaves: list[list[np.ndarray]] = []
    host_w = np.empty(len(hosts))
    for hi, start in enumerate(hosts):
        idx = slice(start, min(start + HOST_GROUP, cohort))
        t0 = time.perf_counter()
        reduced = robust_reduce_tree_np(
            [s[idx] for s in stacks], w[idx], "trimmed_mean",
            trim_k=1, fallback_tree=fallback,
        )
        host_ms = max(host_ms, (time.perf_counter() - t0) * 1e3)
        host_leaves.append(list(reduced))
        host_w[hi] = w[idx].sum()
    host_stacks = [
        np.stack([h[j] for h in host_leaves], axis=0)
        for j in range(len(LEAF_DIMS))
    ]
    stats: dict = {}
    tree_reduce_np(
        host_stacks, host_w, FANOUT, "trimmed_mean", trim_k=1,
        fallback_tree=fallback, stats=stats,
    )
    tree_ms = tree_critical_path_ms(stats)

    # ---- async: commit at quorum K — pay the K-quantile latency, then
    # the buffered fold over the K on-time entries
    k = max(1, int(np.ceil(QUORUM_FRAC * cohort)))
    order = np.argsort(lat, kind="stable")
    quorum_lat = float(lat[order[k - 1]])
    on_time = order[:k]
    entries = [
        BufferEntry(
            worker=str(int(c)), round=0, epoch=0, based_on=0,
            weight=1.0, arrival_ms=float(lat[c]),
            leaves=[s[c] for s in stacks],
        )
        for c in on_time
    ]
    policy = CommitPolicy(quorum=k, staleness_cap=2)
    fold_ms, fold_spread = _timed(
        lambda: fold_commit(fallback, entries, 0, policy, method="mean"),
        max(1, repeats - 1),
    )

    # ---- async uplink bytes: the K on-time contributions over the wire,
    # dense f32 vs sketch-encoded — priced from REAL encode_leaf payload
    # buffers (payload size is shape-deterministic: one encode per leaf
    # prices every contribution of that shape)
    from fedrec_tpu.comms import encode_leaf, payload_nbytes

    sample = [s[0] for s in stacks]
    dense_per = sum(4 * x.size for x in sample)
    sketch_per = sum(
        payload_nbytes(encode_leaf(
            x, SKETCH_CODEC, sketch_width=SKETCH_WIDTH, leaf_id=j,
        ))
        for j, x in enumerate(sample)
    )

    return {
        "cohort": cohort,
        "hosts": len(hosts),
        "quorum": k,
        # deterministic (seeded draw) lanes
        "max_latency_ms": round(max_lat, 3),
        "quorum_latency_ms": round(quorum_lat, 3),
        "gate_saved_ms": round(max_lat - quorum_lat, 3),
        # timing lanes (best-of-repeats + spread)
        "flat_reduce_ms": round(flat_ms, 3),
        "flat_reduce_spread_ms": round(flat_spread, 3),
        "hier_host_ms": round(host_ms, 3),
        "hier_tree_ms": round(tree_ms, 3),
        "async_fold_ms": round(fold_ms, 3),
        "async_fold_spread_ms": round(fold_spread, 3),
        # the frontier itself
        "flat_round_ms": round(max_lat + flat_ms, 3),
        "hier_round_ms": round(max_lat + host_ms + tree_ms, 3),
        "async_round_ms": round(quorum_lat + fold_ms, 3),
        # uplink-bytes column: the K on-time pushes, dense vs sketch
        # (deterministic — real encoded payload sizes x quorum)
        "async_uplink_dense_mb": round(k * dense_per / (1024 * 1024), 4),
        "async_uplink_sketch_mb": round(k * sketch_per / (1024 * 1024), 4),
        "uplink_bytes_per_push_dense": int(dense_per),
        "uplink_bytes_per_push_sketch": int(sketch_per),
    }


def structural_check(rows: list[dict]) -> list[str]:
    """The acceptance criteria, proven on every run."""
    problems = []
    by_c = {r["cohort"]: r for r in rows}
    cohorts = sorted(by_c)
    for c1, c2 in zip(cohorts, cohorts[1:]):
        if c2 < SUBLINEAR_FROM:
            continue
        growth = by_c[c2]["hier_round_ms"] / max(by_c[c1]["hier_round_ms"], 1e-9)
        if growth >= c2 / c1:
            problems.append(
                f"hier_round_ms grew {growth:.2f}x from {c1} to {c2} clients "
                f"(>= the {c2 // c1}x cohort growth — not sub-linear)"
            )
    for r in rows:
        if r["async_round_ms"] >= r["flat_round_ms"]:
            problems.append(
                f"async_round_ms {r['async_round_ms']} >= flat_round_ms "
                f"{r['flat_round_ms']} at {r['cohort']} clients — the "
                "quorum cut saved nothing"
            )
        if (r["cohort"] >= SUBLINEAR_FROM
                and r["async_uplink_sketch_mb"] >= r["async_uplink_dense_mb"]):
            problems.append(
                f"async_uplink_sketch_mb {r['async_uplink_sketch_mb']} >= "
                f"async_uplink_dense_mb {r['async_uplink_dense_mb']} at "
                f"{r['cohort']} clients — the sketch uplink saved nothing"
            )
    return problems


_EXACT = (
    "max_latency_ms", "quorum_latency_ms", "gate_saved_ms",
    "async_uplink_dense_mb", "async_uplink_sketch_mb",
)
_TIMING = (
    "flat_reduce_ms", "hier_host_ms", "hier_tree_ms", "async_fold_ms",
)


def check(baseline: dict, rows: list[dict]) -> int:
    regressions = []
    base_by_c = {r["cohort"]: r for r in baseline["rows"]}
    for row in rows:
        base = base_by_c.get(row["cohort"])
        if base is None:
            regressions.append(
                f"cohort {row['cohort']} missing from the baseline — "
                "scenario drifted; re-bank deliberately (--bank)"
            )
            continue
        for lane in _EXACT:
            if base.get(lane) is None:
                regressions.append(
                    f"cohort {row['cohort']} {lane}: missing from the "
                    "baseline — scenario drifted; re-bank deliberately "
                    "(--bank)"
                )
                continue
            if abs(row[lane] - base[lane]) > 1e-6 * max(abs(base[lane]), 1.0):
                regressions.append(
                    f"cohort {row['cohort']} {lane}: {base[lane]} -> "
                    f"{row[lane]} — the seeded latency draw changed; "
                    "re-bank deliberately (--bank) if intended"
                )
        for lane in _TIMING:
            allowed = max(REL_FLOOR * base[lane], ABS_FLOOR_MS)
            if row[lane] - base[lane] > allowed:
                regressions.append(
                    f"cohort {row['cohort']} {lane}: {base[lane]:.3g} -> "
                    f"{row[lane]:.3g} ms (regressed > allowed {allowed:.3g})"
                )
    if regressions:
        print("AGG_SCALE=FAIL")
        for r in regressions:
            print(f"  REGRESSION {r}")
        return 1
    print(f"AGG_SCALE=PASS ({len(rows)} cohort row(s) within threshold)")
    return 0


def chip_leg(out_path: Path, repeats: int) -> None:
    """On-device flat mean over the largest cohort stack — the DCN-free
    upper bound a chip window can compare the host kernels against."""
    import jax
    import jax.numpy as jnp

    from fedrec_tpu.utils.provenance import provenance

    cohort = COHORTS[-1]
    stacks = [jnp.asarray(s) for s in _stacks(cohort)]
    w = jnp.ones(cohort)

    @jax.jit
    def device_mean(stacks, w):
        return [jnp.einsum("p,p...->...", w, s) / w.sum() for s in stacks]

    jax.block_until_ready(device_mean(stacks, w))  # compile
    best, spread = _timed(
        lambda: jax.block_until_ready(device_mean(stacks, w)), repeats
    )
    out_path.write_text(json.dumps({
        "kind": "agg_scale_chip",
        "cohort": cohort,
        "device_flat_mean_ms": round(best, 3),
        "spread_ms": round(spread, 3),
        "provenance": provenance(),
    }, indent=2))
    print(f"agg_scale: device flat mean over {cohort} x "
          f"{sum(int(np.prod(d)) for d in LEAF_DIMS)} params: {best:.3f} ms "
          f"-> {out_path}")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--bank", action="store_true")
    ap.add_argument("--check", action="store_true")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--chip", action="store_true",
                    help="also time the on-device flat mean "
                         "(writes agg_scale_tpu.json)")
    ap.add_argument("--out", default=str(HERE / "agg_scale.json"))
    args = ap.parse_args()

    # host-side measurement: never touch (or wedge on) a TPU tunnel —
    # except the explicit --chip leg, which exists to use the chip
    if not args.chip:
        from fedrec_tpu.hostenv import cpu_host_env

        if (os.environ.get("PALLAS_AXON_POOL_IPS")
                or os.environ.get("JAX_PLATFORMS") != "cpu"):
            return subprocess.run(
                [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
                env=cpu_host_env(),
            ).returncode

    out_path = Path(args.out)
    if not args.bank and not args.check:
        args.bank = not out_path.exists()
        args.check = not args.bank

    repeats = max(args.repeats, 1)
    rows = []
    for cohort in COHORTS:
        row = measure_cohort(cohort, repeats)
        rows.append(row)
        print(
            f"agg_scale: C={cohort:>6}  flat={row['flat_round_ms']:>9.1f} ms  "
            f"hier={row['hier_round_ms']:>9.1f} ms  "
            f"async={row['async_round_ms']:>9.1f} ms  "
            f"(gate saved {row['gate_saved_ms']:.0f} ms, "
            f"quorum {row['quorum']})"
        )

    problems = structural_check(rows)
    if problems:
        print("AGG_SCALE=FAIL (structural)")
        for p in problems:
            print(f"  FAILED {p}")
        return 1

    if args.chip:
        chip_leg(HERE / "agg_scale_tpu.json", repeats)

    if args.bank:
        from fedrec_tpu.utils.provenance import provenance

        out_path.write_text(json.dumps({
            "kind": "agg_scale",
            "scenario": {
                "cohorts": list(COHORTS),
                "host_group": HOST_GROUP,
                "fanout": FANOUT,
                "quorum_frac": QUORUM_FRAC,
                "leaf_dims": [list(d) for d in LEAF_DIMS],
                "straggle_ms": STRAGGLE_MS,
                "straggle_sigma": STRAGGLE_SIGMA,
                "sketch_width": SKETCH_WIDTH,
                "sketch_codec": SKETCH_CODEC,
                "method": "trimmed_mean (flat/hier), mean fold (async)",
                "repeats": repeats,
            },
            "threshold": {
                "rel_floor": REL_FLOOR, "abs_floor_ms": ABS_FLOOR_MS,
                "sublinear_from": SUBLINEAR_FROM,
            },
            "rows": rows,
            "provenance": provenance(),
        }, indent=2))
        print(f"AGG_SCALE=BANKED ({len(rows)} cohort rows -> {out_path})")
        return 0

    if not out_path.exists():
        print(
            f"agg_scale: no baseline at {out_path} — bank one first "
            "(python benchmarks/agg_scale.py --bank)", file=sys.stderr,
        )
        return 2
    return check(json.loads(out_path.read_text()), rows)


if __name__ == "__main__":
    raise SystemExit(main())
