"""Microbenchmark: attention implementations on the real TPU.

Three-way comparison at reference scale (H=50), long-context (H=1024), and
beyond-dense scales (H=2048 needs a ~21 GB dense score tensor, H=4096 ~85 GB
— on a 16 GB v5e those OOMs are recorded as the datapoint; pallas/chunked
run O(L) end to end, incl. the blocked flash backward):

  * XLA dense attention   (the ``attn_impl='dense'`` model path)
  * Pallas flash kernel   (``'pallas'``)
  * blockwise lax.scan    (``'chunked'``, the O(L)-memory long-context path)

plus ``additive_pool`` (Pallas vs XLA) at the two sizes that fit. Emits one
markdown table (stdout) and ``benchmarks/pallas_bench.json`` — the evidence
behind the ``model.attn_impl`` defaults: enable an implementation only where
it wins on real hardware (VERDICT round 1, item 5).

Off-TPU the kernels run in interpret mode, which measures nothing useful —
the script refuses to run unless a TPU backend is live (or --force).
"""

from __future__ import annotations

import argparse
import json
import time
from pathlib import Path

import sys

import numpy as np

_REPO = str(Path(__file__).resolve().parent.parent)
if _REPO not in sys.path:  # runnable as `python benchmarks/pallas_bench.py`
    sys.path.insert(0, _REPO)


def _time(fn, *args, iters: int = 30) -> float:
    """Honest per-call seconds on the axon-tunnel TPU.

    ``block_until_ready`` does not wait for remote execution there (verified
    against a known-FLOPs 8192^3 matmul: it reported 60 PFLOP/s on a
    197-TFLOP/s chip), and separate same-args dispatches overlap. So the op
    runs INSIDE one jitted ``lax.scan`` with a scalar data dependency
    between iterations, synchronization is a host readback, and the fixed
    tunnel round-trip cancels by differencing a 2x-length chain.

    NOTE: ``bench.py`` ``measure()`` implements the same protocol for
    whole-train-step chains. Any change to the differencing policy must be
    applied to BOTH (see the note there); merging is deferred until a live
    chip can re-validate a shared timer.
    """
    import jax
    import jax.numpy as jnp

    def looped(n):
        @jax.jit
        def run(*args):
            first, rest = args[0], args[1:]

            def body(carry, _):
                out = fn(first + carry, *rest)
                z = sum(jnp.sum(l) for l in jax.tree_util.tree_leaves(out))
                # NOT z*0: x*0 is statically zero, so XLA's algebraic
                # simplifier folds the carry, sees a loop-invariant body,
                # hoists it out of the scan, and the chain times as ~0 ms
                # (observed on CPU for grad components). A tiny non-zero
                # multiplier keeps the data dependency real while leaving
                # the op's inputs numerically unchanged.
                return (z.astype(jnp.float32) * 1e-30).astype(first.dtype), None

            carry, _ = jax.lax.scan(
                body, jnp.zeros((), first.dtype), None, length=n
            )
            return carry

        return run

    def timed(run, repeats=2):
        np.asarray(run(*args))  # compile + warm
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            np.asarray(run(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    # grow the chain until the DIFFERENCED signal (iters * t_op, which
    # excludes the fixed RTT) dwarfs the few-ms tunnel jitter — sub-ms ops
    # at short chains produced nonsense (fwd+bwd "faster" than fwd), and a
    # pilot based on the RTT-inclusive total undercounts for fast ops
    target = 0.3
    for _ in range(6):
        measured_iters = iters
        t1 = timed(looped(measured_iters))
        t2 = timed(looped(2 * measured_iters))
        delta = t2 - t1
        if delta >= target or measured_iters >= 2000:
            break
        if delta <= 0:
            # nonsense sign (jitter or warm-up residue in the 1x chain):
            # the old 1e-7 floor jumped straight to the 2000-iter cap —
            # hours at slow step times; double and re-measure instead.
            # Kept in lockstep with bench.py measure() (see NOTE above).
            iters = min(2000, 2 * measured_iters)
            continue
        per_op = delta / measured_iters
        iters = int(min(2000, max(2 * measured_iters, target / per_op)))
    if delta <= 0:
        raise RuntimeError(
            f"non-positive differenced time for chains of "
            f"{measured_iters}/{2*measured_iters}; tunnel too jittery — rerun"
        )
    return delta / measured_iters


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--force", action="store_true", help="run off-TPU anyway")
    parser.add_argument("--batch", type=int, default=64)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from fedrec_tpu.ops.attention_kernels import additive_pool, flash_attention
    from fedrec_tpu.ops.chunked_attention import chunked_attention

    platform = jax.devices()[0].platform
    if platform == "cpu" and not args.force:
        print("refusing to microbench Pallas kernels off-TPU (interpret mode); "
              "pass --force to override")
        return 1

    skips: dict[str, str] = {}

    def try_time(label, fn, *a):
        """None when the variant fails — dense at H=4096 needs an 85 GB score
        tensor, and that OOM IS the datapoint. The exception class+message is
        recorded per label so a jitter RuntimeError or a kernel bug is never
        mistaken for an OOM in the evidence JSON."""
        try:
            return _time(fn, *a)
        except Exception as e:  # noqa: BLE001
            reason = f"{type(e).__name__}: {str(e)[:160]}"
            skips[label] = reason
            print(f"    [skip] {label}: {reason[:140]}")
            return None

    B, heads, dk, D, hidden = args.batch, 20, 20, 400, 200
    rows = []

    from fedrec_tpu.utils.provenance import provenance, write_artifact

    def _stamp(partial: bool) -> None:
        # incremental banking: tunnel windows are ~20 min and wedge mid-run;
        # every measured row must survive a stall. The watcher re-runs the
        # queue item until a run completes (banking keys off the final
        # stdout table), but a partial artifact is still labeled evidence.
        write_artifact(Path(__file__).with_name("pallas_bench.json"), {
            "platform": platform, "batch": B,
            "rows": [
                {"op": name, "H": H,
                 "xla_ms": t_x and t_x * 1e3,
                 "pallas_ms": t_p and t_p * 1e3,
                 "chunked_ms": t_c and t_c * 1e3}
                for name, H, t_x, t_p, t_c in rows
            ],
            "skipped": skips, "provenance": provenance(),
        }, partial)

    for H in (50, 1024, 2048, 4096):
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((B, H, heads, dk)).astype(np.float32))
        k = jnp.asarray(rng.standard_normal((B, H, heads, dk)).astype(np.float32))
        v = jnp.asarray(rng.standard_normal((B, H, heads, dk)).astype(np.float32))
        mask = jnp.asarray((rng.random((B, H)) > 0.1).astype(np.float32))

        def dense_attn(q, k, v, mask):
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.float32(dk))
            s = jnp.where(mask[:, None, None, :] > 0, s, -1e9)
            p = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("bhqk,bkhd->bqhd", p, v)

        pallas_attn = jax.jit(lambda q, k, v, m: flash_attention(q, k, v, m))
        chunk_attn = jax.jit(lambda q, k, v, m: chunked_attention(q, k, v, m))
        xla_attn = jax.jit(dense_attn)

        def g_of(fn):
            return jax.jit(
                lambda q, k, v, m: jax.grad(lambda q: fn(q, k, v, m).sum())(q)
            )

        rows.append(("attention fwd", H,
                     try_time(f"xla/fwd/{H}", xla_attn, q, k, v, mask),
                     try_time(f"pallas/fwd/{H}", pallas_attn, q, k, v, mask),
                     try_time(f"chunked/fwd/{H}", chunk_attn, q, k, v, mask)))
        rows.append(("attention fwd+bwd", H,
                     try_time(f"xla/bwd/{H}", g_of(dense_attn), q, k, v, mask),
                     try_time(f"pallas/bwd/{H}", g_of(flash_attention), q, k, v, mask),
                     try_time(f"chunked/bwd/{H}", g_of(chunked_attention), q, k, v, mask)))
        _stamp(partial=True)

        if H >= 2048:
            continue  # pool is O(L)-memory everywhere; 2 sizes suffice
        x = jnp.asarray(rng.standard_normal((B, H, D)).astype(np.float32))
        w1 = jnp.asarray(rng.standard_normal((D, hidden)).astype(np.float32) * 0.05)
        b1 = jnp.zeros((hidden,), jnp.float32)
        w2 = jnp.asarray(rng.standard_normal((hidden,)).astype(np.float32) * 0.05)

        def dense_pool(x, w1, b1, w2, mask):
            e = jnp.tanh(jnp.einsum("nld,dh->nlh", x, w1) + b1)
            logits = jnp.einsum("nlh,h->nl", e, w2) + jnp.where(mask > 0, 0.0, -1e9)
            alpha = jax.nn.softmax(logits, axis=-1)
            return jnp.einsum("nl,nld->nd", alpha, x)

        pallas_pool = jax.jit(lambda x, m: additive_pool(x, w1, b1, w2, m))
        xla_pool = jax.jit(lambda x, m: dense_pool(x, w1, b1, w2, m))
        rows.append(("additive_pool fwd", H,
                     try_time(f"xla/pool_fwd/{H}", xla_pool, x, mask),
                     try_time(f"pallas/pool_fwd/{H}", pallas_pool, x, mask), None))
        rows.append((
            "additive_pool fwd+bwd", H,
            try_time(f"xla/pool_bwd/{H}", jax.jit(lambda x, m: jax.grad(
                lambda x: dense_pool(x, w1, b1, w2, m).sum())(x)), x, mask),
            try_time(f"pallas/pool_bwd/{H}", jax.jit(lambda x, m: jax.grad(
                lambda x: additive_pool(x, w1, b1, w2, m).sum())(x)), x, mask),
            None,
        ))
        _stamp(partial=True)

    def fmt(t):
        return f"{t*1e3:.3f}" if t is not None else "OOM/–"

    print(f"\n## attention impls on {platform} "
          f"({getattr(jax.devices()[0], 'device_kind', '?')}), B={B}\n")
    print("| op | H | xla dense ms | pallas ms | chunked ms |")
    print("|---|---|---|---|---|")
    for name, H, t_x, t_p, t_c in rows:
        print(f"| {name} | {H} | {fmt(t_x)} | {fmt(t_p)} | {fmt(t_c)} |")

    _stamp(partial=False)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
