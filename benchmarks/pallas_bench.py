"""Microbenchmark: attention implementations on the real TPU.

Three-way comparison at reference scale (H=50), long-context (H=1024), and
beyond-dense scales (H=2048 needs a ~21 GB dense score tensor, H=4096 ~85 GB
— on a 16 GB v5e those OOMs are recorded as the datapoint; pallas/chunked
run O(L) end to end, incl. the blocked flash backward):

  * XLA dense attention   (the ``attn_impl='dense'`` model path)
  * Pallas flash kernel   (``'pallas'``)
  * blockwise lax.scan    (``'chunked'``, the O(L)-memory long-context path)

plus ``additive_pool`` (Pallas vs XLA) at the two sizes that fit. Emits one
markdown table (stdout) and ``benchmarks/pallas_bench.json`` — the evidence
behind the ``model.attn_impl`` defaults: enable an implementation only where
it wins on real hardware (VERDICT round 1, item 5).

Off-TPU the kernels run in interpret mode, which measures nothing useful —
the script refuses to run unless a TPU backend is live (or --force).
"""

from __future__ import annotations

import argparse
import time
from pathlib import Path

import sys

import numpy as np

_REPO = str(Path(__file__).resolve().parent.parent)
if _REPO not in sys.path:  # runnable as `python benchmarks/pallas_bench.py`
    sys.path.insert(0, _REPO)


def _time(fn, *args, iters: int = 30) -> float:
    """Honest per-call seconds on the axon-tunnel TPU.

    The op runs INSIDE one jitted ``lax.scan`` with a scalar data
    dependency between iterations, synchronization is a host readback, and
    the fixed tunnel round-trip cancels by differencing a 2x-length chain.
    The differencing protocol (and its caveats) lives in ONE place —
    ``fedrec_tpu.utils.chain_timer`` — shared with ``bench.py measure()``;
    this call site keeps its historical policy bits: 6 attempts, and at
    the 2000-iter cap any positive delta is accepted (op chains hit the
    cap on fast ops where the capped delta is still meaningful).
    """
    import jax
    import jax.numpy as jnp

    from fedrec_tpu.utils.chain_timer import differenced_chain_seconds

    def looped(n):
        @jax.jit
        def run(*args):
            first, rest = args[0], args[1:]

            def body(carry, _):
                out = fn(first + carry, *rest)
                z = sum(jnp.sum(l) for l in jax.tree_util.tree_leaves(out))
                # NOT z*0: x*0 is statically zero, so XLA's algebraic
                # simplifier folds the carry, sees a loop-invariant body,
                # hoists it out of the scan, and the chain times as ~0 ms
                # (observed on CPU for grad components). A tiny non-zero
                # multiplier keeps the data dependency real while leaving
                # the op's inputs numerically unchanged.
                return (z.astype(jnp.float32) * 1e-30).astype(first.dtype), None

            carry, _ = jax.lax.scan(
                body, jnp.zeros((), first.dtype), None, length=n
            )
            return carry

        return run

    def chain(n: int) -> float:
        run = looped(n)
        np.asarray(run(*args))  # compile + warm
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            np.asarray(run(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    return differenced_chain_seconds(
        chain, iters, attempts=6, accept_positive_at_cap=True, label="op"
    )


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--force", action="store_true", help="run off-TPU anyway")
    parser.add_argument("--batch", type=int, default=64)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from fedrec_tpu.ops.attention_kernels import additive_pool, flash_attention
    from fedrec_tpu.ops.chunked_attention import chunked_attention

    platform = jax.devices()[0].platform
    if platform == "cpu" and not args.force:
        print("refusing to microbench Pallas kernels off-TPU (interpret mode); "
              "pass --force to override")
        return 1

    skips: dict[str, str] = {}

    def try_time(label, fn, *a):
        """None when the variant fails — dense at H=4096 needs an 85 GB score
        tensor, and that OOM IS the datapoint. The exception class+message is
        recorded per label so a jitter RuntimeError or a kernel bug is never
        mistaken for an OOM in the evidence JSON."""
        try:
            return _time(fn, *a)
        except Exception as e:  # noqa: BLE001
            reason = f"{type(e).__name__}: {str(e)[:160]}"
            skips[label] = reason
            print(f"    [skip] {label}: {reason[:140]}")
            return None

    B, heads, dk, D, hidden = args.batch, 20, 20, 400, 200
    rows = []

    from fedrec_tpu.utils.provenance import provenance, write_artifact

    def _stamp(partial: bool) -> None:
        # incremental banking: tunnel windows are ~20 min and wedge mid-run;
        # every measured row must survive a stall. The watcher re-runs the
        # queue item until a run completes (banking keys off the final
        # stdout table), but a partial artifact is still labeled evidence.
        write_artifact(Path(__file__).with_name("pallas_bench.json"), {
            "platform": platform, "batch": B,
            "rows": [
                {"op": name, "H": H,
                 "xla_ms": t_x and t_x * 1e3,
                 "pallas_ms": t_p and t_p * 1e3,
                 "chunked_ms": t_c and t_c * 1e3,
                 # dtype tags feed the evidence-driven attn_impl="auto"
                 # resolver (fedrec_tpu.ops.autotune) per (H, dtype) regime
                 "dtype": rest[0] if rest else "float32"}
                for name, H, t_x, t_p, t_c, *rest in rows
            ],
            "skipped": skips, "provenance": provenance(),
        }, partial)

    for H in (50, 1024, 2048, 4096):
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((B, H, heads, dk)).astype(np.float32))
        k = jnp.asarray(rng.standard_normal((B, H, heads, dk)).astype(np.float32))
        v = jnp.asarray(rng.standard_normal((B, H, heads, dk)).astype(np.float32))
        mask = jnp.asarray((rng.random((B, H)) > 0.1).astype(np.float32))

        def dense_attn(q, k, v, mask):
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.float32(dk))
            s = jnp.where(mask[:, None, None, :] > 0, s, -1e9)
            p = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("bhqk,bkhd->bqhd", p, v)

        pallas_attn = jax.jit(lambda q, k, v, m: flash_attention(q, k, v, m))
        chunk_attn = jax.jit(lambda q, k, v, m: chunked_attention(q, k, v, m))
        xla_attn = jax.jit(dense_attn)

        def g_of(fn):
            return jax.jit(
                lambda q, k, v, m: jax.grad(lambda q: fn(q, k, v, m).sum())(q)
            )

        rows.append(("attention fwd", H,
                     try_time(f"xla/fwd/{H}", xla_attn, q, k, v, mask),
                     try_time(f"pallas/fwd/{H}", pallas_attn, q, k, v, mask),
                     try_time(f"chunked/fwd/{H}", chunk_attn, q, k, v, mask)))
        rows.append(("attention fwd+bwd", H,
                     try_time(f"xla/bwd/{H}", g_of(dense_attn), q, k, v, mask),
                     try_time(f"pallas/bwd/{H}", g_of(flash_attention), q, k, v, mask),
                     try_time(f"chunked/bwd/{H}", g_of(chunked_attention), q, k, v, mask)))
        _stamp(partial=True)

        if H <= 1024:
            # bf16 rows at the training-relevant sizes: the production TPU
            # dtype (bench.py trains bf16), without which the
            # evidence-driven attn_impl="auto" resolver (ops/autotune.py,
            # exact (H, dtype) match) could never fire for bf16 models
            qb, kb, vb = (a.astype(jnp.bfloat16) for a in (q, k, v))
            rows.append((
                "attention fwd", H,
                try_time(f"xla/fwd16/{H}", xla_attn, qb, kb, vb, mask),
                try_time(f"pallas/fwd16/{H}", pallas_attn, qb, kb, vb, mask),
                try_time(f"chunked/fwd16/{H}", chunk_attn, qb, kb, vb, mask),
                "bfloat16",
            ))

            def g16_of(fn):
                return jax.jit(lambda q, k, v, m: jax.grad(
                    lambda q: fn(q, k, v, m).astype(jnp.float32).sum()
                )(q))

            rows.append((
                "attention fwd+bwd", H,
                try_time(f"xla/bwd16/{H}", g16_of(dense_attn), qb, kb, vb, mask),
                try_time(f"pallas/bwd16/{H}", g16_of(flash_attention), qb, kb, vb, mask),
                try_time(f"chunked/bwd16/{H}", g16_of(chunked_attention), qb, kb, vb, mask),
                "bfloat16",
            ))
            _stamp(partial=True)

        if H >= 2048:
            continue  # pool is O(L)-memory everywhere; 2 sizes suffice
        x = jnp.asarray(rng.standard_normal((B, H, D)).astype(np.float32))
        w1 = jnp.asarray(rng.standard_normal((D, hidden)).astype(np.float32) * 0.05)
        b1 = jnp.zeros((hidden,), jnp.float32)
        w2 = jnp.asarray(rng.standard_normal((hidden,)).astype(np.float32) * 0.05)

        def dense_pool(x, w1, b1, w2, mask):
            e = jnp.tanh(jnp.einsum("nld,dh->nlh", x, w1) + b1)
            logits = jnp.einsum("nlh,h->nl", e, w2) + jnp.where(mask > 0, 0.0, -1e9)
            alpha = jax.nn.softmax(logits, axis=-1)
            return jnp.einsum("nl,nld->nd", alpha, x)

        pallas_pool = jax.jit(lambda x, m: additive_pool(x, w1, b1, w2, m))
        xla_pool = jax.jit(lambda x, m: dense_pool(x, w1, b1, w2, m))
        rows.append(("additive_pool fwd", H,
                     try_time(f"xla/pool_fwd/{H}", xla_pool, x, mask),
                     try_time(f"pallas/pool_fwd/{H}", pallas_pool, x, mask), None))
        rows.append((
            "additive_pool fwd+bwd", H,
            try_time(f"xla/pool_bwd/{H}", jax.jit(lambda x, m: jax.grad(
                lambda x: dense_pool(x, w1, b1, w2, m).sum())(x)), x, mask),
            try_time(f"pallas/pool_bwd/{H}", jax.jit(lambda x, m: jax.grad(
                lambda x: additive_pool(x, w1, b1, w2, m).sum())(x)), x, mask),
            None,
        ))
        _stamp(partial=True)

    # ---- fused hot-path kernels (ISSUE 8): the WHOLE chain at training
    # scale, where isolated kernels provably lose to launch overhead (the
    # H=50 rows above are the evidence). xla_ms = the dense module chain,
    # pallas_ms = the fused kernel — one launch amortized across
    # gather+encode / qkv+attention+pool+score. bf16: the production chip
    # dtype (bf16 operands, f32 accumulation in the kernels).
    from fedrec_tpu.ops.fused_hot_path import (
        fused_gather_encode, fused_history_score,
    )

    H50, C, T, Dh, Ah = 50, 5, 50, 768, 384
    for Bf in (256, 1024):
        rng = np.random.default_rng(1)
        dt = jnp.bfloat16
        x = jnp.asarray(rng.standard_normal((Bf, H50, D)), dt)
        cand = jnp.asarray(rng.standard_normal((Bf, C, D)), dt)
        ap = {
            k: {"kernel": jnp.asarray(
                    rng.standard_normal((D, D)) * 0.05, jnp.float32),
                "bias": jnp.zeros((D,), jnp.float32)}
            for k in ("w_q", "w_k", "w_v")
        }
        pp = {
            "att_fc1": {"kernel": jnp.asarray(
                            rng.standard_normal((D, hidden)) * 0.05,
                            jnp.float32),
                        "bias": jnp.zeros((hidden,), jnp.float32)},
            "att_fc2": {"kernel": jnp.asarray(
                            rng.standard_normal((hidden, 1)) * 0.05,
                            jnp.float32),
                        "bias": jnp.zeros((1,), jnp.float32)},
        }

        def dense_chain(x, cand):
            q, k, v = (
                (x @ ap[n]["kernel"].astype(dt) + ap[n]["bias"].astype(dt))
                .reshape(Bf, H50, heads, dk)
                for n in ("w_q", "w_k", "w_v")
            )
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
                jnp.asarray(dk, dt)
            )
            s = s - jnp.max(s, axis=-1, keepdims=True)
            a = jnp.exp(s)
            a = a / (jnp.sum(a, axis=-1, keepdims=True) + 1e-8)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", a, v).reshape(Bf, H50, D)
            e = jnp.tanh(
                ctx @ pp["att_fc1"]["kernel"].astype(dt)
                + pp["att_fc1"]["bias"].astype(dt)
            )
            lg = (e @ pp["att_fc2"]["kernel"].astype(dt))[..., 0]
            lg = lg - jnp.max(lg, axis=-1, keepdims=True)
            al = jnp.exp(lg)
            al = al / (jnp.sum(al, axis=-1, keepdims=True) + 1e-8)
            user = jnp.einsum("bh,bhd->bd", al, ctx)
            return jnp.einsum("bcd,bd->bc", cand, user)

        fused_chain = lambda x, cand: fused_history_score(  # noqa: E731
            x, cand, None, ap, pp, heads
        )[0]
        rows.append((
            f"hist attn+pool+score fwd (B={Bf})", H50,
            try_time(f"xla/fused_fwd/{Bf}", jax.jit(dense_chain), x, cand),
            try_time(f"pallas/fused_fwd/{Bf}", jax.jit(fused_chain), x, cand),
            None, "bfloat16",
        ))

        def g_of_chain(fn):
            return jax.jit(lambda x, c: jax.grad(
                lambda x: fn(x, c).astype(jnp.float32).sum()
            )(x))

        rows.append((
            f"hist attn+pool+score fwd+bwd (B={Bf})", H50,
            try_time(f"xla/fused_bwd/{Bf}", g_of_chain(dense_chain), x, cand),
            try_time(f"pallas/fused_bwd/{Bf}", g_of_chain(fused_chain), x, cand),
            None, "bfloat16",
        ))
        _stamp(partial=True)

    # gather+encode at the flagship unique-cap scale (one leg: U is the
    # lever, not B)
    rngU = np.random.default_rng(2)
    U = 2560
    dtg = jnp.bfloat16
    table = jnp.asarray(rngU.standard_normal((4096, T, Dh)), dtg)
    uniq = jnp.asarray(rngU.permutation(4096)[:U].astype(np.int32))
    np_ = {
        "pool": {
            "att_fc1": {"kernel": jnp.asarray(
                            rngU.standard_normal((Dh, Ah)) * 0.05,
                            jnp.float32),
                        "bias": jnp.zeros((Ah,), jnp.float32)},
            "att_fc2": {"kernel": jnp.asarray(
                            rngU.standard_normal((Ah, 1)) * 0.05,
                            jnp.float32),
                        "bias": jnp.zeros((1,), jnp.float32)},
        },
        "fc": {"kernel": jnp.asarray(
                   rngU.standard_normal((Dh, D)) * 0.05, jnp.float32),
               "bias": jnp.zeros((D,), jnp.float32)},
    }

    def dense_gather_encode(uniq_ids, tbl):
        states = tbl[uniq_ids]
        p1 = np_["pool"]["att_fc1"]
        e = jnp.tanh(
            jnp.einsum("utd,dh->uth", states, p1["kernel"].astype(dtg))
            + p1["bias"].astype(dtg)
        )
        lg = jnp.einsum(
            "uth,h->ut", e, np_["pool"]["att_fc2"]["kernel"][:, 0].astype(dtg)
        )
        lg = lg - jnp.max(lg, axis=-1, keepdims=True)
        a = jnp.exp(lg)
        a = a / (jnp.sum(a, axis=-1, keepdims=True) + 1e-8)
        pooled = jnp.einsum("ut,utd->ud", a, states)
        return pooled @ np_["fc"]["kernel"].astype(dtg) + np_["fc"][
            "bias"].astype(dtg)

    rows.append((
        f"gather+encode fwd (U={U})", T,
        try_time(
            "xla/gather_fwd",
            jax.jit(lambda u: dense_gather_encode(u, table)), uniq,
        ),
        try_time(
            "pallas/gather_fwd",
            jax.jit(lambda u: fused_gather_encode(table, u, np_)), uniq,
        ),
        None, "bfloat16",
    ))
    _stamp(partial=True)

    def fmt(t):
        return f"{t*1e3:.3f}" if t is not None else "OOM/–"

    print(f"\n## attention impls on {platform} "
          f"({getattr(jax.devices()[0], 'device_kind', '?')}), B={B}\n")
    print("| op | H | xla dense ms | pallas ms | chunked ms |")
    print("|---|---|---|---|---|")
    for name, H, t_x, t_p, t_c, *_rest in rows:
        print(f"| {name} | {H} | {fmt(t_x)} | {fmt(t_p)} | {fmt(t_c)} |")

    _stamp(partial=False)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
