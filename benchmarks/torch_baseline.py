"""Reference-equivalent torch-CPU baseline for bench.py's ``vs_baseline``.

The reference's federated deployment runs torch on CPU EC2 t2.medium nodes
with the gloo backend (reference ``README.md:13,86``; gloo selected in every
driver, e.g. ``client.py:227``). This script measures the *most favorable
reasonable* torch implementation of the same per-batch training math our
flagship step performs:

  * news vectors from the trainable text head over precomputed frozen-trunk
    token states (768 -> additive attention -> 400), B*(C+H) titles per batch
    (the reference re-encodes per sample with no dedup, ``model.py:41-61``;
    we grant the baseline batched encoding, but full-batch no-dedup like the
    reference)
  * user encoder: 20-head self-attention + additive attention (400-d)
  * dot-product scores, sigmoid, CE, backward, Adam step on both towers

This is an independent torch implementation of the documented math — not a
copy of the reference code. Results land in ``benchmarks/baseline_host.json``
and are read by ``bench.py``.
"""

from __future__ import annotations

import json
import math
import platform
import time
from pathlib import Path

import numpy as np
import torch
from torch import nn


class AdditivePool(nn.Module):
    def __init__(self, dim: int, hidden: int):
        super().__init__()
        self.fc1 = nn.Linear(dim, hidden)
        self.fc2 = nn.Linear(hidden, 1)

    def forward(self, x):  # (B, L, D) -> (B, D)
        logits = self.fc2(torch.tanh(self.fc1(x))).squeeze(-1)
        alpha = torch.softmax(logits, dim=-1)
        return torch.einsum("bl,bld->bd", alpha, x)


class TextHeadT(nn.Module):
    def __init__(self, bert_hidden=768, news_dim=400):
        super().__init__()
        self.pool = AdditivePool(bert_hidden, bert_hidden // 2)
        self.fc = nn.Linear(bert_hidden, news_dim)

    def forward(self, states):
        return self.fc(self.pool(states))


class UserEncoderT(nn.Module):
    def __init__(self, news_dim=400, heads=20, head_dim=20, query_dim=200):
        super().__init__()
        d = heads * head_dim
        self.heads, self.head_dim = heads, head_dim
        self.wq = nn.Linear(news_dim, d)
        self.wk = nn.Linear(news_dim, d)
        self.wv = nn.Linear(news_dim, d)
        self.pool = AdditivePool(d, query_dim)

    def forward(self, his):  # (B, H, D)
        B, H, _ = his.shape
        q = self.wq(his).view(B, H, self.heads, self.head_dim).transpose(1, 2)
        k = self.wk(his).view(B, H, self.heads, self.head_dim).transpose(1, 2)
        v = self.wv(his).view(B, H, self.heads, self.head_dim).transpose(1, 2)
        attn = torch.softmax(q @ k.transpose(-1, -2) / math.sqrt(self.head_dim), dim=-1)
        ctx = (attn @ v).transpose(1, 2).reshape(B, H, -1)
        return self.pool(ctx)


def run(batch_size=64, cand=5, his_len=50, title_len=50, num_news=4096,
        warmup=1, iters=3, seed=0, dedup=False):
    torch.manual_seed(seed)
    rng = np.random.default_rng(seed)
    states_table = torch.randn(num_news, title_len, 768)
    head = TextHeadT()
    user = UserEncoderT()
    opt = torch.optim.Adam(list(head.parameters()) + list(user.parameters()), lr=5e-5)
    ce = nn.CrossEntropyLoss()

    def step():
        cand_ids = torch.from_numpy(rng.integers(0, num_news, (batch_size, cand)))
        his_ids = torch.from_numpy(rng.integers(0, num_news, (batch_size, his_len)))
        ids = torch.cat([cand_ids.reshape(-1), his_ids.reshape(-1)])
        if dedup:
            # the best-reasonable-torch variant at large B: encode each
            # distinct news once and index back (at B=1024 the no-dedup
            # gather is 56k slots over a 4k-news table — 13.7x redundant
            # text-tower work no competent implementation would do). Our
            # TPU step dedups in-program, so the sweep measures both.
            uniq, inv = torch.unique(ids, return_inverse=True)
            vecs = head(states_table[uniq])[inv]
        else:
            vecs = head(states_table[ids])  # no dedup, like the reference
        cand_vecs = vecs[: batch_size * cand].view(batch_size, cand, -1)
        his_vecs = vecs[batch_size * cand:].view(batch_size, his_len, -1)
        user_vec = user(his_vecs)
        scores = torch.einsum("bcd,bd->bc", cand_vecs, user_vec)
        loss = ce(torch.sigmoid(scores), torch.zeros(batch_size, dtype=torch.long))
        opt.zero_grad()
        loss.backward()
        opt.step()
        return float(loss)

    for _ in range(warmup):
        step()
    t0 = time.perf_counter()
    for _ in range(iters):
        step()
    dt = (time.perf_counter() - t0) / iters
    return {
        "impl": "torch-cpu reference-equivalent (text head over cached trunk states + user encoder)",
        "batch_size": batch_size,
        "candidates": cand,
        "his_len": his_len,
        "title_len": title_len,
        "sec_per_step": dt,
        "samples_per_sec": batch_size / dt,
        "torch_version": torch.__version__,
        "cpu": platform.processor() or platform.machine(),
        "num_threads": torch.get_num_threads(),
    }


def extend(out_path: Path) -> dict:
    """Fill the existing artifact's sweep up to bench.py's max B (2048/4096)
    without re-measuring rows that already exist (ADVICE r3: bench.py's
    sweep reached B=4096 while this one stopped at 1024, so the headline
    ratio leaned on an unmeasured torch-stops-scaling assumption; until the
    rows exist bench.py clamps the ratio to the baseline's measured range).
    No-dedup rows at large B are minutes-per-step on this 1-core host, so
    they run iters=1 — fine: at >100 s/step, timer noise is negligible.
    """
    from fedrec_tpu.utils.provenance import provenance

    result = json.loads(out_path.read_text())
    sweep = result.get("b_sweep_samples_per_sec") or {}
    result["b_sweep_samples_per_sec"] = sweep  # attach BEFORE the loop so
    # the per-row incremental write_text calls actually persist each row
    # (a detached dict would make a mid-run kill lose every measured row)
    for bsz in (2048, 4096):
        if f"{bsz}_dedup" not in sweep:
            r = run(batch_size=bsz, iters=2, dedup=True)
            sweep[f"{bsz}_dedup"] = round(r["samples_per_sec"], 2)
            out_path.write_text(json.dumps(result, indent=2))
        if str(bsz) not in sweep:
            r = run(batch_size=bsz, iters=1)
            sweep[str(bsz)] = round(r["samples_per_sec"], 2)
            out_path.write_text(json.dumps(result, indent=2))
    result["b_sweep_samples_per_sec"] = sweep
    result["extended_provenance"] = provenance()
    out_path.write_text(json.dumps(result, indent=2))
    return result


if __name__ == "__main__":
    import sys

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from fedrec_tpu.utils.provenance import provenance

    if "--extend" in sys.argv:
        out = Path(__file__).parent / "baseline_host.json"
        print(json.dumps(extend(out), indent=2))
        sys.exit(0)

    result = run()
    # per-B sweep: bench.py's promoted headline divides by the baseline's
    # BEST measured rate over this sweep (not the B=64 row), so the
    # cross-platform ratio never leans on an unmeasured "torch is
    # batch-size-invariant" assumption
    sweep = {"64": result["samples_per_sec"]}
    for bsz in (256, 1024):
        r = run(batch_size=bsz, iters=2)
        sweep[str(bsz)] = r["samples_per_sec"]
    # dedup'd rows: the best-reasonable-torch variant (see run(dedup=True));
    # bench.py divides by the max over ALL rows, so granting the baseline
    # this optimization can only shrink our advertised ratio
    for bsz in (64, 256, 1024):
        r = run(batch_size=bsz, iters=2, dedup=True)
        sweep[f"{bsz}_dedup"] = r["samples_per_sec"]
    result["b_sweep_samples_per_sec"] = {
        k: round(v, 2) for k, v in sweep.items()
    }
    result["provenance"] = provenance()
    out = Path(__file__).parent / "baseline_host.json"
    out.write_text(json.dumps(result, indent=2))
    print(json.dumps(result, indent=2))
