"""Serving-subsystem load generator: closed- and open-loop latency/throughput.

Where ``serve_bench.py`` measures the raw jitted scorer, this measures the
SERVICE — micro-batcher coalescing, fixed-shape padding, store reads, and
(optionally) two-stage retrieval — under the two canonical load models:

* **closed loop**: ``--clients K`` concurrent users, each submitting its
  next request the moment the previous response lands.  Measures the
  system's sustainable throughput and the latency it costs.
* **open loop**: requests arrive on a Poisson process at ``--rate`` req/s
  regardless of completions (the honest tail-latency model: a slow system
  cannot slow its own arrivals down).  Measures p50/p99/p99.9 under a
  fixed offered load, plus how many responses missed their deadline and
  how many were shed at admission (backpressure).

Runs fully in-process (service + load in one event loop) so the numbers
isolate the serving stack from kernel TCP behavior; the artifact is
provenance-stamped like every other ``benchmarks/*.json``.

Usage:
  python benchmarks/serve_load.py [--num-news 65000] [--clusters 0]
      [--clients 32] [--rate 200] [--duration 10] [--out serve_load.json]
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import time
from pathlib import Path

import numpy as np

_REPO = str(Path(__file__).resolve().parent.parent)
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _percentiles(lat_ms: list[float]) -> dict:
    if not lat_ms:
        return {"count": 0}
    a = np.asarray(lat_ms)
    return {
        "count": int(a.size),
        "p50_ms": round(float(np.percentile(a, 50)), 3),
        "p99_ms": round(float(np.percentile(a, 99)), 3),
        "p999_ms": round(float(np.percentile(a, 99.9)), 3),
        "mean_ms": round(float(a.mean()), 3),
    }


async def closed_loop(service, histories, clients: int, duration_s: float) -> dict:
    # requests go through ServingService.handle (not the raw batcher): the
    # measured path is the service path, and the service's OWN latency
    # metrics populate so the artifact's service_metrics section is real
    lat: list[float] = []
    done = errors = 0
    t_end = time.perf_counter() + duration_s

    async def worker(i: int) -> None:
        nonlocal done, errors
        rng = np.random.default_rng(i)
        while time.perf_counter() < t_end:
            h = histories[rng.integers(len(histories))]
            resp = await service.handle({"id": i, "history": h})
            if "error" in resp:
                errors += 1
                continue
            lat.append(resp["latency_ms"])
            done += 1

    t0 = time.perf_counter()
    await asyncio.gather(*(worker(i) for i in range(clients)))
    wall = time.perf_counter() - t0
    return {
        "mode": "closed",
        "clients": clients,
        "throughput_rps": round(done / wall, 2),
        "errors": errors,
        "latency": _percentiles(lat),
    }


async def open_loop(
    service, histories, rate: float, duration_s: float, deadline_ms: float
) -> dict:
    lat: list[float] = []
    shed = missed = errors = 0
    tasks: set[asyncio.Task] = set()
    rng = np.random.default_rng(0)

    async def fire(h) -> None:
        # through service.handle, like closed_loop — handle() converts
        # backpressure and scorer failures into error responses, so one bad
        # request can never lose the whole run's artifact
        nonlocal shed, missed, errors
        resp = await service.handle({"history": h, "deadline_ms": deadline_ms})
        if resp.get("error") == "backpressure":
            shed += 1
            return
        if "error" in resp:
            errors += 1
            return
        lat.append(resp["latency_ms"])
        if not resp["deadline_met"]:
            missed += 1

    t0 = time.perf_counter()
    next_at = t0
    while (now := time.perf_counter()) < t0 + duration_s:
        if now < next_at:
            await asyncio.sleep(next_at - now)
        next_at += rng.exponential(1.0 / rate)  # Poisson arrivals
        t = asyncio.ensure_future(fire(histories[rng.integers(len(histories))]))
        tasks.add(t)
        t.add_done_callback(tasks.discard)
    if tasks:
        await asyncio.gather(*tasks, return_exceptions=True)
    wall = time.perf_counter() - t0
    return {
        "mode": "open",
        "offered_rps": rate,
        "deadline_ms": deadline_ms,
        "completed_rps": round(len(lat) / wall, 2),
        "shed_backpressure": shed,
        "deadline_missed": missed,
        "errors": errors,
        "latency": _percentiles(lat),
    }


def build_service(args):
    import jax
    import jax.numpy as jnp

    from fedrec_tpu.config import ExperimentConfig
    from fedrec_tpu.models import NewsRecommender
    from fedrec_tpu.serving import EmbeddingStore, ServingService

    cfg = ExperimentConfig()
    cfg.model.dtype = "float32"
    model = NewsRecommender(cfg.model)
    rng = np.random.default_rng(0)
    table = jnp.asarray(
        rng.standard_normal((args.num_news, cfg.model.news_dim)), jnp.float32
    )
    h = args.his_len
    dummy = jnp.zeros((1, h, cfg.model.news_dim), jnp.float32)
    user_params = model.init(
        jax.random.PRNGKey(0), dummy, method=NewsRecommender.encode_user
    )["params"]["user_encoder"]
    store = EmbeddingStore()
    store.publish(table, user_params, source="synthetic")
    service = ServingService(
        model, store,
        history_len=h,
        top_k=args.top_k,
        batch_sizes=tuple(int(b) for b in args.batch_sizes.split(",")),
        flush_ms=args.flush_ms,
        max_queue=args.max_queue,
        num_clusters=args.clusters,
        n_probe=args.n_probe,
        exact_threshold=args.exact_threshold,
    )
    histories = [
        rng.integers(1, args.num_news, (rng.integers(3, h),)).tolist()
        for _ in range(256)
    ]
    return service, histories


def make_histories(num_news: int, his_len: int, count: int = 256) -> list:
    rng = np.random.default_rng(0)
    return [
        rng.integers(1, num_news, (rng.integers(3, his_len),)).tolist()
        for _ in range(count)
    ]


async def run(args) -> dict:
    service, histories = build_service(args)
    service.warmup()
    await service.start()
    rows = {}
    rows["closed"] = await closed_loop(
        service, histories, args.clients, args.duration
    )
    rows["open"] = await open_loop(
        service, histories, args.rate, args.duration, args.deadline_ms
    )
    rows["service_metrics"] = service.metrics()
    await service.stop()
    return rows


async def run_remote(args) -> dict:
    """Drive a LIVE ``fedrec-serve`` over TCP (``--connect host:port``)
    through the resilient client pool: reconnect with exponential backoff
    + jitter and per-request deadlines, so a server restart mid-load-run
    degrades to elevated latency (and some error-counted requests) instead
    of a crashed run and a lost artifact. Same closed/open loops as the
    in-process mode — the pool presents the service's ``handle`` surface;
    latency is the CLIENT-observed round trip."""
    from fedrec_tpu.serving.client import ServingClientPool

    host, port_s = args.connect.rsplit(":", 1)
    pool = ServingClientPool(
        host, int(port_s), size=max(args.clients, 4),
        request_timeout_ms=args.request_timeout_ms,
    )
    histories = make_histories(args.num_news, args.his_len)
    rows = {}
    rows["closed"] = await closed_loop(pool, histories, args.clients, args.duration)
    rows["open"] = await open_loop(
        pool, histories, args.rate, args.duration, args.deadline_ms
    )
    metrics = await pool.admin("metrics", deadline_ms=5000.0)
    rows["service_metrics"] = metrics.get("metrics", {"error": metrics.get("error")})
    rows["client_retry"] = pool.retry_metrics()
    await pool.close()
    return rows


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-news", type=int, default=65_000)
    p.add_argument("--his-len", type=int, default=50)
    p.add_argument("--top-k", type=int, default=10)
    p.add_argument("--batch-sizes", default="1,8,32,128")
    p.add_argument("--flush-ms", type=float, default=2.0)
    p.add_argument("--max-queue", type=int, default=1024)
    p.add_argument("--clusters", type=int, default=0)
    p.add_argument("--n-probe", type=int, default=8)
    p.add_argument("--exact-threshold", type=int, default=4096)
    p.add_argument("--clients", type=int, default=32)
    p.add_argument("--rate", type=float, default=200.0, help="open-loop req/s")
    p.add_argument("--deadline-ms", type=float, default=100.0)
    p.add_argument("--connect", default=None, metavar="HOST:PORT",
                   help="drive a live fedrec-serve over TCP (resilient "
                        "client: reconnect with backoff+jitter, per-request "
                        "deadlines) instead of the in-process service")
    p.add_argument("--request-timeout-ms", type=float, default=1000.0,
                   help="closed-loop per-request deadline in --connect mode")
    p.add_argument("--duration", type=float, default=10.0, help="per-mode seconds")
    p.add_argument("--out", default="serve_load.json")
    p.add_argument("--obs-dir", default=None,
                   help="also write the obs artifact trio (metrics.jsonl, "
                        "trace.json, prometheus.txt) for fedrec-obs report")
    args = p.parse_args()

    import jax

    from fedrec_tpu.obs import get_tracer
    from fedrec_tpu.utils.provenance import provenance, write_artifact

    # span recording only pays off when --obs-dir will save the trace
    get_tracer().enabled = bool(args.obs_dir)
    rows = asyncio.run(run_remote(args) if args.connect else run(args))
    out = {
        "metric": "serving_load",
        "transport": f"tcp:{args.connect}" if args.connect else "inproc",
        "num_news": args.num_news,
        "his_len": args.his_len,
        "top_k": args.top_k,
        "batch_sizes": args.batch_sizes,
        "flush_ms": args.flush_ms,
        "clusters": args.clusters,
        "n_probe": args.n_probe,
        "backend": jax.default_backend(),
        **rows,
        "provenance": provenance(),
    }
    # bare filenames land next to this script (the banked-artifact home);
    # an explicit path (absolute or with directories) is honored as given
    out_path = (
        Path(args.out) if Path(args.out).parent != Path(".")
        else Path(__file__).with_name(args.out)
    )
    write_artifact(out_path, out, partial=False)
    if args.obs_dir:
        from fedrec_tpu.obs import dump_artifacts

        paths = dump_artifacts(args.obs_dir)
        print(f"obs artifacts: {paths['metrics']} {paths['trace']} "
              f"{paths['prometheus']}")
    print(f"closed: {rows['closed']['throughput_rps']} rps "
          f"p99={rows['closed']['latency'].get('p99_ms')}ms | "
          f"open@{args.rate}rps: p99={rows['open']['latency'].get('p99_ms')}ms "
          f"shed={rows['open']['shed_backpressure']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
