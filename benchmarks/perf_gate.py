"""Perf-regression gate: banked seeded-CPU perf baseline + noise-aware check.

The chip benches (``bench.py``, ``benchmarks/step_profile.py``) certify
absolute speed but need a TPU window; the systems smokes never look at
performance at all — so a CPU-visible perf regression (a slow import in
the hot loop, a batcher slowdown, an accidental per-step host sync, a
FLOPs-model drift) lands silently and waits for the next chip window to
be noticed.  This gate banks a provenance-stamped perf artifact from a
small fully seeded CPU scenario and fails — NAMING THE LANE — when any
lane regresses beyond a noise-aware threshold against the banked
baseline.  It is the perf analog of ``benchmarks/quality_gate.py``.

Lanes (the flagship joint step at toy scale, everything seeded):

* ``steps_per_sec``        — compiled per-batch train-step throughput
                             (best of ``--repeats`` timed chains)
* ``batch_build_ms``       — host batch assembly (TrainBatcher epoch)
* ``h2d_ms``               — host->device transfer of one built batch
* ``dispatch_gap_sync_ms`` — host gap between dispatches of a
                             build->transfer->dispatch loop against a
                             sleep-simulated off-host device (the
                             interval the device queue would sit empty)
* ``dispatch_gap_prefetch_ms`` — the same loop behind the bounded
                             prefetcher (``data.prefetch_batches``);
                             its regression means the overlap machinery
                             stopped hiding the build
* ``flops_per_step``       — the ANALYTIC step-FLOPs model
                             (``fedrec_tpu.obs.perf``), exact: any
                             change fails until deliberately re-banked
                             (an un-noticed model drift would silently
                             re-price every banked MFU claim)

Noise policy: timing lanes are measured ``--repeats`` times; the banked
artifact records each lane's best value AND its absolute spread
(max-min).  A check fails a timing lane only when it regresses by more
than ``max(REL_FLOOR x baseline, min(NOISE_K x max(spread_bank,
spread_now), NOISE_CAP x baseline), ABS_FLOOR)`` — generous on a
time-sliced CI host, still tight enough to catch a 2x host-pipeline
regression, and the noise term is CAPPED so a pathologically jittery
window can never excuse an arbitrary regression.  The exact lane
allows zero drift.

Usage:
    python benchmarks/perf_gate.py            # bank if absent, else check
    python benchmarks/perf_gate.py --bank     # (re)bank the baseline
    python benchmarks/perf_gate.py --check    # check only (exit 2 if no baseline)
    python benchmarks/perf_gate.py --check --demo-regression steps_per_sec
        # forced-failure demonstration: the named lane's measurement is
        # adversely corrupted 10x (marked "simulated") -> the gate must
        # exit 1 naming it (the obs-smoke's forced-failure leg)

Writes ``benchmarks/perf_gate.json`` (provenance-stamped); exit 0 =
pass/banked, 1 = regression, 2 = usage/missing-baseline.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np

HERE = Path(__file__).resolve().parent
REPO = HERE.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

REL_FLOOR = 0.5       # a timing lane may regress 50% before failing...
NOISE_K = 4.0         # ...or 4x its measured spread, whichever is larger...
NOISE_CAP = 0.8       # ...but the noise term never exceeds 80% of the
                      # baseline: a day so noisy that 4x spread would
                      # excuse ANY regression must not neuter the gate
                      # (and the 10x --demo-regression stays deterministic)
ABS_FLOOR_MS = 0.5    # near-zero ms lanes get an absolute grace floor
DEMO_FACTOR = 10.0    # --demo-regression corruption (90% regression)
SIM_TAU_S = 0.002     # the sleep-simulated off-host device interval


def _gate_cfg():
    from fedrec_tpu.config import ExperimentConfig

    cfg = ExperimentConfig()
    cfg.model.news_dim = 32
    cfg.model.num_heads = 4
    cfg.model.head_dim = 8
    cfg.model.query_dim = 16
    cfg.model.bert_hidden = 48
    cfg.data.max_his_len = 10
    cfg.data.max_title_len = 12
    cfg.data.batch_size = 16
    cfg.fed.num_clients = 1
    return cfg


def measure_lanes(repeats: int = 3) -> dict:
    """The one seeded scenario both bank and check execute.  Returns
    ``{lane: {"value", "unit", "direction", "spread", "kind"}}`` —
    ``direction`` says which way is worse, ``spread`` is the absolute
    max-min over repeats (the noise the threshold adapts to)."""
    import jax
    import jax.numpy as jnp

    from fedrec_tpu.data.batcher import IndexedSamples, TrainBatcher
    from fedrec_tpu.data.prefetch import Prefetcher
    from fedrec_tpu.fed import get_strategy
    from fedrec_tpu.models import NewsRecommender
    from fedrec_tpu.obs.perf import flops_per_train_step
    from fedrec_tpu.parallel import client_mesh, shard_batch
    from fedrec_tpu.train import build_fed_train_step
    from fedrec_tpu.train.state import init_client_state, replicate_state

    cfg = _gate_cfg()
    num_news, L = 128, cfg.data.max_title_len
    B, C, H = cfg.data.batch_size, 1 + cfg.data.npratio, cfg.data.max_his_len
    rng = np.random.default_rng(0)
    token_states = jnp.asarray(
        rng.standard_normal((num_news, L, cfg.model.bert_hidden)),
        jnp.float32,
    )
    model = NewsRecommender(cfg.model)
    mesh = client_mesh(1)
    step = build_fed_train_step(
        model, cfg, get_strategy("grad_avg"), mesh, mode="joint"
    )
    state = replicate_state(
        init_client_state(model, cfg, jax.random.PRNGKey(0), num_news, L),
        1, jax.random.PRNGKey(1),
    )

    def make_batch(seed: int):
        r = np.random.default_rng(seed)
        return shard_batch(mesh, {
            "candidates": r.integers(0, num_news, (1, B, C)).astype(np.int32),
            "history": r.integers(0, num_news, (1, B, H)).astype(np.int32),
            "labels": np.zeros((1, B), np.int32),
        })

    batches = [make_batch(s) for s in range(4)]

    # ---- lane: steps_per_sec (compile + warm first, then timed chains)
    metrics = None
    for i in range(2):
        state, metrics = step(state, batches[i % 4], token_states)
    np.asarray(metrics["loss"])
    K = 6
    rates = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for i in range(K):
            state, metrics = step(state, batches[i % 4], token_states)
        np.asarray(metrics["loss"])  # readback = real synchronization
        rates.append(K * B / (time.perf_counter() - t0))

    # ---- lanes: batch_build_ms / h2d_ms (the host input pipeline)
    n = 4 * B
    pool = 12
    ix = IndexedSamples(
        pos=rng.integers(0, num_news, n).astype(np.int32),
        neg_pools=rng.integers(0, num_news, (n, pool)).astype(np.int32),
        neg_lens=np.full(n, pool, np.int32),
        history=rng.integers(0, num_news, (n, H)).astype(np.int32),
        his_len=np.full(n, H, np.int32),
    )
    batcher = TrainBatcher(ix, B, npratio=C - 1, seed=0)
    builds = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        cnt = sum(1 for _ in batcher.epoch_batches(0))
        builds.append((time.perf_counter() - t0) / max(cnt, 1) * 1e3)
    b0 = next(iter(batcher.epoch_batches(1)))

    def put(b):
        return (jnp.asarray(b.candidates), jnp.asarray(b.history))

    jax.block_until_ready(put(b0))
    h2ds = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(5):
            jax.block_until_ready(put(b0))
        h2ds.append((time.perf_counter() - t0) / 5 * 1e3)

    # ---- lanes: dispatch gap against a sleep-simulated off-host device
    # (sleep releases the GIL and the core, so the prefetcher's producer
    # can actually run ahead — same model step_profile.py uses on CPU
    # hosts, where real overlap is physically impossible on one core)
    def gen(limit: int):
        e, count = 2, 0
        while count < limit:
            for b in batcher.epoch_batches(e):
                yield b
                count += 1
                if count >= limit:
                    return
            e += 1

    def gap_loop(source) -> float:
        # bounded by the source generator itself (gen(K_sim) yields
        # exactly K_sim batches)
        gaps = []
        t_prev = None
        for args in source:
            t_ready = time.perf_counter()
            if t_prev is not None:
                gaps.append(t_ready - t_prev)
            time.sleep(SIM_TAU_S)  # the simulated off-host dispatch
            t_prev = time.perf_counter()
        return float(np.mean(gaps)) * 1e3

    K_sim = 8
    sync_gaps, pf_gaps = [], []
    for _ in range(repeats):
        sync_gaps.append(gap_loop(put(b) for b in gen(K_sim)))
        pf = Prefetcher(gen(K_sim), depth=2, transform=put)
        pf_gaps.append(gap_loop(pf))

    def lane(vals, unit, direction, kind="timing", best=min):
        return {
            "value": round(best(vals), 4),
            "unit": unit,
            "direction": direction,
            "spread": round(max(vals) - min(vals), 4),
            "kind": kind,
        }

    return {
        "steps_per_sec": lane(rates, "samples/sec", "lower_is_worse",
                              best=max),
        "batch_build_ms": lane(builds, "ms", "higher_is_worse"),
        "h2d_ms": lane(h2ds, "ms", "higher_is_worse"),
        "dispatch_gap_sync_ms": lane(sync_gaps, "ms", "higher_is_worse"),
        "dispatch_gap_prefetch_ms": lane(pf_gaps, "ms", "higher_is_worse"),
        "flops_per_step": {
            "value": flops_per_train_step(cfg, B, num_news),
            "unit": "flops",
            "direction": "any_change",
            "spread": 0.0,
            "kind": "exact",
        },
    }


def allowed_regression(base: dict, now: dict) -> float:
    """How much a timing lane may move in its bad direction: the larger
    of REL_FLOOR x baseline, NOISE_K x the larger measured spread
    (capped at NOISE_CAP x baseline so a pathologically noisy window
    cannot excuse arbitrary regressions), and (for ms lanes) an
    absolute grace floor."""
    bval = abs(float(base["value"]))
    noise = NOISE_K * max(
        float(base.get("spread", 0)), float(now.get("spread", 0))
    )
    allowed = max(REL_FLOOR * bval, min(noise, NOISE_CAP * bval))
    if base.get("unit") == "ms":
        allowed = max(allowed, ABS_FLOOR_MS)
    return allowed


def check(baseline: dict, lanes: dict) -> int:
    regressions: list[str] = []
    gated = 0
    for name, base in baseline["lanes"].items():
        now = lanes.get(name)
        if now is None:
            regressions.append(
                f"lane {name}: present in the baseline but MISSING from "
                "this run — the gate scenario drifted; re-bank "
                "deliberately (--bank) if that was intended"
            )
            continue
        gated += 1
        bval, nval = float(base["value"]), float(now["value"])
        if base["kind"] == "exact":
            if abs(nval - bval) > 1e-6 * max(abs(bval), 1.0):
                regressions.append(
                    f"lane {name}: {bval:.6g} -> {nval:.6g} — the analytic "
                    "FLOPs model changed; every banked MFU claim reprices. "
                    "Re-bank deliberately (--bank) if the model change is "
                    "intended"
                )
            continue
        drop = bval - nval if base["direction"] == "lower_is_worse" \
            else nval - bval
        allowed = allowed_regression(base, now)
        if drop > allowed:
            sim = " [SIMULATED]" if now.get("simulated") else ""
            regressions.append(
                f"lane {name}: {bval:.4g} -> {nval:.4g} {base['unit']} "
                f"(regressed {drop:.4g} > allowed {allowed:.4g}){sim}"
            )
    if regressions:
        print("PERF_GATE=FAIL")
        for r in regressions:
            print(f"  REGRESSION {r}")
        print(
            f"  ({gated} lane(s) gated; baseline banked "
            f"{baseline.get('provenance', {}).get('measured_at', '?')} at "
            f"commit {baseline.get('provenance', {}).get('commit', '?')}. "
            "A real change that moves a lane must re-bank with --bank; "
            "see docs/OPERATIONS.md §7e.)"
        )
        return 1
    print(f"PERF_GATE=PASS ({gated} lane(s) within threshold)")
    return 0


def bank(out_path: Path, lanes: dict, repeats: int) -> dict:
    from fedrec_tpu.utils.provenance import provenance

    artifact = {
        "kind": "perf_gate",
        "scenario": {
            "step": "joint-mode per-batch train step, B=16, 128-news "
                    "corpus, toy dims (see _gate_cfg), seed 0",
            "host": "TrainBatcher epoch build + h2d of one batch + "
                    f"sleep-simulated ({SIM_TAU_S * 1e3:g} ms) off-host "
                    "dispatch loop, sync vs prefetch depth 2",
            "repeats": repeats,
        },
        "threshold": {
            "rel_floor": REL_FLOOR, "noise_k": NOISE_K,
            "noise_cap": NOISE_CAP, "abs_floor_ms": ABS_FLOOR_MS,
        },
        "lanes": lanes,
        "provenance": provenance(),
    }
    out_path.write_text(json.dumps(artifact, indent=2))
    return artifact


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--bank", action="store_true",
                    help="(re)bank the baseline artifact")
    ap.add_argument("--check", action="store_true",
                    help="check against the banked baseline (exit 2 if absent)")
    ap.add_argument("--repeats", type=int, default=3,
                    help="timed repeats per timing lane (best banked)")
    ap.add_argument("--demo-regression", default=None, metavar="LANE",
                    help="adversely corrupt LANE's measurement 10x "
                         "(forced-regression demonstration)")
    ap.add_argument("--out", default=str(HERE / "perf_gate.json"),
                    help="baseline artifact path")
    args = ap.parse_args()

    # host-side CPU measurement: never touch (or wedge on) a TPU tunnel
    from fedrec_tpu.hostenv import cpu_host_env

    if os.environ.get("PALLAS_AXON_POOL_IPS") or os.environ.get("JAX_PLATFORMS") != "cpu":
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
            env=cpu_host_env(),
        ).returncode

    out_path = Path(args.out)
    if not args.bank and not args.check:
        # default: bank when absent, else check — the `make perf-gate` mode
        args.bank = not out_path.exists()
        args.check = not args.bank
    # AFTER defaulting: the default path with no baseline resolves to a
    # bank, which must refuse a corrupted run exactly like an explicit
    # --bank (a simulated-regression baseline would gate against garbage)
    if args.bank and args.demo_regression is not None:
        print("perf_gate: refusing to BANK a demo-regression run — the "
              "baseline must describe the healthy scenario", file=sys.stderr)
        return 2

    lanes = measure_lanes(repeats=max(args.repeats, 1))
    if args.demo_regression is not None:
        lane = lanes.get(args.demo_regression)
        if lane is None:
            print(
                f"perf_gate: unknown lane {args.demo_regression!r} "
                f"(lanes: {', '.join(sorted(lanes))})", file=sys.stderr,
            )
            return 2
        # adverse 10x corruption — past any noise allowance (capped at
        # NOISE_CAP) AND, for ms lanes, past the absolute grace floor (a
        # tiny banked h2d_ms times 10 could otherwise hide under
        # ABS_FLOOR_MS) — marked so the failure line says SIMULATED
        if lane["direction"] == "lower_is_worse":
            lane["value"] = lane["value"] / DEMO_FACTOR
        else:
            lane["value"] = max(
                lane["value"] * DEMO_FACTOR,
                lane["value"] + DEMO_FACTOR * ABS_FLOOR_MS,
            )
        lane["simulated"] = True
    for name in sorted(lanes):
        la = lanes[name]
        print(f"perf_gate: {name} = {la['value']:.6g} {la['unit']} "
              f"(spread {la['spread']:.4g})")

    if args.bank:
        bank(out_path, lanes, max(args.repeats, 1))
        print(f"PERF_GATE=BANKED ({len(lanes)} lanes -> {out_path})")
        return 0

    if not out_path.exists():
        print(
            f"perf_gate: no baseline at {out_path} — bank one first "
            "(python benchmarks/perf_gate.py --bank)", file=sys.stderr,
        )
        return 2
    baseline = json.loads(out_path.read_text())
    return check(baseline, lanes)


if __name__ == "__main__":
    raise SystemExit(main())
