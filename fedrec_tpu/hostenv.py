"""CPU-host environment hardening — the one copy of the axon recipe.

The axon TPU plugin's sitecustomize hook (triggered by
``PALLAS_AXON_POOL_IPS``) can wedge ANY jax backend init in a process, even
under ``JAX_PLATFORMS=cpu`` — so every subprocess that must run on the CPU
(fake-mesh tests, dryruns, bench fallbacks, accuracy legs) needs the same
env surgery applied before the interpreter starts. This module is the single
source of that recipe; it imports nothing but the stdlib so it is safe to
use from entry points that must not touch jax before re-exec
(``__graft_entry__``, ``bench.py``).
"""

from __future__ import annotations

import os

_DEVCOUNT_FLAG = "--xla_force_host_platform_device_count="


def cpu_host_env(
    n_devices: int | None = None, base: dict | None = None
) -> dict[str, str]:
    """A copy of ``base`` (default ``os.environ``) hardened for a CPU-host
    jax run: axon hook removed, platform pinned to cpu, and — when
    ``n_devices`` is given — exactly one fake-device-count flag in
    ``XLA_FLAGS`` (other inherited flags are preserved)."""
    env = dict(os.environ if base is None else base)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        kept = [
            t for t in env.get("XLA_FLAGS", "").split()
            if not t.startswith(_DEVCOUNT_FLAG)
        ]
        env["XLA_FLAGS"] = " ".join(kept + [f"{_DEVCOUNT_FLAG}{n_devices}"])
    return env


def fake_device_count(env: dict | None = None) -> int | None:
    """The configured fake-CPU device count, or None when absent/invalid."""
    flags = (os.environ if env is None else env).get("XLA_FLAGS", "")
    if _DEVCOUNT_FLAG not in flags:
        return None
    try:
        return int(flags.split(_DEVCOUNT_FLAG, 1)[1].split()[0])
    except (IndexError, ValueError):
        return None
