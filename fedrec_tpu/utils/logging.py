"""Metric logging: stdout always, wandb when available and enabled.

Mirrors the reference's 6-metric wandb schema (train/valid loss + AUC/MRR/
NDCG@5/NDCG@10, reference ``client.py:182-189``) without the hardcoded API
key (``client.py:214`` — a leaked secret we deliberately do not replicate;
auth comes from the environment).
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any


class MetricLogger:
    def __init__(
        self,
        use_wandb: bool = False,
        project: str = "fedrec_tpu",
        run_name: str = "run",
        stream=None,
    ):
        self.stream = stream or sys.stdout
        self._t0 = time.time()
        self._wandb = None
        if use_wandb:
            try:
                import wandb  # noqa: PLC0415

                wandb.init(project=project, name=run_name)
                self._wandb = wandb
            except Exception as e:  # wandb missing or offline — degrade to stdout
                print(f"[logger] wandb unavailable ({e}); stdout only", file=sys.stderr)

    def log(self, step: int, metrics: dict[str, Any]) -> None:
        clean = {
            k: (float(v) if hasattr(v, "__float__") else v) for k, v in metrics.items()
        }
        record = {"step": step, "elapsed_sec": round(time.time() - self._t0, 2), **clean}
        print(json.dumps(record), file=self.stream)
        if self._wandb is not None:
            self._wandb.log(clean, step=step)

    def finish(self) -> None:
        if self._wandb is not None:
            self._wandb.finish()
