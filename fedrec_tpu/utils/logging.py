"""Metric logging: the obs registry is the backend; stdout/wandb are exporters.

Mirrors the reference's 6-metric wandb schema (train/valid loss + AUC/MRR/
NDCG@5/NDCG@10, reference ``client.py:182-189``) without the hardcoded API
key (``client.py:214`` — a leaked secret we deliberately do not replicate;
auth comes from the environment).

Every ``log()`` call:

* publishes each numeric metric as a gauge in the process-wide
  :mod:`fedrec_tpu.obs` registry (so the Prometheus exposition and the
  registry snapshots see the training schema without extra wiring);
* writes one JSON line to the stream (and to ``jsonl_path`` when given —
  the run's event log ``fedrec-obs report`` consumes), FLUSHED, so a
  killed run keeps every line it printed;
* stringifies non-float-coercible values in the JSONL record instead of
  passing them through raw (a dict or ndarray payload used to make the
  line non-serializable), and sends only the numeric subset to wandb —
  wandb's silent per-key drop is now an explicit contract.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any

from fedrec_tpu.obs import get_registry


class MetricLogger:
    def __init__(
        self,
        use_wandb: bool = False,
        project: str = "fedrec_tpu",
        run_name: str = "run",
        stream=None,
        jsonl_path: str | None = None,
        registry=None,
        jsonl_max_mb: float = 0.0,
    ):
        self.stream = stream or sys.stdout
        self._t0 = time.time()
        self._registry = registry or get_registry()
        self._records = self._registry.counter(
            "log.records_total", "metric-log records emitted"
        )
        # append-per-write (no held handle): the event log is shared with
        # registry.write_snapshot appends AND may be size-rotated out from
        # under us (obs.jsonl_max_mb) — a persistent handle would follow
        # the renamed inode and write new records into the OLD file
        self._jsonl_path = jsonl_path
        self._jsonl_max_mb = float(jsonl_max_mb or 0.0)
        if jsonl_path:
            open(jsonl_path, "a").close()  # fail fast on an unwritable path
        self._wandb = None
        if use_wandb:
            try:
                import wandb  # noqa: PLC0415

                wandb.init(project=project, name=run_name)
                self._wandb = wandb
            except Exception as e:  # wandb missing or offline — degrade to stdout
                print(f"[logger] wandb unavailable ({e}); stdout only", file=sys.stderr)

    def log(self, step: int, metrics: dict[str, Any]) -> None:
        numeric: dict[str, float] = {}
        clean: dict[str, Any] = {}
        for k, v in metrics.items():
            # numeric iff float-coercible by protocol (strings stay strings
            # even when they look like numbers); a >1-element ndarray has
            # __float__ but raises — stringify it like any other non-numeric
            if hasattr(v, "__float__"):
                try:
                    f = float(v)
                except (TypeError, ValueError):
                    clean[k] = str(v)
                    continue
                numeric[k] = f
                clean[k] = f
            else:
                # strings and None are already JSON-native (null stays null —
                # serving emits real Nones for not-yet-populated percentiles);
                # everything else is stringified
                clean[k] = v if isinstance(v, str) or v is None else str(v)
        # fleet correlation keys (obs.fleet): worker/rank/membership_epoch
        # ride every JSONL record so multi-process event logs are joinable
        # offline; explicit metric keys win on collision
        fleet = self._registry.context
        record = {
            "step": step,
            "elapsed_sec": round(time.time() - self._t0, 2),
            **fleet,
            **clean,
        }
        line = json.dumps(record)
        print(line, file=self.stream, flush=True)
        if self._jsonl_path is not None:
            if self._jsonl_max_mb > 0:
                from fedrec_tpu.obs.report import rotate_jsonl

                rotate_jsonl(self._jsonl_path, self._jsonl_max_mb)
            with open(self._jsonl_path, "a") as f:
                f.write(line + "\n")
        # registry backend: the logged schema doubles as gauges, so snapshots
        # and the Prometheus exposition carry training_loss/val_auc/... too
        for k, f in numeric.items():
            try:
                self._registry.gauge(k).set(f)
            except ValueError:
                pass  # name already registered as a non-gauge — skip, don't crash
        self._records.inc()
        if self._wandb is not None:
            self._wandb.log(numeric, step=step)

    def finish(self) -> None:
        self._jsonl_path = None  # writes after finish() go nowhere, as before
        if self._wandb is not None:
            self._wandb.finish()
