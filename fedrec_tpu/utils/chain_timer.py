"""The ONE differencing-timer protocol for chip measurements.

Previously duplicated between ``bench.py measure()`` (whole-train-step
chains) and ``benchmarks/pallas_bench.py _time()`` (op-level scan chains),
with a cross-referenced NOTE in each demanding lockstep edits — unified
here so the repo's perf numbers stay comparable by construction. Both call
sites keep their byte-identical measurement policy (thresholds, chain
growth, cap) via the two knobs below.

Axon-tunnel honesty rules, learned the hard way and verified against a
known-FLOPs 8192^3 bf16 matmul (it "measured" 60 PFLOP/s on a 197-TFLOP/s
chip under the naive timer):

  * ``block_until_ready`` does NOT wait for remote execution over the
    tunnel — only a host readback synchronizes, so every chain must end in
    one (the caller's ``chain`` closure owns that);
  * each synchronized chain pays a fixed ~65 ms tunnel round-trip, and
    separate same-args dispatches overlap — so the per-op time is the
    DIFFERENCE of a 2x-length and a 1x-length chain, cancelling the
    constant;
  * the differenced signal must DWARF the few-ms tunnel jitter, not merely
    be positive: sub-ms ops at short chains produced nonsense (fwd+bwd
    "faster" than fwd), and a tiny positive delta over-reports throughput
    as badly as a clamp — chains grow until ``iters * t_op >= target``;
  * a non-positive delta (jitter or warm-up residue in the 1x chain) must
    DOUBLE the chain, not jump via ``target/per_op``: the old 1e-7 floor
    exploded straight to the iteration cap — hours at slow step times.
"""

from __future__ import annotations

from typing import Callable


def differenced_chain_seconds(
    chain: Callable[[int], float],
    iters: int,
    *,
    target: float = 0.3,
    cap: int = 2000,
    attempts: int = 4,
    accept_positive_at_cap: bool = False,
    label: str = "chain",
    trace: Callable[[str], None] | None = None,
) -> float:
    """Per-iteration seconds from differenced 1x/2x chains.

    ``chain(k)`` runs k synchronized iterations and returns wall seconds
    (including any fixed dispatch/RTT constant — it cancels). The caller
    warms up (compile + steady state) BEFORE calling this.

    ``accept_positive_at_cap``: accept any positive delta at the
    iteration cap OR on attempt exhaustion, raising only for a
    non-positive delta (pallas_bench's historical policy — op chains hit
    the cap on fast ops where the capped delta is still meaningful, and a
    jittery window's last positive reading beats a nulled evidence row);
    ``bench.py`` keeps the stricter raise-below-target policy for step
    chains. These two knobs are the ONLY policy difference between the
    call sites.
    """
    t1 = t2 = delta = float("nan")
    measured = iters
    for _ in range(attempts):
        measured = iters
        t1 = chain(measured)
        t2 = chain(2 * measured)
        delta = t2 - t1
        if trace is not None:
            trace(
                f"t1={t1:.2f} t2={t2:.2f} delta={delta:.2f} iters={measured}"
            )
        if delta >= target:
            return delta / measured
        if accept_positive_at_cap and measured >= cap:
            break
        if delta <= 0:
            # nonsense sign: jitter or warm-up residue landed in the 1x
            # chain — double and re-measure (see module docstring)
            iters = min(cap, 2 * measured)
            continue
        per_op = delta / measured
        iters = int(min(cap, max(2 * measured, target / per_op)))
    if accept_positive_at_cap and delta > 0:
        return delta / measured
    raise RuntimeError(
        f"differenced {label} time never cleared the jitter floor "
        f"(last t1={t1:.4f}, t2={t2:.4f}, iters={measured}); tunnel too "
        "jittery — rerun"
    )
