from fedrec_tpu.utils.chain_timer import differenced_chain_seconds
from fedrec_tpu.utils.logging import MetricLogger
from fedrec_tpu.utils.profiling import profile_if

__all__ = ["MetricLogger", "differenced_chain_seconds", "profile_if"]
