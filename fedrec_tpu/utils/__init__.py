from fedrec_tpu.utils.logging import MetricLogger
from fedrec_tpu.utils.profiling import profile_if

__all__ = ["MetricLogger", "profile_if"]
