"""Host/commit provenance stamp for benchmark artifacts.

Every ``benchmarks/*.json`` must self-describe where and when it was
measured (VERDICT r2 item 8: an artifact claiming 8 threads on a 1-core rig
was unexplainable because nothing recorded the host). Merge
``{"provenance": provenance()}`` into the payload at write time.
"""

from __future__ import annotations

import json
import os
import platform as _platform
import subprocess
import time
from pathlib import Path


def git_head(repo: Path | None = None) -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=repo or Path(__file__).resolve().parents[2],
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001
        return "unknown"


def git_dirty(repo: Path | None = None) -> bool | None:
    """True when TRACKED files have uncommitted changes, None if unknown.

    Untracked scratch files deliberately don't count: the caller's question
    is "does the checkout still match the stamped commit's code", and a
    stray notes file answers nothing about that.
    """
    paths = git_dirty_paths(repo)
    return None if paths is None else bool(paths)


def git_dirty_paths(repo: Path | None = None) -> list[str] | None:
    """Tracked files with uncommitted changes at stamp time, None if unknown.

    Recorded so a later reader can decide whether measure-time dirtiness
    could have affected the measurement (e.g. a benchmark writing its own
    artifact dirties the tree harmlessly; an edited ``fedrec_tpu/`` module
    does not). ``-z`` (NUL-separated) because git C-quotes spaces and
    non-ASCII in line-oriented output, which would defeat any prefix match
    a consumer runs on these paths.
    """
    try:
        out = subprocess.run(
            # --no-renames: rename detection would print only the
            # destination, hiding a source moved out of a watched prefix
            ["git", "diff", "--name-only", "--no-renames", "-z", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=repo or Path(__file__).resolve().parents[2],
        )
        if out.returncode != 0:
            return None
        return sorted(p for p in out.stdout.split("\0") if p)
    except Exception:  # noqa: BLE001
        return None


def write_artifact(path: Path, payload: dict, partial: bool) -> None:
    """Atomic benchmark-artifact write with incremental-run staging.

    Benchmark harnesses stamp after every measured row so a tunnel wedge
    mid-run keeps completed rows as labeled evidence. Three disciplines
    keep that kill-safe AND clobber-safe:

      * ``partial=True`` stamps go to a ``<stem>.inprogress.json`` sidecar
        — the canonical artifact is replaced only by a COMPLETED run, so a
        wedged re-run can never destroy previously banked complete
        evidence;
      * the ``"partial"`` flag is serialized FIRST (a torn tail can then
        never drop the flag while keeping the provenance block);
      * every write goes through a temp file + ``os.replace`` so no reader
        ever sees a half-written JSON.

    A completing write removes the sidecar. The chip watcher banks a queue
    item only when the canonical artifact is fresh and carries no
    ``"partial"`` flag.
    """
    sidecar = path.with_name(path.name[: -len(".json")] + ".inprogress.json"
                             if path.name.endswith(".json")
                             else path.name + ".inprogress")
    target = sidecar if partial else path
    # strip any incoming "partial" key first: a replayed payload (e.g. a
    # harness re-stamping a previously banked dict) could otherwise carry
    # partial=False into the spread and silently mark a sidecar complete —
    # the flag belongs to THIS write's `partial` argument alone
    payload = {k: v for k, v in payload.items() if k != "partial"}
    out = {"partial": True, **payload} if partial else payload
    tmp = target.with_name(target.name + ".tmp")
    tmp.write_text(json.dumps(out, indent=2))
    os.replace(tmp, target)
    if not partial:
        sidecar.unlink(missing_ok=True)


def runtime_versions() -> dict:
    """Installed jax/jaxlib versions via package metadata — read WITHOUT
    importing jax (an import here could trigger backend init, which hangs
    on a wedged tunnel; see the provenance() backend probe below).

    Recorded so a cached-replay reader can tell that a dependency-pin bump
    changed the installed runtime between the measurement and HEAD even
    when no tracked file moved (ADVICE r5 #3): ``bench._cache_delta``
    compares this stamp against the replaying process's own versions.
    """
    from importlib import metadata

    out = {}
    for pkg in ("jax", "jaxlib"):
        try:
            out[pkg] = metadata.version(pkg)
        except Exception:  # noqa: BLE001 — absent package stays absent
            pass
    return out


def provenance(**extra) -> dict:
    """Stamp: commit, wall time, machine, CPU count, installed jax/jaxlib
    versions, and the JAX backend actually in use (when JAX is already
    imported — never imports it)."""
    stamp = {
        "commit": git_head(),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "hostname": _platform.node(),
        "machine": _platform.machine(),
        "nproc": os.cpu_count(),
        "dirty_paths": git_dirty_paths(),
        "runtime_versions": runtime_versions(),
    }
    import sys

    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            # ONLY report an already-initialized backend: default_backend()
            # would otherwise trigger backend init here, and on an axon host
            # with a wedged TPU tunnel that call hangs forever (observed —
            # it froze data_bench.py for minutes before timeout)
            from jax._src import xla_bridge

            if xla_bridge._backends:
                stamp["jax_backend"] = jax.default_backend()
                stamp["jax_device"] = getattr(
                    jax.devices()[0], "device_kind", "unknown"
                )
        except Exception:  # noqa: BLE001
            pass
    stamp.update(extra)
    return stamp
