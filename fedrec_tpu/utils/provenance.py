"""Host/commit provenance stamp for benchmark artifacts.

Every ``benchmarks/*.json`` must self-describe where and when it was
measured (VERDICT r2 item 8: an artifact claiming 8 threads on a 1-core rig
was unexplainable because nothing recorded the host). Merge
``{"provenance": provenance()}`` into the payload at write time.
"""

from __future__ import annotations

import os
import platform as _platform
import subprocess
import time
from pathlib import Path


def git_head(repo: Path | None = None) -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=repo or Path(__file__).resolve().parents[2],
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001
        return "unknown"


def git_dirty(repo: Path | None = None) -> bool | None:
    """True when TRACKED files have uncommitted changes, None if unknown.

    Untracked scratch files deliberately don't count: the caller's question
    is "does the checkout still match the stamped commit's code", and a
    stray notes file answers nothing about that.
    """
    try:
        out = subprocess.run(
            ["git", "status", "--porcelain", "--untracked-files=no"],
            capture_output=True, text=True, timeout=10,
            cwd=repo or Path(__file__).resolve().parents[2],
        )
        if out.returncode != 0:
            return None
        return bool(out.stdout.strip())
    except Exception:  # noqa: BLE001
        return None


def provenance(**extra) -> dict:
    """Stamp: commit, wall time, machine, CPU count, and the JAX backend
    actually in use (when JAX is already imported — never imports it)."""
    stamp = {
        "commit": git_head(),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "hostname": _platform.node(),
        "machine": _platform.machine(),
        "nproc": os.cpu_count(),
    }
    import sys

    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            # ONLY report an already-initialized backend: default_backend()
            # would otherwise trigger backend init here, and on an axon host
            # with a wedged TPU tunnel that call hangs forever (observed —
            # it froze data_bench.py for minutes before timeout)
            from jax._src import xla_bridge

            if xla_bridge._backends:
                stamp["jax_backend"] = jax.default_backend()
                stamp["jax_device"] = getattr(
                    jax.devices()[0], "device_kind", "unknown"
                )
        except Exception:  # noqa: BLE001
            pass
    stamp.update(extra)
    return stamp
