"""Profiling helpers: ``jax.profiler`` traces around the hot loop.

The reference has no profiling subsystem (SURVEY.md section 5.1 — only print
statements and a vestigial counter pair, reference ``model.py:31-32``); here
a context manager wraps any region in a TensorBoard-compatible trace.

``profile_if`` yields the logdir the trace lands in (None when disabled),
so callers can report/stamp where the artifact went instead of hardcoding
the default path a second time.  Host-side round structure goes through
:mod:`fedrec_tpu.obs.tracing` instead; the Trainer annotates each round
with ``jax.profiler.StepTraceAnnotation("fed_round", step_num=...)`` so
the device trace captured here is round-addressable.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def profile_if(enabled: bool, logdir: str | None = None):
    """Wrap the block in a ``jax.profiler`` trace when ``enabled``.

    Yields the logdir path (the handle on the written trace) when
    enabled, None when not — a no-trace region never looks like it
    produced an artifact.  ``logdir=None`` falls back to the historical
    ``/tmp/fedrec_tpu_trace`` default; the Trainer routes it into
    ``obs.dir/jax_profile`` when an obs dir is configured (and points to
    it from ``metrics.jsonl``) so a captured trace is discoverable from
    the artifact trio instead of hiding in /tmp.
    """
    if not enabled:
        yield None
        return
    logdir = logdir or "/tmp/fedrec_tpu_trace"
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()
