"""Profiling helpers: ``jax.profiler`` traces around the hot loop.

The reference has no profiling subsystem (SURVEY.md section 5.1 — only print
statements and a vestigial counter pair, reference ``model.py:31-32``); here
a context manager wraps any region in a TensorBoard-compatible trace.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def profile_if(enabled: bool, logdir: str = "/tmp/fedrec_tpu_trace"):
    if not enabled:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
