"""Fused attention kernels (Pallas, TPU).

Two kernels cover the recommender's attention math (reference
``attention.py``):

  * ``flash_attention``: multi-head scaled-dot-product attention with online
    softmax — never materializes the (L, L) score matrix. The reference
    allocates dense ``(bz, heads, L, L)`` scores (``attention.py:38-44``);
    fine at L=50, fatal for long histories. Numerics match the model's
    ``stable_softmax=True`` path; an optional key mask reproduces the
    multiply-after-exp masking up to its 1e-8 epsilon.
  * ``additive_pool``: learned-query additive pooling
    ``softmax(tanh(x W1 + b1) w2) . x`` in one VMEM pass (reference
    ``attention.py:14-26``).

Kernels auto-fall back to interpret mode off-TPU so the same code path is
exercised by CPU tests. Backward passes go through ``jax.custom_vjp`` with a
dense recompute (correct, memory-light at training shapes); a blocked
backward kernel is a future optimization.

Layout notes (guide: /opt/skills/guides/pallas_guide.md): last dim padded to
128 lanes, blocks padded to 8-sublane multiples, matmuls carry
``preferred_element_type=float32`` so they hit the MXU in full precision.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANE = 128
_SUBLANE = 8
_NEG_INF = -1e9


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pad_to(x: jnp.ndarray, axis: int, mult: int) -> jnp.ndarray:
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


# ============================================================ flash attention
def _flash_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, *, block_k: int, scale: float):
    """One (batch*head, q-block) program: online softmax over key blocks.

    q_ref: (1, block_q, dk)   k_ref/v_ref: (1, L_pad, dk)   bias: (1, 1, L_pad)
    """
    q = q_ref[0].astype(jnp.float32) * scale            # (bq, dk)
    l_pad = k_ref.shape[1]
    block_q = q.shape[0]
    dv = v_ref.shape[2]

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, dv), jnp.float32)

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        b = bias_ref[0, 0, pl.ds(i * block_k, block_k)].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) + b[None, :]                                   # (bq, bk)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * correction + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, l_pad // block_k, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray,
    block_q: int,
    block_k: int,
) -> jnp.ndarray:
    """(BH, Lq, dk) x (BH, Lk, dk) x (BH, Lk, dv) + key bias (BH, Lk) -> (BH, Lq, dv)."""
    bh, lq, dk = q.shape
    dv = v.shape[-1]
    scale = 1.0 / (dk ** 0.5)

    # pad to hardware tiles; padded keys are masked via the bias
    qp = _pad_to(_pad_to(q, 2, _LANE), 1, block_q)
    kp = _pad_to(_pad_to(k, 2, _LANE), 1, block_k)
    vp = _pad_to(_pad_to(v, 2, _LANE), 1, block_k)
    biasp = _pad_to(bias, 1, block_k)
    if biasp.shape[1] > bias.shape[1]:
        biasp = biasp.at[:, bias.shape[1]:].set(_NEG_INF)
    biasp = biasp[:, None, :]                            # (BH, 1, Lk_pad)

    lq_pad, lk_pad = qp.shape[1], kp.shape[1]
    grid = (bh, lq_pad // block_q)
    out = pl.pallas_call(
        functools.partial(_flash_kernel, block_k=block_k, scale=scale),
        out_shape=jax.ShapeDtypeStruct((bh, lq_pad, vp.shape[2]), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, qp.shape[2]), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, lk_pad, kp.shape[2]), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, lk_pad, vp.shape[2]), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, lk_pad), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, vp.shape[2]), lambda b, i: (b, i, 0)),
        interpret=_interpret(),
    )(qp, kp, vp, biasp)
    return out[:, :lq, :dv]


def _attention_dense(q, k, v, bias):
    """Reference dense math (also the backward recompute)."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale + bias[:, None, :]
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p.astype(q.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash(q, k, v, bias, block_q, block_k):
    return _flash_forward(q, k, v, bias, block_q, block_k)


def _flash_fwd(q, k, v, bias, block_q, block_k):
    return _flash_forward(q, k, v, bias, block_q, block_k), (q, k, v, bias)


def _flash_bwd(block_q, block_k, res, g):
    q, k, v, bias = res
    _, vjp = jax.vjp(_attention_dense, q, k, v, bias)
    dq, dk, dv, dbias = vjp(g)
    return dq, dk, dv, dbias


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: jnp.ndarray | None = None,
    block_q: int = 128,
    block_k: int = 128,
) -> jnp.ndarray:
    """Multi-head attention, (..., L, H, D) layout like the Flax module.

    ``q``: (..., Lq, H, Dk); ``k``/``v``: (..., Lk, H, D); ``mask``:
    optional (..., Lk) key mask (1 = attend). Returns (..., Lq, H, Dv).
    """
    *batch, lq, h, dk = q.shape
    lk, dv = k.shape[-3], v.shape[-1]
    bsz = 1
    for b in batch:
        bsz *= b

    def flat(x, L, d):
        # (..., L, H, d) -> (B*H, L, d)
        x = x.reshape(bsz, L, h, d)
        return x.transpose(0, 2, 1, 3).reshape(bsz * h, L, d)

    qf, kf, vf = flat(q, lq, dk), flat(k, lk, dk), flat(v, lk, dv)
    if mask is None:
        bias = jnp.zeros((bsz * h, lk), jnp.float32)
    else:
        m = mask.reshape(bsz, lk).astype(jnp.float32)
        bias = jnp.repeat(jnp.where(m > 0, 0.0, _NEG_INF), h, axis=0)
    out = _flash(qf, kf, vf, bias, block_q, block_k)
    if mask is not None:
        # additive bias is shift-invariant under softmax, so a fully-masked
        # row would attend uniformly; the module's exp*mask/(sum+eps) math
        # (attention.py:41) returns ~0 there — match it
        has_valid = (mask.reshape(bsz, lk).sum(-1) > 0).astype(out.dtype)
        out = out * jnp.repeat(has_valid, h)[:, None, None]
    out = out.reshape(bsz, h, lq, dv).transpose(0, 2, 1, 3)
    return out.reshape(*batch, lq, h, dv)


# ============================================================ additive pool
def _pool_kernel(x_ref, w1_ref, b1_ref, w2_ref, bias_ref, o_ref):
    """One row-block program: fused tanh-MLP scores + softmax + weighted sum.

    x_ref: (block_n, L, D)  w1: (D, Hd)  b1: (1, Hd)  w2: (Hd, 1)
    bias_ref: (block_n, 1, L) additive key bias; o_ref: (block_n, 1, D).
    (bias/out carry a middle singleton so their constrained last-two block
    dims equal the array dims for any block_n — the sublane rule.)
    """
    bn, L, D = x_ref.shape
    x = x_ref[:].astype(jnp.float32)
    flat = x.reshape(bn * L, D)
    e = jnp.tanh(
        jax.lax.dot_general(
            flat, w1_ref[:].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        + b1_ref[0][None, :]
    )
    # w2 is lane-padded to (Hd, 128); only column 0 is the real query vector
    logits = jax.lax.dot_general(
        e, w2_ref[:].astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )[:, :1].reshape(bn, L) + bias_ref[:, 0, :]
    alpha = jax.nn.softmax(logits, axis=-1)
    pooled = jax.lax.dot_general(
        alpha[:, None, :], x, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )[:, 0, :]
    o_ref[:, 0, :] = pooled.astype(o_ref.dtype)


def _pool_forward(x, w1, b1, w2, bias, block_n):
    n, L, D = x.shape
    # the kernel holds x (block_n, L_pad, d_pad) plus the tanh activations
    # (block_n*L_pad, h_pad) in f32 VMEM; shrink block_n so long sequences
    # stay under the ~16 MB scoped-vmem limit (H=1024 at the default 8 OOMs)
    l_pad = L + (-L) % _SUBLANE
    d_pad = D + (-D) % _LANE
    h_pad = w1.shape[1] + (-w1.shape[1]) % _LANE
    per_row_bytes = l_pad * (d_pad + h_pad) * 4
    block_n = max(1, min(block_n, (6 << 20) // per_row_bytes))
    xp = _pad_to(_pad_to(_pad_to(x, 0, block_n), 1, _SUBLANE), 2, _LANE)
    biasp = _pad_to(_pad_to(bias, 0, block_n), 1, _SUBLANE)
    if biasp.shape[1] > L:  # padded sequence slots must never win the softmax
        biasp = biasp.at[:, L:].set(_NEG_INF)
    w1p = _pad_to(_pad_to(w1, 0, _LANE), 1, _LANE)
    b1p = _pad_to(b1.reshape(1, -1), 1, _LANE)
    w2p = _pad_to(_pad_to(w2.reshape(-1, 1), 0, _LANE), 1, _LANE)
    n_pad, d_pad, h_pad = xp.shape[0], xp.shape[2], w1p.shape[1]

    out = pl.pallas_call(
        _pool_kernel,
        out_shape=jax.ShapeDtypeStruct((n_pad, 1, d_pad), x.dtype),
        grid=(n_pad // block_n,),
        in_specs=[
            pl.BlockSpec((block_n, xp.shape[1], d_pad), lambda i: (i, 0, 0)),
            pl.BlockSpec((d_pad, h_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, h_pad), lambda i: (0, 0)),
            pl.BlockSpec((h_pad, w2p.shape[1]), lambda i: (0, 0)),
            pl.BlockSpec((block_n, 1, xp.shape[1]), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, 1, d_pad), lambda i: (i, 0, 0)),
        interpret=_interpret(),
    )(xp, w1p, b1p, w2p, biasp[:, None, :])
    return out[:n, 0, :D]


def _pool_dense(x, w1, b1, w2, bias):
    e = jnp.tanh(jnp.einsum("nld,dh->nlh", x, w1) + b1)
    logits = jnp.einsum("nlh,h->nl", e, w2.reshape(-1)) + bias
    alpha = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(x.dtype)
    return jnp.einsum("nl,nld->nd", alpha, x)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _pool(x, w1, b1, w2, bias, block_n):
    return _pool_forward(x, w1, b1, w2, bias, block_n)


def _pool_fwd(x, w1, b1, w2, bias, block_n):
    return _pool_forward(x, w1, b1, w2, bias, block_n), (x, w1, b1, w2, bias)


def _pool_bwd(block_n, res, g):
    x, w1, b1, w2, bias = res
    _, vjp = jax.vjp(_pool_dense, x, w1, b1, w2, bias)
    return vjp(g)


_pool.defvjp(_pool_fwd, _pool_bwd)


def additive_pool(
    x: jnp.ndarray,
    w1: jnp.ndarray,
    b1: jnp.ndarray,
    w2: jnp.ndarray,
    mask: jnp.ndarray | None = None,
    block_n: int = 8,
) -> jnp.ndarray:
    """Fused additive-attention pooling: (..., L, D) -> (..., D).

    ``w1``: (D, hidden), ``b1``: (hidden,), ``w2``: (hidden,) — the two Dense
    layers of ``AdditiveAttention`` (reference ``attention.py:14-26``).
    ``mask``: optional (..., L), 1 = keep.
    """
    *batch, L, D = x.shape
    n = 1
    for b in batch:
        n *= b
    xf = x.reshape(n, L, D)
    if mask is None:
        bias = jnp.zeros((n, L), jnp.float32)
    else:
        bias = jnp.where(mask.reshape(n, L) > 0, 0.0, _NEG_INF).astype(jnp.float32)
    out = _pool(xf, w1, b1, w2, bias, block_n)
    if mask is not None:
        # fully-masked rows pool to ~0 on the jnp path (attention.py:41) —
        # softmax shift-invariance would otherwise make them uniform here
        has_valid = (mask.reshape(n, L).sum(-1) > 0).astype(out.dtype)
        out = out * has_valid[:, None]
    return out.reshape(*batch, D)
