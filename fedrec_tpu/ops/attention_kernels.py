"""Fused attention kernels (Pallas, TPU).

Two kernels cover the recommender's attention math (reference
``attention.py``):

  * ``flash_attention``: multi-head scaled-dot-product attention with online
    softmax — never materializes the (L, L) score matrix. The reference
    allocates dense ``(bz, heads, L, L)`` scores (``attention.py:38-44``);
    fine at L=50, fatal for long histories. Numerics match the model's
    ``stable_softmax=True`` path; an optional key mask reproduces the
    multiply-after-exp masking up to its 1e-8 epsilon.
  * ``additive_pool``: learned-query additive pooling
    ``softmax(tanh(x W1 + b1) w2) . x`` in one VMEM pass (reference
    ``attention.py:14-26``).

Kernels auto-fall back to interpret mode off-TPU so the same code path is
exercised by CPU tests. ``flash_attention``'s backward is a blocked Pallas
kernel pair (FlashAttention-2 style: forward saves the per-row log-sum-exp;
backward rebuilds p blockwise — O(L) memory end to end, VERDICT r2 item 6).
``additive_pool``'s backward stays a dense ``jax.vjp`` recompute: its math
has no (L, L) term, so the recompute is already O(L)-memory.

Layout notes (guide: /opt/skills/guides/pallas_guide.md): last dim padded to
128 lanes, blocks padded to 8-sublane multiples, matmuls carry
``preferred_element_type=float32`` so they hit the MXU in full precision.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# module-local alias, NOT a monkeypatch of jax's namespace: pre-rename jax
# spells it TPUCompilerParams, and other libraries feature-detect on pltpu
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams", None
)

_LANE = 128
_SUBLANE = 8
_NEG_INF = -1e9


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pad_to(x: jnp.ndarray, axis: int, mult: int) -> jnp.ndarray:
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


# ============================================================ flash attention
def _flash_kernel(
    q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
    acc_ref, m_ref, l_ref, *, scale: float,
):
    """One (batch*head, q-block, k-block) grid step of the online softmax.

    K/V stream through the GRID's innermost dimension — one (block_k, dk)
    tile in VMEM at a time, double-buffered by the pipeline — instead of
    the whole (L, dk) K/V residing per program (the r3 kernel's layout:
    it serialized a full-L HBM->VMEM copy before any compute and its
    remote compile failed outright at L=4096). Running softmax state
    (m/l/acc) lives in VMEM scratch across k-steps; outputs are written on
    the last k-step. The dots run in the INPUT dtype with f32
    accumulation (``preferred_element_type``) — on bf16 models that is
    the MXU's native 4x-rate path, where the old kernel upcast everything
    to f32 first.

    q_ref: (1, block_q, dk)  k_ref/v_ref: (1, block_k, dk)
    bias_ref: (1, 1, block_k)  lse_ref: (1, 1, block_q) log-sum-exp — the
    residual the blocked backward needs to rebuild p without a dense pass.
    """
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]                                         # (bq, dk) input dtype
    k = k_ref[0]
    v = v_ref[0]
    b = bias_ref[0, 0, :].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale + b[None, :]                               # (bq, bk) f32
    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    correction = jnp.exp(m_prev - m_new)
    l_ref[:, :1] = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:, :1] = m_new

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        l_safe = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0, :] = (m_ref[:, :1] + jnp.log(l_safe))[:, 0]


def _flash_pad(q, k, v, bias, block_q, block_k):
    """Shared hardware-tile padding; padded keys are masked via the bias."""
    lk = bias.shape[1]
    qp = _pad_to(_pad_to(q, 2, _LANE), 1, block_q)
    kp = _pad_to(_pad_to(k, 2, _LANE), 1, block_k)
    vp = _pad_to(_pad_to(v, 2, _LANE), 1, block_k)
    biasp = _pad_to(bias, 1, block_k)
    if biasp.shape[1] > lk:
        biasp = biasp.at[:, lk:].set(_NEG_INF)
    return qp, kp, vp, biasp[:, None, :]                 # bias -> (BH, 1, Lk_pad)


def _flash_forward(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray,
    block_q: int,
    block_k: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(BH, Lq, dk) x (BH, Lk, dk) x (BH, Lk, dv) + key bias (BH, Lk)
    -> ((BH, Lq, dv) out, (BH, Lq) log-sum-exp)."""
    bh, lq, dk = q.shape
    dv = v.shape[-1]
    scale = 1.0 / (dk ** 0.5)
    qp, kp, vp, biasp = _flash_pad(q, k, v, bias, block_q, block_k)
    lq_pad, lk_pad = qp.shape[1], kp.shape[1]
    dkp, dvp = qp.shape[2], vp.shape[2]
    grid = (bh, lq_pad // block_q, lk_pad // block_k)
    out, lse = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale),
        out_shape=(
            jax.ShapeDtypeStruct((bh, lq_pad, dvp), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, lq_pad), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, dkp), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, dkp), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, dvp), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, dvp), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, dvp), jnp.float32),      # acc
            pltpu.VMEM((block_q, _LANE), jnp.float32),    # running max
            pltpu.VMEM((block_q, _LANE), jnp.float32),    # running sum
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(qp, kp, vp, biasp)
    return out[:, :lq, :dv], lse[:, 0, :lq]


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, bias_ref, do_ref, delta_ref, lse_ref, dq_ref,
    acc_ref, *, scale: float,
):
    """dq, one (batch*head, q-block, k-block) grid step: K/V stream through
    the grid, p is rebuilt from the saved log-sum-exp (FlashAttention-2
    backward, q-parallel half). Accumulates into VMEM scratch; dq is
    written on the last k-step."""
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]                                         # (bq, dk) input dtype
    do = do_ref[0]
    lse = lse_ref[0, 0, :].astype(jnp.float32)           # (bq,)
    delta = delta_ref[0, 0, :].astype(jnp.float32)[:, None]  # (bq, 1)
    k = k_ref[0]
    v = v_ref[0]
    b = bias_ref[0, 0, :].astype(jnp.float32)
    s = scale * jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) + b[None, :]
    p = jnp.exp(s - lse[:, None])                        # (bq, bk) f32
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = (p * (dp - delta)).astype(k.dtype)
    acc_ref[:] = acc_ref[:] + jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        dq_ref[0] = (acc_ref[:] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    k_ref, v_ref, bias_ref, q_ref, do_ref, delta_ref, lse_ref,
    dk_ref, dv_ref, dbias_ref, dk_acc, dv_acc, db_acc, *, scale: float,
):
    """dk/dv/dbias, one (batch*head, k-block, q-block) grid step: query
    blocks stream through the grid (FlashAttention-2 backward, k-parallel
    half). Accumulates in VMEM scratch; outputs written on the last
    q-step."""
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)
        db_acc[:] = jnp.zeros_like(db_acc)

    k = k_ref[0]                                         # (bk, dk) input dtype
    v = v_ref[0]
    b = bias_ref[0, 0, :].astype(jnp.float32)            # (bk,)
    q = q_ref[0]                                         # (bq, dk)
    do = do_ref[0]
    lse = lse_ref[0, 0, :].astype(jnp.float32)
    delta = delta_ref[0, 0, :].astype(jnp.float32)[:, None]  # (bq, 1)
    s = scale * jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) + b[None, :]                                       # (bq, bk)
    p = jnp.exp(s - lse[:, None])
    pc = p.astype(do.dtype)
    dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
        pc, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta)
    dsc = ds.astype(q.dtype)
    dk_acc[:] = dk_acc[:] + scale * jax.lax.dot_general(
        dsc, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    db_acc[:, :] = db_acc[:, :] + jnp.sum(ds, axis=0)[None, :]

    @pl.when(i == pl.num_programs(2) - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)
        dbias_ref[0, 0, :] = db_acc[0, :].astype(dbias_ref.dtype)


def _attention_dense(q, k, v, bias):
    """Reference dense math (golden path for kernel tests)."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale + bias[:, None, :]
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p.astype(q.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash(q, k, v, bias, block_q, block_k):
    out, _ = _flash_forward(q, k, v, bias, block_q, block_k)
    return out


def _flash_fwd(q, k, v, bias, block_q, block_k):
    out, lse = _flash_forward(q, k, v, bias, block_q, block_k)
    return out, (q, k, v, bias, out, lse)


def _flash_bwd(block_q, block_k, res, g):
    """Blocked backward: O(L) memory like the forward (VERDICT r2 item 6 —
    the previous dense recompute materialized the (L, L) scores, capping the
    kernel at exactly the sizes dense attention fits anyway)."""
    q, k, v, bias = res[:4]
    out, lse = res[4], res[5]
    bh, lq, dk_dim = q.shape
    lk, dv_dim = v.shape[1], v.shape[2]
    scale = 1.0 / (dk_dim ** 0.5)

    qp, kp, vp, biasp = _flash_pad(q, k, v, bias, block_q, block_k)
    # padded q rows carry do=0, so they contribute nothing to dk/dv/dbias
    dop = _pad_to(_pad_to(g, 2, _LANE), 1, block_q)
    # FA2's delta = rowsum(do * o), computed ONCE here (XLA) instead of per
    # (k-block x q-block) program inside the kernels; o itself is then not
    # needed by the kernels at all
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )
    deltap = _pad_to(delta, 1, block_q)[:, None, :]      # (BH, 1, Lq_pad)
    lsep = _pad_to(lse, 1, block_q)[:, None, :]          # (BH, 1, Lq_pad)
    lq_pad, lk_pad = qp.shape[1], kp.shape[1]
    dkp_dim, dvp_dim = kp.shape[2], vp.shape[2]

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((bh, lq_pad, qp.shape[2]), q.dtype),
        grid=(bh, lq_pad // block_q, lk_pad // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, qp.shape[2]), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, dkp_dim), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, dvp_dim), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j)),
            pl.BlockSpec((1, block_q, dvp_dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, qp.shape[2]), lambda b, i, j: (b, i, 0)
        ),
        scratch_shapes=[pltpu.VMEM((block_q, qp.shape[2]), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(qp, kp, vp, biasp, dop, deltap, lsep)

    dk, dv, dbias = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale),
        out_shape=(
            jax.ShapeDtypeStruct((bh, lk_pad, dkp_dim), k.dtype),
            jax.ShapeDtypeStruct((bh, lk_pad, dvp_dim), v.dtype),
            jax.ShapeDtypeStruct((bh, 1, lk_pad), bias.dtype),
        ),
        grid=(bh, lk_pad // block_k, lq_pad // block_q),
        in_specs=[
            pl.BlockSpec((1, block_k, dkp_dim), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, dvp_dim), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b, j, i: (b, 0, j)),
            pl.BlockSpec((1, block_q, qp.shape[2]), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, dvp_dim), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, dkp_dim), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, dvp_dim), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b, j, i: (b, 0, j)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, dkp_dim), jnp.float32),
            pltpu.VMEM((block_k, dvp_dim), jnp.float32),
            pltpu.VMEM((1, block_k), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(kp, vp, biasp, qp, dop, deltap, lsep)

    return (
        dq[:, :lq, :dk_dim],
        dk[:, :lk, :dk_dim],
        dv[:, :lk, :dv_dim],
        dbias[:, 0, :lk],
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: jnp.ndarray | None = None,
    block_q: int = 128,
    block_k: int = 128,
) -> jnp.ndarray:
    """Multi-head attention, (..., L, H, D) layout like the Flax module.

    ``q``: (..., Lq, H, Dk); ``k``/``v``: (..., Lk, H, D); ``mask``:
    optional (..., Lk) key mask (1 = attend). Returns (..., Lq, H, Dv).
    """
    *batch, lq, h, dk = q.shape
    lk, dv = k.shape[-3], v.shape[-1]
    bsz = 1
    for b in batch:
        bsz *= b

    def flat(x, L, d):
        # (..., L, H, d) -> (B*H, L, d)
        x = x.reshape(bsz, L, h, d)
        return x.transpose(0, 2, 1, 3).reshape(bsz * h, L, d)

    qf, kf, vf = flat(q, lq, dk), flat(k, lk, dk), flat(v, lk, dv)
    if mask is None:
        bias = jnp.zeros((bsz * h, lk), jnp.float32)
    else:
        m = mask.reshape(bsz, lk).astype(jnp.float32)
        bias = jnp.repeat(jnp.where(m > 0, 0.0, _NEG_INF), h, axis=0)
    out = _flash(qf, kf, vf, bias, block_q, block_k)
    if mask is not None:
        # additive bias is shift-invariant under softmax, so a fully-masked
        # row would attend uniformly; the module's exp*mask/(sum+eps) math
        # (attention.py:41) returns ~0 there — match it
        has_valid = (mask.reshape(bsz, lk).sum(-1) > 0).astype(out.dtype)
        out = out * jnp.repeat(has_valid, h)[:, None, None]
    out = out.reshape(bsz, h, lq, dv).transpose(0, 2, 1, 3)
    return out.reshape(*batch, lq, h, dv)


# ================================================== VMEM working-set model
VMEM_BYTES = 16 * 1024 * 1024   # per-core VMEM (pallas_guide.md: ~16 MB)
# block inputs/outputs are pipeline double-buffered; in-kernel f32
# temporaries are dominated by a few (block_q, block_k) score-sized arrays
# (s, p, dp, ds in the backward) — modeled with a fixed count
_PIPELINE_BUFFERS = 2
_SCORE_TEMPS = 4


def _iter_pallas_calls(jaxpr):
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            yield eqn
        for sub in jax.core.jaxprs_in_params(eqn.params):
            yield from _iter_pallas_calls(sub)


def _block_index_is_constant(bm) -> bool:
    """True when a block mapping's index map ignores the grid — the
    pipeline then keeps ONE resident copy (weights, accumulators) instead
    of double-buffering it. Conservative: anything unrecognizable counts
    as varying (over-estimates VMEM, never under)."""
    try:
        jaxpr = bm.index_map_jaxpr.jaxpr
        return not jaxpr.eqns and all(
            isinstance(v, jax.core.Literal) for v in jaxpr.outvars
        )
    except Exception:  # noqa: BLE001
        return False


def _pallas_call_buffer_bytes(eqn) -> tuple[int, int]:
    """(pipeline-buffered block bytes, scratch bytes) of one traced
    pallas_call eqn — the shared walk behind every VMEM working-set model
    (flash here, the fused hot-path kernels in ``fused_hot_path.py``), so
    all of them read the same grid-mapping truth instead of
    hand-maintained formulas. Grid-varying blocks count twice (pipeline
    double-buffering); constant-index blocks (weights, grad accumulators)
    count once."""
    gm = eqn.params["grid_mapping"]
    block_bytes = 0
    for bm in gm.block_mappings:
        aval = bm.block_aval
        n = 1
        for s in aval.shape:
            n *= s
        mult = 1 if _block_index_is_constant(bm) else _PIPELINE_BUFFERS
        block_bytes += n * aval.dtype.itemsize * mult
    # scratch operands live in the inner jaxpr's trailing invars
    inner = eqn.params["jaxpr"]
    n_scratch = gm.num_scratch_operands
    scratch_bytes = 0
    for var in (
        inner.invars[len(inner.invars) - n_scratch:] if n_scratch else []
    ):
        aval = var.aval
        n = 1
        for s in aval.shape:
            n *= s
        scratch_bytes += n * aval.dtype.itemsize
    return block_bytes, scratch_bytes


def flash_vmem_working_set(
    lq: int,
    lk: int,
    dk: int,
    dv: int,
    dtype=jnp.float32,
    block_q: int = 128,
    block_k: int = 128,
    batch_heads: int = 8,
    backward: bool = True,
) -> dict:
    """Per-program VMEM working set of the flash kernels, in bytes, derived
    from the TRACED pallas_call grid mappings — not a hand-maintained
    formula, so a layout regression (e.g. reverting to full-L K/V residency
    per program, the r3 kernel's failure mode that OOM'd the H=4096
    compile) shows up here without TPU hardware.

    Returns ``{"forward": bytes, "backward": bytes, "worst": bytes,
    "fits": bool}`` where each entry is the LARGEST single kernel's
    estimate: sum of block-operand bytes (x2 pipeline double-buffering) +
    scratch + ``_SCORE_TEMPS`` f32 (block_q, block_k) temporaries.
    Interpret-mode goldens cannot catch a VMEM regression (VERDICT r4 #5);
    this model can, and the test pins it at H=4096.
    """
    q = jax.ShapeDtypeStruct((batch_heads, lq, dk), dtype)
    k = jax.ShapeDtypeStruct((batch_heads, lk, dk), dtype)
    v = jax.ShapeDtypeStruct((batch_heads, lk, dv), dtype)
    bias = jax.ShapeDtypeStruct((batch_heads, lk), jnp.float32)

    def per_call_bytes(eqn) -> int:
        # buffered block bytes already carry the pipeline multiplier
        block_bytes, scratch_bytes = _pallas_call_buffer_bytes(eqn)
        temps = _SCORE_TEMPS * block_q * block_k * 4
        return block_bytes + scratch_bytes + temps

    fwd_jaxpr = jax.make_jaxpr(
        lambda *a: _flash_forward(*a, block_q, block_k)
    )(q, k, v, bias)
    fwd = max(per_call_bytes(e) for e in _iter_pallas_calls(fwd_jaxpr.jaxpr))
    bwd = 0
    if backward:
        bwd_jaxpr = jax.make_jaxpr(
            jax.grad(
                lambda qq, kk, vv, bb: jnp.sum(
                    _flash(qq, kk, vv, bb, block_q, block_k).astype(jnp.float32)
                ),
                argnums=(0, 1, 2),
            )
        )(q, k, v, bias)
        bwd = max(per_call_bytes(e) for e in _iter_pallas_calls(bwd_jaxpr.jaxpr))
    worst = max(fwd, bwd)
    return {
        "forward": fwd,
        "backward": bwd,
        "worst": worst,
        "fits": worst <= VMEM_BYTES,
    }


# ============================================================ additive pool
def _pool_kernel(x_ref, w1_ref, b1_ref, w2_ref, bias_ref, o_ref):
    """One row-block program: fused tanh-MLP scores + softmax + weighted sum.

    x_ref: (block_n, L, D)  w1: (D, Hd)  b1: (1, Hd)  w2: (Hd, 1)
    bias_ref: (block_n, 1, L) additive key bias; o_ref: (block_n, 1, D).
    (bias/out carry a middle singleton so their constrained last-two block
    dims equal the array dims for any block_n — the sublane rule.)
    """
    bn, L, D = x_ref.shape
    x = x_ref[:].astype(jnp.float32)
    flat = x.reshape(bn * L, D)
    e = jnp.tanh(
        jax.lax.dot_general(
            flat, w1_ref[:].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        + b1_ref[0][None, :]
    )
    # w2 is lane-padded to (Hd, 128); only column 0 is the real query vector
    logits = jax.lax.dot_general(
        e, w2_ref[:].astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )[:, :1].reshape(bn, L) + bias_ref[:, 0, :]
    alpha = jax.nn.softmax(logits, axis=-1)
    pooled = jax.lax.dot_general(
        alpha[:, None, :], x, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )[:, 0, :]
    o_ref[:, 0, :] = pooled.astype(o_ref.dtype)


def _pool_forward(x, w1, b1, w2, bias, block_n):
    n, L, D = x.shape
    # the kernel holds x (block_n, L_pad, d_pad) plus the tanh activations
    # (block_n*L_pad, h_pad) in f32 VMEM; shrink block_n so long sequences
    # stay under the ~16 MB scoped-vmem limit (H=1024 at the default 8 OOMs)
    l_pad = L + (-L) % _SUBLANE
    d_pad = D + (-D) % _LANE
    h_pad = w1.shape[1] + (-w1.shape[1]) % _LANE
    per_row_bytes = l_pad * (d_pad + h_pad) * 4
    block_n = max(1, min(block_n, (6 << 20) // per_row_bytes))
    xp = _pad_to(_pad_to(_pad_to(x, 0, block_n), 1, _SUBLANE), 2, _LANE)
    biasp = _pad_to(_pad_to(bias, 0, block_n), 1, _SUBLANE)
    if biasp.shape[1] > L:  # padded sequence slots must never win the softmax
        biasp = biasp.at[:, L:].set(_NEG_INF)
    w1p = _pad_to(_pad_to(w1, 0, _LANE), 1, _LANE)
    b1p = _pad_to(b1.reshape(1, -1), 1, _LANE)
    w2p = _pad_to(_pad_to(w2.reshape(-1, 1), 0, _LANE), 1, _LANE)
    n_pad, d_pad, h_pad = xp.shape[0], xp.shape[2], w1p.shape[1]

    out = pl.pallas_call(
        _pool_kernel,
        out_shape=jax.ShapeDtypeStruct((n_pad, 1, d_pad), x.dtype),
        grid=(n_pad // block_n,),
        in_specs=[
            pl.BlockSpec((block_n, xp.shape[1], d_pad), lambda i: (i, 0, 0)),
            pl.BlockSpec((d_pad, h_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, h_pad), lambda i: (0, 0)),
            pl.BlockSpec((h_pad, w2p.shape[1]), lambda i: (0, 0)),
            pl.BlockSpec((block_n, 1, xp.shape[1]), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, 1, d_pad), lambda i: (i, 0, 0)),
        interpret=_interpret(),
    )(xp, w1p, b1p, w2p, biasp[:, None, :])
    return out[:n, 0, :D]


def _pool_dense(x, w1, b1, w2, bias):
    e = jnp.tanh(jnp.einsum("nld,dh->nlh", x, w1) + b1)
    logits = jnp.einsum("nlh,h->nl", e, w2.reshape(-1)) + bias
    alpha = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(x.dtype)
    return jnp.einsum("nl,nld->nd", alpha, x)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _pool(x, w1, b1, w2, bias, block_n):
    return _pool_forward(x, w1, b1, w2, bias, block_n)


def _pool_fwd(x, w1, b1, w2, bias, block_n):
    return _pool_forward(x, w1, b1, w2, bias, block_n), (x, w1, b1, w2, bias)


def _pool_bwd(block_n, res, g):
    x, w1, b1, w2, bias = res
    _, vjp = jax.vjp(_pool_dense, x, w1, b1, w2, bias)
    return vjp(g)


_pool.defvjp(_pool_fwd, _pool_bwd)


def additive_pool(
    x: jnp.ndarray,
    w1: jnp.ndarray,
    b1: jnp.ndarray,
    w2: jnp.ndarray,
    mask: jnp.ndarray | None = None,
    block_n: int = 8,
) -> jnp.ndarray:
    """Fused additive-attention pooling: (..., L, D) -> (..., D).

    ``w1``: (D, hidden), ``b1``: (hidden,), ``w2``: (hidden,) — the two Dense
    layers of ``AdditiveAttention`` (reference ``attention.py:14-26``).
    ``mask``: optional (..., L), 1 = keep.
    """
    *batch, L, D = x.shape
    n = 1
    for b in batch:
        n *= b
    xf = x.reshape(n, L, D)
    if mask is None:
        bias = jnp.zeros((n, L), jnp.float32)
    else:
        bias = jnp.where(mask.reshape(n, L) > 0, 0.0, _NEG_INF).astype(jnp.float32)
    out = _pool(xf, w1, b1, w2, bias, block_n)
    if mask is not None:
        # fully-masked rows pool to ~0 on the jnp path (attention.py:41) —
        # softmax shift-invariance would otherwise make them uniform here
        has_valid = (mask.reshape(n, L).sum(-1) > 0).astype(out.dtype)
        out = out * has_valid[:, None]
    return out.reshape(*batch, D)
