"""Fused Pallas kernels for the train step's hot chain (ISSUE 8).

``benchmarks/pallas_bench.json`` proved that ISOLATED kernels lose at the
reference scale: at H=50 the flash-attention kernel is 50x slower than XLA
dense (1.89 ms vs 0.038 ms fwd) because per-call overhead dominates ops
this small. The only way a kernel wins here is by fusing the WHOLE chain
and amortizing one launch across it. Two kernels cover the step's hot path:

  * :func:`fused_gather_encode` — frozen-table embedding gather + text-head
    encode in ONE kernel: the per-batch unique news ids ride a scalar-
    prefetch grid, so each grid step DMAs exactly one ``token_states[id]``
    row HBM->VMEM (double-buffered by the Pallas pipeline) and streams it
    straight into the additive-attention pool + output projection. The
    (U, T, Dh) gather result never round-trips HBM as a materialized
    activation — forward OR backward (the trunk is frozen: the custom VJP
    produces head-parameter cotangents only and never touches the table).
  * :func:`fused_history_score` — the user tower + scorer in ONE kernel
    per row-block: Q/K/V projections, per-head attention over the (H, D)
    history, additive pooling to the user vector, and dot-scoring of the
    1+C candidate vectors, all in one VMEM residency. bf16 operands hit
    the MXU at native rate; every accumulation is f32.

Numerics contract (the trajectory pin in ``tests/test_fused_hot_path.py``):
the kernels reproduce the module chain's EXACT normalization semantics —
max-subtracted exp, mask multiplied AFTER exp, ``+ 1e-8`` on the
denominator (``attention.py::_masked_normalize`` with ``stable=True``) —
so a fully-masked history row pools to ~0 exactly like the jnp path. Under
float32 the fused chain matches the dense chain to float roundoff
(identical op sequence; reassociation across padded tiles is the only
difference). Under bfloat16 the kernels are tolerance-banded and MORE
precise than the dense chain: the module requantizes to bf16 after every
Dense/softmax, while the kernels keep f32 through every normalization and
requantize only at the same four points the module casts activations
(q/k/v, ctx, e, outputs). The backward treats the stabilization max as a
constant (standard flash-kernel practice); the jnp path routes an
O(1e-8)-relative subgradient through ``jnp.max`` — below every test
tolerance.

Gradient ledger — two parameters have MATHEMATICALLY zero gradients:
the key-projection bias (it shifts every score in a softmax row
uniformly — shift-invariant) and the pool fc2 bias (a constant shift on
pool logits). Autodiff on the dense path yields pure float-cancellation
noise there (~1e-7 relative), which Adam amplifies into noise-scale
parameter drift; the fused backward produces its own (different) noise
for the key bias and an EXACT zero for the fc2 bias (it is not a kernel
input — its true gradient is identically zero). Trajectory pins
therefore compare those two leaves at a noise bound, not the tight
tolerance; every functional output is unaffected (exact invariance).

Backward design: a blocked custom VJP, like ``flash_attention``'s — but
where the flash backward must carry a log-sum-exp residual because K/V
stream through the grid in blocks, the hot chain at H=50 holds the whole
history in one VMEM block, so the lse residual degenerates to "recompute
the one-block softmax" (one max+sum next to the dots the backward rebuilds
anyway). The backward kernels therefore recompute forward intermediates
per row-block and accumulate parameter cotangents across the sequential
grid; the lse-residual machinery stays in ``attention_kernels.py`` where
blocking over keys makes it load-bearing (H >= 2048).

Both kernels run in interpret mode off-TPU so tier-1 exercises the same
code path; interpret executes the grid as a host loop (~ms/step), which is
fine at test scale and is why the CPU bench legs run at reduced U.

Chip-validation risk (open until the queued pallas_bench window runs):
the gather kernel's table block is (1, T, Dh) with T=50 — NOT a sublane
multiple, because the (N, T, Dh) table cannot be padded without either a
per-step full-table copy or changing the dense path's no-mask pool
numerics (zero token rows would still contribute bias logits). Modern
Mosaic masks unaligned block windows, and Dh=768 keeps the lane dim
aligned; if the first real-chip compile rejects it regardless, the
fallback is ``model.fuse_hot_path=false`` (OPERATIONS §1b) while the
layout gets a revisit — interpret mode cannot adjudicate this.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fedrec_tpu.ops.attention_kernels import (
    VMEM_BYTES,
    _CompilerParams,
    _interpret,
    _iter_pallas_calls,
    _LANE,
    _pad_to,
    _pallas_call_buffer_bytes,
)

_NEG_INF = -1e9
_EPS = 1e-8  # the module's denominator epsilon (attention.py:41)


def _sub_mult(dtype) -> int:
    """Sublane pad multiple per dtype (pallas_guide.md tiling table)."""
    return 16 if jnp.dtype(dtype) == jnp.bfloat16 else 8


def _lane_pad(x: jnp.ndarray, width: int) -> jnp.ndarray:
    """Zero-pad the last dim of an in-kernel value up to ``width``."""
    if x.shape[-1] == width:
        return x
    pad = jnp.zeros(x.shape[:-1] + (width - x.shape[-1],), x.dtype)
    return jnp.concatenate([x, pad], axis=-1)


def _masked_softmax(
    logits: jnp.ndarray, mask: jnp.ndarray, pad_from: int
) -> jnp.ndarray:
    """The module's exp-normalization, f32, on (..., L) logits.

    ``pad_from``: first PADDED slot along the last axis — padded slots are
    forced to -inf BEFORE the max so the stabilizer matches the module's
    (which sees only real slots, masked-but-real slots included, exactly
    like this); ``mask`` multiplies AFTER exp, and the denominator carries
    the module's ``+ 1e-8`` — a fully-masked row therefore yields exactly
    the jnp path's ~0 weights instead of a uniform distribution.
    """
    iota = jax.lax.broadcasted_iota(jnp.int32, logits.shape, logits.ndim - 1)
    logits = jnp.where(iota >= pad_from, _NEG_INF, logits)
    m = jnp.max(logits, axis=-1, keepdims=True)
    w = jnp.exp(logits - m) * mask
    return w / (jnp.sum(w, axis=-1, keepdims=True) + _EPS)


# ===================================================== fused gather + encode
def _gather_encode_fwd_kernel(
    ids_ref, row_ref, w1_ref, b1_ref, w2_ref, fcw_ref, fcb_ref, o_ref,
    *, out_dtype,
):
    """One unique news id per grid step: the scalar-prefetch index map has
    already DMA'd ``token_states[ids[i]]`` into ``row_ref`` (the pipeline
    double-buffers the next row's copy behind this step's compute), so the
    kernel goes token states -> pooled -> news vector without the gather
    ever existing outside VMEM."""
    x = row_ref[0]                                       # (T, Dh) operand dtype
    t = x.shape[0]
    e = jnp.tanh(
        jax.lax.dot_general(
            x, w1_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        + b1_ref[0][None, :].astype(jnp.float32)
    ).astype(x.dtype)                                    # (T, Ah)
    # fc2's bias is a softmax-invariant constant shift under the max-
    # subtracted form — omitted exactly like additive_pool's kernel
    lg = jax.lax.dot_general(
        e, w2_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).reshape(1, t)                                      # (1, T) f32
    ones = jnp.ones((1, t), jnp.float32)                 # reference: no token mask
    alpha = _masked_softmax(lg, ones, t).astype(x.dtype)
    pooled = jax.lax.dot_general(
        alpha, x, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)                                    # (1, Dh)
    out = jax.lax.dot_general(
        pooled, fcw_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + fcb_ref[0][None, :].astype(jnp.float32)
    o_ref[:] = out.astype(out_dtype)                     # (1, Dp)


def _gather_encode_bwd_kernel(
    ids_ref, row_ref, w1_ref, b1_ref, w2_ref, fcw_ref, fcb_ref, g_ref,
    dw1_ref, db1_ref, dw2_ref, dfcw_ref, dfcb_ref,
):
    """Blocked backward, one unique row per sequential grid step: re-gathers
    the row through the same scalar-prefetch pipeline, recomputes the
    one-block pool (see module docstring: the lse residual degenerates
    here), and ACCUMULATES head-parameter cotangents into constant-index
    output blocks. No table cotangent exists anywhere — the frozen-trunk
    ``stop_gradient`` is structural, not an op XLA must simplify away."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dw1_ref[:] = jnp.zeros_like(dw1_ref)
        db1_ref[:] = jnp.zeros_like(db1_ref)
        dw2_ref[:] = jnp.zeros_like(dw2_ref)
        dfcw_ref[:] = jnp.zeros_like(dfcw_ref)
        dfcb_ref[:] = jnp.zeros_like(dfcb_ref)

    x = row_ref[0]                                       # (T, Dh)
    t = x.shape[0]
    x32 = x.astype(jnp.float32)
    e32 = jnp.tanh(
        jax.lax.dot_general(
            x, w1_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        + b1_ref[0][None, :].astype(jnp.float32)
    )
    e = e32.astype(x.dtype)
    lg = jax.lax.dot_general(
        e, w2_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).reshape(1, t)
    ones = jnp.ones((1, t), jnp.float32)
    alpha = _masked_softmax(lg, ones, t)                 # (1, T) f32
    pooled = jax.lax.dot_general(
        alpha.astype(x.dtype), x, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                    # (1, Dh) f32

    g = g_ref[:].astype(jnp.float32)                     # (1, Dp)
    dfcb_ref[:] += g
    dfcw_ref[:] += jax.lax.dot_general(
        pooled, g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                    # (Dh, Dp)
    dpooled = jax.lax.dot_general(
        g, fcw_ref[:].astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                    # (1, Dh)
    dalpha = jax.lax.dot_general(
        dpooled, x32, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                    # (1, T)
    dlg = alpha * (dalpha - jnp.sum(alpha * dalpha, axis=-1, keepdims=True))
    dw2_ref[:] += jax.lax.dot_general(
        dlg, e32, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                    # (1, Ah)
    de = jax.lax.dot_general(
        dlg, w2_ref[:].astype(jnp.float32), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                    # (T, Ah)
    dpre = de * (1.0 - e32 * e32)
    dw1_ref[:] += jax.lax.dot_general(
        x32, dpre, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                    # (Dh, Ah)
    db1_ref[:] += jnp.sum(dpre, axis=0, keepdims=True)


def _gather_encode_specs(t, dh_dim, ahp, dp):
    """Input specs shared by the fwd and bwd pallas_calls: the table row
    selected by the scalar-prefetch id, then the (padded) head params."""
    return [
        pl.BlockSpec((1, t, dh_dim), lambda i, ids: (ids[i], 0, 0)),
        pl.BlockSpec((dh_dim, ahp), lambda i, ids: (0, 0)),
        pl.BlockSpec((1, ahp), lambda i, ids: (0, 0)),
        pl.BlockSpec((1, ahp), lambda i, ids: (0, 0)),
        pl.BlockSpec((dh_dim, dp), lambda i, ids: (0, 0)),
        pl.BlockSpec((1, dp), lambda i, ids: (0, 0)),
    ]


def _gather_encode_pads(table, w1, b1, w2, fcw, fcb):
    dt = table.dtype
    w1p = _pad_to(w1, 1, _LANE).astype(dt)
    b1p = _pad_to(b1.reshape(1, -1), 1, _LANE).astype(dt)
    w2p = _pad_to(w2.reshape(1, -1), 1, _LANE).astype(dt)
    fcwp = _pad_to(fcw, 1, _LANE).astype(dt)
    fcbp = _pad_to(fcb.reshape(1, -1), 1, _LANE).astype(dt)
    return w1p, b1p, w2p, fcwp, fcbp


@jax.custom_vjp
def _gather_encode(table, uniq, w1, b1, w2, fcw, fcb):
    t, dh_dim = table.shape[1], table.shape[2]
    u = uniq.shape[0]
    w1p, b1p, w2p, fcwp, fcbp = _gather_encode_pads(table, w1, b1, w2, fcw, fcb)
    dp = fcwp.shape[1]
    out = pl.pallas_call(
        functools.partial(_gather_encode_fwd_kernel, out_dtype=table.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(u,),
            in_specs=_gather_encode_specs(t, dh_dim, w1p.shape[1], dp),
            out_specs=pl.BlockSpec((1, dp), lambda i, ids: (i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((u, dp), table.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=_interpret(),
    )(uniq, table, w1p, b1p, w2p, fcwp, fcbp)
    return out[:, : fcw.shape[1]]


def _gather_encode_fwd(table, uniq, w1, b1, w2, fcw, fcb):
    out = _gather_encode(table, uniq, w1, b1, w2, fcw, fcb)
    return out, (table, uniq, w1, b1, w2, fcw, fcb)


def _gather_encode_bwd(res, g):
    table, uniq, w1, b1, w2, fcw, fcb = res
    t, dh_dim = table.shape[1], table.shape[2]
    u = uniq.shape[0]
    w1p, b1p, w2p, fcwp, fcbp = _gather_encode_pads(table, w1, b1, w2, fcw, fcb)
    ahp, dp = w1p.shape[1], fcwp.shape[1]
    gp = _pad_to(g.astype(jnp.float32), 1, _LANE)        # (U, Dp), pads zero
    specs = _gather_encode_specs(t, dh_dim, ahp, dp)
    specs.append(pl.BlockSpec((1, dp), lambda i, ids: (i, 0)))  # cotangent row
    dw1, db1, dw2, dfcw, dfcb = pl.pallas_call(
        _gather_encode_bwd_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(u,),
            in_specs=specs,
            out_specs=(
                pl.BlockSpec((dh_dim, ahp), lambda i, ids: (0, 0)),
                pl.BlockSpec((1, ahp), lambda i, ids: (0, 0)),
                pl.BlockSpec((1, ahp), lambda i, ids: (0, 0)),
                pl.BlockSpec((dh_dim, dp), lambda i, ids: (0, 0)),
                pl.BlockSpec((1, dp), lambda i, ids: (0, 0)),
            ),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((dh_dim, ahp), jnp.float32),
            jax.ShapeDtypeStruct((1, ahp), jnp.float32),
            jax.ShapeDtypeStruct((1, ahp), jnp.float32),
            jax.ShapeDtypeStruct((dh_dim, dp), jnp.float32),
            jax.ShapeDtypeStruct((1, dp), jnp.float32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=_interpret(),
    )(uniq, table, w1p, b1p, w2p, fcwp, fcbp, gp)
    ah, d = w1.shape[1], fcw.shape[1]
    # the frozen table's cotangent is symbolically dropped by the caller's
    # stop_gradient; the zeros here are DCE'd, never materialized
    return (
        jnp.zeros_like(table),
        np.zeros(uniq.shape, jax.dtypes.float0),
        dw1[:, :ah].astype(w1.dtype),
        db1[0, :ah].astype(b1.dtype),
        dw2[0, :ah].astype(w2.dtype),
        dfcw[:, :d].astype(fcw.dtype),
        dfcb[0, :d].astype(fcb.dtype),
    )


_gather_encode.defvjp(_gather_encode_fwd, _gather_encode_bwd)


def fused_gather_encode(
    token_states: jnp.ndarray,
    uniq: jnp.ndarray,
    news_params: dict,
    dtype=None,
) -> jnp.ndarray:
    """Fused frozen-table gather + additive text head: (N, T, Dh) table +
    (U,) unique ids -> (U, news_dim) news vectors.

    ``news_params`` is the additive ``TextHead`` tree
    (``{"pool": {"att_fc1", "att_fc2"}, "fc"}``). Operands are cast to
    ``dtype`` (default: the table's dtype) before the kernel — the same
    quantization points as ``nn.Dense(dtype=...)`` on the module path.
    """
    p1 = news_params["pool"]["att_fc1"]
    p2 = news_params["pool"]["att_fc2"]
    fc = news_params["fc"]
    dt = jnp.dtype(dtype or token_states.dtype)
    return _gather_encode(
        token_states.astype(dt),
        uniq,
        p1["kernel"].astype(dt),
        p1["bias"].astype(dt),
        p2["kernel"][:, 0].astype(dt),
        fc["kernel"].astype(dt),
        fc["bias"].astype(dt),
    )


# ================================================ fused history-attn + score
def _score_block_b(block_b, hp, dp, qp, cp, itemsize, backward: bool):
    """Shrink the row-block so one program's block operands + f32
    temporaries stay inside a conservative VMEM budget (the same guard
    ``_pool_forward`` applies; the traced model below is the test-time
    check, this is the runtime one)."""
    per_row = (
        hp * dp * (itemsize + 4 * 4)       # x block + f32 q/k/v/ctx temps
        + 2 * hp * hp * 4                  # one head's s/w
        + hp * qp * 4                      # e
        + cp * dp * itemsize               # cand block
    )
    if backward:
        per_row += hp * hp * 4 * 24        # per-head attention maps kept live
        per_row += 3 * hp * dp * 4         # dq/dk/dv
    budget = (6 << 20) if not backward else (7 << 20)
    return max(1, min(block_b, budget // per_row))


def _hist_forward_core(
    x_ref, mask_ref, wq_ref, bq_ref, wk_ref, bk_ref, wv_ref, bv_ref,
    pw1_ref, pb1_ref, pw2_ref, *, nh: int, dh: int, h: int, keep_attn: bool,
):
    """Shared forward math for the fused score kernels (fwd + recompute in
    bwd): projections -> per-head masked attention -> additive pool.

    Quantization points mirror the module chain exactly: every Dense-like
    output is cast back to the operand dtype (identity under f32), every
    normalization runs in f32. Returns the f32 attention maps per head only
    when the backward asks (``keep_attn``)."""
    bb, hp, dp = x_ref.shape
    dt = x_ref.dtype
    d = nh * dh
    x2 = x_ref[:].reshape(bb * hp, dp)

    def proj(w_ref, b_ref):
        y = jax.lax.dot_general(
            x2, w_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) + b_ref[0][None, :].astype(jnp.float32)
        return y.astype(dt).reshape(bb, hp, dp)

    qa, ka, va = proj(wq_ref, bq_ref), proj(wk_ref, bk_ref), proj(wv_ref, bv_ref)
    mask = mask_ref[:, 0, :hp].astype(jnp.float32)       # (bb, hp)
    kmask = mask[:, None, :]
    scale = jnp.sqrt(jnp.float32(dh))
    ctx_heads, attn_heads = [], []
    for head in range(nh):
        sl = slice(head * dh, (head + 1) * dh)
        qh, kh, vh = qa[:, :, sl], ka[:, :, sl], va[:, :, sl]
        s = jax.lax.dot_general(
            qh, kh, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) / scale                                        # (bb, hp, hp)
        a = _masked_softmax(s, kmask, h)
        if keep_attn:
            attn_heads.append(a)
        ctx_heads.append(
            jax.lax.dot_general(
                a.astype(dt), vh, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            ).astype(dt)
        )
    ctx = jnp.concatenate(ctx_heads, axis=-1)            # (bb, hp, d)
    e32 = jnp.tanh(
        jax.lax.dot_general(
            ctx.reshape(bb * hp, d), pw1_ref[:d, :], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        + pb1_ref[0][None, :].astype(jnp.float32)
    )                                                    # (bb*hp, Qp)
    lg = jax.lax.dot_general(
        e32.astype(dt), pw2_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).reshape(bb, hp)
    alpha = _masked_softmax(lg, mask, h)                 # (bb, hp) f32
    user = jax.lax.dot_general(
        alpha.astype(dt), ctx, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                                    # (bb, d) f32
    return qa, ka, va, attn_heads, ctx, e32, alpha, user


def _hist_score_fwd_kernel(
    x_ref, cand_ref, mask_ref, wq_ref, bq_ref, wk_ref, bk_ref, wv_ref,
    bv_ref, pw1_ref, pb1_ref, pw2_ref, scores_ref, user_ref, *, nh, dh, h,
):
    dt = x_ref.dtype
    d = nh * dh
    *_, _, _, _, user = _hist_forward_core(
        x_ref, mask_ref, wq_ref, bq_ref, wk_ref, bk_ref, wv_ref, bv_ref,
        pw1_ref, pb1_ref, pw2_ref, nh=nh, dh=dh, h=h, keep_attn=False,
    )
    user_dt = user.astype(dt)
    sc = jax.lax.dot_general(
        cand_ref[:, :, :d], user_dt, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                                    # (bb, Cp)
    scores_ref[:] = _lane_pad(sc.astype(dt), scores_ref.shape[1])
    user_ref[:] = _lane_pad(user_dt, user_ref.shape[1])


def _hist_score_bwd_kernel(
    x_ref, cand_ref, mask_ref, wq_ref, bq_ref, wk_ref, bk_ref, wv_ref,
    bv_ref, pw1_ref, pb1_ref, pw2_ref, gsc_ref, guser_ref,
    dx_ref, dcand_ref, dwq_ref, dbq_ref, dwk_ref, dbk_ref, dwv_ref,
    dbv_ref, dpw1_ref, dpb1_ref, dpw2_ref, *, nh, dh, h, c,
):
    """Blocked backward: recompute the row-block's forward (module
    docstring: at H=50 the whole history is one block, so recompute IS the
    degenerate lse-residual path), then walk the chain backward producing
    per-block dx/dcand and accumulating parameter cotangents across the
    sequential grid."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        for ref in (
            dwq_ref, dbq_ref, dwk_ref, dbk_ref, dwv_ref, dbv_ref,
            dpw1_ref, dpb1_ref, dpw2_ref,
        ):
            ref[:] = jnp.zeros_like(ref)

    bb, hp, dp = x_ref.shape
    d = nh * dh
    qa, ka, va, attn, ctx, e32, alpha, user = _hist_forward_core(
        x_ref, mask_ref, wq_ref, bq_ref, wk_ref, bk_ref, wv_ref, bv_ref,
        pw1_ref, pb1_ref, pw2_ref, nh=nh, dh=dh, h=h, keep_attn=True,
    )
    ctx32 = ctx.astype(jnp.float32)
    cand32 = cand_ref[:, :, :d].astype(jnp.float32)      # (bb, Cp, d)
    gs = gsc_ref[:, :c].astype(jnp.float32)              # (bb, C)
    gu = guser_ref[:, :d].astype(jnp.float32)            # (bb, d)

    # ---- scorer
    dcand = jnp.einsum("bc,bd->bcd", gs, user)           # (bb, C, d)
    du = jnp.einsum("bc,bcd->bd", gs, cand32[:, :c, :]) + gu

    # ---- additive pool
    dalpha = jnp.einsum("bd,bhd->bh", du, ctx32)
    dctx = alpha[:, :, None] * du[:, None, :]            # (bb, hp, d)
    dlg = alpha * (dalpha - jnp.sum(alpha * dalpha, axis=-1, keepdims=True))
    e3 = e32.reshape(bb, hp, -1)                         # (bb, hp, Qp)
    dpw2_ref[:] += jnp.sum(
        jnp.einsum("bh,bhq->bq", dlg, e3), axis=0, keepdims=True
    )
    de = dlg[:, :, None] * pw2_ref[0][None, None, :].astype(jnp.float32)
    dpre = de * (1.0 - e3 * e3)                          # (bb, hp, Qp)
    dpw1 = jnp.einsum("bhd,bhq->dq", ctx32, dpre)        # (d, Qp)
    if dpw1.shape[0] < dpw1_ref.shape[0]:                # rows pad -> (Dp, Qp)
        dpw1 = jnp.concatenate(
            [dpw1, jnp.zeros((dpw1_ref.shape[0] - d, dpw1.shape[1]),
                             jnp.float32)],
            axis=0,
        )
    dpw1_ref[:] += dpw1
    dpb1_ref[:] += jnp.sum(dpre, axis=(0, 1))[None, :]
    dctx = dctx + jnp.einsum(
        "bhq,dq->bhd", dpre, pw1_ref[:d, :].astype(jnp.float32)
    )

    # ---- per-head attention (attn maps recomputed in the shared core)
    scale = jnp.sqrt(jnp.float32(dh))
    dq_heads, dk_heads, dv_heads = [], [], []
    for head in range(nh):
        sl = slice(head * dh, (head + 1) * dh)
        a = attn[head]                                   # (bb, hp, hp) f32
        vh = va[:, :, sl].astype(jnp.float32)
        qh = qa[:, :, sl].astype(jnp.float32)
        kh = ka[:, :, sl].astype(jnp.float32)
        dctx_h = dctx[:, :, sl]
        dv_heads.append(jnp.einsum("bqk,bqd->bkd", a, dctx_h))
        da = jnp.einsum("bqd,bkd->bqk", dctx_h, vh)
        ds = a * (da - jnp.sum(a * da, axis=-1, keepdims=True)) / scale
        dq_heads.append(jnp.einsum("bqk,bkd->bqd", ds, kh))
        dk_heads.append(jnp.einsum("bqk,bqd->bkd", ds, qh))
    dq = _lane_pad(jnp.concatenate(dq_heads, axis=-1), dp).reshape(bb * hp, dp)
    dk = _lane_pad(jnp.concatenate(dk_heads, axis=-1), dp).reshape(bb * hp, dp)
    dv = _lane_pad(jnp.concatenate(dv_heads, axis=-1), dp).reshape(bb * hp, dp)

    # ---- projections
    x32 = x_ref[:].astype(jnp.float32).reshape(bb * hp, dp)
    dx = jnp.zeros((bb * hp, dp), jnp.float32)
    for dy, w_ref, dw_ref, db_ref in (
        (dq, wq_ref, dwq_ref, dbq_ref),
        (dk, wk_ref, dwk_ref, dbk_ref),
        (dv, wv_ref, dwv_ref, dbv_ref),
    ):
        dw_ref[:] += jax.lax.dot_general(
            x32, dy, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        db_ref[:] += jnp.sum(dy, axis=0, keepdims=True)
        dx = dx + jax.lax.dot_general(
            dy, w_ref[:].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    dx_ref[:] = dx.reshape(bb, hp, dp)
    dcand_ref[:] = _lane_pad(
        jnp.pad(dcand, ((0, 0), (0, dcand_ref.shape[1] - c), (0, 0))), dp
    )


def _hist_score_pads(x, cand, mask, wq, bq, wk, bk, wv, bv, pw1, pb1, pw2,
                     block_b):
    """One padding policy for the fwd and bwd calls: lane dims to 128,
    sequence dims to the dtype's sublane multiple, rows to the block."""
    dt = x.dtype
    sm = _sub_mult(dt)
    xp = _pad_to(_pad_to(_pad_to(x, 0, block_b), 1, sm), 2, _LANE)
    candp = _pad_to(_pad_to(_pad_to(cand, 0, block_b), 1, sm), 2, _LANE)
    hm = xp.shape[1] + (-xp.shape[1]) % _LANE
    maskp = _pad_to(_pad_to(mask.astype(jnp.float32), 0, block_b), 1, hm)
    maskp = maskp[:, None, :]                            # (np, 1, Hm)
    wqp = _pad_to(_pad_to(wq, 0, _LANE), 1, _LANE).astype(dt)
    wkp = _pad_to(_pad_to(wk, 0, _LANE), 1, _LANE).astype(dt)
    wvp = _pad_to(_pad_to(wv, 0, _LANE), 1, _LANE).astype(dt)
    bqp = _pad_to(bq.reshape(1, -1), 1, _LANE).astype(dt)
    bkp = _pad_to(bk.reshape(1, -1), 1, _LANE).astype(dt)
    bvp = _pad_to(bv.reshape(1, -1), 1, _LANE).astype(dt)
    pw1p = _pad_to(_pad_to(pw1, 0, _LANE), 1, _LANE).astype(dt)
    pb1p = _pad_to(pb1.reshape(1, -1), 1, _LANE).astype(dt)
    pw2p = _pad_to(pw2.reshape(1, -1), 1, _LANE).astype(dt)
    return xp, candp, maskp, wqp, bqp, wkp, bkp, wvp, bvp, pw1p, pb1p, pw2p


def _hist_score_wspecs(dp, qp):
    """BlockSpecs of the 9 (padded) parameter operands — constant index
    maps, so the pipeline keeps them VMEM-resident across row-blocks."""
    return [
        pl.BlockSpec((dp, dp), lambda i: (0, 0)),
        pl.BlockSpec((1, dp), lambda i: (0, 0)),
        pl.BlockSpec((dp, dp), lambda i: (0, 0)),
        pl.BlockSpec((1, dp), lambda i: (0, 0)),
        pl.BlockSpec((dp, dp), lambda i: (0, 0)),
        pl.BlockSpec((1, dp), lambda i: (0, 0)),
        pl.BlockSpec((dp, qp), lambda i: (0, 0)),
        pl.BlockSpec((1, qp), lambda i: (0, 0)),
        pl.BlockSpec((1, qp), lambda i: (0, 0)),
    ]


@functools.partial(jax.custom_vjp, nondiff_argnums=(12, 13))
def _hist_score(x, cand, mask, wq, bq, wk, bk, wv, bv, pw1, pb1, pw2,
                nh, block_b):
    return _hist_score_forward(
        x, cand, mask, wq, bq, wk, bk, wv, bv, pw1, pb1, pw2, nh, block_b
    )


def _hist_score_forward(x, cand, mask, wq, bq, wk, bk, wv, bv, pw1, pb1,
                        pw2, nh, block_b):
    n, h, d = x.shape
    c = cand.shape[1]
    dh = d // nh
    dt = x.dtype
    bb = _score_block_b(
        block_b,
        h + (-h) % _sub_mult(dt),
        d + (-d) % _LANE,
        pw1.shape[1] + (-pw1.shape[1]) % _LANE,
        c + (-c) % _sub_mult(dt),
        dt.itemsize,
        backward=False,
    )
    padded = _hist_score_pads(
        x, cand, mask, wq, bq, wk, bk, wv, bv, pw1, pb1, pw2, bb
    )
    xp, candp, maskp = padded[:3]
    np_, hp, dp = xp.shape
    cp, qp = candp.shape[1], padded[9].shape[1]
    cs = cp + (-cp) % _LANE
    scores, user = pl.pallas_call(
        functools.partial(_hist_score_fwd_kernel, nh=nh, dh=dh, h=h),
        grid=(np_ // bb,),
        in_specs=[
            pl.BlockSpec((bb, hp, dp), lambda i: (i, 0, 0)),
            pl.BlockSpec((bb, cp, dp), lambda i: (i, 0, 0)),
            pl.BlockSpec((bb, 1, maskp.shape[2]), lambda i: (i, 0, 0)),
            *_hist_score_wspecs(dp, qp),
        ],
        out_specs=(
            pl.BlockSpec((bb, cs), lambda i: (i, 0)),
            pl.BlockSpec((bb, dp), lambda i: (i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((np_, cs), dt),
            jax.ShapeDtypeStruct((np_, dp), dt),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",),
        ),
        interpret=_interpret(),
    )(*padded)
    return scores[:n, :c], user[:n, :d]


def _hist_score_vjp_fwd(x, cand, mask, wq, bq, wk, bk, wv, bv, pw1, pb1,
                        pw2, nh, block_b):
    out = _hist_score_forward(
        x, cand, mask, wq, bq, wk, bk, wv, bv, pw1, pb1, pw2, nh, block_b
    )
    return out, (x, cand, mask, wq, bq, wk, bk, wv, bv, pw1, pb1, pw2)


def _hist_score_vjp_bwd(nh, block_b, res, g):
    x, cand, mask, wq, bq, wk, bk, wv, bv, pw1, pb1, pw2 = res
    gsc, guser = g
    n, h, d = x.shape
    c = cand.shape[1]
    dh = d // nh
    dt = x.dtype
    bb = _score_block_b(
        block_b,
        h + (-h) % _sub_mult(dt),
        d + (-d) % _LANE,
        pw1.shape[1] + (-pw1.shape[1]) % _LANE,
        c + (-c) % _sub_mult(dt),
        dt.itemsize,
        backward=True,
    )
    padded = _hist_score_pads(
        x, cand, mask, wq, bq, wk, bk, wv, bv, pw1, pb1, pw2, bb
    )
    xp, candp, maskp = padded[:3]
    np_, hp, dp = xp.shape
    cp, qp = candp.shape[1], padded[9].shape[1]
    cs = cp + (-cp) % _LANE
    gscp = _pad_to(_pad_to(gsc.astype(jnp.float32), 0, bb), 1, cs)
    guserp = _pad_to(_pad_to(guser.astype(jnp.float32), 0, bb), 1, dp)
    outs = pl.pallas_call(
        functools.partial(_hist_score_bwd_kernel, nh=nh, dh=dh, h=h, c=c),
        grid=(np_ // bb,),
        in_specs=[
            pl.BlockSpec((bb, hp, dp), lambda i: (i, 0, 0)),
            pl.BlockSpec((bb, cp, dp), lambda i: (i, 0, 0)),
            pl.BlockSpec((bb, 1, maskp.shape[2]), lambda i: (i, 0, 0)),
            *_hist_score_wspecs(dp, qp),
            pl.BlockSpec((bb, cs), lambda i: (i, 0)),
            pl.BlockSpec((bb, dp), lambda i: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((bb, hp, dp), lambda i: (i, 0, 0)),
            pl.BlockSpec((bb, cp, dp), lambda i: (i, 0, 0)),
            pl.BlockSpec((dp, dp), lambda i: (0, 0)),
            pl.BlockSpec((1, dp), lambda i: (0, 0)),
            pl.BlockSpec((dp, dp), lambda i: (0, 0)),
            pl.BlockSpec((1, dp), lambda i: (0, 0)),
            pl.BlockSpec((dp, dp), lambda i: (0, 0)),
            pl.BlockSpec((1, dp), lambda i: (0, 0)),
            pl.BlockSpec((dp, qp), lambda i: (0, 0)),
            pl.BlockSpec((1, qp), lambda i: (0, 0)),
            pl.BlockSpec((1, qp), lambda i: (0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((np_, hp, dp), jnp.float32),
            jax.ShapeDtypeStruct((np_, cp, dp), jnp.float32),
            jax.ShapeDtypeStruct((dp, dp), jnp.float32),
            jax.ShapeDtypeStruct((1, dp), jnp.float32),
            jax.ShapeDtypeStruct((dp, dp), jnp.float32),
            jax.ShapeDtypeStruct((1, dp), jnp.float32),
            jax.ShapeDtypeStruct((dp, dp), jnp.float32),
            jax.ShapeDtypeStruct((1, dp), jnp.float32),
            jax.ShapeDtypeStruct((dp, qp), jnp.float32),
            jax.ShapeDtypeStruct((1, qp), jnp.float32),
            jax.ShapeDtypeStruct((1, qp), jnp.float32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=_interpret(),
    )(*padded, gscp, guserp)
    dx, dcand, dwq, dbq, dwk, dbk, dwv, dbv, dpw1, dpb1, dpw2 = outs
    q = pw1.shape[1]
    return (
        dx[:n, :h, :d].astype(x.dtype),
        dcand[:n, :c, :d].astype(cand.dtype),
        jnp.zeros_like(mask),
        dwq[:d, :d].astype(wq.dtype),
        dbq[0, :d].astype(bq.dtype),
        dwk[:d, :d].astype(wk.dtype),
        dbk[0, :d].astype(bk.dtype),
        dwv[:d, :d].astype(wv.dtype),
        dbv[0, :d].astype(bv.dtype),
        dpw1[:d, :q].astype(pw1.dtype),
        dpb1[0, :q].astype(pb1.dtype),
        dpw2[0, :q].astype(pw2.dtype),
    )


_hist_score.defvjp(_hist_score_vjp_fwd, _hist_score_vjp_bwd)


def _flatten_params(attn_params: dict, pool_params: dict, dt):
    return tuple(
        p.astype(dt)
        for p in (
            attn_params["w_q"]["kernel"], attn_params["w_q"]["bias"],
            attn_params["w_k"]["kernel"], attn_params["w_k"]["bias"],
            attn_params["w_v"]["kernel"], attn_params["w_v"]["bias"],
            pool_params["att_fc1"]["kernel"], pool_params["att_fc1"]["bias"],
            pool_params["att_fc2"]["kernel"][:, 0],
        )
    )


def fused_history_score(
    his_vecs: jnp.ndarray,
    cand_vecs: jnp.ndarray,
    mask: jnp.ndarray | None,
    attn_params: dict,
    pool_params: dict,
    num_heads: int,
    block_b: int = 8,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fused user tower + scorer: (..., H, D) history (post-dropout) and
    (..., C, D) candidates -> ((..., C) scores, (..., D) user vector).

    ``attn_params``/``pool_params``: the ``self_attn``/``pool`` subtrees of
    ``UserEncoder`` (fc2's bias is a softmax-invariant shift — omitted, its
    gradient is exactly zero either way). ``mask``: optional (..., H) key
    mask, 1 = real click; fully-masked rows pool to ~0 exactly like the
    module path's multiply-after-exp epsilon semantics.
    """
    *batch, h, d = his_vecs.shape
    c = cand_vecs.shape[-2]
    n = 1
    for b in batch:
        n *= b
    dt = his_vecs.dtype
    xf = his_vecs.reshape(n, h, d)
    cf = cand_vecs.astype(dt).reshape(n, c, d)
    mf = (
        jnp.ones((n, h), jnp.float32)
        if mask is None
        else mask.reshape(n, h).astype(jnp.float32)
    )
    flat = _flatten_params(attn_params, pool_params, dt)
    scores, user = _hist_score(xf, cf, mf, *flat, num_heads, block_b)
    return scores.reshape(*batch, c), user.reshape(*batch, d)


def fused_user_vector(
    his_vecs: jnp.ndarray,
    mask: jnp.ndarray | None,
    attn_params: dict,
    pool_params: dict,
    num_heads: int,
    block_b: int = 8,
) -> jnp.ndarray:
    """The serving/eval entry: attention + pool fused, no candidates —
    ``serve.py``'s full-catalog matmul then runs on the kernel's user
    vector (one launch per request batch instead of the 5-op chain)."""
    *batch, h, d = his_vecs.shape
    dummy = jnp.zeros((*batch, 1, d), his_vecs.dtype)
    _, user = fused_history_score(
        his_vecs, dummy, mask, attn_params, pool_params, num_heads, block_b
    )
    return user


# ================================================== VMEM working-set model
def _traced_call_bytes(fn, *args) -> int:
    """Largest single pallas_call's buffered-block+scratch bytes in
    ``fn``'s jaxpr (grid-varying blocks x2 for pipeline double-buffering,
    constant-index blocks x1), via the shared traced-grid-mapping walk —
    the same machinery ``flash_vmem_working_set`` uses, so a BlockSpec
    regression in the fused kernels is catchable on CPU."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    per_call = []
    for eqn in _iter_pallas_calls(jaxpr.jaxpr):
        block, scratch = _pallas_call_buffer_bytes(eqn)
        per_call.append(block + scratch)
    if not per_call:
        raise AssertionError("no pallas_call in traced fn — fusion not routed")
    return max(per_call)


def fused_score_vmem_working_set(
    batch: int = 1024,
    his: int = 50,
    news_dim: int = 400,
    cands: int = 5,
    num_heads: int = 20,
    query_dim: int = 200,
    dtype=jnp.bfloat16,
    block_b: int = 8,
) -> dict:
    """Per-program VMEM working set of the fused history-attention+score
    kernel (fwd and bwd), bytes: traced block operands (x2 pipeline) plus
    the f32 temporaries the kernel body materializes (q/k/v/ctx copies,
    one head's score map — all heads' maps in the backward — e, and the
    dq/dk/dv assembly). Same contract as ``flash_vmem_working_set``:
    derived from the TRACED grid mappings so a layout regression fails on
    CPU without hardware."""
    dt = jnp.dtype(dtype)
    x = jax.ShapeDtypeStruct((batch, his, news_dim), dt)
    cand = jax.ShapeDtypeStruct((batch, cands, news_dim), dt)
    mask = jax.ShapeDtypeStruct((batch, his), jnp.float32)
    d = news_dim
    params = tuple(
        jax.ShapeDtypeStruct(s, dt)
        for s in [(d, d), (d,)] * 3 + [(d, query_dim), (query_dim,), (query_dim,)]
    )
    hp = his + (-his) % _sub_mult(dt)
    dp = d + (-d) % _LANE
    qp = query_dim + (-query_dim) % _LANE
    cp = cands + (-cands) % _sub_mult(dt)

    def temps(bb: int, backward: bool) -> int:
        t = 4 * bb * hp * dp * 4 + 2 * bb * hp * hp * 4 + bb * hp * qp * 4
        if backward:
            t += num_heads * bb * hp * hp * 4   # kept attention maps
            t += (3 + 1) * bb * hp * dp * 4     # dq/dk/dv + dctx
        return t

    bb_f = _score_block_b(block_b, hp, dp, qp, cp, dt.itemsize, False)
    bb_b = _score_block_b(block_b, hp, dp, qp, cp, dt.itemsize, True)
    fwd = _traced_call_bytes(
        lambda *a: _hist_score_forward(*a, num_heads, block_b), x, cand, mask,
        *params,
    ) + temps(bb_f, False)

    def loss(*a):
        s, _ = _hist_score(*a, num_heads, block_b)
        return jnp.sum(s.astype(jnp.float32))

    bwd_jaxpr_fn = jax.grad(loss, argnums=tuple(range(3, 12)))
    bwd = 0
    jaxpr = jax.make_jaxpr(bwd_jaxpr_fn)(x, cand, mask, *params)
    for eqn in _iter_pallas_calls(jaxpr.jaxpr):
        block, scratch = _pallas_call_buffer_bytes(eqn)
        bwd = max(bwd, block + scratch)
    bwd += temps(bb_b, True)
    worst = max(fwd, bwd)
    return {"forward": fwd, "backward": bwd, "worst": worst,
            "fits": worst <= VMEM_BYTES}


def fused_gather_encode_vmem_working_set(
    unique: int = 4096,
    title: int = 50,
    bert_hidden: int = 768,
    news_dim: int = 400,
    dtype=jnp.bfloat16,
) -> dict:
    """Per-program VMEM working set of the fused gather+encode kernel.

    The whole point of the scalar-prefetch layout is that ONE table row
    (not the (U, T, Dh) gather) is VMEM-resident per program — this model
    pins that: the traced block bytes are dominated by the head params and
    one (T, Dh) row, independent of U."""
    dt = jnp.dtype(dtype)
    ah = bert_hidden // 2
    table = jax.ShapeDtypeStruct((max(unique, 8), title, bert_hidden), dt)
    uniq = jax.ShapeDtypeStruct((unique,), jnp.int32)
    params = tuple(
        jax.ShapeDtypeStruct(s, dt)
        for s in [
            (bert_hidden, ah), (ah,), (ah,), (bert_hidden, news_dim),
            (news_dim,),
        ]
    )
    fwd_t = title * (ah + (-ah) % _LANE) * 4 * 2 + title * bert_hidden * 4
    fwd = _traced_call_bytes(
        lambda *a: _gather_encode(*a), table, uniq, *params
    ) + fwd_t

    def loss(t_, u_, *p):
        return jnp.sum(_gather_encode(t_, u_, *p).astype(jnp.float32))

    bwd = 0
    jaxpr = jax.make_jaxpr(
        jax.grad(loss, argnums=tuple(range(2, 7)))
    )(table, uniq, *params)
    for eqn in _iter_pallas_calls(jaxpr.jaxpr):
        block, scratch = _pallas_call_buffer_bytes(eqn)
        bwd = max(bwd, block + scratch)
    bwd += 3 * fwd_t
    worst = max(fwd, bwd)
    return {"forward": fwd, "backward": bwd, "worst": worst,
            "fits": worst <= VMEM_BYTES}
