"""Pallas TPU kernels for the framework's hot ops.

The reference's compute kernels are whatever cuDNN/MKL ships inside the torch
wheel (SURVEY.md section 2: zero native sources in-repo). Here the hot ops get
first-class TPU kernels:

  * ``flash_attention`` — blocked online-softmax attention (the user encoder's
    self-attention over click histories; keeps long histories O(L) in VMEM
    instead of materializing the (heads, L, L) score tensor the reference
    allocates, reference ``attention.py:38``).
  * ``additive_pool`` — fused learned-query additive pooling (tanh-MLP scores
    + softmax + weighted sum in one VMEM pass; reference ``attention.py:14-26``).

Both run in Pallas interpret mode on CPU (tests) and compiled on TPU, and are
routed from the Flax modules via ``ModelConfig.use_pallas``.
"""

from fedrec_tpu.ops.attention_kernels import additive_pool, flash_attention
from fedrec_tpu.ops.chunked_attention import chunked_attention
from fedrec_tpu.ops.fused_hot_path import (
    fused_gather_encode,
    fused_history_score,
    fused_user_vector,
)

__all__ = [
    "additive_pool",
    "chunked_attention",
    "flash_attention",
    "fused_gather_encode",
    "fused_history_score",
    "fused_user_vector",
]
