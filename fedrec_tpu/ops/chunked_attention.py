"""Blockwise (chunked) attention — O(L) memory long-context path, pure XLA.

The reference materializes dense ``(bz, heads, L, L)`` attention scores
(reference ``attention.py:38-44``); fine at its fixed L=50, impossible for
long histories (L=4096 at B=64 x 20 heads = 85 GB of scores). The measured
TPU answer (``benchmarks/pallas_bench.json``) is that XLA's fused dense path
beats our Pallas flash kernel at every size that FITS — the 20-dim heads pad
to 128 lanes in a hand kernel, wasting 6.4x MXU/bandwidth, while XLA packs
them. So the long-context strategy is:

  * L <= ~1k: dense XLA (fastest, fits)
  * beyond:   THIS module — ``lax.scan`` over query/key blocks with an
    online softmax, ``jax.checkpoint`` on the block body so the backward
    re-computes block scores instead of storing them (Blockwise Parallel
    Transformer style). Everything stays inside one jit region; each block
    matmul is MXU-sized; nothing O(L^2) is ever resident.
  * multi-chip: ring/Ulysses sequence parallelism (``parallel/ring.py``).

Numerics match ``flash_attention`` in ``ops/attention_kernels.py``: stable
softmax, additive -1e9 key bias for the mask, fully-masked rows return 0
(the jnp path's ``alpha * mask / (sum + 1e-8)`` semantics, reference
``attention.py:41``).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e9


def _pad_axis(x: jnp.ndarray, axis: int, mult: int, value: float = 0.0) -> jnp.ndarray:
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def chunked_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: jnp.ndarray | None = None,
    block_q: int = 256,
    block_k: int = 512,
) -> jnp.ndarray:
    """Multi-head attention, (..., L, H, D) layout like the Flax module.

    ``q``: (..., Lq, H, Dk); ``k``/``v``: (..., Lk, H, Dv); ``mask``:
    optional (..., Lk) key mask (1 = attend). Returns (..., Lq, H, Dv).
    Peak memory is O(block_q * block_k) scores per step instead of O(L^2).
    """
    *batch, lq, h, dk = q.shape
    lk, dv = k.shape[-3], v.shape[-1]
    bsz = 1
    for b in batch:
        bsz *= b
    qf = q.reshape(bsz, lq, h, dk)
    kf = k.reshape(bsz, lk, h, dk)
    vf = v.reshape(bsz, lk, h, dv)

    if mask is None:
        bias = jnp.zeros((bsz, lk), jnp.float32)
    else:
        bias = jnp.where(mask.reshape(bsz, lk) > 0, 0.0, _NEG_INF).astype(
            jnp.float32
        )

    block_q = min(block_q, max(lq, 1))
    block_k = min(block_k, max(lk, 1))

    # pad; padded keys carry -inf bias so they never win the softmax
    qp = _pad_axis(qf, 1, block_q)
    kp = _pad_axis(kf, 1, block_k)
    vp = _pad_axis(vf, 1, block_k)
    biasp = _pad_axis(bias, 1, block_k, value=_NEG_INF)
    nq, nk = qp.shape[1] // block_q, kp.shape[1] // block_k

    # (n, bsz, block, ...) chunk-leading layouts for scan
    qc = qp.reshape(bsz, nq, block_q, h, dk).transpose(1, 0, 2, 3, 4)
    kc = kp.reshape(bsz, nk, block_k, h, dk).transpose(1, 0, 2, 3, 4)
    vc = vp.reshape(bsz, nk, block_k, h, dv).transpose(1, 0, 2, 3, 4)
    bc = biasp.reshape(bsz, nk, block_k).transpose(1, 0, 2)

    scale = 1.0 / (dk**0.5)

    def attend_q_chunk(qb):
        qbf = qb.astype(jnp.float32)

        # checkpointed: the backward re-computes this block's scores from
        # (qb, kb, vb) instead of storing (block_q, block_k) residuals per
        # step — the whole point of the blockwise formulation
        @jax.checkpoint
        def kv_step(carry, inputs):
            m, l, acc = carry
            kb, vb, bb = inputs
            s = (
                jnp.einsum(
                    "bqhd,bkhd->bhqk", qbf, kb,
                    preferred_element_type=jnp.float32,
                )
                * scale
                + bb[:, None, None, :]
            )
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[..., None])
            corr = jnp.exp(m - m_new)
            l_new = l * corr + jnp.sum(p, axis=-1)
            acc_new = acc * corr[..., None] + jnp.einsum(
                "bhqk,bkhd->bhqd", p, vb.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            return (m_new, l_new, acc_new), None

        m0 = jnp.full((bsz, h, block_q), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((bsz, h, block_q), jnp.float32)
        acc0 = jnp.zeros((bsz, h, block_q, dv), jnp.float32)
        (m, l, acc), _ = lax.scan(kv_step, (m0, l0, acc0), (kc, vc, bc))
        out = acc / jnp.maximum(l, 1e-30)[..., None]  # (bsz, h, block_q, dv)
        return out.transpose(0, 2, 1, 3)  # (bsz, block_q, h, dv)

    out = lax.map(attend_q_chunk, qc)  # (nq, bsz, block_q, h, dv)
    out = out.transpose(1, 0, 2, 3, 4).reshape(bsz, nq * block_q, h, dv)
    out = out[:, :lq].astype(q.dtype)

    if mask is not None:
        has_valid = (mask.reshape(bsz, lk).sum(-1) > 0).astype(out.dtype)
        out = out * has_valid[:, None, None, None]
    return out.reshape(*batch, lq, h, dv)
