"""Evidence-driven ``model.attn_impl="auto"`` resolution.

``config.py`` used to hard-code the never-pallas comment ("dense XLA wins
at every size that fits") — true when written, but a policy frozen at one
measurement. This module reads the banked microbenchmark evidence
(``benchmarks/pallas_bench.json``) and picks the MEASURED winner for the
model's (H, dtype) regime instead, falling back to the static defaults
whenever no applicable clean evidence exists.

Evidence is applicable only when ALL of:

  * a TPU backend is live (chip measurements say nothing about the CPU
    interpret path, where tier-1 runs — off-TPU this always returns None,
    so test behavior is deterministic);
  * the artifact is complete (no ``"partial"`` flag) and its provenance
    stamps the SAME installed jax version that is resolving now — a
    runtime bump invalidates kernel timings exactly like it invalidates
    cached bench replays (``bench._cache_delta``);
  * a row of the training-relevant op ("attention fwd+bwd") exists within
    2x of the model's history length, measured at the model's dtype (rows
    without a dtype tag are float32 — the pre-ISSUE-8 artifact schema).

The winner is the smallest non-null timing among {xla_ms -> "dense",
pallas_ms -> "pallas", chunked_ms -> "chunked"} on the nearest-H row
(log-space distance). Results are cached per (path, mtime, H, dtype,
backend) so the file is read once per process, not once per trace.
"""

from __future__ import annotations

import functools
import json
import math
from pathlib import Path

_DEFAULT_PATH = (
    Path(__file__).resolve().parents[2] / "benchmarks" / "pallas_bench.json"
)
_COLS = {"xla_ms": "dense", "pallas_ms": "pallas", "chunked_ms": "chunked"}


def _current_jax_version() -> str | None:
    from importlib import metadata

    try:
        return metadata.version("jax")
    except Exception:  # noqa: BLE001
        return None


@functools.lru_cache(maxsize=64)
def _resolve(path_str: str, mtime_ns: int, seq_len: int, dtype: str,
             backend: str) -> str | None:
    if backend != "tpu":
        return None
    try:
        artifact = json.loads(Path(path_str).read_text())
    except Exception:  # noqa: BLE001 — absent/corrupt artifact = no evidence
        return None
    if artifact.get("partial"):
        return None
    stamped = (
        (artifact.get("provenance") or {}).get("runtime_versions") or {}
    ).get("jax")
    if stamped is None or stamped != _current_jax_version():
        # unknowable or stale runtime: timings describe another jax —
        # the same fail-unsafe rule the cached-bench verdict applies
        return None
    best_row, best_dist = None, None
    for row in artifact.get("rows") or []:
        if row.get("op") != "attention fwd+bwd":
            continue
        if row.get("dtype", "float32") != dtype:
            continue
        h = row.get("H")
        if not h or not any(row.get(c) is not None for c in _COLS):
            continue
        dist = abs(math.log(h / seq_len))
        if best_dist is None or dist < best_dist:
            best_row, best_dist = row, dist
    if best_row is None or best_dist > math.log(2.0):
        return None  # no row within 2x of this regime
    timed = {
        impl: best_row[col]
        for col, impl in _COLS.items()
        if best_row.get(col) is not None
    }
    winner = min(timed, key=timed.get)
    if winner == "dense" and best_row["H"] < seq_len:
        # a dense win does NOT extrapolate upward: the score tensor is
        # O(L^2) and a row that fit at H says nothing about memory
        # feasibility at 2x H (the regime the chunk_threshold guard
        # exists for). O(L) winners (pallas/chunked) extrapolate fine;
        # dense evidence applies at its own H and below only.
        return None
    return winner


def measured_attn_impl(
    seq_len: int,
    dtype,
    path: Path | str | None = None,
    backend: str | None = None,
) -> str | None:
    """The measured attention winner for this (H, dtype) regime, or None
    when no provenance-clean evidence applies (caller falls back to the
    static defaults). ``backend``/``path`` are injectable for tests."""
    import jax
    import jax.numpy as jnp

    p = Path(path) if path is not None else _DEFAULT_PATH
    try:
        mtime = p.stat().st_mtime_ns
    except OSError:
        return None
    if backend is None:
        backend = jax.default_backend()
    return _resolve(
        str(p), mtime, int(seq_len), jnp.dtype(dtype).name, backend
    )
