"""Ranking metrics: AUC / MRR / NDCG, host-side (numpy) and device-side (jnp).

Semantics match the reference ``evaluation_functions.py:5-47`` (DCG with
``2**rel - 1`` gains and log2 discounts, MRR normalized by the positive count,
binary AUC) with two deliberate divergences, both recorded in the parity
ledger:

  * AUC is computed natively (Mann-Whitney U with average-rank tie handling,
    identical to ``sklearn.roc_auc_score`` for binary labels) so the device
    path has no sklearn dependency.
  * Aggregation over a validation set is the *mean over impressions* — the
    reference computes per-impression lists but returns only the final
    sample's metrics (bug at reference ``client.py:166-171``).

The jnp batch variant assumes the reference's fixed impression layout: one
positive at slot 0 + ``npratio`` sampled negatives (reference
``dataset.py:79-86``), which makes every metric a closed-form function of the
positive's rank — ideal for the VPU (no sort needed, just comparisons).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

# --------------------------------------------------------------------------
# host-side (numpy) — API parity with reference evaluation_functions.py
# --------------------------------------------------------------------------


def dcg_score(y_true: np.ndarray, y_score: np.ndarray, k: int = 10) -> float:
    """DCG@k with (2**rel - 1) gains (reference ``evaluation_functions.py:5-10``)."""
    y_true = np.asarray(y_true, dtype=np.float64)
    y_score = np.asarray(y_score, dtype=np.float64)
    order = np.argsort(y_score)[::-1]
    taken = np.take(y_true, order[:k])
    gains = 2.0**taken - 1.0
    discounts = np.log2(np.arange(len(taken)) + 2.0)
    return float(np.sum(gains / discounts))


def ndcg_score(y_true: np.ndarray, y_score: np.ndarray, k: int = 10) -> float:
    """NDCG@k (reference ``evaluation_functions.py:13-16``)."""
    best = dcg_score(y_true, y_true, k)
    actual = dcg_score(y_true, y_score, k)
    return actual / best


def mrr_score(y_true: np.ndarray, y_score: np.ndarray) -> float:
    """Mean reciprocal rank over positives (reference ``evaluation_functions.py:19-23``)."""
    y_true = np.asarray(y_true, dtype=np.float64)
    y_score = np.asarray(y_score, dtype=np.float64)
    order = np.argsort(y_score)[::-1]
    ranked = np.take(y_true, order)
    rr = ranked / (np.arange(len(ranked)) + 1.0)
    return float(np.sum(rr) / np.sum(y_true))


def auc_score(y_true: np.ndarray, y_score: np.ndarray) -> float:
    """Binary ROC-AUC via the Mann-Whitney U statistic with average ranks.

    Equivalent to ``sklearn.metrics.roc_auc_score`` for binary labels
    (reference imports sklearn at ``evaluation_functions.py:3``); implemented
    natively so eval has no sklearn dependency.
    """
    y_true = np.asarray(y_true, dtype=np.float64)
    y_score = np.asarray(y_score, dtype=np.float64)
    n_pos = float(np.sum(y_true == 1))
    n_neg = float(np.sum(y_true == 0))
    if n_pos == 0 or n_neg == 0:
        raise ValueError("AUC undefined: need at least one positive and one negative")
    # average ranks (1-based) with tie correction
    order = np.argsort(y_score, kind="mergesort")
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(y_score) + 1, dtype=np.float64)
    sorted_scores = y_score[order]
    # assign average rank within tie groups
    i = 0
    while i < len(sorted_scores):
        j = i
        while j + 1 < len(sorted_scores) and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        if j > i:
            avg = 0.5 * (i + 1 + j + 1)
            ranks[order[i : j + 1]] = avg
        i = j + 1
    rank_sum_pos = float(np.sum(ranks[y_true == 1]))
    u = rank_sum_pos - n_pos * (n_pos + 1) / 2.0
    return u / (n_pos * n_neg)


def safe_auc_score(y_true: np.ndarray, y_score: np.ndarray) -> float:
    """Degenerate-safe :func:`auc_score`: NaN instead of ValueError when
    the label set holds only one class.

    The host-side variant for sliced/stratified analysis: a stratum that
    happens to be all-positive (or all-negative) has no defined AUC, and
    ``auc_score``'s raise would abort a whole sliced pass — NaN lets the
    caller skip that stratum and keep the rest.  (The in-graph sliced
    eval never hits this case — its per-impression closed forms always
    see 1 positive + the real negatives, and empty strata are skipped by
    count, ``eval.slices_skipped_total``.)  ``auc_score`` itself keeps
    raising — ``evaluation_split``'s try/except skip is reference
    parity.
    """
    y_true = np.asarray(y_true, dtype=np.float64)
    if np.sum(y_true == 1) == 0 or np.sum(y_true == 0) == 0:
        return float("nan")
    return auc_score(y_true, y_score)


def compute_amn(y_true: np.ndarray, y_score: np.ndarray) -> tuple[float, float, float, float]:
    """(AUC, MRR, NDCG@5, NDCG@10) — reference ``evaluation_functions.py:26-31``."""
    return (
        auc_score(y_true, y_score),
        mrr_score(y_true, y_score),
        ndcg_score(y_true, y_score, 5),
        ndcg_score(y_true, y_score, 10),
    )


def evaluation_split(
    news_vecs: np.ndarray,
    user_vecs: np.ndarray,
    samples: list,
    nid2index: dict,
) -> np.ndarray:
    """Offline split evaluation (reference ``evaluation_functions.py:33-47``).

    For each impression: scores = news_vec . user_vec over positives +
    negatives; returns an (n_valid, 4) array of per-impression (AUC, MRR,
    NDCG@5, NDCG@10). Impressions whose metrics are undefined (e.g. no
    negatives) are skipped, as the reference's try/except does.
    """
    results = []
    for i, sample in enumerate(samples):
        _, poss, negs, _, _ = sample
        if isinstance(poss, str):
            poss = [poss]
        user_vec = user_vecs[i]
        y_true = np.array([1] * len(poss) + [0] * len(negs))
        news_ids = [nid2index[n] for n in list(poss) + list(negs)]
        scores = news_vecs[news_ids] @ user_vec
        try:
            results.append(compute_amn(y_true, scores))
        except ValueError:
            continue
    return np.array(results)


# --------------------------------------------------------------------------
# device-side (jnp) — vectorized closed forms for the fixed 1-pos + K-neg layout
# --------------------------------------------------------------------------


def _metrics_from_rank(rank: jnp.ndarray) -> dict:
    """MRR/NDCG@5/NDCG@10 from the positive's 1-based rank — the shared
    closed forms (single positive, ideal DCG = 1). AUC differs between the
    fixed-C and masked-pool layouts, so each caller supplies its own."""
    mrr = 1.0 / rank
    ndcg = 1.0 / jnp.log2(rank + 1.0)
    return {
        "mrr": mrr,
        "ndcg5": jnp.where(rank <= 5, ndcg, 0.0),
        "ndcg10": jnp.where(rank <= 10, ndcg, 0.0),
    }


def ranking_metrics_batch(scores: jnp.ndarray, positive_index: int = 0) -> dict:
    """Per-impression AUC/MRR/NDCG@5/NDCG@10 for fixed-size impressions, on device.

    ``scores``: (B, C) candidate scores where column ``positive_index`` is the
    single positive (reference layout ``dataset.py:83,86``: positive at slot 0,
    label 0). With one positive among C candidates every metric depends only on
    the positive's rank r (1-based):

      AUC      = (C - r) / (C - 1)         (fraction of negatives outranked)
      MRR      = 1 / r
      NDCG@k   = 1/log2(r+1) if r <= k else 0

    Ties are broken pessimistically against the positive (a negative with an
    equal score outranks it), matching ``np.argsort``'s stable descending-order
    behavior in the host metrics for the common all-distinct case and giving a
    deterministic device result.
    """
    scores = jnp.asarray(scores)
    b, c = scores.shape
    pos = scores[:, positive_index][:, None]
    # rank = 1 + number of candidates (excluding self) with score >= positive
    others = jnp.concatenate(
        [scores[:, :positive_index], scores[:, positive_index + 1 :]], axis=1
    )
    rank = 1.0 + jnp.sum(others >= pos, axis=1).astype(jnp.float32)
    return {"auc": (c - rank) / (c - 1), **_metrics_from_rank(rank)}


def full_pool_metrics_batch(
    pos_scores: jnp.ndarray,
    neg_scores: jnp.ndarray,
    neg_mask: jnp.ndarray,
) -> dict:
    """Per-impression AUC/MRR/NDCG over each impression's FULL negative pool.

    ``pos_scores``: (B,) the single positive's score per impression.
    ``neg_scores``: (B, P) scores over the padded negative pool.
    ``neg_mask``:   (B, P) 1.0 for real negatives, 0.0 for padding.

    Deterministic full-pool evaluation — the reference's published MIND
    numbers are full-pool (``evaluation_split``, reference
    ``evaluation_functions.py:33-47``), not npratio-sampled. With one
    positive the closed forms still hold with n_neg = mask sum:

      rank r   = 1 + #{real negatives with score >= positive}
      AUC      = (n_neg - (r - 1)) / n_neg
      MRR      = 1 / r
      NDCG@k   = 1/log2(r+1) if r <= k else 0   (ideal DCG = 1)

    Impressions with zero real negatives get AUC 0 and must be masked out by
    the caller (the reference skips them via try/except).
    """
    pos = jnp.asarray(pos_scores)[:, None]
    neg = jnp.asarray(neg_scores)
    mask = jnp.asarray(neg_mask, jnp.float32)
    n_neg = jnp.sum(mask, axis=1)
    beaten_by = jnp.sum((neg >= pos) * mask, axis=1)
    rank = 1.0 + beaten_by
    auc = jnp.where(n_neg > 0, (n_neg - beaten_by) / jnp.maximum(n_neg, 1.0), 0.0)
    return {"auc": auc, **_metrics_from_rank(rank)}


# --------------------------------------------------------------------------
# device-side quality stats: fixed-shape score histograms + reliability bins
# --------------------------------------------------------------------------

# every key quality_stats_batch returns — the step builders key their
# sharding specs off this, the host accumulator its sums
QUALITY_SUM_KEYS = (
    "q.pos_hist", "q.neg_hist",
    "q.pos_sum", "q.pos_sq", "q.pos_n",
    "q.neg_sum", "q.neg_sq", "q.neg_n",
    "q.cal_n", "q.cal_conf", "q.cal_label",
)


def _fixed_bin_counts(
    values: jnp.ndarray, weights: jnp.ndarray, lo: float, hi: float, bins: int
) -> jnp.ndarray:
    """Weighted fixed-bin histogram counts, fully in-graph.

    ``bins`` equal-width buckets over [lo, hi); out-of-range values clamp
    to the edge bins (a score histogram must never lose mass to an
    unlucky range guess).  One-hot matmul keeps every shape static — no
    host sync, no data-dependent shapes."""
    import jax

    width = (hi - lo) / bins
    idx = jnp.clip(jnp.floor((values - lo) / width), 0, bins - 1).astype(jnp.int32)
    # NaN scores floor to index 0 via clip-of-NaN -> 0 after astype; mask
    # them out entirely instead (a non-finite score is the sentry's
    # problem, not a histogram bin)
    w = jnp.where(jnp.isfinite(values), weights, 0.0)
    onehot = jax.nn.one_hot(idx, bins, dtype=jnp.float32)
    return jnp.einsum("...b,...->b", onehot, w.astype(jnp.float32))


def quality_stats_batch(
    pos_scores: jnp.ndarray,
    neg_scores: jnp.ndarray,
    neg_mask: jnp.ndarray,
    keep: jnp.ndarray,
    score_bins: int,
    score_range: float,
    ece_bins: int,
) -> dict:
    """Score-distribution + calibration partial sums for one eval batch.

    All outputs are FIXED-shape reductions (no data-dependent shapes, no
    host syncs) so the jitted full-pool eval pass can return them next to
    its per-impression metrics:

      * ``q.pos_hist`` / ``q.neg_hist``: (score_bins,) weighted counts of
        positive / real-negative scores over
        ``[-score_range, +score_range]`` (edge bins absorb outliers);
      * ``q.pos_sum`` / ``q.pos_sq`` / ``q.pos_n`` (and ``neg_``
        equivalents): moments for separation stats;
      * ``q.cal_n`` / ``q.cal_conf`` / ``q.cal_label``: (ece_bins,)
        reliability-table partial sums over ``sigmoid(score)`` with label
        1 for positives, 0 for negatives — ECE is a closed form of these.

    ``keep`` (B,) zeroes padded impressions; ``neg_mask`` (B, P) zeroes
    pool padding. Pinned hand-exact against a numpy reference in
    ``tests/test_quality.py``.
    """
    pos = jnp.asarray(pos_scores)
    neg = jnp.asarray(neg_scores)
    keep = jnp.asarray(keep, jnp.float32)
    nw = jnp.asarray(neg_mask, jnp.float32) * keep[:, None]

    out = {
        "q.pos_hist": _fixed_bin_counts(
            pos, keep, -score_range, score_range, score_bins
        ),
        "q.neg_hist": _fixed_bin_counts(
            neg, nw, -score_range, score_range, score_bins
        ),
        "q.pos_sum": jnp.sum(pos * keep),
        "q.pos_sq": jnp.sum(pos * pos * keep),
        "q.pos_n": jnp.sum(keep),
        "q.neg_sum": jnp.sum(neg * nw),
        "q.neg_sq": jnp.sum(neg * neg * nw),
        "q.neg_n": jnp.sum(nw),
    }
    # reliability bins over predicted click probability sigmoid(s):
    # bin b covers [b/B, (b+1)/B); prob 1.0 clamps into the last bin
    prob_pos = 1.0 / (1.0 + jnp.exp(-pos))
    prob_neg = 1.0 / (1.0 + jnp.exp(-neg))
    out["q.cal_n"] = _fixed_bin_counts(prob_pos, keep, 0.0, 1.0, ece_bins) + \
        _fixed_bin_counts(prob_neg, nw, 0.0, 1.0, ece_bins)
    out["q.cal_conf"] = _fixed_bin_counts(
        prob_pos, prob_pos * keep, 0.0, 1.0, ece_bins
    ) + _fixed_bin_counts(prob_neg, prob_neg * nw, 0.0, 1.0, ece_bins)
    # labels: positives contribute 1 per impression, negatives 0 — the
    # label sum is just the positives' bin counts
    out["q.cal_label"] = _fixed_bin_counts(prob_pos, keep, 0.0, 1.0, ece_bins)
    return out
