from fedrec_tpu.eval.metrics import (
    auc_score,
    compute_amn,
    dcg_score,
    evaluation_split,
    mrr_score,
    ndcg_score,
    full_pool_metrics_batch,
    ranking_metrics_batch,
)

__all__ = [
    "auc_score",
    "compute_amn",
    "dcg_score",
    "evaluation_split",
    "mrr_score",
    "ndcg_score",
    "full_pool_metrics_batch",
    "ranking_metrics_batch",
]
