from fedrec_tpu.eval.metrics import (
    auc_score,
    compute_amn,
    dcg_score,
    evaluation_split,
    mrr_score,
    ndcg_score,
    full_pool_metrics_batch,
    quality_stats_batch,
    ranking_metrics_batch,
    safe_auc_score,
)

__all__ = [
    "auc_score",
    "compute_amn",
    "dcg_score",
    "evaluation_split",
    "mrr_score",
    "ndcg_score",
    "full_pool_metrics_batch",
    "quality_stats_batch",
    "ranking_metrics_batch",
    "safe_auc_score",
]
