"""Batched top-k recommendation over the news table — the serving path.

The reference stops at validation (``client.py:149-171``); it has no way to
actually produce recommendations for a user. A recommender framework needs
one, so this closes the loop: given trained user-tower params and the
``(N, D)`` news-vector table (from ``encode_all_news`` /
``encode_corpus_tokens``), score EVERY news item for a batch of users in one
jitted program and return the top-k ids and scores.

TPU shape: the full-catalog scoring is a single ``(B, D) x (D, N)`` matmul —
MXU-friendly at any realistic catalog size (MIND-small: N≈65k, D=400 →
26 MFLOP/user) — followed by an in-HBM masked ``lax.top_k``. No host
round-trips besides the final (B, k) result.

History items are excluded by default (recommending something the user just
read is a wasted slot); id 0 — the reference's history pad slot
(``dataset.py:83-85``) — is always excluded.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from fedrec_tpu.models import NewsRecommender

_NEG = jnp.finfo(jnp.float32).min


def build_recommend_fn(
    model: NewsRecommender,
    top_k: int = 10,
    exclude_history: bool = True,
    valid_mask: jnp.ndarray | None = None,
) -> Callable:
    """Compile ``recommend(user_params, news_vecs, history) -> (ids, scores)``.

    ``history``: (B, H) int32 clicked-news ids, 0-padded like training
    batches. Returns ``ids`` (B, k) int32 and ``scores`` (B, k) float32,
    best first, with ``k = min(top_k, N)``. When fewer than ``k`` valid
    items exist (tiny catalog, long history), the tail slots carry id ``-1``
    and the float32-min sentinel score — callers truncate at the first -1.

    ``valid_mask``: optional (N,) bool — False rows are never recommended.
    Real artifacts need this: the reference's own demo shard has more token
    rows than mapped nids (225 vs 139), and an unmapped row has no id to
    report.
    """
    if valid_mask is not None:
        valid_mask = jnp.asarray(valid_mask, bool)

    def recommend(user_params: Any, news_vecs: jnp.ndarray, history: jnp.ndarray):
        his_vecs = news_vecs[history]  # (B, H, D)
        user_vec = model.apply(
            {"params": {"user_encoder": user_params}},
            his_vecs,
            method=NewsRecommender.encode_user,
        )  # (B, D)
        scores = jnp.einsum(
            "bd,nd->bn", user_vec.astype(jnp.float32), news_vecs.astype(jnp.float32)
        )
        n = news_vecs.shape[0]
        # drop the pad slot, and (optionally) everything already clicked
        invalid = jnp.zeros((history.shape[0], n), bool).at[:, 0].set(True)
        if valid_mask is not None:
            invalid = invalid | ~valid_mask[None, :]
        if exclude_history:
            rows = jnp.arange(history.shape[0])[:, None]
            invalid = invalid.at[rows, history].set(True)
        scores = jnp.where(invalid, _NEG, scores)
        top_scores, top_ids = lax.top_k(scores, min(top_k, n))
        top_ids = jnp.where(top_scores <= _NEG, -1, top_ids)
        return top_ids.astype(jnp.int32), top_scores

    return jax.jit(recommend)
