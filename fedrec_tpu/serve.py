"""Batched top-k recommendation over the news table — the serving path.

The reference stops at validation (``client.py:149-171``); it has no way to
actually produce recommendations for a user. A recommender framework needs
one, so this closes the loop: given trained user-tower params and the
``(N, D)`` news-vector table (from ``encode_all_news`` /
``encode_corpus_tokens``), score EVERY news item for a batch of users in one
jitted program and return the top-k ids and scores.

TPU shape: the full-catalog scoring is a single ``(B, D) x (D, N)`` matmul —
MXU-friendly at any realistic catalog size (MIND-small: N≈65k, D=400 →
26 MFLOP/user) — followed by an in-HBM masked ``lax.top_k``. No host
round-trips besides the final (B, k) result.

History items are excluded by default (recommending something the user just
read is a wasted slot); id 0 — the reference's history pad slot
(``dataset.py:83-85``) — is always excluded.

With ``model.fuse_hot_path`` the user encoding inside both scorers rides
the fused attention+pool Pallas kernel (``ops.fused_user_vector`` via
``encode_user`` — one launch per request batch instead of the projection/
attention/pool op chain), then the full-catalog matmul runs as before;
parity with the dense model is pinned in ``tests/test_fused_hot_path.py``.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from fedrec_tpu.compat import shard_map
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from fedrec_tpu.models import NewsRecommender

_NEG = jnp.finfo(jnp.float32).min


def _exclude_ids(invalid: jnp.ndarray, ids: jnp.ndarray, n: int) -> jnp.ndarray:
    """Mark ``ids`` (B, H) invalid in the (B, n) mask via boolean
    scatter-max; ids outside ``[0, n)`` are no-ops. Shared by the dense
    and sharded scorers so their degenerate-input semantics cannot drift
    apart — JAX's default scatter mode (promise_in_bounds) would WRAP a
    negative id and exclude real item ``n-|id|``."""
    rows = jnp.arange(ids.shape[0])[:, None]
    in_range = (ids >= 0) & (ids < n)
    safe = jnp.clip(ids, 0, n - 1)
    return invalid.at[rows, safe].max(in_range)


def build_recommend_fn(
    model: NewsRecommender,
    top_k: int = 10,
    exclude_history: bool = True,
    valid_mask: jnp.ndarray | None = None,
) -> Callable:
    """Compile ``recommend(user_params, news_vecs, history) -> (ids, scores)``.

    ``history``: (B, H) int32 clicked-news ids, 0-padded like training
    batches; ids outside ``[0, N)`` are ignored by the EXCLUSION mask
    (identically in the dense and sharded scorers) — but the history
    GATHER that feeds the user encoding clamps them into range (explicitly,
    identically in both scorers), so garbage ids still perturb the user
    vector — deterministically. Returns ``ids``
    (B, k) int32 and ``scores`` (B, k) float32,
    best first, with ``k = min(top_k, N)``. When fewer than ``k`` valid
    items exist (tiny catalog, long history), the tail slots carry id ``-1``
    and the float32-min sentinel score — callers truncate at the first -1.

    ``valid_mask``: optional (N,) bool — False rows are never recommended.
    Real artifacts need this: the reference's own demo shard has more token
    rows than mapped nids (225 vs 139), and an unmapped row has no id to
    report.
    """
    if valid_mask is not None:
        valid_mask = jnp.asarray(valid_mask, bool)

    def recommend(user_params: Any, news_vecs: jnp.ndarray, history: jnp.ndarray):
        # clamp the gather indices explicitly: out-of-range ids otherwise
        # hit XLA's OOB gather lowering, which differs between the dense
        # and sharded partitionings (and across XLA versions) — clamping
        # pins one deterministic degenerate-input behavior for both paths
        his_vecs = news_vecs[jnp.clip(history, 0, news_vecs.shape[0] - 1)]  # (B, H, D)
        user_vec = model.apply(
            {"params": {"user_encoder": user_params}},
            his_vecs,
            method=NewsRecommender.encode_user,
        )  # (B, D)
        scores = jnp.einsum(
            "bd,nd->bn", user_vec.astype(jnp.float32), news_vecs.astype(jnp.float32)
        )
        n = news_vecs.shape[0]
        # drop the pad slot, and (optionally) everything already clicked
        invalid = jnp.zeros((history.shape[0], n), bool).at[:, 0].set(True)
        if valid_mask is not None:
            invalid = invalid | ~valid_mask[None, :]
        if exclude_history:
            invalid = _exclude_ids(invalid, history, n)
        scores = jnp.where(invalid, _NEG, scores)
        top_scores, top_ids = lax.top_k(scores, min(top_k, n))
        top_ids = jnp.where(top_scores <= _NEG, -1, top_ids)
        return top_ids.astype(jnp.int32), top_scores

    return jax.jit(recommend)


def build_recommend_fn_sharded(
    model: NewsRecommender,
    mesh: Mesh,
    top_k: int = 10,
    exclude_history: bool = True,
    valid_mask: jnp.ndarray | None = None,
) -> Callable:
    """Mesh-sharded full-catalog scorer: same contract as
    :func:`build_recommend_fn`, but the news table — and the (B, N) score
    matrix, serving's memory/compute bottleneck — is sharded over EVERY
    mesh axis (the :func:`fedrec_tpu.train.step.encode_all_news_sharded`
    layout). Each device scores its N/mesh.size catalog shard, takes a
    LOCAL top-k, and one tiled ``all_gather`` of the (B, k) candidates +
    a second ``top_k`` merges them: every global top-k item is by
    construction in its own shard's local top-k, so the merge is exact.
    The full score matrix never exists on one device, so the catalog and
    the user batch scale with the mesh instead of a single chip's HBM
    (VERDICT r3 #6: the serving path must ride the mesh the eval path
    already has).

    History exclusion is computed per shard with a scatter (``.at[].max``)
    on ids translated to shard-local coordinates — never a (B, N, H)
    membership tensor.
    """
    axes = tuple(mesh.axis_names)
    nd = mesh.size
    if valid_mask is not None:
        valid_mask = jnp.asarray(valid_mask, bool)

    def recommend(user_params: Any, news_vecs: jnp.ndarray, history: jnp.ndarray):
        n, d = news_vecs.shape
        pad = (-n) % nd
        table = jnp.pad(news_vecs, ((0, pad), (0, 0))) if pad else news_vecs
        valid = (
            jnp.ones(n, bool) if valid_mask is None else valid_mask
        )
        valid = jnp.pad(valid, (0, pad)) if pad else valid  # pad rows False
        # user encoding is tiny ((B, H, D)); the history gather over the
        # sharded table is a global-semantics take — XLA inserts the
        # collective pieces it needs. Indices clamped exactly like the
        # dense path, so degenerate ids cannot diverge across paths
        his_vecs = news_vecs[jnp.clip(history, 0, n - 1)]
        user_vec = model.apply(
            {"params": {"user_encoder": user_params}},
            his_vecs,
            method=NewsRecommender.encode_user,
        ).astype(jnp.float32)
        k_local = min(top_k, table.shape[0] // nd)

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(axes, None), P(axes), P()),
            out_specs=(P(), P()),
            check_vma=False,
        )
        def shard_topk(uv, table_local, valid_local, hist):
            n_local = table_local.shape[0]
            base = lax.axis_index(axes) * n_local
            scores = jnp.einsum(
                "bd,nd->bn", uv, table_local.astype(jnp.float32)
            )  # (B, n_local)
            gids = base + jnp.arange(n_local)
            invalid = jnp.broadcast_to(
                (~valid_local | (gids == 0))[None, :],
                (hist.shape[0], n_local),
            )
            if exclude_history:
                # shard-local coordinates: out-of-shard ids fall outside
                # [0, n_local) and are no-ops
                invalid = _exclude_ids(invalid, hist - base, n_local)
            scores = jnp.where(invalid, _NEG, scores)
            s_loc, i_loc = lax.top_k(scores, k_local)
            g_loc = base + i_loc
            # (B, k_local) per shard -> (B, nd * k_local) candidates
            s_all = lax.all_gather(s_loc, axes, axis=1, tiled=True)
            g_all = lax.all_gather(g_loc, axes, axis=1, tiled=True)
            k = min(top_k, n)
            s_top, pick = lax.top_k(s_all, k)
            g_top = jnp.take_along_axis(g_all, pick, axis=1)
            return g_top.astype(jnp.int32), s_top

        top_ids, top_scores = shard_topk(user_vec, table, valid, history)
        top_ids = jnp.where(top_scores <= _NEG, -1, top_ids)
        return top_ids, top_scores

    return jax.jit(recommend)
