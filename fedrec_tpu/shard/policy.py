"""Size-aware FSDP parameter-sharding policy (``shard.fsdp``).

THE one pytree -> ``NamedSharding`` rule for the at-rest client state
(SNIPPETS [2], the largest-evenly-divisible-dimension rule):

    * scalars and 1-D arrays -> replicated over the fsdp axis;
    * sub-threshold arrays (``shard.fsdp_min_size_mb``) -> replicated;
    * 2-D+ arrays -> sharded along the LARGEST dimension the fsdp axis
      size divides evenly;
    * no divisible dimension -> replicated (fallback).

When ``mesh.shape[FSDP_AXIS] == 1`` every leaf is replicated, making the
result equivalent to pure data parallelism — the degenerate contract the
trajectory tests pin (``tests/test_shard_fsdp.py``).

Two entry points:

* :func:`fsdp_shardings` — the bare rule over any pytree of arrays or
  ``jax.ShapeDtypeStruct`` leaves (``jax.eval_shape`` output), for
  params/optimizer trees without a client dimension;
* :func:`fsdp_state_shardings` — the stacked-``ClientState`` form the
  Trainer uses: every leaf carries a leading ``(num_clients,)`` dim
  pinned to the client mesh axis, and the rule applies to the PER-CLIENT
  dims behind it (the threshold too — "is one client's leaf worth
  sharding", independent of cohort size).

The Trainer keeps state AT REST in this layout (params, optimizer
moments, grad accumulators, codec residuals all shard); each compiled
step gathers on entry (the ``shard_map`` in-spec forces it) and
re-shards on exit via ``jax.lax.with_sharding_constraint`` — ZeRO-style
residency sharding, one all-gather/slice pair per dispatch, value-exact
by construction. docs/DESIGN.md §5i.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedrec_tpu.parallel.mesh import FSDP_AXIS

__all__ = [
    "FSDP_AXIS",
    "fsdp_leaf_sharding",
    "fsdp_shardings",
    "fsdp_state_shardings",
    "shard_bytes_per_device",
]


def fsdp_leaf_sharding(
    leaf: Any,
    mesh: Mesh,
    min_size_mbytes: float = 4.0,
    axis: str = FSDP_AXIS,
    lead_spec: tuple = (),
) -> NamedSharding:
    """The rule for ONE leaf (array or ``ShapeDtypeStruct``).

    ``lead_spec`` pins leading dims to mesh axes before the rule applies
    (the stacked-state form pins dim 0 to the clients axis); the size
    threshold and the dimensionality test then see only the remaining
    per-client dims.
    """
    fsdp_size = int(mesh.shape[axis])
    lead = tuple(lead_spec)
    shape = tuple(leaf.shape)[len(lead):]
    base = NamedSharding(mesh, P(*lead))
    if fsdp_size == 1 or len(shape) < 2:
        return base  # rule 2: scalars and 1-D replicate (and fsdp=1 = off)
    size_mb = (
        float(np.prod(shape)) * np.dtype(leaf.dtype).itemsize / (1024 * 1024)
    )
    if size_mb < min_size_mbytes:
        return base  # rule 1: small arrays replicate
    # rule 3: shard along the largest evenly-divisible dimension
    spec: list = list(lead) + [None] * len(shape)
    for i in np.argsort(shape)[::-1]:
        if shape[i] % fsdp_size == 0:
            spec[len(lead) + int(i)] = axis
            return NamedSharding(mesh, P(*spec))
    return base  # fallback: no divisible dim -> replicate


def fsdp_shardings(
    pytree: Any,
    mesh: Mesh,
    min_size_mbytes: float = 4.0,
    axis: str = FSDP_AXIS,
) -> Any:
    """Apply the rule to every leaf of ``pytree`` (e.g. a param tree from
    ``jax.eval_shape``); returns a matching pytree of ``NamedSharding``."""
    return jax.tree_util.tree_map(
        lambda x: fsdp_leaf_sharding(x, mesh, min_size_mbytes, axis), pytree
    )


def fsdp_state_shardings(state: Any, mesh: Mesh, cfg: Any) -> Any | None:
    """Shardings for a stacked ``ClientState`` (leading clients dim), or
    ``None`` when fsdp is off / the mesh has no fsdp axis — the builders
    treat ``None`` as "emit the exact pre-fsdp program", which is what
    makes the ``fsdp=1`` degenerate config bit-identical by construction.

    ``state`` may be concrete arrays or the ``jax.eval_shape`` abstraction
    of ``replicate_state(init_client_state(...))`` — shapes and dtypes are
    all the rule reads.
    """
    shard_cfg = getattr(cfg, "shard", None)
    if shard_cfg is None or shard_cfg.fsdp <= 1:
        return None
    if FSDP_AXIS not in mesh.axis_names:
        return None
    lead = (cfg.fed.mesh_axis,)
    return jax.tree_util.tree_map(
        lambda x: fsdp_leaf_sharding(
            x, mesh, shard_cfg.fsdp_min_size_mb, FSDP_AXIS, lead
        ),
        state,
    )


def reshard_state(state: Any, mesh: Mesh, cfg: Any) -> Any:
    """Re-commit a HOST-complete state pytree (e.g. the last
    ``gather_for_save`` checkpoint, or a multihost gather of the
    survivors' shards) to a re-formed mesh's at-rest layout — the
    FSDP half of shrink-and-continue.  The policy re-derives per-leaf
    shardings for the NEW mesh (the fsdp axis size may have changed with
    the world), so a state sharded 4-way re-commits 2-way without any
    layout assumptions carried over; with ``shard.fsdp <= 1`` (or no fsdp
    axis) it falls back to the classic leading-dim client sharding —
    exactly the Trainer's ``_place_state`` rule, value-exact by
    construction (host bytes in, host bytes out; only residency moves).

    Production resumes go through the Trainer (``adopt_state`` →
    ``_place_state``); this is the LIBRARY twin for host-side tooling
    (and the unit pin that the contract holds across a world change,
    ``tests/test_membership.py``) — keep the two rules in lockstep.
    """
    import jax.numpy as jnp
    from fedrec_tpu.parallel.mesh import client_sharding

    shardings = fsdp_state_shardings(state, mesh, cfg)
    if shardings is None:
        sharding = client_sharding(mesh, cfg.fed.mesh_axis)
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(jnp.asarray(x), sharding), state
        )
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(jnp.asarray(x), s), state, shardings
    )


def shard_bytes_per_device(state: Any, shardings: Any) -> int:
    """At-rest bytes ONE device holds under ``shardings`` — the number the
    ``shard.state_bytes_per_device`` gauge publishes, so an operator can
    read the residency win (vs the replicated ``sum(leaf.nbytes)``)
    straight off a scrape."""
    total = 0
    for leaf, sh in zip(
        jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(shardings)
    ):
        nbytes = float(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
        factor = 1
        for dim, name in zip(leaf.shape, sh.spec + (None,) * len(leaf.shape)):
            if name is not None:
                factor *= int(sh.mesh.shape[name] if isinstance(name, str)
                              else np.prod([sh.mesh.shape[n] for n in name]))
        total += nbytes / max(factor, 1)
    return int(total)
