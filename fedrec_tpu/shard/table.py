"""Mesh-sharded news catalog (``shard.table``): the token-state table
row-sharded across the client mesh axis, with an in-step fixed-shape
owner-bucketed gather.

Today the frozen ``token_states`` table is replicated on every device, so
catalog size is capped by single-device HBM (ROADMAP item 2). Here the
table lives row-sharded — device *s* of *S* holds rows
``[s*R, (s+1)*R)`` — and the step's unique-news gather becomes a
four-phase exchange, every shape static so nothing retraces:

    1. BUCKET   each client's ``(U,)`` unique ids by owner shard
                (``owner = id // R``) into an ``(S, U)`` request buffer —
                bucket capacity U is the worst case (all ids on one
                shard), so no id can ever be dropped;
    2. A2A OUT  ``lax.all_to_all`` the id buckets: shard *s* receives the
                ``(S, U)`` requests destined to it;
    3. GATHER   each shard answers from its local rows
                (``local[req - s*R]``) — an ordinary local gather;
    4. A2A BACK the ``(S, U, ...)`` answer rows return to their
                requesters, which scatter them back to the original id
                order (the sort permutation inverts exactly).

The result is bit-identical to ``full_table[ids]`` for every id in
``[0, num_rows)`` (pinned in ``tests/test_shard_table.py``), so the
train step's downstream math — dedup inverse scatter, text-head encode,
``data.gather_chunk`` tiling, the unique-cap policy — is untouched.
Capacity scales linearly with devices: ``rows_per_device = ceil(N / S)``.

Why fixed shapes: a "send only what each shard needs" exchange would put
a data-dependent dimension inside the compiled step (retrace per batch,
illegal under ``lax.scan`` rounds-in-jit). The ``(S, U)`` worst-case
bucket wastes wire on padding slots, which is exactly what
``data.unique_news_cap`` bounds — the cap lever prices the exchange.
docs/DESIGN.md §5i.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "TableSpec",
    "ShardedNewsTable",
    "owner_bucketed_gather",
    "a2a_bytes_per_gather",
    "lost_row_mask",
    "recover_table_rows",
    "reshard_table",
]


@dataclass(frozen=True)
class TableSpec:
    """Static layout of a sharded table — what the step builders compile
    against (all ints, so it can never introduce a dynamic shape)."""

    axis: str             # mesh axis the rows shard over
    num_shards: int       # devices along that axis
    rows_per_shard: int   # padded_rows / num_shards
    num_rows: int         # the REAL catalog rows (ids are < this)

    @property
    def padded_rows(self) -> int:
        return self.num_shards * self.rows_per_shard


@dataclass(frozen=True)
class ShardedNewsTable:
    """The at-rest sharded table: ``rows`` is the zero-padded
    ``(padded_rows, ...)`` array committed to
    ``NamedSharding(mesh, P(axis))`` — dim 0 split across the mesh — plus
    the :class:`TableSpec` the compiled programs need."""

    rows: jax.Array
    spec: TableSpec

    @classmethod
    def create(
        cls,
        table: Any,
        mesh: Mesh,
        axis: str,
        dtype: Any = None,
    ) -> "ShardedNewsTable":
        """Pad ``table`` (N, ...) to a multiple of the axis size and commit
        it row-sharded. Padding rows are zeros and unreachable (ids are
        < N); ``shard.table_occupancy`` reports N / padded."""
        arr = np.asarray(table)
        if dtype is not None:
            arr = arr.astype(np.dtype(dtype))
        num_shards = int(mesh.shape[axis])
        n = arr.shape[0]
        pad = (-n) % num_shards
        if pad:
            arr = np.concatenate(
                [arr, np.zeros((pad,) + arr.shape[1:], arr.dtype)]
            )
        spec = TableSpec(
            axis=axis,
            num_shards=num_shards,
            rows_per_shard=arr.shape[0] // num_shards,
            num_rows=n,
        )
        rows = jax.device_put(arr, NamedSharding(mesh, P(axis)))
        return cls(rows=rows, spec=spec)


def owner_bucketed_gather(
    local_rows: jnp.ndarray, ids: jnp.ndarray, spec: TableSpec
) -> jnp.ndarray:
    """Inside a ``shard_map`` block: gather ``full_table[ids]`` from the
    row-sharded table via the fixed-shape owner-bucketed exchange above.

    ``local_rows`` is this device's ``(rows_per_shard, ...)`` block,
    ``ids`` any ``(U,)`` int vector of global row ids in
    ``[0, num_rows)``; returns ``(U, ...)`` rows in ``ids`` order, exact.
    Degenerates to a plain local gather at ``num_shards == 1`` (the
    ``all_to_all`` over a size-1 axis is the identity).
    """
    u = ids.shape[0]
    s, r = spec.num_shards, spec.rows_per_shard
    owner = jnp.clip(ids // r, 0, s - 1).astype(jnp.int32)
    # stable sort by owner: contiguous per-owner runs whose in-run rank is
    # the bucket slot — the permutation is inverted exactly on the way back
    order = jnp.argsort(owner, stable=True)
    sorted_ids = ids[order]
    sorted_owner = owner[order]
    first = jnp.searchsorted(sorted_owner, sorted_owner, side="left")
    rank = jnp.arange(u, dtype=jnp.int32) - first.astype(jnp.int32)
    send = (
        jnp.zeros((s, u), ids.dtype).at[sorted_owner, rank].set(sorted_ids)
    )
    # phase 2: row d of `send` travels to shard d; we receive (S, U)
    # requests, row s' = the ids shard s' wants from OUR rows
    req = lax.all_to_all(send, spec.axis, split_axis=0, concat_axis=0, tiled=True)
    my_base = lax.axis_index(spec.axis).astype(req.dtype) * r
    local_idx = jnp.clip(req - my_base, 0, r - 1)
    answers = local_rows[local_idx]  # (S, U, ...)
    # phase 4: answers[s'] returns to shard s'; recv[d] = our requested
    # rows as held by shard d
    recv = lax.all_to_all(
        answers, spec.axis, split_axis=0, concat_axis=0, tiled=True
    )
    gathered_sorted = recv[sorted_owner, rank]
    inv = jnp.argsort(order, stable=True)
    return gathered_sorted[inv]


def lost_row_mask(spec: TableSpec, lost_shards) -> np.ndarray:
    """``(num_rows,)`` bool: True for the TRUE catalog rows whose owner
    shard is in ``lost_shards`` — the rows a dead host/device took with it
    under the ``[s*R, (s+1)*R)`` row-sharded layout.  Padding rows are
    outside ``num_rows`` and never appear."""
    owner = np.arange(spec.num_rows) // spec.rows_per_shard
    return np.isin(owner, np.asarray(sorted(set(int(s) for s in lost_shards))))


def recover_table_rows(
    surviving_rows: Any,
    lost_shards,
    spec: TableSpec,
    checkpoint_rows: Any,
) -> tuple[np.ndarray, int]:
    """Rebuild the full TRUE-row table after a shrink lost some shards.

    ``surviving_rows`` is a host copy of the old ``(padded_rows, ...)``
    sharded buffer in which the ``lost_shards`` blocks are gone (garbage,
    zeros — whatever the dead owner left unreachable); ``checkpoint_rows``
    is the last :func:`~fedrec_tpu.train.checkpoint.save_table_checkpoint`
    table (unpadded ``(num_rows, ...)``).  Lost rows are refilled from the
    checkpoint, surviving rows are kept LIVE (bit-identical to what the
    survivors held), and the result is the exact ``(num_rows, ...)`` table
    ready for :meth:`ShardedNewsTable.create` on the new, smaller mesh.
    Returns ``(full_rows, rows_recovered)``.

    Raises when a lost row has no checkpoint to come back from — losing
    catalog rows silently is the pre-elastic failure this replaces.

    Call-site note: the COORDINATOR deployment's elastic recovery reloads
    the whole table (each host builds its local sharded table from the
    full token source / ``load_table_checkpoint``), so this partial-rows
    path serves the single-process multi-device loss case and pins the
    no-rows-lost acceptance contract (``tests/test_membership.py``).
    """
    surviving = np.asarray(surviving_rows)[: spec.num_rows]
    mask = lost_row_mask(spec, lost_shards)
    if not mask.any():
        return surviving.copy(), 0
    if checkpoint_rows is None:
        raise ValueError(
            f"{int(mask.sum())} catalog rows lived on lost shard(s) "
            f"{sorted(set(int(s) for s in lost_shards))} and no table "
            "checkpoint exists to recover them from — save one with "
            "train.checkpoint.save_table_checkpoint (the Trainer does at "
            "save cadence under shard.table) or re-supply the token source"
        )
    ckpt = np.asarray(checkpoint_rows)
    if ckpt.shape[0] < spec.num_rows:
        raise ValueError(
            f"table checkpoint holds {ckpt.shape[0]} rows but the catalog "
            f"has {spec.num_rows}; it cannot recover the lost shards"
        )
    full = surviving.copy()
    full[mask] = ckpt[: spec.num_rows][mask]
    return full, int(mask.sum())


def reshard_table(
    full_rows: Any, mesh: Mesh, axis: str, dtype: Any = None
) -> ShardedNewsTable:
    """Commit a recovered full-row table to a (re-formed) mesh — the
    shrink-and-continue tail of :func:`recover_table_rows`.  Identical to
    :meth:`ShardedNewsTable.create` (padding recomputed for the NEW shard
    count), named separately so reshard call sites read as what they are."""
    return ShardedNewsTable.create(full_rows, mesh, axis, dtype=dtype)


def a2a_bytes_per_gather(
    unique_slots: int, row_shape: tuple, row_dtype: Any, spec: TableSpec
) -> int:
    """Modeled interconnect bytes of ONE owner-bucketed gather across the
    whole mesh: the (S, U) id buckets out plus the (S, U, row) answers
    back, summed over the S participating devices. Static per compiled
    batch shape — the ``shard.a2a_bytes_total`` counter advances by this
    per dispatched step."""
    s, u = spec.num_shards, unique_slots
    id_bytes = 4  # int32 ids
    row_bytes = int(np.prod(row_shape)) * np.dtype(row_dtype).itemsize
    per_device = s * u * (id_bytes + row_bytes)
    return per_device * s
