"""Sharding subsystem: scale the news catalog and the client state past
per-device HBM (ROADMAP item 2).

Two pillars, one package:

* :mod:`fedrec_tpu.shard.policy` — size-aware FSDP parameter sharding
  (``shard.fsdp``): the SNIPPETS [2] largest-evenly-divisible-dimension
  pytree -> ``NamedSharding`` rule, applied to params AND optimizer
  moments via ``jax.eval_shape``; ``fsdp=1`` degenerates bit-identically
  to the replicated layout.
* :mod:`fedrec_tpu.shard.table` — the mesh-sharded news catalog
  (``shard.table``): ``token_states`` row-sharded behind
  :class:`~fedrec_tpu.shard.table.ShardedNewsTable`, gathered in-step by
  the fixed-shape owner-bucketed ``all_to_all`` exchange; catalog
  capacity scales linearly with devices.

docs/DESIGN.md §5i (design), docs/OPERATIONS.md "sizing a catalog across
a slice" (runbook), ``make shard-smoke`` (2-process gloo CPU world).
"""

from fedrec_tpu.shard.policy import (
    FSDP_AXIS,
    fsdp_leaf_sharding,
    fsdp_shardings,
    fsdp_state_shardings,
    reshard_state,
    shard_bytes_per_device,
)
from fedrec_tpu.shard.table import (
    ShardedNewsTable,
    TableSpec,
    a2a_bytes_per_gather,
    lost_row_mask,
    owner_bucketed_gather,
    recover_table_rows,
    reshard_table,
)

__all__ = [
    "FSDP_AXIS",
    "ShardedNewsTable",
    "TableSpec",
    "a2a_bytes_per_gather",
    "fsdp_leaf_sharding",
    "fsdp_shardings",
    "fsdp_state_shardings",
    "lost_row_mask",
    "owner_bucketed_gather",
    "recover_table_rows",
    "reshard_state",
    "reshard_table",
    "shard_bytes_per_device",
]
