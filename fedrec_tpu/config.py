"""Configuration system for fedrec_tpu.

The reference configures each driver through bare positional ``sys.argv``
(reference ``main.py:178-184``, ``client.py:297-305``, ``server.py:108-113``)
plus hardcoded constants scattered through the code (lr 5e-5 ``model.py:22-23``;
npratio=4 / max_his_len=50 ``dataset.py:8-9``; DP constants C=2, delta=1e-5
``client.py:220-224``). Here everything is a typed dataclass tree with
``key=value`` CLI overrides and asdict round-tripping for checkpoint metadata.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any


@dataclass
class DataConfig:
    """Dataset and sampling knobs (reference ``dataset.py:8-9,69-86``)."""

    data_dir: str = "UserData"
    dataset: str = "mind"              # "mind" | "adressa" | "synthetic"
    npratio: int = 4                   # negatives per impression
    max_his_len: int = 50              # click-history cap (pad id 0 = <unk>)
    max_title_len: int = 50            # tokens per news title
    batch_size: int = 64
    shuffle: bool = True
    seed: int = 0
    drop_remainder: bool = True        # static shapes under jit
    # host-side batch assembly in the native C++ engine (threaded; see
    # native/fedrec_data.cpp). Falls back to the Python batcher if the
    # library is unavailable.
    native_loader: bool = False
    # cross-PROCESS disjoint data sharding (coordinator deployment): this
    # host trains shard `shard_index` of `num_shards` equal-as-possible
    # slices dealt from a (data.seed)-seeded permutation. The coordinator
    # CLI defaults these from (process_id, num_processes) so each host
    # trains disjoint data — the reference's DistributedSampler-by-rank
    # (reference main.py:166, client.py:243-249). 0 = unset (the
    # coordinator auto-shards); an EXPLICIT num_shards=1 opts out — every
    # host trains the full corpus even multi-process.
    num_shards: int = 0
    shard_index: int = 0
    # static bound on unique news encoded per joint-mode step. 0 = the exact
    # worst case B*(C+H). Real batches hold far fewer distinct ids (history
    # padding collapses to one <unk> row; popular news repeat), so a cap cuts
    # text-tower FLOPs proportionally. Exact while the batch's distinct count
    # stays <= cap; the step emits a `unique_overflow` metric (count of
    # clients whose batch overflowed — results invalid if ever nonzero).
    unique_news_cap: int = 0
    # per-B bucketed cap policy: "64:2560,256:4096" means per-client batches
    # up to B=64 cap at 2,560 unique slots, up to B=256 at 4,096; batches
    # larger than every bucket run uncapped (exact). A batch's dedup bound
    # scales with B, so one global constant either over-caps small batches
    # or under-caps large ones (a 2,560 cap overflows every B>=128 batch).
    # Empty = use the global unique_news_cap. Resolved at trace time per
    # compiled batch shape (train.step.resolve_unique_cap).
    unique_news_cap_buckets: str = ""
    # tile the unique token-state gather + text encode in lax.map chunks of
    # this many rows, with the chunk body rematerialized in backward: the
    # (unique, L, bert_hidden) gather result is never materialized in HBM
    # beyond one chunk (peak activation memory drops from O(unique*L*Dh) to
    # O(chunk*L*Dh)), at the price of re-gathering in backward. Exact same
    # math (row-wise encode). 0 = off; only bites when unique slots > chunk.
    gather_chunk: int = 0
    # bounded host-side prefetch: build batch t+1 on a producer thread while
    # step t runs on device, keeping the dispatch queue non-empty across an
    # epoch. Value = queue depth (2 = classic double buffering); 0 = off.
    # Batch order and contents are identical with prefetch on or off
    # (tests/test_prefetch.py).
    prefetch_batches: int = 0


@dataclass
class ModelConfig:
    """Two-tower model hyperparameters (reference ``encoder.py``, ``attention.py``)."""

    news_dim: int = 400                # news/user embedding dim
    num_heads: int = 20                # user-encoder MHA heads
    head_dim: int = 20                 # d_k = d_v
    query_dim: int = 200               # additive-attention query hidden
    dropout_rate: float = 0.2
    # user-tower family:
    #   "mha" — self-attention encoder (reference parity, encoder.py:36-56)
    #   "gru" — recurrent encoder (LSTUR-family, An et al. 2019): GRU over
    #           the click sequence + additive-attention pooling of the
    #           hidden states. Order-aware where MHA+pool is permutation-
    #           equivariant; lax.scan-based, so jit-friendly on TPU. Not
    #           combinable with fed.seq_shards>1 (sequence parallelism is
    #           attention-specific).
    user_tower: str = "mha"
    # text-head family (the trainable tail over frozen trunk token states):
    #   "additive" — additive attention + linear (reference encoder.py:20-29)
    #   "cnn"      — Conv1D + ReLU + additive pooling (NAML family, Wu et
    #                al. 2019). head/table modes only; finetune keeps the
    #                additive head.
    text_head_arch: str = "additive"
    cnn_kernel: int = 3                # CNN head context window
    bert_hidden: int = 768             # DistilBERT hidden size
    # "table"    — gather a precomputed news-embedding table (fast path)
    # "head"     — frozen-trunk token states + trainable additive-attn/linear head
    # "finetune" — full DistilBERT fine-tuned in-loop (BASELINE config 5)
    text_encoder_mode: str = "table"
    # trunk architecture for "finetune" mode (defaults = distilbert-base;
    # shrink for tests). dim is bert_hidden above.
    trunk_layers: int = 6
    trunk_heads: int = 12
    trunk_ffn: int = 3072
    trunk_vocab: int = 30522
    trunk_dropout: float = 0.1         # trunk hidden+attention dropout (HF default)
    trunk_remat: bool = True           # jax.checkpoint per block (HBM for FLOPs)
    # numerics: the reference uses unstabilized exp-normalization
    # (``attention.py:19,39``) — a defect; we default to stable softmax and keep
    # the knob for bit-parity experiments.
    stable_softmax: bool = True
    # score->loss parity: CE over sigmoid(scores) (reference ``model.py:123-126``)
    sigmoid_before_ce: bool = True
    dtype: str = "float32"             # compute dtype for encoders ("bfloat16" on TPU)
    # Route hot ops through the ISOLATED Pallas kernels. EXPERIMENTAL
    # OPT-IN: at every chip-measured size so far the XLA dense path wins
    # (20-dim heads pad to 128 lanes; benchmarks/pallas_bench.json). In
    # the one regime needing O(L) attention — training at H>=2048, dense
    # fwd+bwd OOM — the r3 chip window measured pallas AHEAD of the
    # chunked scan (255 vs 299 ms fwd+bwd at H=2048), so this opt-in is
    # the measured-better choice there. For the reference H=50 scale the
    # measured answer is fuse_hot_path below — isolated kernels lose to
    # per-call overhead there (50x at H=50 fwd); only a fused chain can
    # amortize the launch.
    use_pallas: bool = False
    # Fuse the step's hot chain into two Pallas kernels
    # (fedrec_tpu.ops.fused_hot_path): (1) frozen-table gather + text-head
    # encode — token rows stream HBM->VMEM per unique id, the (U, T, Dh)
    # gather never materializes; (2) user-tower QKV + per-head attention +
    # additive pool + candidate scoring in one VMEM residency (serving's
    # encode_user reuses it). bf16 operands / f32 accumulation; exact
    # module epsilon semantics; blocked custom VJPs; interpret-mode CPU
    # fallback so tier-1 runs the same code path. Requires
    # user_tower='mha' + stable_softmax; kernel (1) additionally needs
    # text_head_arch='additive' (cnn heads keep the dense gather+encode).
    # Not combinable with seq_shards>1, in-device cohorts (k>1), or
    # per-example DP-SGD — the step builders fail fast. docs/DESIGN.md §5h.
    fuse_hot_path: bool = False
    # user-encoder self-attention implementation:
    #   "auto"    — EVIDENCE-DRIVEN when a provenance-clean
    #               benchmarks/pallas_bench.json exists for the current
    #               jax version and a TPU backend is live: the measured
    #               winner for the nearest (H, dtype) regime is picked
    #               (fedrec_tpu.ops.autotune). Otherwise the static
    #               defaults: dense XLA up to attn_chunk_threshold history
    #               items, then blockwise lax.scan (O(L) memory); pallas
    #               if use_pallas (explicit opt-in still wins over
    #               evidence).
    #   "dense" | "chunked" | "pallas" — force one path
    attn_impl: str = "auto"
    attn_chunk_threshold: int = 1024


@dataclass
class OptimConfig:
    """Reference uses two inner Adams at lr 5e-5 (``model.py:22-23``)."""

    user_lr: float = 5e-5
    news_lr: float = 5e-5
    optimizer: str = "adam"
    grad_clip_norm: float = 0.0        # 0 = off (DP clipping is separate)
    # "constant" | "cosine" (optax.cosine_decay_schedule over decay_steps
    # optimizer updates, floored at lr * lr_min_frac). Set decay_steps =
    # rounds * local_epochs * steps_per_epoch; 0 disables the schedule.
    lr_schedule: str = "constant"
    decay_steps: int = 0
    lr_min_frac: float = 0.1


@dataclass
class RobustConfig:
    """Byzantine-robust aggregation + quarantine/rollback recovery.

    ``method`` selects the round-end aggregator (``fedrec_tpu.fed.robust``),
    compiled INTO the same shard_map program as the plain FedAvg sync so it
    composes with DP noise (applied per client, pre-sync) and FedOpt server
    optimizers (which step the post-aggregation global):

      * "mean"         — participation-weighted mean (FedAvg; the default,
                         bit-identical to pre-robust behavior)
      * "clip"         — norm-clipped mean: each client's deviation from the
                         coordinate-wise cohort median is clipped to
                         ``clip_norm`` (global L2 over both towers) before
                         the weighted mean; non-finite contributions clip
                         to zero. Bounds any one client's round influence
                         by clip_norm / num_participants.
      * "trimmed_mean" — coordinate-wise: drop the ``trim_k`` highest and
                         lowest finite participant values per coordinate,
                         mean the rest (unweighted over kept participants)
      * "median"       — coordinate-wise median over finite participants

    ``recover`` turns the PR-4 health sentry's detection into reaction:
    on a non-finite update or an outlier client (round-mean update-norm >
    ``obs.health.outlier_k`` x cohort median) the Trainer quarantines the
    client (participation weight 0 for ``quarantine_rounds`` rounds),
    rolls the cohort back to the round-entry state, and replays the round
    — up to ``max_retries`` distinct quarantines per round, then the
    existing flight-recorder abort. A quarantined client rejoins healed:
    params reset to the global, optimizer moments zeroed.
    """

    method: str = "mean"               # "mean" | "clip" | "trimmed_mean" | "median"
    trim_k: int = 1                    # coords trimmed from EACH end (trimmed_mean)
    clip_norm: float = 10.0            # global-L2 clip for method="clip"
    recover: bool = False              # quarantine + rollback instead of abort
    quarantine_rounds: int = 3         # rounds a flagged client sits out
    max_retries: int = 2               # rollback/replay attempts per round


@dataclass
class PopulationConfig:
    """Cross-device cohort engine (``fedrec_tpu.fed.population``).

    Separates *logical clients* (``num_clients`` of them, per-client state
    kept host-side) from the physical device slots (``fed.num_clients``,
    the mesh's cohort layout): each round a seeded
    :class:`~fedrec_tpu.fed.sampling.CohortSampler` draws
    ``ceil(slots * over_select)`` logical clients, the survivors of the
    (chaos-simulated) dropout are packed into the slots, and clients whose
    simulated report latency exceeds ``round_deadline_ms`` are cut with
    participation weight 0.  Below ``min_reports`` reporting clients the
    round is discarded and replayed with a fresh draw (the quorum policy);
    ``quorum_retries`` bounds the re-draws before the run aborts.

    ``num_clients == fed.num_clients`` is the degenerate (cross-silo)
    configuration: every client is selected every round, the data path and
    trajectory are bit-identical to a run without a population section
    (pinned in ``tests/test_population.py``).  ``num_clients`` above the
    slot count turns on real per-round sampling: each logical client then
    OWNS a static, seeded, equal-size shard of the corpus (non-IID-ready),
    and its optimizer sidecar persists across selections
    (``client_state="persist"``) or resets to the template each time
    (``"reset"`` — stateless cross-device semantics).
    """

    num_clients: int = 0               # 0 = off; == slots = degenerate; > slots = sampled
    sampler: str = "uniform"           # "uniform" | "weighted" | "skew"
    seed: int = 0                      # cohort-draw seed (schedule identity)
    over_select: float = 1.0           # sample ceil(slots * over_select) candidates
    round_deadline_ms: float = 0.0     # report-latency cut; 0 = no deadline
    min_reports: int = 0               # quorum: fewer reporters discards the round
    quorum_retries: int = 3            # re-draws per round before aborting
    client_state: str = "persist"      # "persist" sidecars across selections | "reset"
    # sidecar residency: how many clients' optimizer sidecars stay in host
    # RAM; above the cap the least-recently-selected spill to disk
    # (``spill_dir``, default <snapshot_dir>/popspill). 0 = unbounded.
    resident_cap: int = 0
    spill_dir: str = ""


@dataclass
class ElasticConfig:
    """Elastic membership (``fedrec_tpu.parallel.membership``).

    Activated by ``fedrec-coordinator --membership HOST:PORT`` (which sets
    ``enabled``): the deployment's world size stops being the static
    ``--num-processes`` and becomes a *membership epoch* maintained by a
    lease service. A dead peer shrinks the world at the next epoch
    boundary (shrink-and-continue — survivors keep federating instead of
    each degrading to standalone); a supervisor-respawned peer rejoins at
    the next boundary and the world grows back. A run whose membership
    never changes is bit-identical to the fixed world.

    ``lease_ms`` is how long a silent worker stays a member (the failure
    detector; size it above the worst-case round time so a slow round is
    not a death), ``heartbeat_ms`` the renewal cadence (≤ lease/3),
    ``formation_grace_ms`` how long a forming epoch waits for stragglers
    before continuing with fewer (the shrink window), ``min_world`` the
    floor below which no epoch forms (survivors then keep waiting),
    ``join_timeout_s`` how long a joining worker parks before its
    supervisor retries.
    """

    enabled: bool = False
    lease_ms: float = 15000.0
    heartbeat_ms: float = 5000.0
    formation_grace_ms: float = 10000.0
    min_world: int = 1
    join_timeout_s: float = 180.0


@dataclass
class ShardConfig:
    """Model/catalog sharding (``fedrec_tpu.shard``) — scale state past
    per-device HBM.

    ``fsdp`` adds an ``fsdp`` mesh axis (``parallel.mesh.fed_mesh``) and
    keeps every client's AT-REST state — parameters, optimizer moments,
    grad accumulators, codec residuals — sharded across it per the
    size-aware largest-evenly-divisible-dimension policy
    (``shard.policy``, SNIPPETS [2]): scalars/1-D and sub-threshold
    leaves replicated, 2-D+ leaves sharded along the largest dim the
    axis size divides evenly, replicate fallback.  The compiled step
    gathers on entry and re-shards on exit (ZeRO-style residency), so
    the trajectory is bit-identical to the replicated layout
    (``tests/test_shard_fsdp.py``); ``fsdp=1`` builds the exact pre-PR
    1-D mesh and programs.  Not combinable with ``fed.seq_shards>1``
    (both claim the second mesh axis).

    ``table`` row-shards the frozen token-state news table across the
    client mesh axis behind ``shard.table.ShardedNewsTable``: each step
    buckets its unique news ids by owner shard, ``all_to_all``s the id
    buckets out and the gathered rows back (fixed shapes, exact —
    ``docs/DESIGN.md`` §5i), so catalog capacity scales linearly with
    devices instead of per-device HBM.  Composes with
    ``data.gather_chunk`` / the unique-cap policy; joint ("head") mode
    only, and not with ``model.fuse_hot_path``, DP-SGD, seq sharding or
    in-device cohorts (the step builders fail fast).
    """

    # fsdp axis size: shard at-rest client state across this many devices
    # per client slot. 1 = off (bit-identical degenerate layout).
    fsdp: int = 1
    # leaves smaller than this many MB (per client) stay replicated —
    # sharding tiny tensors buys nothing and costs collective latency
    fsdp_min_size_mb: float = 4.0
    # row-shard the token-state news table over the client mesh axis with
    # the in-step owner-bucketed all_to_all gather
    table: bool = False


@dataclass
class FedConfig:
    """Federation strategy (reference modes a-d, SURVEY.md section 0)."""

    # "local"     — no federation (single client)
    # "grad_avg"  — pmean of grads every step (Gradient_Averaging_main.py parity)
    # "param_avg" — pmean of params every round  (Parameter_Averaging_main.py:144-148)
    # "coordinator" — host-0 server broadcast/gather over DCN (client.py/server.py)
    strategy: str = "param_avg"
    num_clients: int = 8
    local_epochs: int = 1              # client epochs per round
    rounds: int = 10                   # global rounds (server.py global_epochs)
    participation: float = 1.0         # fraction of clients aggregated per round
    # classic FedAvg weighting by client example count in coordinator mode
    # (McMahan et al.); False = reference parity — the server's key-wise
    # UNWEIGHTED mean over whatever shard sizes clients hold
    # (reference server.py:37-55)
    weight_by_samples: bool = False
    mesh_axis: str = "clients"
    # sequence/context parallelism for long click-histories: shard the history
    # axis over `seq_shards` chips per client and attend via ring or Ulysses
    # all-to-all collectives (fedrec_tpu.parallel.ring). 1 = off.
    seq_shards: int = 1
    seq_axis: str = "seq"
    seq_impl: str = "ring"             # "ring" | "ulysses"
    # server-side optimization over round deltas (FedOpt, Reddi et al. 2021):
    # "none" adopts the client mean (plain FedAvg = reference behavior);
    # "sgd" with server_momentum>0 is FedAvgM; "adam" is FedAdam. Applies to
    # param_avg and coordinator strategies.
    server_opt: str = "none"           # "none" | "sgd" | "adam"
    server_lr: float = 1.0
    server_momentum: float = 0.9
    # client->server UPDATE compression (fedrec_tpu.comms): applied at the
    # in-graph round-end sync (each cohort client's round delta — the
    # simulated cross-device uplink, host-driven AND rounds-in-jit) and at
    # the coordinator's cross-host DCN gather (real wire buffers). The
    # server->client fan-out stays full precision in every mode.
    #   "none"     — dense f32 (bit-identical to the pre-codec sync)
    #   "int8"     — symmetric per-tensor int8 deltas (~4x the wire)
    #   "sign1bit" — 1 bit/coord + per-tensor scale (~32x); needs EF
    #   "topk"     — keep the dcn_topk_ratio largest coords (~1/(2*ratio)x);
    #                needs EF
    #   "countsketch" — LINEAR seeded count-sketch, ceil(width * n) buckets
    #                per tensor (~1/width x); unbiased, decodes AFTER the
    #                sum (one decode at the root)
    #   "randproj" — LINEAR seeded ±1/√d random projection in 256-wide
    #                chunks (~1/width x); unbiased, decodes AFTER the sum
    #   "auto"     — adaptive per-leaf selection: a seeded warmup window
    #                measures per-tensor reconstruction, then pins a
    #                per-leaf codec map (sketch for dense towers, topk for
    #                sparse deltas, none for scalars) recorded in
    #                provenance and held fixed for replayability
    # The per-contribution codecs (int8/sign1bit/topk) decode each
    # contribution BEFORE any reduction, so robust aggregation
    # (fed.robust.method) composes with them (decode-before-reduce). The
    # linear sketches only decode after the sum — order statistics don't
    # commute with sketch collision, so robust non-mean methods fail fast
    # (the capability table in fedrec_tpu.comms marks the boundary).
    dcn_compress: str = "none"  # none|int8|sign1bit|topk|countsketch|randproj|auto
    # topk: fraction of coordinates kept per tensor (ceil(ratio * n), >= 1)
    dcn_topk_ratio: float = 0.01
    # linear sketches: sketch-to-dense size ratio in (0, 1] — wire cost is
    # ~width * dense bytes, reconstruction variance ~ ||x||^2 * width / m.
    # 0.1 → ~10x uplink reduction (the banked comm_cost contract is >= 8x).
    dcn_sketch_width: float = 0.1
    # seed for the shared sketch hash/projection: every client, process and
    # async worker must hold the SAME seed for sketches to sum.
    dcn_sketch_seed: int = 0
    # dcn_compress="auto": rounds observed (with the sync running dense)
    # before the per-leaf codec map is pinned. The map derives from the
    # warmup round's global delta, identical on every process.
    dcn_auto_warmup: int = 1
    # per-client error-feedback residuals for the biased codecs
    # (sign1bit/topk): the mass a lossy encode drops is carried in
    # ClientState.ef_residual (a fed.population sidecar field — LRU/spill,
    # checkpointed, reset on quarantine heal) and re-enters the next
    # round's update. Disable only for ablations: biased codecs without EF
    # are known not to converge (EF-signSGD, Karimireddy et al. 2019).
    # Async wire workers bank the same residual per EDGE (worker id),
    # keyed to the global version the push was based on.
    dcn_error_feedback: bool = True
    # Byzantine-robust aggregation + quarantine/rollback recovery (see
    # RobustConfig). Applies wherever params aggregate: the in-graph
    # round-end sync (param_avg, host-driven AND rounds-in-jit) and the
    # coordinator's cross-host gather.
    robust: RobustConfig = field(default_factory=RobustConfig)
    # cross-device cohort engine: logical-client population sampled onto
    # the device slots each round (see PopulationConfig).
    population: PopulationConfig = field(default_factory=PopulationConfig)
    # elastic membership: epoch-based world formation over heartbeat
    # leases — shrink-and-continue on peer loss, rejoin at epoch
    # boundaries (see ElasticConfig).
    elastic: ElasticConfig = field(default_factory=ElasticConfig)


@dataclass
class PrivacyConfig:
    """DP-SGD (honest version of reference ``client.py:87-89,220-225,271-281``)."""

    enabled: bool = False
    epsilon: float = 10.0
    delta: float = 1e-5
    clip_norm: float = 2.0             # C (MAX_GRAD_NORM, client.py:220)
    # if sigma > 0 it overrides the accountant-calibrated value
    sigma: float = 0.0
    accountant_epochs: int = 50        # EPOCHS used for calibration (client.py:223)
    # "dpsgd"  — per-example clip + noise on all trainable grads (correct)
    # "ldp_news" — reference parity: noise only on news-embedding grads, no clipping
    mechanism: str = "dpsgd"
    # what DP rounds train (and therefore clip + noise):
    # "all"  — user tower + text head (P ~ 25.5k on the harness model)
    # "user" — user tower only, text head frozen at its current params;
    #          shrinks the noised dimension (noise norm ~ sigma*C*sqrt(P)/B,
    #          docs/DP.md section 2) and keeps the news representation
    #          stationary under noise. dpsgd mechanism only.
    dp_scope: str = "all"


@dataclass
class HealthConfig:
    """Training-health flight recorder (fedrec_tpu.obs.health/device).

    ``sentry`` turns on the in-graph numeric sentry: the jitted train step
    returns a compact per-client health vector (grad/update/param global
    norms + a non-finite flag, DP clip-rate under dpsgd) that the host
    fetches asynchronously with the round's losses.  On a non-finite
    sentinel (or the optional loss-spike predicate) the flight recorder
    dumps the offending batch, a params/opt-state checkpoint, the registry
    snapshot, and a replay manifest into ``obs.dir/flightrec/`` —
    ``fedrec-obs replay`` re-executes that exact step on CPU.
    """

    sentry: bool = True                # in-graph health vector in step metrics
    abort_on_nonfinite: bool = True    # raise TrainingHealthError after dump
    flight_recorder: bool = True       # keep the batch ring + dump (needs obs.dir)
    ring_size: int = 16                # last-N (batch, metadata) records kept
    dump_policy: str = "first"         # "first" = one dump per TRIGGER KIND | "all"
    # keep a host copy of the full client state at every round/chunk entry
    # (what replay starts from). The copy is a blocking device->host
    # transfer of params + optimizer state each round — negligible in
    # simulation, but at large model x cohort scale it is the flight
    # recorder's dominant cost; turn it off to keep batch-ring forensics
    # (dumps then have no state checkpoint and cannot replay).
    snapshot_state: bool = True
    # loss-spike divergence predicate: trigger a dump (no abort) when a
    # round's mean loss exceeds spike_factor * mean(trailing spike_window
    # round losses). 0 = off.
    spike_factor: float = 0.0
    spike_window: int = 8
    # outlier-client flag: a client whose round-mean update-norm exceeds
    # outlier_k * cohort median is counted/logged (poisoning/divergence
    # triage). 0 = off.
    outlier_k: float = 3.0
    # the replay dump includes the feature table (token states / news-vec
    # table) up to this many MB; larger tables are skipped and noted in
    # the manifest (replay then needs the table re-supplied).
    dump_table_max_mb: int = 512
    # recompile watchdog: warn (registry counter + stderr) when this many
    # XLA backend compiles land within storm_window_s seconds.
    storm_threshold: int = 5
    storm_window_s: float = 60.0


@dataclass
class QualityConfig:
    """Model-quality observability (``fedrec_tpu.obs.quality``).

    ``enabled`` turns on the sliced-evaluation telemetry layer: at eval
    cadence the full-pool eval pass additionally accumulates per-SLICE
    ranking metrics (news-category hash buckets, user history-length
    buckets, client-activity quantile buckets, per-device-client) and
    publishes ``eval.{auc,mrr,ndcg5,ndcg10}{slice=…}`` gauges plus
    per-slice impression counts — corpus-wide means hide exactly the
    per-slice skew a federated run is supposed to be judged on. The same
    jitted eval pass also emits fixed-shape score histograms and
    reliability-bin calibration sums (no extra host syncs in the step),
    from which ``eval.ece``, score-separation stats and the
    positive/negative score distributions are derived. Per-client quality
    digests flag clients whose eval AUC falls ``outlier_auc_drop`` below
    the cohort median — informational (composes with quarantine's ignore
    set, never triggers it). ``probe_users > 0`` additionally arms the
    serving store's pre-swap drift probe (``serve.drift_*``).

    Default OFF: with ``enabled=false`` the eval and serving paths run
    the exact pre-quality programs (byte-identical trajectories, pinned
    in ``tests/test_quality.py``).
    """

    enabled: bool = False
    seed: int = 0                      # seeded slice definitions (category hash)
    # news-category slices: seeded multiplicative-hash buckets of the
    # positive news id (a topic proxy when no category metadata exists)
    category_buckets: int = 8
    # user history-length bucket edges (comma ints): "10,30" = <=10,
    # 11..30, >30
    hist_len_edges: str = "10,30"
    # client-activity slices: impressions bucketed by their user's
    # validation-impression count into this many quantile buckets
    # (10 = deciles). 0 = off.
    activity_buckets: int = 10
    # per-device-client slices + quality-outlier digest (uses the
    # per-client eval breakdown when clients have diverged)
    per_client: bool = True
    # reliability bins over sigmoid(score) for ECE (fixed, equal-width)
    ece_bins: int = 10
    # fixed score-histogram shape: score_bins equal bins over
    # [-score_range, +score_range], outliers clamped to the edge bins
    score_bins: int = 20
    score_range: float = 10.0
    # flag a client as a quality outlier when its eval AUC sits this far
    # below the cohort median (absolute AUC drop). 0 = off.
    outlier_auc_drop: float = 0.05
    # serving drift probe: seeded probe-user vectors scored against the
    # outgoing AND incoming store generation BEFORE the hot-swap;
    # publishes score-shift and top-k rank-churn. 0 = off.
    probe_users: int = 32
    probe_topk: int = 10


@dataclass
class PerfConfig:
    """Performance observability (``fedrec_tpu.obs.perf``).

    ``enabled`` turns on the live efficiency telemetry layer: per-round
    ``perf.mfu`` / ``perf.samples_per_sec`` gauges priced with the SAME
    analytic FLOPs model and peak-FLOPs table ``bench.py`` certifies
    headline MFU with, a per-round roofline verdict
    (compute/HBM/input-bound — one spelling with
    ``benchmarks/step_profile.py``) derived from the existing
    ``batch_build``/``h2d``/``dispatch`` span timings, compile-cost
    telemetry (every watched XLA compilation records its
    ``cost_analysis()`` FLOPs / bytes accessed into ``xla.cost_*``
    gauges), and ``jax.live_arrays()`` HBM attribution
    (``hbm.component_bytes{component=…}``) at round cadence.

    Default OFF: with ``enabled=false`` none of this is constructed and
    the train/serve paths run the exact pre-perf programs
    (byte-identical trajectories, pinned in ``tests/test_perf.py``).
    """

    enabled: bool = False
    # record lowered.compile().cost_analysis() (FLOPs / bytes accessed /
    # arithmetic intensity) for every watched compilation; degrades
    # gracefully on backends returning None/partial dicts
    compile_cost: bool = True
    # bucket jax.live_arrays() bytes by component (params / optimizer /
    # news_table / batch / other) into hbm.component_bytes gauges at
    # round cadence
    hbm_components: bool = True
    # triggered capture window: "N" wraps round N (only) in a
    # jax.profiler trace under obs.dir/perf_capture_rNNNN; "N:K" wraps
    # rounds [N, N+K). A pointer record lands in metrics.jsonl. Empty =
    # no configured window.
    capture_rounds: str = ""
    # efficiency-drop trigger: when a round's samples/s falls this
    # fraction below the trailing-window mean, capture the NEXT round
    # (bounded at 3 triggered captures per run). 0 = off.
    capture_drop: float = 0.0
    # trailing rounds the drop trigger averages over
    capture_window: int = 8


@dataclass
class FleetConfig:
    """Fleet-wide telemetry (``fedrec_tpu.obs.fleet``).

    ``collector`` names the TCP JSON-lines telemetry collector this
    worker pushes registry snapshots + completed spans to at round
    cadence — standalone (``CollectorServer``) or riding the membership
    service's port (``python -m fedrec_tpu.parallel.membership ...
    --telemetry-dir D``).  Empty = no pushes; the per-worker
    ``obs.dir/worker_*`` artifacts remain the lossless offline source
    either way (``fedrec-obs fleet`` merges them post-hoc), so a
    no-collector run loses nothing.  Push failures are counted
    (``obs.fleet_push_failures_total``), never raised.
    """

    collector: str = ""                # HOST:PORT; "" = offline artifacts only
    push_every: int = 1                # rounds between telemetry pushes
    push_timeout_s: float = 5.0        # per-push TCP deadline


@dataclass
class WireConfig:
    """Wire-layer observability (``fedrec_tpu.obs.wire``).

    Every TCP JSON-lines exchange (fleet pushes, membership control
    plane, async agg pushes, serving requests) carries an ADDITIVE
    trace-context envelope: causal flow arrows across processes in the
    merged fleet trace, per-edge ``wire.*`` RTT/byte telemetry, and
    NTP-style clock-offset estimation that aligns barrier-less (async)
    incarnations.  ``enabled=false`` sends no envelope at all — wire
    bytes are byte-identical to the pre-envelope protocol (pinned in
    ``tests/test_wire.py``).  Spans follow the ``Tracer.enabled``
    contract: default-on costs registry counters only when no
    ``obs.dir`` will persist a trace.
    """

    enabled: bool = True               # false = byte-identical legacy wire
    window: int = 32                   # per-edge offset median window


@dataclass
class SloConfig:
    """Declarative SLOs + multi-window burn-rate alerting
    (``fedrec_tpu.obs.watch``).

    ``objectives`` is a semicolon list of objectives over metrics the
    registry already publishes::

        round_time:train.round_seconds:p95<2.5;mfu:perf.mfu>=0.3;
        serve_p99:serve.p99_ms<50;auc_all:eval.auc{slice=all}>0.6

    Each objective is ``name:metric[{label=value,...}][:pQQ]OPthreshold``
    with ``OP`` one of ``< <= > >=`` and an optional per-objective
    error-budget target suffix ``@0.999`` (otherwise ``target``
    applies).  Histogram metrics are read as the per-evaluation DELTA of
    their bucket counts (the quantile of *this round's* observations,
    not the lifetime distribution); counters as per-evaluation deltas;
    gauges and record keys at face value.  Every evaluation scores one
    good/bad event per objective, and the alert fires Google-SRE style:
    when the burn rate (bad fraction / error budget) exceeds
    ``fast_burn`` over the last ``fast_window`` evaluations AND
    ``slow_burn`` over the last ``slow_window`` — windows are counted in
    evaluations, so the thresholds scale with round cadence for the
    Trainer, heartbeat cadence for ``fedrec-serve``, and commit cadence
    for the async agg server.

    Default OFF: with ``enabled=false`` no watch layer is constructed,
    no ``alert.*`` instrument exists and the training program is
    byte-identical to a pre-watch build (pinned in
    ``tests/test_watch.py``).
    """

    enabled: bool = False
    objectives: str = ""               # "" = burn-rate SLOs off (anomaly only)
    target: float = 0.99               # default objective target (budget = 1-target)
    fast_window: int = 12              # evaluations in the fast burn window
    slow_window: int = 60              # evaluations in the slow burn window
    fast_burn: float = 14.4            # burn-rate threshold over the fast window
    slow_burn: float = 6.0             # burn-rate threshold over the slow window


@dataclass
class WatchConfig:
    """Alert lifecycle + streaming anomaly detection knobs
    (``fedrec_tpu.obs.watch``/``obs.alerts``; active only under
    ``obs.slo.enabled``).

    The anomaly detector keeps, per round-cadence series the
    MetricLogger already emits, an EWMA baseline and a MAD
    (median-absolute-deviation) scale over the trailing residual window;
    a point whose robust z-score ``|x - ewma| / (1.4826 * MAD)`` exceeds
    ``anomaly_z`` after ``anomaly_warmup`` observations raises an
    anomaly alert — the net that catches regressions no explicit SLO
    names.  The lifecycle engine drives every alert (SLO, anomaly, and
    the unified health/quality/drift/perf triggers) through
    pending→firing→resolved with dedup (a firing alert re-breaching
    emits nothing new), flap suppression (``flap_max`` fire→resolve
    cycles within ``flap_window`` evaluations mutes further transitions)
    and severity.
    """

    anomaly: bool = True               # EWMA+MAD robust z-score detector on/off
    anomaly_z: float = 6.0             # robust z-score firing threshold
    anomaly_alpha: float = 0.3         # EWMA smoothing factor
    anomaly_window: int = 32           # trailing residuals kept for the MAD scale
    anomaly_warmup: int = 8            # observations before a series may fire
    pending_for: int = 2               # consecutive breached evals before firing
    resolve_after: int = 3             # consecutive healthy evals before resolve
    flap_max: int = 3                  # fire cycles within flap_window -> suppress
    flap_window: int = 20              # evaluations the flap counter looks back
    history: int = 256                 # resolved alerts kept for surfaces
    # serving drift-probe breach: a pre-swap probe whose top-k rank churn
    # exceeds this fraction raises a serve:drift alert. 0 = off.
    drift_churn_max: float = 0.5
    # ---- fleet-level rules (collector/membership side):
    # persistent straggler: a worker whose per-push mean round seconds
    # exceeds factor x the fleet median for N consecutive pushes
    fleet_straggler_factor: float = 2.0
    fleet_straggler_evals: int = 3
    # quorum-wait growth: last agg.quorum_wait_ms > factor x trailing median
    fleet_quorum_factor: float = 3.0
    # stalled commit version: a worker whose adopted agg version stops
    # advancing for N pushes while its rounds keep completing
    fleet_stalled_pushes: int = 3


@dataclass
class ObsConfig:
    """Unified telemetry (fedrec_tpu.obs): registry snapshots + host spans.

    The registry and tracer always record in memory (cheap); ``dir``
    turns on the file artifacts — ``metrics.jsonl`` (MetricLogger
    records + per-round registry snapshots), ``trace.json``
    (Chrome-trace/Perfetto host spans), ``prometheus.txt`` (final text
    exposition).  ``fedrec-obs report <dir>`` renders them.
    """

    dir: str = ""                      # "" = no files written
    snapshot_every: int = 1            # rounds between registry snapshots
    trace_capacity: int = 200_000      # host-span ring bound (earliest kept)
    # size-based rotation for metrics.jsonl: when the event log exceeds
    # this many MB it is renamed to metrics.jsonl.1 (one level kept) and a
    # fresh file continues — a long serve/train run cannot fill the disk.
    # Readers (fedrec-obs, load_jsonl) consume rotated files in order.
    # 0 = unbounded.
    jsonl_max_mb: float = 0.0
    health: HealthConfig = field(default_factory=HealthConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    quality: QualityConfig = field(default_factory=QualityConfig)
    perf: PerfConfig = field(default_factory=PerfConfig)
    wire: WireConfig = field(default_factory=WireConfig)
    slo: SloConfig = field(default_factory=SloConfig)
    watch: WatchConfig = field(default_factory=WatchConfig)


@dataclass
class ChaosConfig:
    """Deterministic fault injection (``fedrec_tpu.fed.chaos``).

    A seeded :class:`FaultPlan` schedules per-round, per-client faults.
    Client-side faults are applied as masks at the optimizer-update
    boundary INSIDE the jitted step (the per-client fault vector rides the
    batch as ``chaos.code``/``chaos.scale`` arrays, so every dispatch mode
    — per-batch, epoch scan, rounds-in-jit — and the flight-recorder
    replay see identical faults), and two runs of the same plan are
    bit-identical. Host-level faults (peer kill, torn snapshot) exercise
    the coordinator deployment's recovery paths.

    ``faults`` is a comma list of ``kind@round:client[xscale]`` specs,
    ``round`` may be ``*`` (every round):

        nan@2:3          client 3's round-2 updates become NaN
        scale@*:5x100    client 5's updates x100 every round (poison)
        flip@4:2         client 2's round-4 updates sign-flipped
    """

    enabled: bool = False
    seed: int = 0
    drop_rate: float = 0.0             # per-(round, client) Bernoulli dropout
    straggle_rate: float = 0.0         # ditto; weight 0 + optional host delay
    straggle_ms: float = 0.0           # host-driven path: sleep per straggler round
    faults: str = ""                   # "kind@round:client[xscale]" comma list
    # ---- population-level fault distributions (fed.population): applied
    # to LOGICAL client ids at cohort-sampling time, seeded per
    # (seed, round, attempt, client) so a whole sampled-cohort run replays
    # bit-identically. pop_drop_rate is each sampled client's per-round
    # Bernoulli dropout probability; a seeded pop_flaky_fraction subset of
    # the population drops at pop_flaky_drop_rate instead (chronically bad
    # radios). pop_straggle_ms > 0 draws each reporting client's simulated
    # report latency from lognormal(median=pop_straggle_ms,
    # sigma=pop_straggle_sigma); clients past fed.population's
    # round_deadline_ms are deadline-cut (weight 0).
    pop_drop_rate: float = 0.0
    pop_flaky_fraction: float = 0.0
    pop_flaky_drop_rate: float = 0.5
    pop_straggle_ms: float = 0.0
    pop_straggle_sigma: float = 1.0
    # host faults (coordinator deployment only):
    kill_round: int = -1               # process exits hard at this round's entry
    kill_process: int = -1             #   which coordinator process dies
    torn_snapshot_round: int = -1      # truncate the just-written local snapshot
    # elastic kill->shrink->rejoin scripting: after the chaos kill, the
    # respawned worker HOLDS OFF joining the membership service for this
    # many seconds (once, marker-guarded), so the survivors demonstrably
    # form the SHRUNK epoch first and the rejoin lands as its own later
    # epoch — without it a fast respawn can race straight back into the
    # formation window and the shrink never becomes observable. 0 = off.
    rejoin_delay_s: float = 0.0
    # ---- wire-level fault injection (fed.chaos.ChaosProxy): a seeded
    # TCP chaos proxy fronting the commit authority (or membership
    # service) applies time-windowed transport faults per connection.
    # wire_faults is a comma list of "kind@start[-end][:arg]" specs,
    # start/end in seconds since proxy start, "*" = always:
    #
    #     drop@5-10          refuse/black-hole connections in [5s, 10s)
    #     drop@*:0.3         drop 30% of connections, always
    #     delay@0-60:250     add 250ms before forwarding the request
    #     tear@5-10          forward HALF the request bytes, then RST
    #     dup@5-10           deliver the request TWICE upstream
    #     partition@20-30    full partition: nothing gets through
    #
    # Faults are drawn from a PRNG seeded per (wire_seed, connection
    # index), so a soak's fault schedule replays bit-identically; with
    # wire_faults empty the proxy forwards every byte verbatim (pinned).
    wire_faults: str = ""
    wire_seed: int = 0


@dataclass
class AggConfig:
    """Round-end aggregation topology (``fedrec_tpu.agg``).

    ``mode`` selects how the cohort's contributions become the next
    global:

      * "flat"         — the all-reporting single reduce (the default;
                         every prior PR's behavior, bit-for-bit).
      * "hierarchical" — tiered reduce: cohort contributions are grouped
                         into ``tree_fanout``-wide tiers, each tier
                         pre-aggregated with the ``fed.robust`` method,
                         and the tier outputs reduced up a tree whose
                         critical path is O(log_fanout P) instead of
                         O(P).  With ``fed.robust.method="mean"`` the
                         tree of (sum(w*x), sum(w)) partials with ONE
                         final divide is *algebraically* the flat
                         weighted mean, so the mode lowers to the
                         unchanged flat reduce and stays bit-identical
                         (pinned in tests/test_agg.py); any other robust
                         method trims/medians per tier and genuinely
                         diverges from the flat trajectory (documented
                         in docs/DESIGN.md, bounded-delta pinned).
      * "async"        — buffered quorum commit (``agg/buffer.py`` +
                         ``agg/commit.py``): the global commits once
                         ``quorum`` contributions arrive; late
                         contributions are staleness-weighted by
                         1/(1+staleness) into the NEXT commit and
                         dropped once staleness exceeds
                         ``staleness_cap`` commits.  The round barrier
                         disappears — a straggler's marginal ``gate_ms``
                         goes to ~0 (scripts/async_smoke.sh).

    ``quorum`` = 0 means all-reporting (async mode then still commits
    per round, but without early-commit savings).  The buffer state is
    checkpointed beside the model snapshot so pending late contributions
    survive a restart.
    """

    mode: str = "flat"                 # "flat" | "hierarchical" | "async"
    quorum: int = 0                    # async commit quorum K; 0 = all-reporting
    staleness_cap: int = 2             # drop buffered updates older than this (commits)
    tree_fanout: int = 2               # hierarchical tier width (>= 2)
    # ---- async worker wire policy (agg/worker.py + parallel/rpc.py):
    # the failure-handling budgets one worker<->authority edge runs
    # under. Exchanges retry transport failures with full-jitter
    # exponential backoff inside worker_rpc_attempts; a dead host fails
    # in worker_connect_timeout_s (the dial budget) while a slow fold
    # still gets worker_timeout_s on the established socket. When the
    # wire stays silent past worker_unreachable_budget_s the worker
    # stops degrading and exits rc-75 for the supervisor to respawn.
    worker_timeout_s: float = 60.0     # per-exchange read/socket deadline
    worker_connect_timeout_s: float = 5.0   # dial budget (dead host fails fast)
    worker_poll_s: float = 0.2         # sleep between commit-poll ticks
    worker_global_wait_s: float = 20.0  # bounded wait for a newer commit per round
    worker_rpc_attempts: int = 4       # per-op transport retry budget
    worker_backoff_ms: float = 50.0    # full-jitter backoff base
    worker_backoff_cap_ms: float = 2000.0   # backoff ceiling per retry
    worker_unreachable_budget_s: float = 120.0  # wire silence before rc-75 degrade


@dataclass
class TrainConfig:
    save_every: int = 1                # snapshot cadence (reference main.py argv)
    snapshot_dir: str = "snapshots"
    resume: bool = True                # auto-resume if snapshot exists (main.py:113-115)
    eval_every: int = 1
    # "sampled" — 1 pos + npratio sampled negatives per impression (the
    #             reference's per-epoch validate, client.py:149-171)
    # "full"    — deterministic full-negative-pool scoring (the protocol
    #             behind the published MIND table, evaluation_functions.py:33-47)
    # "last4"   — deterministic last-4-pool-negatives slice (client.py:159-160)
    eval_protocol: str = "full"
    # epoch-in-jit: dispatch the train step in lax.scan chains of this many
    # batches (1 = per-batch dispatch). Amortizes host->device dispatch —
    # the dominant cost of small-batch steps on remote-dispatch links
    # (train.step.build_fed_train_scan); trajectories are identical
    # (tests/test_scan.py). Chains compile for this one static length; a
    # short epoch tail falls back to per-batch dispatch.
    scan_steps: int = 1
    # rounds-in-jit: execute whole federated ROUNDS (all local epochs + the
    # round-end param sync) in compiled chunks of up to this many rounds via
    # train.step.build_fed_round_scan — one XLA dispatch per chunk instead
    # of one per batch. Chunks always break at eval/save cadence boundaries,
    # so checkpoint and evaluation behavior is byte-identical to the
    # host-driven loop (and so is the trajectory — tests/test_scan.py).
    # Requires joint/finetune mode, no server optimizer (FedOpt steps are
    # host-side by design). 1 = host-driven rounds (default).
    rounds_per_scan: int = 1
    # donate the batch buffers to the compiled step/scan programs: the
    # (steps, clients, B, ...) stacks of a round chunk are hundreds of MB at
    # large B, and donation lets XLA reclaim them as scratch once consumed.
    # Safe in the Trainer (every dispatch device_puts fresh arrays); leave
    # False when driving the step builders directly with reused batches
    # (bench.py's chain timer re-dispatches the same 8 batches).
    donate_batch: bool = False
    # keep a separate best-validation-AUC snapshot under
    # <snapshot_dir>/best (full snapshot dir incl. config.json, so
    # `fedrec-recommend --snapshot-dir .../best` serves the best round
    # directly); the incumbent best survives resume. Off by default: the
    # round-cadence snapshots stay the only writers unless asked.
    keep_best: bool = False
    seed: int = 42
    profile: bool = False              # jax.profiler trace around the hot loop
    wandb: bool = False
    wandb_project: str = "fedrec_tpu"
    run_name: str = "run"


@dataclass
class ExperimentConfig:
    data: DataConfig = field(default_factory=DataConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    fed: FedConfig = field(default_factory=FedConfig)
    privacy: PrivacyConfig = field(default_factory=PrivacyConfig)
    shard: ShardConfig = field(default_factory=ShardConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    agg: AggConfig = field(default_factory=AggConfig)

    # ------------------------------------------------------------------ io
    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ExperimentConfig":
        cfg = cls()
        for section_name, section_val in d.items():
            section = getattr(cfg, section_name, None)
            if section is None or not dataclasses.is_dataclass(section):
                raise KeyError(f"unknown config section: {section_name!r}")
            _merge_dataclass(section, section_val, section_name)
        return cfg

    # ------------------------------------------------------- cli overrides
    def apply_overrides(self, overrides: list[str]) -> "ExperimentConfig":
        """Apply ``section.key=value`` strings (e.g. ``fed.num_clients=32``).
        Paths may descend into nested sections (``obs.health.sentry=0``)."""
        for item in overrides:
            if "=" not in item:
                raise ValueError(f"override must be section.key=value, got {item!r}")
            path, raw = item.split("=", 1)
            parts = path.split(".")
            if len(parts) < 2:
                raise ValueError(f"override path must be section.key, got {path!r}")
            section: Any = self
            for part in parts[:-1]:
                section = getattr(section, part, None)
                if section is None or not dataclasses.is_dataclass(section):
                    raise KeyError(f"unknown config section: {path!r}")
            key = parts[-1]
            if not hasattr(section, key):
                raise KeyError(f"unknown config key: {path!r}")
            current = getattr(section, key)
            if dataclasses.is_dataclass(current):
                raise KeyError(
                    f"config path {path!r} names a section, not a key; "
                    f"set one of its fields ({path}.<key>=...)"
                )
            setattr(section, key, _coerce(raw, type(current)))
        return self


# flags deleted from the schema (fedrec-lint CC202 dead-flag findings).
# from_dict tolerates them so snapshot config.json files written by older
# runs keep loading; everything else unknown still fails fast.
_REMOVED_KEYS = {
    "train.total_epochs",   # the CLI positional writes fed.rounds directly
    "train.log_every",      # never consulted; the Trainer logs every round
}


def _merge_dataclass(section: Any, values: dict[str, Any], path: str) -> None:
    """Set ``values`` onto a (possibly nested) config dataclass — the
    recursion behind ``from_dict``, so nested sections like ``obs.health``
    round-trip through to_dict/from_dict like every flat one."""
    for k, v in values.items():
        if f"{path}.{k}" in _REMOVED_KEYS:
            continue
        if not hasattr(section, k):
            raise KeyError(f"unknown config key: {path}.{k}")
        current = getattr(section, k)
        if dataclasses.is_dataclass(current) and isinstance(v, dict):
            _merge_dataclass(current, v, f"{path}.{k}")
        else:
            setattr(section, k, v)


def _coerce(raw: str, ty: type) -> Any:
    if ty is bool:
        low = raw.strip().lower()
        if low in ("1", "true", "yes", "on"):
            return True
        if low in ("0", "false", "no", "off"):
            return False
        raise ValueError(f"cannot parse bool from {raw!r}")
    if ty is int:
        return int(raw)
    if ty is float:
        return float(raw)
    return raw
