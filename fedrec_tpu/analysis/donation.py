"""DA5xx — donated buffers read after dispatch.

``jax.jit(fn, donate_argnums=...)`` hands the argument's buffer to XLA as
scratch: after the dispatch the Python array object still exists but its
buffer is deleted, and the next read raises (or worse, on some backends,
silently reads garbage in async dispatch).  The failure only reproduces
when the donated path actually compiles — i.e. on the TPU, not in a CPU
unit test — which is exactly the class of bug a static check should own.

Scope (deliberately conservative, to keep the analyzer quiet on correct
code): within one module, variables or ``self.<attr>`` slots assigned from
``jax.jit(..., donate_argnums=<literal>)`` are *donating callables*.  At
every call site of one, a donated positional argument that is a plain
name is tracked through the REST of the enclosing straight-line block: a
read before any rebinding is **DA501**.  The idiomatic rebinding
``state, metrics = step(state, batch)`` never fires — the name is rebound
by the very statement that donates it.

Calls inside loops are not chased across iterations (the donated name is
usually rebound by the loop's own dataflow); that asymmetry is the
documented false-negative edge, not a false-positive one.
"""

from __future__ import annotations

import ast

from .core import Finding, ProjectFile, dotted_name, register_codes

CODES = {
    "DA501": "argument donated via donate_argnums is read after the dispatch",
}
register_codes("donation", CODES)


def _donate_positions(call: ast.Call) -> tuple[int, ...] | None:
    """Literal donate_argnums positions of a jax.jit(...) call, else None."""
    dotted = dotted_name(call.func)
    if not (dotted in ("jit", "pjit") or dotted.endswith(".jit") or dotted.endswith(".pjit")):
        return None
    for kw in call.keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for elt in v.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                    out.append(elt.value)
                else:
                    return None  # computed positions: out of static scope
            return tuple(out)
        if isinstance(v, ast.IfExp):
            # the codebase idiom: donate_argnums=(0, 1) if donate_batch
            # else (0,) — the INTERSECTION is always donated
            a = _literal_positions(v.body)
            b = _literal_positions(v.orelse)
            if a is not None and b is not None:
                return tuple(sorted(set(a) & set(b)))
        return None
    return None


def _literal_positions(node: ast.AST) -> tuple[int, ...] | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def _collect_donators(tree: ast.Module) -> dict[str, tuple[int, ...]]:
    """name / "self.attr" -> donated positions, module-wide."""
    out: dict[str, tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        pos = _donate_positions(node.value)
        if pos is None or not pos:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out[t.id] = pos
            elif (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                out[f"self.{t.attr}"] = pos
    return out


def _names_read(node: ast.AST) -> set[str]:
    return {
        n.id for n in ast.walk(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


def _names_bound(stmt: ast.stmt) -> set[str]:
    bound: set[str] = set()
    for n in ast.walk(stmt):
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store, ast.Del)):
            bound.add(n.id)
    return bound


class _BlockScanner:
    """Scan each statement block for donate-then-read sequences."""

    def __init__(self, pf: ProjectFile, donators: dict[str, tuple[int, ...]]):
        self.pf = pf
        self.donators = donators
        self.findings: list[Finding] = []

    def scan_body(self, body: list[ast.stmt]) -> None:
        for i, stmt in enumerate(body):
            for call in self._calls_in(stmt):
                key = self._donator_key(call)
                if key is None:
                    continue
                positions = self.donators[key]
                donated_names = {
                    call.args[p].id
                    for p in positions
                    if p < len(call.args) and isinstance(call.args[p], ast.Name)
                }
                # rebinding by the donating statement itself is the idiom
                donated_names -= _names_bound(stmt)
                if donated_names:
                    self._scan_tail(body[i + 1:], donated_names, key)
            # recurse into nested blocks — but not nested defs/classes,
            # which the top-level walk visits as their own scopes
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if isinstance(sub, list) and sub and isinstance(sub[0], ast.stmt):
                    self.scan_body(sub)
            for h in getattr(stmt, "handlers", []) or []:
                self.scan_body(h.body)

    def _calls_in(self, stmt: ast.stmt):
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                yield node

    def _donator_key(self, call: ast.Call) -> str | None:
        f = call.func
        if isinstance(f, ast.Name) and f.id in self.donators:
            return f.id
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "self"
            and f"self.{f.attr}" in self.donators
        ):
            return f"self.{f.attr}"
        return None

    def _scan_tail(
        self, tail: list[ast.stmt], names: set[str], fn_key: str
    ) -> None:
        live = set(names)
        for stmt in tail:
            if not live:
                return
            # reads anywhere in the statement fire first (a = x + 1 both
            # reads x and binds a)
            read = _names_read(stmt) & live
            for name in sorted(read):
                self.findings.append(Finding(
                    path=self.pf.path,
                    line=stmt.lineno,
                    col=stmt.col_offset,
                    code="DA501",
                    message=(
                        f"`{name}` was donated to `{fn_key}` (donate_argnums) "
                        "and is read after the dispatch — its buffer now "
                        "belongs to XLA; reorder the read or drop the "
                        "donation"
                    ),
                ))
            live -= read  # one report per donated name
            live -= _names_bound(stmt)


def analyze_file(pf: ProjectFile) -> list[Finding]:
    if not pf.path.startswith("fedrec_tpu/"):
        return []
    donators = _collect_donators(pf.tree)
    if not donators:
        return []
    scanner = _BlockScanner(pf, donators)
    for node in ast.walk(pf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
            scanner.scan_body(node.body)
    return scanner.findings
