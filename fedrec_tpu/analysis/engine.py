"""The ``fedrec-lint`` engine: run analyzers, apply suppressions + baseline.

Composition contract (docs/ANALYSIS.md "adding an analyzer"):

* a **per-file analyzer** exports ``analyze_file(pf: ProjectFile) ->
  list[Finding]`` and is listed in :data:`FILE_ANALYZERS`;
* a **project analyzer** exports ``analyze_project(project: Project) ->
  list[Finding]`` and is listed in :data:`PROJECT_ANALYZERS`;
* codes are registered via :func:`core.register_codes` at import time.

The engine owns everything cross-cutting: inline suppressions, the
baseline file, ``--select``/``--ignore`` filtering, and stable ordering.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable

from . import (
    config_contract,
    donation,
    feature_matrix,
    generic,
    metric_contract,
    trace_safety,
)
from .core import (
    CODE_CATALOG,
    DEFAULT_SCAN_ROOTS,
    Finding,
    Project,
    finding_fingerprint,
    load_baseline,
    normalize_scan_roots,
)

FILE_ANALYZERS = {
    "trace_safety": trace_safety.analyze_file,
    "donation": donation.analyze_file,
    "generic": generic.analyze_file,
}
PROJECT_ANALYZERS = {
    "config_contract": config_contract.analyze_project,
    "metric_contract": metric_contract.analyze_project,
    "feature_matrix": feature_matrix.analyze_project,
}

DEFAULT_BASELINE = "fedrec_tpu/analysis/lint_baseline.json"


@dataclass
class LintResult:
    findings: list[Finding]                 # new findings (reported)
    suppressed: int = 0
    baselined: int = 0
    files_scanned: int = 0
    stale_baseline: list[str] = field(default_factory=list)
    all_fingerprints: list[str] = field(default_factory=list)
    # True when ANY filter narrowed the run (paths, select/ignore,
    # analyzers) — THE definition consumers use: --write-baseline refuses
    # filtered results, and stale_baseline is cleared on them (a filtered
    # run reports every deselected entry as "stale")
    filtered: bool = False

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0


def _code_selected(
    code: str, select: set[str] | None, ignore: set[str]
) -> bool:
    def match(spec: str) -> bool:
        return code == spec or code.startswith(spec)

    if any(match(s) for s in ignore):
        return False
    if select is not None:
        return any(match(s) for s in select)
    return True


def _under(path: str, roots: Iterable[str]) -> bool:
    return any(path == r or path.startswith(r.rstrip("/") + "/") for r in roots)


def run_lint(
    root: str | Path,
    scan_roots: Iterable[str] = DEFAULT_SCAN_ROOTS,
    select: Iterable[str] | None = None,
    ignore: Iterable[str] = (),
    baseline_path: str | Path | None = DEFAULT_BASELINE,
    analyzers: Iterable[str] | None = None,
) -> LintResult:
    """Run the lint engine over ``root``.  ``select``/``ignore`` take full
    codes or prefixes (``TS``, ``CC2``).  ``baseline_path`` (relative to
    root) of None disables the baseline.

    ``scan_roots`` narrower than the default is a REPORTING filter, not an
    analysis scope: the project-level analyzers always see the full
    default tree (a partial view would turn every unseen guard/flag into
    a false FM402/CC finding), and findings are then restricted to paths
    under the requested roots.
    """
    root = Path(root).resolve()
    scan_roots = normalize_scan_roots(root, scan_roots)
    partial = set(scan_roots) != set(DEFAULT_SCAN_ROOTS)
    if partial:
        # explicit roots must exist: a typo'd path silently matching
        # nothing would filter the run down to a false-clean exit 0.
        # (DEFAULT roots may legitimately be absent — miniature trees have
        # no benchmarks/ — so only the explicit case is strict.)
        for r in scan_roots:
            if not (root / r).exists():
                raise ValueError(
                    f"scan root {r!r} does not exist under {root} — "
                    "a typo here would lint nothing and report clean"
                )
    load_roots = (
        tuple(dict.fromkeys((*DEFAULT_SCAN_ROOTS, *scan_roots)))
        if partial else scan_roots
    )
    project = Project.load(root, load_roots)
    select_set = set(select) if select is not None else None
    ignore_set = set(ignore)
    wanted = set(analyzers) if analyzers is not None else (
        set(FILE_ANALYZERS) | set(PROJECT_ANALYZERS)
    )
    unknown = wanted - set(FILE_ANALYZERS) - set(PROJECT_ANALYZERS)
    if unknown:
        raise ValueError(f"unknown analyzers: {sorted(unknown)}")

    raw: list[Finding] = []
    for name, fn in FILE_ANALYZERS.items():
        if name not in wanted:
            continue
        for pf in project.files:
            if partial and not _under(pf.path, scan_roots):
                continue
            raw.extend(fn(pf))
    for name, fn in PROJECT_ANALYZERS.items():
        if name in wanted:
            raw.extend(fn(project))

    raw = [f for f in raw if _code_selected(f.code, select_set, ignore_set)]
    if partial:
        raw = [f for f in raw if _under(f.path, scan_roots)]

    # suppressions: line/file comments in the flagged file
    suppressed = 0
    kept: list[Finding] = []
    files_by_path = {pf.path: pf for pf in project.files}
    for f in sorted(set(raw)):
        pf = files_by_path.get(f.path)
        if pf is not None and pf.suppressions.covers(f):
            suppressed += 1
            continue
        kept.append(f)

    # fingerprints are always computed (they feed --write-baseline even on
    # a baseline-less run); the baseline filter applies when a file is set
    baselined = 0
    stale: list[str] = []
    all_fps: list[str] = []
    seen_fps: set[str] = set()
    fingerprinted: list[tuple[Finding, str]] = []
    for f in kept:
        pf = files_by_path.get(f.path)
        lines = pf.lines if pf is not None else []
        fp = finding_fingerprint(f, lines)
        all_fps.append(fp)
        seen_fps.add(fp)
        fingerprinted.append((f, fp))
    filtered = (
        partial
        or select_set is not None
        or bool(ignore_set)
        or analyzers is not None
    )
    if baseline_path is not None:
        known = load_baseline(root / baseline_path)
        kept = [f for f, fp in fingerprinted if fp not in known]
        baselined = len(fingerprinted) - len(kept)
        if not filtered:
            stale = sorted(known - seen_fps)

    return LintResult(
        findings=sorted(kept),
        suppressed=suppressed,
        baselined=baselined,
        files_scanned=len(project.files),
        stale_baseline=stale,
        all_fingerprints=all_fps,
        filtered=filtered,
    )


def codes_table() -> list[tuple[str, str, str]]:
    """(code, analyzer, description) rows, sorted — the ``--list-codes``
    surface and the docs/ANALYSIS.md catalogue source."""
    return sorted(
        (code, analyzer, desc)
        for code, (desc, analyzer) in CODE_CATALOG.items()
    )
