"""MC3xx — the metric contract: registered ⟷ catalogued ⟷ exposable.

The obs registry (``fedrec_tpu.obs.registry``) already fails fast at
RUNTIME when one process re-registers a name as a different kind — but two
subsystems that never run in the same process (trainer vs serving) can
still ship conflicting kinds, and nothing at runtime notices a metric that
was renamed in code but not in docs/OBSERVABILITY.md.  This analyzer makes
those contracts static:

* **MC301** — a metric name registered in code that the
  docs/OBSERVABILITY.md catalogue does not list (operators grep the
  catalogue; an uncatalogued metric is invisible).
* **MC302** — a metric name that is not cleanly Prometheus-exposable:
  after ``sanitize_prom_name`` it must be a valid metric name AND the raw
  name must stick to ``[a-zA-Z0-9_.:@]`` so two distinct dotted names can
  never sanitize into the same exposition name.
* **MC303** — one name registered with conflicting kinds across call sites
  (counter here, gauge there — the cross-process shadowing the runtime
  check cannot see).

Registration sites are ``.counter("name", ...)`` / ``.gauge`` /
``.histogram`` calls with a literal first argument, anywhere in the
package/benchmarks.  Dynamic names (f-strings with holes, variables) are
skipped — the MetricLogger's numeric-gauge mirror is the documented
dynamic surface and is catalogued as such.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path

from .core import Finding, Project, register_codes

CODES = {
    "MC301": "metric registered in code but absent from docs/OBSERVABILITY.md",
    "MC302": "metric name not cleanly Prometheus-sanitizable",
    "MC303": "metric name registered with conflicting kinds across call sites",
}
register_codes("metric_contract", CODES)

CATALOG_DOC = "docs/OBSERVABILITY.md"
REGISTER_METHODS = {"counter", "gauge", "histogram"}

# raw names must stay inside this set so sanitize_prom_name is injective
# on the names the repo actually uses ('@' sanitizes to '_' but only the
# eval\@k family uses it, documented as such)
_RAW_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_.:@]*$")
_BACKTICK_RE = re.compile(r"`([^`]+)`")


@dataclass(frozen=True)
class Registration:
    name: str
    kind: str
    path: str
    line: int
    col: int


def collect_registrations(project: Project) -> list[Registration]:
    regs: list[Registration] = []
    for pf in project.files:
        if pf.path == "fedrec_tpu/obs/registry.py":
            continue  # the registry's own plumbing, not a call site
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (
                isinstance(func, ast.Attribute)
                and func.attr in REGISTER_METHODS
            ):
                continue
            if not node.args:
                continue
            first = node.args[0]
            if not (
                isinstance(first, ast.Constant)
                and isinstance(first.value, str)
            ):
                continue  # dynamic name: out of static scope
            regs.append(Registration(
                name=first.value,
                kind=func.attr,
                path=pf.path,
                line=node.lineno,
                col=node.col_offset,
            ))
    return regs


def catalogued_names(root: Path) -> set[str] | None:
    """Backticked metric tokens from the OBSERVABILITY.md tables; None when
    the doc is missing (each registration then reports MC301)."""
    doc = root / CATALOG_DOC
    if not doc.exists():
        return None
    names: set[str] = set()
    for line in doc.read_text().splitlines():
        for m in _BACKTICK_RE.finditer(line):
            for tok in re.split(r"[,\s/]+", m.group(1)):
                tok = tok.strip()
                tok = re.sub(r"\{[^}]*\}?$", "", tok)   # strip {label=...}
                tok = tok.strip("`*.,:;()[]")
                if tok and _RAW_NAME_RE.match(tok):
                    names.add(tok)
    return names


def analyze_project(project: Project) -> list[Finding]:
    regs = collect_registrations(project)
    catalog = catalogued_names(project.root)
    findings: list[Finding] = []

    kinds: dict[str, dict[str, Registration]] = {}
    for reg in regs:
        kinds.setdefault(reg.name, {}).setdefault(reg.kind, reg)

    reported_301: set[str] = set()
    for reg in regs:
        if not _RAW_NAME_RE.match(reg.name):
            findings.append(Finding(
                path=reg.path, line=reg.line, col=reg.col, code="MC302",
                message=(
                    f"metric name {reg.name!r} is not cleanly "
                    "Prometheus-sanitizable (stick to [a-zA-Z0-9_.:@], "
                    "leading letter/underscore)"
                ),
            ))
        if (catalog is None or reg.name not in catalog) and (
            reg.name not in reported_301
        ):
            reported_301.add(reg.name)
            findings.append(Finding(
                path=reg.path, line=reg.line, col=reg.col, code="MC301",
                message=(
                    f"metric `{reg.name}` ({reg.kind}) is not catalogued "
                    f"in {CATALOG_DOC} — add a table row (name, kind, "
                    "meaning) or rename to an existing entry"
                ),
            ))
    for name, by_kind in sorted(kinds.items()):
        if len(by_kind) > 1:
            sites = sorted(by_kind.values(), key=lambda r: (r.path, r.line))
            desc = ", ".join(
                f"{r.kind} at {r.path}:{r.line}" for r in sites
            )
            first = sites[0]
            findings.append(Finding(
                path=first.path, line=first.line, col=first.col,
                code="MC303",
                message=(
                    f"metric `{name}` registered with conflicting kinds "
                    f"({desc}) — the registry will fail fast only when "
                    "both call sites share a process"
                ),
            ))
    return findings
