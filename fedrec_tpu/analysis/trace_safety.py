"""TS1xx — trace-safety inside jitted scopes.

FedJAX-style stacks live or die by keeping host Python out of traced code:
a ``float(loss)`` inside a jitted step is a blocking device sync per call,
``time.time()`` bakes a trace-time constant into the compiled program, and
a Python ``if`` on a tracer raises ``TracerBoolConversionError`` only on
the path that actually executes.  This analyzer finds **traced scopes**
structurally — functions passed to / decorated with ``jax.jit``,
``shard_map``, ``lax.scan``/``map``/``cond``/``while_loop``, ``vmap``,
``grad``, ``jax.checkpoint``, ``custom_vjp``/``defvjp`` or
``pl.pallas_call``, plus everything nested inside one — and then runs a
lightweight intra-function taint pass:

* parameters are assumed tracer-valued (minus ``self``/``cfg``/``config``/
  ``mesh``, the conventional static closures);
* anything assigned from a tainted expression or a ``jnp.``/``jax.``/
  ``lax.`` call is tainted;
* ``.shape``/``.ndim``/``.dtype``/``.size`` reads are STATIC under jit and
  break the taint — ``int(x.shape[0])`` is idiomatic and never flagged.

Codes:

* **TS101** — ``float()``/``int()``/``bool()`` on a tracer-valued
  expression (host sync / TracerBoolConversionError).
* **TS102** — ``.item()``/``.tolist()``/``.block_until_ready()`` on a
  tracer-valued expression (explicit host sync).
* **TS103** — ``np.*`` call applied to a tracer-valued argument (silently
  materializes the array on host; use ``jnp``).
* **TS104** — ``time.*`` / stdlib ``random.*`` call inside a traced scope
  (trace-time constant masquerading as a runtime value).  Only fires when
  the module imports the STDLIB modules (``jax.random`` via other names is
  untouched).
* **TS105** — Python ``if``/``while`` on a tracer-valued test (heuristic;
  use ``lax.cond``/``jnp.where`` or suppress where the value is provably
  static).

TS105 is the one deliberately-heuristic code: trace-time branching on
static values is idiomatic in the step builders, so taint — not the mere
presence of a branch — is what fires it, and a
``# fedrec-lint: disable=TS105`` with a word of justification is the
documented escape hatch for false positives.
"""

from __future__ import annotations

import ast

from .core import Finding, ProjectFile, dotted_name, register_codes

CODES = {
    "TS101": "float()/int()/bool() on a tracer value inside a jitted scope",
    "TS102": ".item()/.tolist()/.block_until_ready() inside a jitted scope",
    "TS103": "np.* applied to a tracer value inside a jitted scope",
    "TS104": "time.*/random.* call inside a jitted scope",
    "TS105": "Python if/while on a tracer-valued expression (heuristic)",
}
register_codes("trace_safety", CODES)

# call targets whose function-valued arguments become traced scopes; matched
# on the full dotted name or any '.'-boundary suffix (jax.lax.scan ~ lax.scan)
TRACING_CALLS = {
    "jax.jit", "jit", "pjit",
    "pallas_call",                      # pl.pallas_call / pltpu variants
    "lax.scan", "lax.map", "lax.cond", "lax.switch",
    "lax.while_loop", "lax.fori_loop", "lax.associative_scan",
    "jax.vmap", "vmap", "jax.pmap",
    "jax.grad", "jax.value_and_grad", "value_and_grad",
    "jax.checkpoint", "jax.remat", "checkpoint", "remat",
    "jax.custom_vjp", "custom_vjp", "jax.custom_jvp", "custom_jvp",
    "shard_map",
}

# attribute-call registrations: f.defvjp(fwd, bwd) / f.defjvp(...)
TRACING_METHOD_CALLS = {"defvjp", "defjvp", "def_fwd", "def_bwd"}

UNTAINT_ATTRS = {"shape", "ndim", "dtype", "size"}
STATIC_PARAM_NAMES = {"self", "cls", "cfg", "config", "mesh", "hparams"}
# params annotated with host-static types are trace-time constants by the
# repo's own convention (robust_aggregate(method: str, trim_k: int, ...))
STATIC_ANNOTATIONS = {"str", "bool", "int", "float"}
HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready", "__array__"}
SCALAR_COERCIONS = {"float", "int", "bool"}


def _matches_tracing(dotted: str) -> bool:
    if dotted in TRACING_CALLS:
        return True
    return any(dotted.endswith("." + s) for s in TRACING_CALLS)


TRACED_SCOPE_MARK = "fedrec-lint: traced-scope"


def _collect_traced_functions(
    tree: ast.Module, lines: list[str] | None = None
) -> set[ast.AST]:
    """Function nodes that execute under a trace (see module docstring).

    Besides the structural rules, a ``# fedrec-lint: traced-scope``
    comment on the def line (or the line above) marks a function traced —
    the opt-in for code only ever CALLED from jitted scopes in other
    modules (fed/robust.py's in-graph aggregators), which no
    single-module structural rule can see.
    """
    funcs: dict[str, list[ast.AST]] = {}
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.setdefault(node.name, []).append(node)

    traced: set[ast.AST] = set()

    def mark_name(name: str) -> None:
        for fn in funcs.get(name, []):
            traced.add(fn)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                dotted = dotted_name(target)
                if _matches_tracing(dotted):
                    traced.add(node)
                # @partial(jax.jit, ...) — the wrapper is the first arg
                if isinstance(dec, ast.Call) and dotted.endswith("partial"):
                    if dec.args and _matches_tracing(dotted_name(dec.args[0])):
                        traced.add(node)
        if isinstance(node, ast.Call):
            dotted = dotted_name(node.func)
            is_tracing = _matches_tracing(dotted)
            is_method_reg = (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in TRACING_METHOD_CALLS
            )
            if not (is_tracing or is_method_reg):
                continue
            cands = list(node.args) + [kw.value for kw in node.keywords]
            # partial(jax.jit, body, ...): skip the wrapper itself
            if dotted.endswith("partial"):
                cands = cands[1:]
            for arg in cands:
                if isinstance(arg, ast.Name):
                    mark_name(arg.id)
                elif isinstance(arg, (ast.FunctionDef, ast.Lambda)):
                    traced.add(arg)

    if lines:
        for lst in funcs.values():
            for fn in lst:
                first = (
                    fn.decorator_list[0].lineno
                    if getattr(fn, "decorator_list", None)
                    else fn.lineno
                )
                for lineno in (first, first - 1):
                    if (
                        1 <= lineno <= len(lines)
                        and TRACED_SCOPE_MARK in lines[lineno - 1]
                    ):
                        traced.add(fn)

    # nesting: every def inside a traced def is traced
    def chain_traced(node: ast.AST) -> bool:
        cur = parents.get(node)
        while cur is not None:
            if cur in traced:
                return True
            cur = parents.get(cur)
        return False

    for lst in funcs.values():
        for fn in lst:
            if fn not in traced and chain_traced(fn):
                traced.add(fn)

    # call-graph propagation: a module-local function CALLED from a traced
    # scope executes under the same trace (local_step is never passed to
    # jax.jit itself — sharded_step, which IS, calls it).  Fixpoint over
    # name edges; cross-module callees are the traced-scope marker's job.
    calls_by_fn: dict[ast.AST, set[str]] = {}
    for lst in funcs.values():
        for fn in lst:
            called: set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    if isinstance(node.func, ast.Name):
                        called.add(node.func.id)
                    # function-VALUED args into a call made under trace run
                    # under the same trace (_cohort_call(local_step, ...))
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        if isinstance(arg, ast.Name) and arg.id in funcs:
                            called.add(arg.id)
            calls_by_fn[fn] = called
    changed = True
    while changed:
        changed = False
        for fn in list(traced):
            for name in calls_by_fn.get(fn, ()):
                for callee in funcs.get(name, []):
                    if callee not in traced:
                        traced.add(callee)
                        changed = True
    return traced


class _TaintChecker:
    """One traced function: seed taint, sweep statements, emit findings."""

    def __init__(self, pf: ProjectFile, fn: ast.AST, flag_time: bool,
                 flag_random: bool):
        self.pf = pf
        self.fn = fn
        self.flag_time = flag_time
        self.flag_random = flag_random
        self.findings: list[Finding] = []
        self.tainted: set[str] = set()
        # names assigned from list/dict/set displays or comprehensions:
        # their ELEMENTS may be tracers but their truthiness/emptiness is a
        # static host property, so `if not leaves:` never fires TS105
        self.containers: set[str] = set()
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            args = fn.args
            for a in (
                args.posonlyargs + args.args + args.kwonlyargs
                + ([args.vararg] if args.vararg else [])
                + ([args.kwarg] if args.kwarg else [])
            ):
                if a.arg in STATIC_PARAM_NAMES:
                    continue
                ann = getattr(a, "annotation", None)
                if ann is not None and dotted_name(ann) in STATIC_ANNOTATIONS:
                    continue
                self.tainted.add(a.arg)

    # ------------------------------------------------------------- taint
    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in UNTAINT_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            dotted = dotted_name(node.func)
            # type-level / shape-level builtins are static under jit
            if dotted in ("isinstance", "len", "type", "hasattr"):
                return False
            root = dotted.split(".", 1)[0]
            if root in ("jnp", "lax", "jax"):
                return True
            # method call on a tainted receiver (batch.sum()) stays tainted
            if isinstance(node.func, ast.Attribute) and self.is_tainted(
                node.func.value
            ):
                return True
            return any(
                self.is_tainted(a)
                for a in list(node.args) + [kw.value for kw in node.keywords]
            )
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value) or self.is_tainted(node.slice)
        if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        ):
            # identity tests (`x is None`) are host-level structure checks,
            # never tracer-valued — evaluated once at trace time
            return False
        if isinstance(node, (ast.Constant, ast.Lambda)):
            return False
        return any(self.is_tainted(c) for c in ast.iter_child_nodes(node))

    def _taint_target(self, target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self.tainted.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._taint_target(elt)
        elif isinstance(target, ast.Starred):
            self._taint_target(target.value)

    # ------------------------------------------------------------- sweep
    def run(self) -> list[Finding]:
        body = getattr(self.fn, "body", [])
        if isinstance(body, ast.expr):  # lambda: body is a single expression
            self._check_expr(body)
            return self.findings
        # two passes: loop bodies can read names assigned later in the loop
        for _ in range(2):
            self.findings = []
            for stmt in body:
                self._visit_stmt(stmt)
        return self.findings

    def _visit_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs are analyzed as their own traced scopes
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = stmt.value
            if value is not None:
                self._check_expr(value)
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                if isinstance(value, (
                    ast.List, ast.ListComp, ast.Dict, ast.DictComp,
                    ast.Set, ast.SetComp,
                )):
                    for t in targets:
                        if isinstance(t, ast.Name):
                            self.containers.add(t.id)
                if self.is_tainted(value):
                    for t in targets:
                        self._taint_target(t)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._check_expr(stmt.test)
            if self.is_tainted(stmt.test) and not self._container_truthiness(
                stmt.test
            ):
                kind = "if" if isinstance(stmt, ast.If) else "while"
                self._emit(
                    stmt, "TS105",
                    f"Python `{kind}` on a tracer-valued expression — "
                    "traced code sees only one branch; use lax.cond / "
                    "jnp.where (or suppress if provably static)",
                )
            for s in stmt.body + getattr(stmt, "orelse", []):
                self._visit_stmt(s)
            return
        if isinstance(stmt, ast.For):
            self._check_expr(stmt.iter)
            if self.is_tainted(stmt.iter):
                self._taint_target(stmt.target)
            for s in stmt.body + stmt.orelse:
                self._visit_stmt(s)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._check_expr(item.context_expr)
            for s in stmt.body:
                self._visit_stmt(s)
            return
        if isinstance(stmt, ast.Try):
            for s in stmt.body + stmt.orelse + stmt.finalbody:
                self._visit_stmt(s)
            for h in stmt.handlers:
                for s in h.body:
                    self._visit_stmt(s)
            return
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            self._check_expr(stmt.value)
            return
        if isinstance(stmt, ast.Expr):
            self._check_expr(stmt.value)
            return
        # Raise/Pass/Break/...: check any embedded expressions generically
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._check_expr(child)

    def _container_truthiness(self, test: ast.expr) -> bool:
        """`if leaves:` / `if not leaves:` on a known container name — its
        emptiness is static even when its elements are tracers."""
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            test = test.operand
        return isinstance(test, ast.Name) and test.id in self.containers

    # ------------------------------------------------------------ checks
    def _check_expr(self, expr: ast.expr) -> None:
        for node in ast.walk(expr):
            if isinstance(node, (ast.FunctionDef, ast.Lambda)):
                continue
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in SCALAR_COERCIONS
                and node.args
                and self.is_tainted(node.args[0])
            ):
                self._emit(
                    node, "TS101",
                    f"`{node.func.id}()` on a tracer value forces a host "
                    "sync (or TracerBoolConversionError) inside a jitted "
                    "scope",
                )
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in HOST_SYNC_METHODS
                and self.is_tainted(node.func.value)
            ):
                self._emit(
                    node, "TS102",
                    f"`.{node.func.attr}()` on a tracer value is an "
                    "explicit host sync inside a jitted scope",
                )
            root = dotted.split(".", 1)[0]
            if root in ("np", "numpy") and any(
                self.is_tainted(a)
                for a in list(node.args) + [kw.value for kw in node.keywords]
            ):
                self._emit(
                    node, "TS103",
                    f"`{dotted}` on a tracer value materializes it on host "
                    "— use the jnp equivalent inside jitted scopes",
                )
            if (self.flag_time and dotted.startswith("time.")) or (
                self.flag_random and dotted.startswith("random.")
            ):
                self._emit(
                    node, "TS104",
                    f"`{dotted}()` inside a jitted scope bakes a "
                    "trace-time host value into the compiled program",
                )

    def _emit(self, node: ast.AST, code: str, message: str) -> None:
        self.findings.append(Finding(
            path=self.pf.path,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            code=code,
            message=message,
        ))


def _stdlib_import_flags(tree: ast.Module) -> tuple[bool, bool]:
    """(imports stdlib time as `time`, imports stdlib random as `random`)."""
    time_flag = random_flag = False
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name
                if alias.name == "time" and name == "time":
                    time_flag = True
                if alias.name == "random" and name == "random":
                    random_flag = True
    return time_flag, random_flag


def analyze_file(pf: ProjectFile) -> list[Finding]:
    if not pf.path.startswith("fedrec_tpu/"):
        return []
    traced = _collect_traced_functions(pf.tree, pf.lines)
    if not traced:
        return []
    flag_time, flag_random = _stdlib_import_flags(pf.tree)
    findings: list[Finding] = []
    for fn in traced:
        checker = _TaintChecker(pf, fn, flag_time, flag_random)
        findings.extend(checker.run())
    # one finding per (line, code): the 2-pass sweep and nested walks can
    # revisit the same node
    seen: set[tuple] = set()
    out = []
    for f in sorted(findings):
        key = (f.line, f.col, f.code)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out
