"""Shared machinery for the ``fedrec-lint`` analyzers.

The engine's contract, in one place:

* A **Finding** is ``(path, line, col, code, message)``.  Codes are
  ``<family><number>`` (``TS101``, ``CC202``, ...); every analyzer owns one
  family and registers its codes in :data:`CODE_CATALOG` so ``--list-codes``
  and docs/ANALYSIS.md can never drift from the implementation.
* **Suppressions** are source comments.  ``# fedrec-lint: disable=TS101``
  (comma list) silences matching findings on that line;
  ``# fedrec-lint: disable-next=TS101`` silences the following line;
  ``# fedrec-lint: disable-file=TS101`` anywhere silences the whole file.
  ``disable=all`` works in each position.  Suppressions are deliberately
  *code-scoped* — a bare ``# fedrec-lint: disable`` is a parse error, so a
  suppression always says what it is hiding.
* The **baseline** is a checked-in JSON file of finding fingerprints.
  Fingerprints hash ``(path, code, stripped source line, occurrence index)``
  — NOT the line number — so unrelated edits above a baselined finding do
  not resurrect it, while editing the offending line itself does.
* A **Project** is the parsed file set the project-level analyzers (config
  contract, metric contract, feature matrix) share; per-file analyzers
  (trace safety, donation, generic) see one :class:`ProjectFile` at a time.

Everything here is stdlib-only (``ast`` + ``re`` + ``json``); the linter
must run in any environment the package itself runs in.
"""

from __future__ import annotations

import ast
import hashlib
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable

# ----------------------------------------------------------------- findings


@dataclass(frozen=True, order=True)
class Finding:
    """One lint result, sortable into stable report order."""

    path: str          # repo-relative, forward slashes
    line: int          # 1-based; 0 = file-level finding
    col: int
    code: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"


# code -> (one-line description, analyzer name); analyzers register at import
CODE_CATALOG: dict[str, tuple[str, str]] = {}


def register_codes(analyzer: str, codes: dict[str, str]) -> None:
    for code, desc in codes.items():
        existing = CODE_CATALOG.get(code)
        if existing is not None and existing != (desc, analyzer):
            raise ValueError(f"lint code {code!r} registered twice")
        CODE_CATALOG[code] = (desc, analyzer)


# ------------------------------------------------------------- suppressions

_SUPPRESS_RE = re.compile(
    r"#\s*fedrec-lint:\s*(disable|disable-next|disable-file)\s*=\s*"
    r"([A-Za-z0-9_,\s]+)"
)


@dataclass
class Suppressions:
    """Per-file suppression map parsed from source comments."""

    line_codes: dict[int, set[str]] = field(default_factory=dict)
    file_codes: set[str] = field(default_factory=set)

    def covers(self, finding: Finding) -> bool:
        for codes in (self.file_codes, self.line_codes.get(finding.line, ())):
            if "all" in codes or finding.code in codes:
                return True
        return False


def parse_suppressions(src: str) -> Suppressions:
    sup = Suppressions()
    for lineno, line in enumerate(src.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        kind = m.group(1)
        codes = {c.strip() for c in m.group(2).split(",") if c.strip()}
        if kind == "disable-file":
            sup.file_codes |= codes
        elif kind == "disable-next":
            sup.line_codes.setdefault(lineno + 1, set()).update(codes)
        else:
            sup.line_codes.setdefault(lineno, set()).update(codes)
    return sup


# ----------------------------------------------------------------- baseline


def finding_fingerprint(finding: Finding, src_lines: list[str]) -> str:
    """Line-number-independent identity of a finding (see module docstring).

    The occurrence index disambiguates identical lines (two ``import os``
    statements) without pinning absolute positions.  FILE-level findings
    (line 0 — stale matrix rules, drifted docs tables) have no source line
    to anchor to, so their MESSAGE is the identity: without it, every
    line-0 finding with the same (path, code) would collapse into one
    fingerprint and baselining one stale rule would silence them all.
    """
    if not (1 <= finding.line <= len(src_lines)):
        raw = f"{finding.path}\x00{finding.code}\x00msg\x00{finding.message}"
        return hashlib.sha1(raw.encode()).hexdigest()[:16]
    text = src_lines[finding.line - 1].strip()
    occurrence = 0
    for line in src_lines[: finding.line - 1]:
        if line.strip() == text:
            occurrence += 1
    raw = f"{finding.path}\x00{finding.code}\x00{text}\x00{occurrence}"
    return hashlib.sha1(raw.encode()).hexdigest()[:16]


def load_baseline(path: Path) -> set[str]:
    if not path.exists():
        return set()
    data = json.loads(path.read_text())
    return set(data.get("fingerprints", []))


def write_baseline(path: Path, fingerprints: Iterable[str]) -> None:
    payload = {
        "format": "fedrec-lint-baseline-v1",
        "fingerprints": sorted(set(fingerprints)),
    }
    path.write_text(json.dumps(payload, indent=2) + "\n")


# ------------------------------------------------------------ project model


@dataclass
class ProjectFile:
    """One parsed source file plus its derived per-file state."""

    path: str                   # repo-relative, forward slashes
    abspath: Path
    src: str
    tree: ast.Module
    lines: list[str]
    suppressions: Suppressions

    @classmethod
    def load(cls, root: Path, abspath: Path) -> "ProjectFile | None":
        src = abspath.read_text()
        try:
            tree = ast.parse(src, filename=str(abspath))
        except SyntaxError:
            return None
        rel = abspath.relative_to(root).as_posix()
        return cls(
            path=rel,
            abspath=abspath,
            src=src,
            tree=tree,
            lines=src.splitlines(),
            suppressions=parse_suppressions(src),
        )


# source roots scanned by default, relative to the repo root.  tests/ are
# deliberately excluded: they construct adversarial configs and fake traced
# scopes on purpose (the lint fixture corpus most of all).
DEFAULT_SCAN_ROOTS = ("fedrec_tpu", "benchmarks", "bench.py")

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def iter_python_files(root: Path, scan_roots: Iterable[str]) -> list[Path]:
    out: list[Path] = []
    seen: set[Path] = set()
    for rel in scan_roots:
        p = root / rel
        candidates: list[Path] = []
        if p.is_file() and p.suffix == ".py":
            candidates = [p]
        elif p.is_dir():
            candidates = [
                sub for sub in sorted(p.rglob("*.py"))
                # skip-dirs are judged INSIDE the scan root: a repo that
                # happens to live under an ancestor named .venv or
                # node_modules must still scan
                if not any(part in _SKIP_DIRS for part in sub.relative_to(p).parts)
            ]
        for c in candidates:
            # overlapping roots (fedrec_tpu + fedrec_tpu/fed) must not
            # load/analyze a file twice
            r = c.resolve()
            if r not in seen:
                seen.add(r)
                out.append(c)
    return out


def normalize_scan_roots(root: Path, scan_roots: Iterable[str]) -> tuple[str, ...]:
    """Repo-relative, './'-free, forward-slash scan roots.  A root outside
    the repo raises — silently matching nothing would make a filtered run
    false-clean."""
    out = []
    for r in scan_roots:
        p = (root / r).resolve() if not Path(r).is_absolute() else Path(r).resolve()
        try:
            out.append(p.relative_to(root.resolve()).as_posix())
        except ValueError:
            raise ValueError(
                f"scan root {r!r} is outside the repo root {root} — "
                "paths must name files/dirs under the tree being linted"
            ) from None
    return tuple(out)


@dataclass
class Project:
    """The whole parsed file set, shared by project-level analyzers."""

    root: Path
    files: list[ProjectFile]

    @classmethod
    def load(
        cls, root: Path, scan_roots: Iterable[str] = DEFAULT_SCAN_ROOTS
    ) -> "Project":
        root = Path(root).resolve()
        files = []
        for abspath in iter_python_files(root, scan_roots):
            pf = ProjectFile.load(root, abspath)
            if pf is not None:
                files.append(pf)
        return cls(root=root, files=files)

    def file(self, rel: str) -> ProjectFile | None:
        for f in self.files:
            if f.path == rel:
                return f
        return None


# ---------------------------------------------------------------- ast utils


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target: ``jax.lax.scan(...)`` -> ``jax.lax.scan``.

    Non-name bases (``foo().bar(...)``) contribute an empty head; the
    trailing attribute path is what the analyzers match on.
    """
    return dotted_name(node.func)


def dotted_name(node: ast.AST) -> str:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    parts.reverse()
    return ".".join(parts)


def literal_str(node: ast.AST) -> str | None:
    """Best-effort literal string: constants, implicit/explicit concatenation
    and f-strings (literal parts only, ``{...}`` holes become ``*``)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return "".join(parts)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = literal_str(node.left)
        right = literal_str(node.right)
        if left is not None and right is not None:
            return left + right
    return None
