"""FM4xx — the feature-compatibility matrix, machine-checked.

The repo's incompatible feature combinations (``fuse_hot_path`` × DP-SGD,
``seq_shards>1`` × chaos, non-decodable codecs × numpy robust reduce, ...)
are enforced by fail-fast guards scattered through ``train/step.py``,
``train/trainer.py``, ``models/``, ``parallel/`` and the CLIs.  Before
this analyzer they were ALSO documented by hand, in three different docs
— the classic three-copies drift.  Now ``analysis/feature_matrix.toml``
is the single declared source:

* each ``[[rules]]`` entry names the feature pair, its status
  (``incompatible`` / ``requires``), the guard file(s) and a regex the
  guard's raise message must match, and the one-line why;
* the **docs table** (docs/ANALYSIS.md between the
  ``FEATURE_MATRIX_BEGIN/END`` markers) is GENERATED from the toml
  (``fedrec-lint --write-feature-table``), never hand-edited.

Codes:

* **FM401** — a feature-combination guard in code (a ``ValueError`` /
  ``NotImplementedError`` whose message reads like a compatibility
  contract) that no toml rule claims: the matrix is missing a row.
* **FM402** — a toml rule whose regex matches no raise in its guard files:
  the guard was removed/reworded and the matrix is stale.
* **FM403** — the generated docs table does not match the toml (drift;
  run ``fedrec-lint --write-feature-table``).

Guard-candidate detection is deliberately message-based: the guard's
raise message IS the operator contract, so a guard whose message doesn't
state the incompatibility is a guard worth rewording.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path

from .core import Finding, Project, dotted_name, literal_str, register_codes

try:  # Python 3.11+
    import tomllib as _toml
except ImportError:  # pragma: no cover - 3.10 rig
    import tomli as _toml  # type: ignore[no-redef]

CODES = {
    "FM401": "feature-combination guard in code not declared in feature_matrix.toml",
    "FM402": "feature_matrix.toml rule with no matching guard in code (stale)",
    "FM403": "generated feature-compatibility docs table drifted from the toml",
}
register_codes("feature_matrix", CODES)

MATRIX_PATH = "fedrec_tpu/analysis/feature_matrix.toml"
DOCS_PATH = "docs/ANALYSIS.md"
TABLE_BEGIN = "<!-- FEATURE_MATRIX_BEGIN (generated from analysis/feature_matrix.toml — edit the toml, then `fedrec-lint --write-feature-table`) -->"
TABLE_END = "<!-- FEATURE_MATRIX_END -->"

GUARD_EXCEPTIONS = {"ValueError", "NotImplementedError"}
# unconditional markers: the message states a combination contract outright
CANDIDATE_MARKERS = (
    "not supported",
    "not combinable",
    "cannot be combined",
    "cannot run under",
    "incompatible",
)
# conditional markers: common words, only a contract when a dotted flag is
# also named in the message
CONDITIONAL_MARKERS = ("requires", "needs", "assumes")
FLAG_TOKEN_RE = re.compile(
    r"\b(data|model|optim|fed|privacy|shard|train|obs|chaos)\.[a-z_]"
)


@dataclass(frozen=True)
class GuardFact:
    path: str
    line: int
    message: str          # literal text, f-string holes as '*'

    @property
    def is_candidate(self) -> bool:
        low = self.message.lower()
        if any(m in low for m in CANDIDATE_MARKERS):
            return True
        return any(m in low for m in CONDITIONAL_MARKERS) and bool(
            FLAG_TOKEN_RE.search(self.message)
        )


@dataclass
class Rule:
    id: str
    feature: str
    other: str
    status: str           # "incompatible" | "requires"
    guard_files: list[str]
    guard_pattern: str
    why: str

    def matches(self, fact: GuardFact) -> bool:
        if fact.path not in self.guard_files:
            return False
        return re.search(self.guard_pattern, fact.message) is not None


def collect_guard_facts(project: Project) -> list[GuardFact]:
    facts: list[GuardFact] = []
    for pf in project.files:
        if not pf.path.startswith("fedrec_tpu/") or pf.path.startswith(
            "fedrec_tpu/analysis/"
        ):
            continue
        for node in ast.walk(pf.tree):
            if not (isinstance(node, ast.Raise) and isinstance(node.exc, ast.Call)):
                continue
            exc_name = dotted_name(node.exc.func).split(".")[-1]
            if exc_name not in GUARD_EXCEPTIONS or not node.exc.args:
                continue
            msg = literal_str(node.exc.args[0])
            if msg is None:
                continue
            facts.append(GuardFact(path=pf.path, line=node.lineno, message=msg))
    return facts


def load_rules(root: Path) -> list[Rule] | None:
    p = root / MATRIX_PATH
    if not p.exists():
        return None
    data = _toml.loads(p.read_text())
    rules = []
    for raw in data.get("rules", []):
        rules.append(Rule(
            id=raw["id"],
            feature=raw["feature"],
            other=raw["other"],
            status=raw.get("status", "incompatible"),
            guard_files=list(raw["guard_files"]),
            guard_pattern=raw["guard_pattern"],
            why=raw.get("why", ""),
        ))
    return rules


# ------------------------------------------------------------- docs table


def render_table(rules: list[Rule]) -> str:
    """The generated compatibility table, sorted by rule id for stability."""
    lines = [
        TABLE_BEGIN,
        "",
        "| feature | combined with / requirement | status | enforced at | why |",
        "|---|---|---|---|---|",
    ]
    for r in sorted(rules, key=lambda r: r.id):
        status = "✗ incompatible" if r.status == "incompatible" else "→ requires"
        guards = ", ".join(f"`{g}`" for g in r.guard_files)
        lines.append(
            f"| `{r.feature}` | `{r.other}` | {status} | {guards} | {r.why} |"
        )
    lines += ["", TABLE_END]
    return "\n".join(lines)


def _find_table_region(text: str) -> tuple[int, int] | None:
    begin = text.find(TABLE_BEGIN)
    end = text.find(TABLE_END)
    if begin == -1 or end == -1 or end < begin:
        return None
    return begin, end + len(TABLE_END)


def write_docs_table(root: Path) -> bool:
    """Regenerate the docs table in place; returns True if the file changed."""
    rules = load_rules(root)
    if rules is None:
        raise FileNotFoundError(MATRIX_PATH)
    doc = root / DOCS_PATH
    rendered = render_table(rules)
    text = doc.read_text() if doc.exists() else ""
    region = _find_table_region(text)
    if region is None:
        new = text.rstrip() + "\n\n" + rendered + "\n"
    else:
        new = text[: region[0]] + rendered + text[region[1]:]
    if new != text:
        doc.write_text(new)
        return True
    return False


# ------------------------------------------------------------------ driver


def analyze_project(project: Project) -> list[Finding]:
    rules = load_rules(project.root)
    if rules is None:
        return [Finding(
            path=MATRIX_PATH, line=0, col=0, code="FM402",
            message="analysis/feature_matrix.toml is missing — the "
                    "feature-compatibility matrix cannot be checked",
        )]
    facts = collect_guard_facts(project)
    findings: list[Finding] = []

    for fact in facts:
        if not fact.is_candidate:
            continue
        if not any(r.matches(fact) for r in rules):
            findings.append(Finding(
                path=fact.path, line=fact.line, col=0, code="FM401",
                message=(
                    "feature-combination guard not declared in "
                    f"{MATRIX_PATH} (message: "
                    f"{fact.message[:80]!r}...) — add a [[rules]] entry "
                    "so the docs table stays complete"
                ),
            ))
    for rule in rules:
        if not any(rule.matches(f) for f in facts):
            findings.append(Finding(
                path=MATRIX_PATH, line=0, col=0, code="FM402",
                message=(
                    f"rule {rule.id!r} matches no raise in "
                    f"{rule.guard_files} — the guard moved or was "
                    "reworded; update the rule (or delete it if the "
                    "combination became legal)"
                ),
            ))

    doc = project.root / DOCS_PATH
    text = doc.read_text() if doc.exists() else ""
    region = _find_table_region(text)
    current = text[region[0]: region[1]] if region else None
    if current != render_table(rules):
        findings.append(Finding(
            path=DOCS_PATH, line=0, col=0, code="FM403",
            message=(
                "feature-compatibility table is stale (or missing) — run "
                "`fedrec-lint --write-feature-table` to regenerate it "
                "from analysis/feature_matrix.toml"
            ),
        ))
    return findings
