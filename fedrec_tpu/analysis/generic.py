"""GL9xx — generic hygiene layer (pyflakes-subset, stdlib-only).

The environment this repo targets does not ship ruff or pyflakes, so the
handful of generic rules worth gating on are implemented here and run as
part of ``fedrec-lint``.  When ruff IS installed, ``scripts/lint.sh``
additionally runs the ``[tool.ruff]`` rule subset from pyproject.toml —
the two layers agree by construction because the builtin rules are a
strict subset of the configured ruff ones (F401/F601/F541 equivalents).

Codes:

* **GL901** — unused import (module or function scope).  ``__init__.py``
  re-export surfaces are exempt, as are imports under
  ``try:/except ImportError`` compat shims, ``if TYPE_CHECKING:`` blocks,
  and lines carrying a ``# noqa`` marker.
* **GL902** — duplicate literal key in a dict display (the last one wins
  silently — always a bug or a merge scar).
* **GL903** — f-string with no placeholders (usually a forgotten ``f`` on
  the NEXT string, or a stray ``f`` that will confuse a future editor).
"""

from __future__ import annotations

import ast

from .core import Finding, ProjectFile, register_codes

CODES = {
    "GL901": "unused import",
    "GL902": "duplicate literal key in dict display",
    "GL903": "f-string without placeholders",
}
register_codes("generic", CODES)

_NOQA_MARKERS = ("# noqa", "#noqa")


def _binding_names(node: ast.Import | ast.ImportFrom) -> list[str]:
    names = []
    for alias in node.names:
        if alias.name == "*":
            continue
        if alias.asname:
            names.append(alias.asname)
        else:
            names.append(alias.name.split(".")[0])
    return names


def _in_compat_block(node: ast.AST, parents: dict[ast.AST, ast.AST]) -> bool:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.Try):
            for h in cur.handlers:
                t = h.type
                names = []
                if isinstance(t, ast.Name):
                    names = [t.id]
                elif isinstance(t, ast.Tuple):
                    names = [e.id for e in t.elts if isinstance(e, ast.Name)]
                if any(n in ("ImportError", "ModuleNotFoundError") for n in names):
                    return True
        if isinstance(cur, ast.If):
            test = cur.test
            t_name = test.id if isinstance(test, ast.Name) else (
                test.attr if isinstance(test, ast.Attribute) else ""
            )
            if t_name == "TYPE_CHECKING":
                return True
        cur = parents.get(cur)
    return False


def analyze_file(pf: ProjectFile) -> list[Finding]:
    findings: list[Finding] = []
    if not pf.path.endswith("__init__.py"):
        findings.extend(_unused_imports(pf))
    findings.extend(_dict_and_fstring_checks(pf))
    return findings


def _unused_imports(pf: ProjectFile) -> list[Finding]:
    parents: dict[ast.AST, ast.AST] = {}
    imports: list[tuple[ast.stmt, str]] = []
    used: set[str] = set()
    exported: set[str] = set()

    for node in ast.walk(pf.tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    for node in ast.walk(pf.tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            if isinstance(node, ast.ImportFrom) and node.module == "__future__":
                continue
            if _in_compat_block(node, parents):
                continue
            line = pf.lines[node.lineno - 1] if node.lineno <= len(pf.lines) else ""
            if any(m in line for m in _NOQA_MARKERS):
                continue
            for name in _binding_names(node):
                imports.append((node, name))
        elif isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            pass  # the chain root is a Name node, already walked
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            # __all__ entries and string annotations keep imports alive
            exported.add(node.value)

    findings = []
    for node, name in imports:
        if name in used or name in exported:
            continue
        findings.append(Finding(
            path=pf.path, line=node.lineno, col=node.col_offset,
            code="GL901",
            message=f"`{name}` is imported but never used",
        ))
    return findings


def _dict_and_fstring_checks(pf: ProjectFile) -> list[Finding]:
    findings: list[Finding] = []
    # format specs (`{x:.4f}`) are themselves JoinedStr nodes with no
    # placeholders — collect them so GL903 never fires on one
    format_specs = {
        id(n.format_spec)
        for n in ast.walk(pf.tree)
        if isinstance(n, ast.FormattedValue) and n.format_spec is not None
    }
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Dict):
            seen: dict[object, int] = {}
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(
                    k.value, (str, int, float, bool, bytes)
                ):
                    key = (type(k.value).__name__, k.value)
                    if key in seen:
                        findings.append(Finding(
                            path=pf.path, line=k.lineno, col=k.col_offset,
                            code="GL902",
                            message=(
                                f"duplicate dict key {k.value!r} (first at "
                                f"line {seen[key]}) — the later value "
                                "silently wins"
                            ),
                        ))
                    else:
                        seen[key] = k.lineno
        elif isinstance(node, ast.JoinedStr):
            if id(node) not in format_specs and not any(
                isinstance(v, ast.FormattedValue) for v in node.values
            ):
                findings.append(Finding(
                    path=pf.path, line=node.lineno, col=node.col_offset,
                    code="GL903",
                    message="f-string has no placeholders — drop the `f` "
                            "(or it hides a missing `{}`)",
                ))
    return findings
