"""CC2xx — the config contract: declared ⟷ read ⟷ documented.

``config.py`` is the single source of truth for every ``cfg.<section>.<key>``
flag.  Three drifts are possible as the tree grows, and each gets a code:

* **CC201** — a ``cfg.<section>.<key>`` attribute read that does NOT
  resolve to a declared default (typo'd key, or a flag someone removed).
  ``from_dict`` would only catch this at runtime, on the config path that
  actually executes.
* **CC202** — a declared default that is never read anywhere in the
  package, benchmarks or CLIs (dead flag: it parses, round-trips, and does
  nothing — the worst kind of knob).
* **CC203** — a declared flag that appears in no README/docs flag table
  (doc drift: the flag works but operators can't discover it).

Read detection understands the codebase's real access idioms:

* direct chains rooted at ``cfg``/``config`` or ``self.cfg``/``self.config``
  (``cfg.fed.robust.method``);
* section aliases — ``rb = cfg.fed.robust`` then ``rb.method``, at function
  or ``self.attr`` scope;
* annotation aliases — a parameter or class attribute annotated with a
  config dataclass (``data_cfg: DataConfig``) makes ``data_cfg.shuffle`` a
  read of ``data.shuffle``;
* ``getattr(cfg.model, "fuse_hot_path", default)`` guarded reads.

Documentation detection accepts a flag if its full dotted path appears
backticked in README.md or docs/*.md, or its bare key appears backticked on
a line that also mentions the section prefix (the grouped-row idiom:
```chaos.pop_drop_rate` / `pop_straggle_ms```).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path

from .core import Finding, Project, dotted_name, register_codes

CODES = {
    "CC201": "config attribute read with no declared default in config.py",
    "CC202": "declared config default never read anywhere (dead flag)",
    "CC203": "declared config flag absent from every README/docs flag table",
}
register_codes("config_contract", CODES)

CONFIG_MODULE = "fedrec_tpu/config.py"
ROOT_CLASS = "ExperimentConfig"
CFG_ROOT_NAMES = {"cfg", "config"}
DOC_GLOBS = ("README.md", "docs/*.md")


# ------------------------------------------------------------- declarations


@dataclass
class ConfigSchema:
    """Parsed shape of config.py: sections, nested sections, keys."""

    # "fed" -> class name; "fed.robust" -> class name; ...
    section_class: dict[str, str] = field(default_factory=dict)
    # "fed.robust" -> {"method", "trim_k", ...}
    section_keys: dict[str, set[str]] = field(default_factory=dict)
    # class name -> list of section paths using it (usually one)
    class_paths: dict[str, list[str]] = field(default_factory=dict)
    # (section_path, key) -> declaration line in config.py
    decl_lines: dict[tuple[str, str], int] = field(default_factory=dict)

    def all_flags(self) -> list[tuple[str, str]]:
        return sorted(
            (path, key)
            for path, keys in self.section_keys.items()
            for key in keys
        )

    def resolve(self, parts: list[str]) -> tuple[str, str] | str | None:
        """Resolve ["fed","robust","method"] -> ("fed.robust", "method");
        a pure section path returns the section string; unknown -> None."""
        if not parts or parts[0] not in self.section_class:
            return None
        path = parts[0]
        i = 1
        while i < len(parts):
            candidate = f"{path}.{parts[i]}"
            if candidate in self.section_class:
                path = candidate
                i += 1
                continue
            break
        if i == len(parts):
            return path  # section reference, not a key read
        # first non-section component is the key; anything after it is
        # method/attribute access ON the value (cfg.data.data_dir.rstrip)
        return (path, parts[i])


def _dataclass_fields(node: ast.ClassDef) -> dict[str, ast.AnnAssign]:
    out = {}
    for stmt in node.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            out[stmt.target.id] = stmt
    return out


def _nested_class(ann: ast.AnnAssign, classes: set[str]) -> str | None:
    """Return the config-class name this field nests, if any — from the
    annotation (``robust: RobustConfig``) or ``field(default_factory=X)``."""
    ann_name = dotted_name(ann.annotation)
    if ann_name in classes:
        return ann_name
    v = ann.value
    if isinstance(v, ast.Call) and dotted_name(v.func) == "field":
        for kw in v.keywords:
            if kw.arg == "default_factory":
                name = dotted_name(kw.value)
                if name in classes:
                    return name
    return None


def load_schema(project: Project) -> ConfigSchema | None:
    pf = project.file(CONFIG_MODULE)
    if pf is None:
        return None
    classes: dict[str, ast.ClassDef] = {
        n.name: n for n in ast.walk(pf.tree) if isinstance(n, ast.ClassDef)
    }
    if ROOT_CLASS not in classes:
        return None
    schema = ConfigSchema()
    class_names = set(classes)

    def descend(cls_name: str, prefix: str) -> None:
        fields = _dataclass_fields(classes[cls_name])
        for key, ann in fields.items():
            nested = _nested_class(ann, class_names)
            path = f"{prefix}.{key}" if prefix else key
            if nested is not None:
                schema.section_class[path] = nested
                schema.class_paths.setdefault(nested, []).append(path)
                descend(nested, path)
            else:
                schema.section_keys.setdefault(prefix, set()).add(key)
                schema.decl_lines[(prefix, key)] = ann.lineno

    # top level: every ExperimentConfig field is a section
    descend(ROOT_CLASS, "")
    # drop the synthetic "" section (ExperimentConfig has no scalar fields,
    # but keep the contract honest if one appears)
    return schema


# ------------------------------------------------------------------- reads


def _attr_chain(node: ast.AST) -> list[str] | None:
    """``a.b.c`` -> ["a","b","c"]; None for non-name-rooted chains."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    parts.reverse()
    return parts


class _FileReads(ast.NodeVisitor):
    """Collect config reads + CC201 candidates for one file."""

    def __init__(self, pf, schema: ConfigSchema):
        self.pf = pf
        self.schema = schema
        self.reads: set[tuple[str, str]] = set()
        self.findings: list[Finding] = []
        # alias name -> section path, per enclosing function (flat is fine:
        # config aliases are short-lived locals)
        self.aliases: dict[str, str] = {}
        # self.<attr> -> section path (assigned in __init__ etc.)
        self.self_aliases: dict[str, str] = {}
        # annotation aliases: name -> section path (from class->path map)
        self._collect_annotation_aliases()

    def _class_to_path(self, cls_name: str) -> str | None:
        paths = self.schema.class_paths.get(cls_name)
        return paths[0] if paths else None

    @staticmethod
    def _ann_name(node: ast.AST) -> str:
        # handles plain names, dotted names and string annotations
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value.split(".")[-1].strip()
        return dotted_name(node).split(".")[-1]

    def _collect_annotation_aliases(self) -> None:
        for node in ast.walk(self.pf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = node.args
                for a in args.posonlyargs + args.args + args.kwonlyargs:
                    if a.annotation is None:
                        continue
                    ann = self._ann_name(a.annotation)
                    path = self._class_to_path(ann)
                    if path is not None:
                        # `cfg: RobustConfig`-style params are safe to alias
                        # even under a root name: _resolve_chain tries every
                        # interpretation and keeps the valid one
                        self.aliases[a.arg] = path
            if isinstance(node, ast.ClassDef):
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) and isinstance(
                        stmt.target, ast.Name
                    ):
                        ann = self._ann_name(stmt.annotation)
                        path = self._class_to_path(ann)
                        if path is not None:
                            self.self_aliases[stmt.target.id] = path

    # ---------------------------------------------------------- resolution
    def _resolve_chain(self, parts: list[str]) -> tuple[str, str] | str | None:
        """Resolve an attribute chain to (section, key) / section / None,
        honoring cfg roots, self roots, and aliases."""
        if parts[0] == "self" and len(parts) >= 2:
            # a section alias on self (including an annotated `cfg:
            # ModelConfig` Flax field) wins over the whole-config root names
            alias = self.self_aliases.get(parts[1])
            if alias is not None:
                return self._resolve_from(alias, parts[2:])
            if parts[1] in CFG_ROOT_NAMES or parts[1] in ("_cfg",):
                return self.schema.resolve(parts[2:]) if len(parts) > 2 else None
            return None
        # a name may be BOTH a root (`cfg: ExperimentConfig` in one function)
        # and an alias (`cfg: PrivacyConfig` in another) within one file —
        # the alias map is file-flat, so try every interpretation and keep
        # the first VALID one; an invalid resolution only surfaces when no
        # interpretation works (that's the CC201).
        candidates = []
        if parts[0] in CFG_ROOT_NAMES and len(parts) > 1:
            candidates.append(self.schema.resolve(parts[1:]))
        alias = self.aliases.get(parts[0])
        if alias is not None:
            candidates.append(self._resolve_from(alias, parts[1:]))
        best = None
        for cand in candidates:
            if cand is None:
                continue
            if isinstance(cand, str):
                return cand
            section, key = cand
            if key in self.schema.section_keys.get(section, set()):
                return cand
            best = best or cand
        return best

    def _resolve_from(self, section: str, rest: list[str]) -> tuple[str, str] | str | None:
        if not rest:
            return section
        resolved = self.schema.resolve(section.split(".") + rest)
        return resolved

    def _record(self, node: ast.AST, resolved) -> None:
        if resolved is None or isinstance(resolved, str):
            return
        section, key = resolved
        if key not in self.schema.section_keys.get(section, set()):
            self.findings.append(Finding(
                path=self.pf.path,
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", 0),
                code="CC201",
                message=(
                    f"`{section}.{key}` is not declared in config.py — "
                    f"typo'd key or removed flag (section `{section}` has "
                    "no such default)"
                ),
            ))
        else:
            self.reads.add((section, key))

    # -------------------------------------------------------------- visits
    def visit_Assign(self, node: ast.Assign) -> None:
        # alias bindings: x = cfg.fed.robust / self.pcfg = cfg.fed.population
        chain = _attr_chain(node.value)
        if chain is not None:
            resolved = self._resolve_chain(chain)
            if isinstance(resolved, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.aliases[t.id] = resolved
                    elif (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        self.self_aliases[t.attr] = resolved
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        chain = _attr_chain(node)
        if chain is not None:
            resolved = self._resolve_chain(chain)
            if isinstance(resolved, tuple):
                self._record(node, resolved)
                return  # don't double-count inner chains
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # getattr(cfg.model, "fuse_hot_path"[, default]) guarded reads
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ("getattr", "hasattr")
            and len(node.args) >= 2
            and isinstance(node.args[1], ast.Constant)
            and isinstance(node.args[1].value, str)
        ):
            chain = _attr_chain(node.args[0])
            if chain is not None:
                resolved = self._resolve_chain(chain)
                if isinstance(resolved, str):
                    key = node.args[1].value
                    if key in self.schema.section_keys.get(resolved, set()):
                        self.reads.add((resolved, key))
                    # unknown key under getattr/hasattr with a default is a
                    # deliberate compat probe, not a typo — no CC201
        self.generic_visit(node)


# --------------------------------------------------------- loose read pass

# argparse namespaces share attribute names with config keys by design
# (`args.data_dir`); never let them count as config reads
_LOOSE_EXCLUDED_BASES = {"args", "argv", "ns", "namespace"}


def loose_reads(project: Project, schema: ConfigSchema) -> set[tuple[str, str]]:
    """Low-precision read detection for DEAD-FLAG accounting only (never
    CC201): the codebase deliberately duck-types section configs
    (``robust: Any``, ``chaos_cfg: Any``), so the precise alias pass
    cannot see those reads.  Two unambiguous rules recover them:

    * a key declared by exactly ONE section counts as read wherever
      ``<anything>.key`` or ``getattr(x, "key", ...)`` appears (unique
      attribution);
    * any key counts as read when the base is a bare name equal to the
      section's last path component, with or without a ``_cfg`` suffix
      (``robust.trim_k``, ``model_cfg.trunk_remat``).
    """
    owners: dict[str, list[str]] = {}
    for section, key in schema.all_flags():
        owners.setdefault(key, []).append(section)
    section_by_basename: dict[str, str] = {}
    for section in schema.section_keys:
        last = section.rsplit(".", 1)[-1]
        # first writer wins; section basenames are unique in practice
        section_by_basename.setdefault(last, section)
        section_by_basename.setdefault(f"{last}_cfg", section)

    reads: set[tuple[str, str]] = set()

    def record(key: str, base_name: str | None) -> None:
        if base_name in _LOOSE_EXCLUDED_BASES:
            return
        if base_name is not None:
            section = section_by_basename.get(base_name)
            if section is not None and key in schema.section_keys.get(
                section, set()
            ):
                reads.add((section, key))
                return
        sections = owners.get(key, [])
        if len(sections) == 1:
            reads.add((sections[0], key))

    for pf in project.files:
        if pf.path == CONFIG_MODULE:
            continue
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Attribute):
                base = node.value
                base_name = base.id if isinstance(base, ast.Name) else None
                record(node.attr, base_name)
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in ("getattr", "hasattr")
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
            ):
                base = node.args[0]
                base_name = base.id if isinstance(base, ast.Name) else None
                record(node.args[1].value, base_name)
    return reads


# -------------------------------------------------------------------- docs

_BACKTICK_RE = re.compile(r"`([^`]+)`")


def _doc_lines(root: Path) -> list[str]:
    lines: list[str] = []
    for pattern in DOC_GLOBS:
        for p in sorted(root.glob(pattern)):
            try:
                lines.extend(p.read_text().splitlines())
            except OSError:
                continue
    return lines


def documented_flags(root: Path, schema: ConfigSchema) -> set[tuple[str, str]]:
    """Flags mentioned in docs: full dotted path backticked anywhere, or a
    backticked bare key on a line that names the section prefix."""
    doc_lines = _doc_lines(root)
    documented: set[tuple[str, str]] = set()
    flags = schema.all_flags()
    by_key: dict[str, list[tuple[str, str]]] = {}
    for section, key in flags:
        by_key.setdefault(key, []).append((section, key))
    for line in doc_lines:
        tokens = set()
        for m in _BACKTICK_RE.finditer(line):
            for tok in re.split(r"[,\s/+]+", m.group(1)):
                tok = tok.strip("`*.,:;()[]{}")
                if tok:
                    tokens.add(tok)
        for tok in tokens:
            if "." in tok:
                parts = tok.split(".")
                section, key = ".".join(parts[:-1]), parts[-1]
                if (section, key) in flags:
                    documented.add((section, key))
            else:
                for section, key in by_key.get(tok, []):
                    if (section + ".") in line:
                        documented.add((section, key))
    return documented


# ------------------------------------------------------------------ driver


def analyze_project(project: Project) -> list[Finding]:
    schema = load_schema(project)
    if schema is None:
        return [Finding(
            path=CONFIG_MODULE, line=0, col=0, code="CC201",
            message="config.py missing or has no ExperimentConfig — the "
                    "config contract cannot be checked",
        )]
    findings: list[Finding] = []
    reads: set[tuple[str, str]] = set()
    for pf in project.files:
        if pf.path == CONFIG_MODULE:
            continue
        visitor = _FileReads(pf, schema)
        visitor.visit(pf.tree)
        findings.extend(visitor.findings)
        reads |= visitor.reads

    documented = documented_flags(project.root, schema)
    reads |= loose_reads(project, schema)
    for section, key in schema.all_flags():
        line = schema.decl_lines.get((section, key), 0)
        if (section, key) not in reads:
            findings.append(Finding(
                path=CONFIG_MODULE, line=line, col=0, code="CC202",
                message=(
                    f"`{section}.{key}` is declared but never read by any "
                    "package/benchmark/CLI code — dead flag (wire it up or "
                    "remove it)"
                ),
            ))
        if (section, key) not in documented:
            findings.append(Finding(
                path=CONFIG_MODULE, line=line, col=0, code="CC203",
                message=(
                    f"`{section}.{key}` appears in no README/docs flag "
                    "table — operators cannot discover it (docs/CONFIG.md "
                    "is the catch-all reference)"
                ),
            ))
    return findings
