"""``fedrec_tpu.analysis`` — the project-invariant static-analysis subsystem.

An AST-based lint engine (stdlib ``ast``, zero new dependencies) that
machine-checks the invariants the codebase previously enforced by
convention and review:

* ``trace_safety``  (TS1xx) — no host syncs / trace-time host values
  inside jitted scopes;
* ``config_contract`` (CC2xx) — every ``cfg.*`` read declared, every
  declared default read, every flag documented;
* ``metric_contract`` (MC3xx) — every registry metric catalogued in
  docs/OBSERVABILITY.md, Prometheus-exposable, kind-consistent;
* ``feature_matrix`` (FM4xx) — fail-fast guards ⟷
  ``analysis/feature_matrix.toml`` ⟷ the generated docs table;
* ``donation`` (DA5xx) — no reads of donated buffers after dispatch;
* ``generic`` (GL9xx) — pyflakes-subset hygiene (unused imports, ...).

Entry points: the ``fedrec-lint`` CLI (``fedrec_tpu.cli.lint``),
``make lint`` / ``make check``, and :func:`run_lint` for tests.
See docs/ANALYSIS.md for the full catalogue, suppression syntax
(``# fedrec-lint: disable=CODE``) and the baseline workflow.
"""

from .core import (
    CODE_CATALOG,
    Finding,
    Project,
    ProjectFile,
    finding_fingerprint,
    load_baseline,
    parse_suppressions,
    register_codes,
    write_baseline,
)
from .engine import (
    DEFAULT_BASELINE,
    FILE_ANALYZERS,
    PROJECT_ANALYZERS,
    LintResult,
    codes_table,
    run_lint,
)
from .feature_matrix import render_table, write_docs_table

__all__ = [
    "CODE_CATALOG",
    "DEFAULT_BASELINE",
    "FILE_ANALYZERS",
    "PROJECT_ANALYZERS",
    "Finding",
    "LintResult",
    "Project",
    "ProjectFile",
    "codes_table",
    "finding_fingerprint",
    "load_baseline",
    "parse_suppressions",
    "register_codes",
    "render_table",
    "run_lint",
    "write_baseline",
    "write_docs_table",
]
