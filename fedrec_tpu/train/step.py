"""Jitted SPMD train/eval steps — the framework's hot loop.

One compiled XLA program replaces the reference's Python-per-sample hot loop
(reference ``model.py:41-61`` rebuilt a DataLoader and re-ran DistilBERT per
sample per batch). Design:

  * The frozen-trunk token states (or any per-news feature table) live
    HBM-resident; the step gathers only the batch's unique news
    (``jnp.unique`` with a static size bound) and runs the trainable
    ``TextHead`` on those — duplicates across candidate/history slots are
    encoded once, and their gradients sum automatically through the gather.
  * Per-nid news-embedding gradients (reference dict scatter-add
    ``main.py:20-52``, ``model.py:97-109``) become a static-shape
    ``.at[ids].add`` scatter into an ``(N_news, D)`` accumulator.
  * Federation hooks (``FedStrategy``) run inside the same program, so
    grad/param averaging compiles to XLA collectives over the mesh's
    ``clients`` axis (ICI), not a separate gloo phase.
  * Two update paths:
      - ``joint``     (TPU-first default): end-to-end autodiff through both
        towers, Adam step per batch.
      - ``decoupled`` (reference parity): user tower trains on gathered news
        vectors from a cached table; embedding grads accumulate and are
        replayed through the head via ``jax.vjp`` at epoch end — exactly the
        semantics of ``UserModel.collect``/``update_news_grad``
        (``model.py:66-109``), minus its one-Adam-step-per-epoch quirk for
        the user tower (ledger).

All functions here build *closed* jitted callables; nothing retraces across
steps because every shape is static.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from fedrec_tpu.compat import shard_map

from fedrec_tpu.config import ExperimentConfig
from fedrec_tpu.eval.metrics import ranking_metrics_batch
from fedrec_tpu.fed.strategies import FedStrategy, ParamAvg
from fedrec_tpu.models import NewsRecommender, score_loss
from fedrec_tpu.models.recommender import score_candidates
from fedrec_tpu.privacy.dpsgd import make_noise_fn, per_example_clipped_grads
from fedrec_tpu.train.state import ClientState, make_optimizers


# ----------------------------------------------------------------- helpers
@jax.custom_vjp
def _scale_grad(x: jnp.ndarray, s: float) -> jnp.ndarray:
    """Identity forward; scales the cotangent by ``s`` on the way back.

    Used under sequence parallelism: replicated computations (candidate
    encoding runs identically on every seq shard) would have their gradient
    counted ``n_seq`` times by the post-grad ``psum`` — scaling by ``1/n_seq``
    makes the psum sum to exactly one contribution.
    """
    return x


def _scale_grad_fwd(x, s):
    return x, s


def _scale_grad_bwd(s, g):
    return (g * s, None)


_scale_grad.defvjp(_scale_grad_fwd, _scale_grad_bwd)


def _tree_global_norm(*trees: Any) -> jnp.ndarray:
    """Global L2 norm over every leaf of every (non-None) tree, accumulated
    in float32 — the health sentry's one norm definition (grad, update and
    param norms all use it, so their scales are comparable)."""
    leaves = [
        leaf
        for t in trees
        if t is not None
        for leaf in jax.tree_util.tree_leaves(t)
    ]
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    )


def _unstack(tree: Any) -> Any:
    """Strip the local leading block dim (size 1) inside shard_map."""
    return jax.tree_util.tree_map(lambda x: x[0], tree)


def _restack(tree: Any) -> Any:
    return jax.tree_util.tree_map(lambda x: x[None], tree)


def _apply_update_fault(tree: Any, code: jnp.ndarray, scale: jnp.ndarray) -> Any:
    """Chaos update-fault mask at the optimizer-update boundary.

    ``code`` is this client's scalar fault code (``fed.chaos.FAULT_CODES``:
    0 none, 1 nan, 2 scale, 3 sign-flip) and ``scale`` the multiplier for
    code 2 — both ride the batch dict so every dispatch mode (and the
    flight-recorder replay) compiles identical fault arithmetic. Code 0
    selects the original update untouched (exact, not ``u * 1``).
    """

    def one(u):
        factor = jnp.where(code == 3, -1.0, scale).astype(u.dtype)
        faulted = jnp.where(code == 1, jnp.full_like(u, jnp.nan), u * factor)
        return jnp.where(code == 0, u, faulted)

    return jax.tree_util.tree_map(one, tree)


# vmap axis name for the in-device client cohort (num_clients > devices):
# cross-client collectives then run over (LOCAL_AXIS, mesh_axis) jointly, so
# "average over all clients" means exactly that regardless of how clients
# map onto chips. The TPU-native analogue of oversubscribing torchrun ranks
# onto one node (reference README.md:27-34 runs N ranks on localhost).
LOCAL_AXIS = "local_clients"


def clients_per_device(cfg: ExperimentConfig, mesh: Mesh) -> int:
    """Cohort size: how many of ``fed.num_clients`` live on each mesh slot.

    1 == the classic one-client-per-chip layout. >1 requires equal cohorts
    (enforced here; ``parallel.mesh.client_mesh`` builds such meshes when
    clients outnumber devices).
    """
    m = int(mesh.shape[cfg.fed.mesh_axis])
    n = cfg.fed.num_clients
    if n % m != 0:
        raise ValueError(
            f"fed.num_clients={n} is not divisible by the mesh's "
            f"{cfg.fed.mesh_axis!r} axis size {m}; cohort sharding needs "
            "equal cohorts per device"
        )
    return n // m


def cohort_axes(cfg: ExperimentConfig, mesh: Mesh) -> tuple[int, Any]:
    """(cohort size k, the axes every cross-client collective must span).

    The ONE definition of the cohort-axes policy — all step builders use it,
    so "average over all clients" can never mean different things in
    different parts of a round.
    """
    k = clients_per_device(cfg, mesh)
    axis = cfg.fed.mesh_axis
    return k, (axis if k == 1 else (LOCAL_AXIS, axis))


def _cohort_call(local_fn: Callable, k: int, n_args_mapped: int, *args):
    """Run ``local_fn`` on a shard_map block: squeeze for k==1, vmap the
    in-device cohort (axis name LOCAL_AXIS) for k>1.

    ``n_args_mapped``: how many leading args carry the per-client block dim
    (the rest — feature tables — are replicated/unmapped).
    """
    if k == 1:
        out = local_fn(*(_unstack(a) for a in args[:n_args_mapped]),
                       *args[n_args_mapped:])
        return _restack(out)
    in_axes = (0,) * n_args_mapped + (None,) * (len(args) - n_args_mapped)
    return jax.vmap(local_fn, in_axes=in_axes, axis_name=LOCAL_AXIS)(*args)


def parse_cap_buckets(spec: str) -> list[tuple[int, int]]:
    """Parse ``data.unique_news_cap_buckets`` ("64:2560,256:4096") into a
    B-ascending list of (max_batch, cap) pairs. Raises on malformed entries
    so a typo'd policy fails at build time, not silently uncapped."""
    buckets = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        try:
            b_s, cap_s = item.split(":")
            b, cap = int(b_s), int(cap_s)
        except ValueError:
            raise ValueError(
                f"data.unique_news_cap_buckets entry {item!r} is not "
                "'<max_batch>:<cap>' (e.g. '64:2560,256:4096')"
            ) from None
        if b <= 0 or cap <= 0:
            raise ValueError(
                f"data.unique_news_cap_buckets entry {item!r}: both the "
                "batch bound and the cap must be positive"
            )
        buckets.append((b, cap))
    bounds = [b for b, _ in buckets]
    if len(set(bounds)) != len(bounds):
        raise ValueError(
            f"data.unique_news_cap_buckets has duplicate batch bounds "
            f"({spec!r}); each bound may appear once"
        )
    return sorted(buckets)


def resolve_unique_cap(cfg: ExperimentConfig, batch_size: int) -> int:
    """The unique-news cap for one compiled per-client batch size.

    With ``data.unique_news_cap_buckets`` set, picks the cap of the smallest
    bucket whose batch bound covers ``batch_size``; batches larger than
    every bucket run uncapped (0 = exact worst-case bound) — a fixed global
    cap either over-caps small batches or silently overflows large ones
    (the flagship 2,560 cap overflows every B>=128 batch against the 4,096
    bench corpus). Without buckets, the global ``data.unique_news_cap``.
    Called at trace time, so each compiled batch shape gets its own bound.
    """
    buckets = parse_cap_buckets(cfg.data.unique_news_cap_buckets)
    if buckets:
        for b, cap in buckets:
            if batch_size <= b:
                return cap
        return 0
    return cfg.data.unique_news_cap


def _encode_gathered(
    model: NewsRecommender,
    news_params: Any,
    token_states: jnp.ndarray,
    uniq: jnp.ndarray,
    chunk: int = 0,
    fused: bool = False,
    gather_fn: Callable | None = None,
) -> jnp.ndarray:
    """Gather unique token-state rows and run the text head over them.

    The gather result is ``stop_gradient``-ed (the trunk is frozen: no
    cotangent may ever flow into the (N, L, Dh) table, and saying so lets
    XLA drop the zero-cotangent scatter a differentiated gather would
    imply) and tagged ``checkpoint_name("token_gather")`` so remat policies
    can address it.

    ``chunk`` (``data.gather_chunk``): tile the gather+encode in
    ``lax.map`` chunks with the chunk body rematerialized in backward —
    the (unique, L, Dh) gather result then never occupies HBM beyond one
    chunk (forward residual AND backward), at the price of re-gathering
    per tile in the backward pass. Row-wise encode, so tiling is exact.

    ``fused`` (``model.fuse_hot_path``, additive head only): ONE Pallas
    kernel streams each id's token row HBM->VMEM straight into the pool +
    projection (``ops.fused_gather_encode``) — the (U, L, Dh) gather never
    exists, forward or backward, so the remat tag moves from the gathered
    states (which no longer materialize) to the kernel's (U, D) output;
    ``stop_gradient`` on the table keeps the frozen-trunk contract and the
    kernel's VJP never computes a table cotangent anyway. Composes with
    ``chunk`` unchanged (the tile body swaps implementations).

    ``gather_fn(table, ids) -> rows`` swaps the local ``table[ids]`` for
    the sharded-catalog exchange (``shard.table``,
    ``shard.table.owner_bucketed_gather``): collectives live inside the
    per-tile body, so ``chunk`` tiling replays the exchange per tile in
    lockstep on every device (same static trip count everywhere), and the
    ``stop_gradient`` outside it keeps any cotangent from ever touching
    the wire.
    """
    from jax.ad_checkpoint import checkpoint_name

    if gather_fn is None:
        def gather_fn(t, ids):
            return t[ids]

    if fused:
        from fedrec_tpu.ops import fused_gather_encode

        frozen = lax.stop_gradient(token_states)

        def encode(ids):
            return checkpoint_name(
                fused_gather_encode(
                    frozen, ids, news_params, dtype=model.cfg.dtype
                ),
                "token_gather",
            )
    else:
        def encode(ids):
            states = checkpoint_name(
                lax.stop_gradient(gather_fn(token_states, ids)), "token_gather"
            )
            return model.apply(
                {"params": {"text_head": news_params}},
                states,
                method=NewsRecommender.encode_news,
            )

    u = uniq.shape[0]
    if not chunk or u <= chunk:
        return encode(uniq)
    pad = (-u) % chunk
    tiles = jnp.pad(uniq, (0, pad)).reshape(-1, chunk)
    vecs = lax.map(jax.checkpoint(encode), tiles)  # (tiles, chunk, D)
    return vecs.reshape(-1, vecs.shape[-1])[:u]


def _batch_news_vecs(
    model: NewsRecommender,
    news_params: Any,
    token_states: jnp.ndarray,
    candidates: jnp.ndarray,
    history: jnp.ndarray,
    cap: int = 0,
    chunk: int = 0,
    fused: bool = False,
    gather_fn: Callable | None = None,
    n_news: int | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Encode the batch's unique news once; gather into cand/history slots.

    ``token_states``: (N_news, L, bert_hidden) HBM-resident feature table.
    Returns cand_vecs (B, C, D) and his_vecs (B, H, D).

    ``cap`` (``data.unique_news_cap`` / the bucketed policy resolved by
    :func:`resolve_unique_cap`): static bound on the unique slots actually
    encoded — the worst case B*(C+H) wastes text-tower FLOPs on
    duplicate/padding rows. Exact while distinct ids <= cap; callers must
    surface :func:`unique_overflow` when setting it. ``chunk``: see
    :func:`_encode_gathered`. ``gather_fn``/``n_news``: the sharded-
    catalog form (``shard.table``) — ``token_states`` is then this
    device's local row block, so the GLOBAL row count must come in
    explicitly (the local block's dim 0 would wrongly cap the dedup).
    """
    b, c = candidates.shape
    h = history.shape[1]
    ids = jnp.concatenate([candidates.reshape(-1), history.reshape(-1)])
    if n_news is None:
        n_news = token_states.shape[0]
    size = min(ids.shape[0], n_news)
    if cap:
        size = min(size, cap)
    uniq, inv = jnp.unique(
        ids, size=size, fill_value=0, return_inverse=True
    )
    vecs = _encode_gathered(
        model, news_params, token_states, uniq, chunk, fused=fused,
        gather_fn=gather_fn,
    )
    flat = vecs[inv]
    cand_vecs = flat[: b * c].reshape(b, c, -1)
    his_vecs = flat[b * c :].reshape(b, h, -1)
    return cand_vecs, his_vecs


def unique_overflow(
    candidates: jnp.ndarray,
    history: jnp.ndarray,
    cap: int,
    n_news: int,
) -> jnp.ndarray:
    """1 when this batch's distinct news ids exceed the static ``cap``.

    ``jnp.unique(size=cap)`` silently drops ids past the cap, corrupting the
    gather — so a capped step must emit this flag; any nonzero value in
    training metrics means the cap is too small and results are invalid.
    """
    ids = jnp.concatenate([candidates.reshape(-1), history.reshape(-1)])
    sorted_ids = jnp.sort(ids)
    distinct = 1 + jnp.sum((jnp.diff(sorted_ids) != 0).astype(jnp.int32))
    bound = min(cap, ids.shape[0], n_news)
    return (distinct > bound).astype(jnp.int32)


def _encode_unique_tokens(
    text_encoder: Any,
    news_params: Any,
    tokens_table: jnp.ndarray,
    ids: jnp.ndarray,
    dropout_rng: jax.Array | None,
    cap: int = 0,
) -> jnp.ndarray:
    """Encode a flat id vector's unique news through the full TextEncoder.

    Gathers the unique token rows from the (N, 2, L) table, runs trunk +
    head once per distinct news, and scatters back to (len(ids), D).
    ``cap`` bounds the unique slots like in :func:`_batch_news_vecs` — it
    matters MOST here, where every slot pays a full trunk forward+backward;
    callers must surface :func:`unique_overflow`.
    """
    size = min(ids.shape[0], tokens_table.shape[0])
    if cap:
        size = min(size, cap)
    uniq, inv = jnp.unique(ids, size=size, fill_value=0, return_inverse=True)
    toks = tokens_table[uniq]  # (size, 2, L)
    train = dropout_rng is not None
    vecs = text_encoder.apply(
        {"params": news_params},
        toks,
        train,
        rngs={"dropout": dropout_rng} if train else None,
    )  # (size, D)
    return vecs[inv]


def _batch_news_vecs_tokens(
    text_encoder: Any,
    news_params: Any,
    tokens_table: jnp.ndarray,
    candidates: jnp.ndarray,
    history: jnp.ndarray,
    dropout_rng: jax.Array | None,
    cap: int = 0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Finetune-mode analogue of ``_batch_news_vecs``: one joint dedup over
    candidate + history ids, full trainable TextEncoder on the unique rows."""
    b, c = candidates.shape
    h = history.shape[1]
    ids = jnp.concatenate([candidates.reshape(-1), history.reshape(-1)])
    flat = _encode_unique_tokens(
        text_encoder, news_params, tokens_table, ids, dropout_rng, cap=cap
    )
    cand_vecs = flat[: b * c].reshape(b, c, -1)
    his_vecs = flat[b * c :].reshape(b, h, -1)
    return cand_vecs, his_vecs


def _encode_tokens_rows(
    text_encoder: Any,
    news_params: Any,
    tokens_table: jnp.ndarray,
    ids_2d: jnp.ndarray,
    dropout_rng: jax.Array | None,
) -> jnp.ndarray:
    """Encode one (B, K) id block's unique news through the full TextEncoder.

    Used under sequence parallelism in finetune mode, where candidates and
    history must be encoded SEPARATELY: a joint ``jnp.unique`` over
    candidates + the local history shard would place the same candidate news
    at a different row index on each seq shard, giving it a different trunk
    dropout mask despite the shared key — silently de-replicating the
    candidate encode (and making the 1/n_seq grad correction inexact).
    Encoding candidates alone keeps their row layout (and mask) identical on
    every shard; history rows live on exactly one shard each, so their masks
    are free to differ.
    """
    b, k = ids_2d.shape
    flat = _encode_unique_tokens(
        text_encoder, news_params, tokens_table, ids_2d.reshape(-1), dropout_rng
    )
    return flat.reshape(b, k, -1)


def encode_corpus_tokens(
    text_encoder: Any,
    news_params: Any,
    news_tokens: jnp.ndarray,
    chunk: int = 512,
) -> jnp.ndarray:
    """(N, 2, L) token table -> (N, D) news vectors via the full TextEncoder
    (finetune-mode corpus encode for evaluation), chunked over N."""
    n = news_tokens.shape[0]
    chunk = min(chunk, n)
    pad = (-n) % chunk
    padded = jnp.pad(news_tokens, ((0, pad), (0, 0), (0, 0)))
    chunks = padded.reshape(-1, chunk, *padded.shape[1:])

    def encode(c):
        return text_encoder.apply({"params": news_params}, c)

    vecs = lax.map(encode, chunks)
    return vecs.reshape(-1, vecs.shape[-1])[:n]


def encode_all_news(
    model: NewsRecommender,
    news_params: Any,
    token_states: jnp.ndarray,
    chunk: int = 2048,
) -> jnp.ndarray:
    """(N, L, bert_hidden) -> (N, D) news-vector table, chunked over N.

    The TPU answer to ``gen_news_vecs`` over the full corpus (reference
    ``model.py:41-61``): one jitted ``lax.map`` over fixed-size chunks keeps
    peak VMEM bounded while the matmuls stay MXU-sized.
    """
    n = token_states.shape[0]
    chunk = min(chunk, n)  # don't pad small corpora up to the chunk size
    pad = (-n) % chunk
    padded = jnp.pad(token_states, ((0, pad), (0, 0), (0, 0)))
    chunks = padded.reshape(-1, chunk, *padded.shape[1:])

    def encode(c):
        return model.apply(
            {"params": {"text_head": news_params}},
            c,
            method=NewsRecommender.encode_news,
        )

    vecs = lax.map(encode, chunks)
    return vecs.reshape(-1, vecs.shape[-1])[:n]


def encode_all_news_sharded(
    model: NewsRecommender,
    news_params: Any,
    token_states: jnp.ndarray,
    mesh: Mesh,
    chunk: int = 2048,
) -> jnp.ndarray:
    """Corpus encode sharded over EVERY mesh axis: each of the mesh's
    ``mesh.size`` devices encodes ``N / mesh.size`` rows (a (clients, seq)
    mesh shards over both axes jointly), and the result is logically the
    full (N, D) table (XLA inserts the gather only where a consumer needs
    it replicated).

    On a pod this turns the per-round corpus refresh — the eval-path
    bottleneck at MIND scale (65k news) — into ``1/mesh.size`` of the
    single-chip wall time. Exact same math as :func:`encode_all_news`
    (the per-shard body IS that function).
    """
    axes = tuple(mesh.axis_names)
    n = token_states.shape[0]
    pad = (-n) % mesh.size
    padded = (
        jnp.pad(token_states, ((0, pad), (0, 0), (0, 0))) if pad else token_states
    )

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(axes)),
        out_specs=P(axes),
        check_vma=False,
    )
    def enc(params, rows):
        return encode_all_news(model, params, rows, chunk)

    return enc(news_params, padded)[:n]


def _reshard_state_out(fn: Callable, state_shardings: Any) -> Callable:
    """Wrap a compiled program so its STATE output is re-committed to the
    at-rest FSDP layout (``shard.policy``) inside the same program: the
    ``shard_map`` in-spec forces the gather on entry, this constraint is
    the slice on exit — one dispatch, no host round-trip, and donation
    still works because input and output carry identical layouts.
    ``None`` returns ``fn`` untouched (the byte-identical ``fsdp=1``
    degenerate program)."""
    if state_shardings is None:
        return fn

    def wrapped(*args):
        out = fn(*args)
        if isinstance(out, tuple):
            return (
                jax.lax.with_sharding_constraint(out[0], state_shardings),
                *out[1:],
            )
        return jax.lax.with_sharding_constraint(out, state_shardings)

    return wrapped


# ------------------------------------------------------------- train steps
def _build_local_step(
    model: NewsRecommender,
    cfg: ExperimentConfig,
    strategy: FedStrategy,
    mesh: Mesh,
    mode: str | None = None,
    noise_fn: Callable[[Any, jax.Array], Any] | None = None,
    sharded_table: Any | None = None,
) -> tuple[Callable, int, Any, str]:
    """The ONE construction of the per-client step math.

    Returns ``(local_step, cohort_k, batch_spec, mesh_axis)`` — wrapped into
    a per-batch program by ``build_fed_train_step`` and into an
    epoch-in-jit ``lax.scan`` by ``build_fed_train_scan``; both wrappers
    share this body so a fix to the step math can never diverge them.

    ``noise_fn(grads, rng) -> grads`` is the LDP hook: applied per client,
    device-side, *before* any cross-client collective (the honest version of
    reference ``client.py:87-89``). When None and ``cfg.privacy.enabled``, it
    is built from the config; with ``mechanism='dpsgd'`` the joint path
    additionally switches to per-example clipped gradients.

    ``sharded_table`` (a ``shard.table.TableSpec``, from ``shard.table``):
    the feature table arrives as this device's LOCAL row block instead of
    the replicated array, and the unique-news gather runs the
    owner-bucketed ``all_to_all`` exchange — bit-identical rows, catalog
    capacity scaling with the mesh. Joint ("head") mode only; the
    unsupported combinations fail fast here, at build time.
    """
    if mode is None:
        mode = {"table": "decoupled", "head": "joint", "finetune": "finetune"}.get(
            cfg.model.text_encoder_mode, "joint"
        )
    text_encoder = None
    if mode == "finetune":
        from fedrec_tpu.models.bert import make_text_encoder

        text_encoder = make_text_encoder(cfg.model)
    opt_user_tx, opt_news_tx = make_optimizers(cfg)
    axis = cfg.fed.mesh_axis
    # in-device client cohorts (num_clients > mesh slots): the local block
    # carries k clients, vmapped under LOCAL_AXIS; every cross-client
    # collective then spans (LOCAL_AXIS, mesh axis) so federation semantics
    # are independent of the client->chip packing
    k, sync_axes = cohort_axes(cfg, mesh)
    # sequence parallelism: history sharded over a second mesh axis, user
    # tower attends via ring/Ulysses collectives (fedrec_tpu.parallel.ring)
    n_seq = cfg.fed.seq_shards
    seq_ax = cfg.fed.seq_axis
    if n_seq > 1:
        if mode not in ("joint", "finetune"):
            raise NotImplementedError(
                "fed.seq_shards > 1 requires mode='joint'/'finetune' (the "
                "decoupled news-grad accumulator is not seq-sharded)"
            )
        if seq_ax not in mesh.axis_names:
            raise ValueError(
                f"fed.seq_shards={n_seq} but mesh {mesh.axis_names} has no "
                f"{seq_ax!r} axis — build the mesh with parallel.mesh.fed_mesh"
            )
        model = model.clone(seq_axis=seq_ax, seq_impl=cfg.fed.seq_impl)
    if noise_fn is None and cfg.privacy.enabled:
        noise_fn = make_noise_fn(cfg.privacy, cfg.data.batch_size)
    use_dpsgd = cfg.privacy.enabled and cfg.privacy.mechanism == "dpsgd"
    if use_dpsgd and n_seq > 1:
        raise NotImplementedError(
            "per-example DP-SGD with sequence parallelism is not supported; "
            "use seq_shards=1 with mechanism='dpsgd'"
        )
    if use_dpsgd and mode == "finetune":
        raise NotImplementedError(
            "per-example DP-SGD over the full trunk is not supported; use "
            "mode='joint' (frozen trunk) for DP training"
        )
    if use_dpsgd and mode != "joint":
        # decoupled mode has no per-example clipping path yet; noising
        # unclipped grads with a DP-SGD-calibrated sigma would claim an
        # (epsilon, delta) guarantee that does not hold
        raise ValueError(
            "mechanism='dpsgd' requires mode='joint'; use mechanism='ldp_news' "
            "(reference-parity noise, no rigorous epsilon) for decoupled mode"
        )
    if cfg.privacy.enabled and cfg.privacy.dp_scope not in ("all", "user"):
        raise ValueError(
            f"unknown privacy.dp_scope {cfg.privacy.dp_scope!r}; "
            "expected 'all' or 'user'"
        )
    # dp_scope='user': DP rounds train ONLY the user tower; the text head is
    # frozen at its current params, so its grads are never computed, clipped,
    # or noised — the per-example sensitivity bound C applies to the user
    # grads alone and the noised dimension shrinks accordingly (docs/DP.md)
    dp_user_only = use_dpsgd and cfg.privacy.dp_scope == "user"
    if cfg.privacy.enabled and cfg.privacy.dp_scope == "user" and not use_dpsgd:
        raise ValueError(
            "privacy.dp_scope='user' requires mechanism='dpsgd' — ldp_news "
            "noises only the news grads, which contradicts a user-only scope"
        )

    # fused hot-path kernels (model.fuse_hot_path, ops.fused_hot_path):
    # kernel (2) — attention+pool+score — rides the model modules, so it is
    # active in every mode (and composes with in-device cohorts: the
    # kernels batch under the cohort vmap); kernel (1) — gather+encode —
    # replaces the joint-mode dense gather for the additive head. The
    # unsupported combinations fail fast HERE, at build time, with the
    # lever to unset.
    fuse = getattr(cfg.model, "fuse_hot_path", False)
    fuse_gather = (
        fuse
        and getattr(cfg.model, "text_head_arch", "additive") == "additive"
    )
    if fuse:
        if use_dpsgd:
            raise NotImplementedError(
                "model.fuse_hot_path with privacy.mechanism='dpsgd' is not "
                "supported (per-example clipping would pay the kernel "
                "launch per example, exactly the overhead regime where "
                "fusion loses); unset one of the two"
            )
        if n_seq > 1:
            raise NotImplementedError(
                "model.fuse_hot_path with fed.seq_shards>1 is not supported "
                "(the fused kernel holds the whole history per row); use "
                "the ring/Ulysses path for sharded histories"
            )

    # mesh-sharded news catalog (shard.table, fedrec_tpu.shard.table): the
    # table in-spec becomes P(clients) and every unique-news gather runs
    # the owner-bucketed all_to_all exchange. The combinations the
    # exchange cannot serve fail fast HERE, with the lever to unset.
    table_gather = None
    if sharded_table is not None:
        if mode != "joint":
            raise NotImplementedError(
                "shard.table requires model.text_encoder_mode='head' (the "
                "joint frozen-trunk step): the decoupled per-epoch table "
                "refresh and the finetune token gather read a replicated "
                "table — unset shard.table for those modes"
            )
        if use_dpsgd:
            raise NotImplementedError(
                "shard.table with privacy.mechanism='dpsgd' is not "
                "supported (per-example clipping gathers each example's "
                "rows directly, bypassing the owner-bucketed exchange); "
                "unset one of the two"
            )
        if n_seq > 1:
            raise NotImplementedError(
                "shard.table with fed.seq_shards>1 is not supported (the "
                "catalog shards over the clients axis; a seq-sharded mesh "
                "would need a 2-D exchange); unset one of the two"
            )
        if fuse:
            raise NotImplementedError(
                "model.fuse_hot_path with shard.table is not supported "
                "until the fused gather+encode kernel learns remote rows "
                "(it streams LOCAL HBM rows only); unset one of the two"
            )
        if k > 1:
            raise NotImplementedError(
                "shard.table with in-device cohorts (fed.num_clients above "
                "the mesh's client slots) is not supported: the "
                "owner-bucketed all_to_all runs once per mesh slot, not "
                "per vmapped cohort client — match fed.num_clients to the "
                "device count"
            )
        from fedrec_tpu.shard.table import owner_bucketed_gather

        def table_gather(rows, ids):
            return owner_bucketed_gather(rows, ids, sharded_table)

    # in-graph numeric sentry (obs.health.sentry): the step additionally
    # returns per-client grad/update/param global norms and a non-finite
    # flag (+ DP clip-rate under dpsgd) — computed on device, fetched by
    # the host with the round's losses, so a silent NaN or a divergent
    # client is visible without a blocking readback per step
    sentry = cfg.obs.health.sentry
    # deterministic fault injection (fed.chaos): per-client update-fault
    # vectors ride the batch as chaos.code/chaos.scale and apply at the
    # update boundary below — same compiled arithmetic in every dispatch
    # mode, bit-identical across runs of the same FaultPlan
    chaos = cfg.chaos.enabled
    if chaos and n_seq > 1:
        raise NotImplementedError(
            "chaos fault injection with fed.seq_shards > 1 is not supported "
            "(the seq-parallel batch spec does not carry the per-client "
            "fault vectors); run the plan with seq_shards=1"
        )

    def local_step(state: ClientState, batch: dict, table: jnp.ndarray):
        # trace-time cap resolution: each compiled per-client batch shape
        # gets the bound its own B implies (bucketed policy or the global)
        cap = resolve_unique_cap(cfg, batch["labels"].shape[0])
        dp_stats = None
        sentry_grads: tuple = ()
        sentry_updates: tuple = ()
        rng, dropout_rng, noise_rng = jax.random.split(state.rng, 3)
        # text-encoder dropout key must be IDENTICAL across seq shards so the
        # replicated candidate encode stays replicated (finetune mode)
        enc_rng = jax.random.fold_in(dropout_rng, 1)
        if n_seq > 1:
            # distinct user-encoder dropout masks per history shard
            # (state.rng is replicated over the seq axis)
            dropout_rng = jax.random.fold_in(dropout_rng, lax.axis_index(seq_ax))

        if mode in ("joint", "finetune"):
            if use_dpsgd:
                # DP-SGD: per-example grads, clipped to C, averaged; each
                # example encodes its own C+H news directly (no cross-example
                # dedup — it would couple examples and break the per-example
                # sensitivity bound; and within one example unique() saves
                # nothing, so gather + encode is the cheapest form)
                def per_example_loss(packed, cand_row, his_row, label, ex_rng):
                    user_params, news_params = packed
                    c = cand_row.shape[0]
                    ids = jnp.concatenate([cand_row, his_row])
                    vecs = model.apply(
                        {"params": {"text_head": news_params}},
                        table[ids],
                        method=NewsRecommender.encode_news,
                    )
                    scores = model.apply(
                        {"params": {"user_encoder": user_params}},
                        vecs[:c][None],
                        vecs[c:][None],
                        train=True,
                        rngs={"dropout": ex_rng},
                    )
                    return score_loss(
                        scores, label[None], cfg.model.sigmoid_before_ce
                    )

                b = batch["labels"].shape[0]
                ex_rngs = jax.random.split(dropout_rng, b)
                batch_args = (
                    batch["candidates"], batch["history"], batch["labels"], ex_rngs,
                )
                if dp_user_only:
                    out = per_example_clipped_grads(
                        lambda up, c, h, l, r: per_example_loss(
                            (up, state.news_params), c, h, l, r
                        ),
                        state.user_params,
                        batch_args,
                        cfg.privacy.clip_norm,
                        with_stats=sentry,
                    )
                    loss, user_g = out[0], out[1]
                    news_g = None  # head frozen: no grad exists to leak
                else:
                    out = per_example_clipped_grads(
                        per_example_loss,
                        (state.user_params, state.news_params),
                        batch_args,
                        cfg.privacy.clip_norm,
                        with_stats=sentry,
                    )
                    loss, (user_g, news_g) = out[0], out[1]
                dp_stats = out[2] if sentry else None
            else:

                def loss_fn(user_params, news_params):
                    if mode == "finetune" and n_seq > 1:
                        # candidates and the local history shard are encoded
                        # separately so the candidate row layout — and hence
                        # its trunk dropout mask under the shared enc_rng —
                        # is identical on every seq shard (see
                        # _encode_tokens_rows)
                        cand_vecs = _encode_tokens_rows(
                            text_encoder, news_params, table,
                            batch["candidates"], enc_rng,
                        )
                        his_vecs = _encode_tokens_rows(
                            text_encoder, news_params, table,
                            batch["history"],
                            jax.random.fold_in(enc_rng, 1 + lax.axis_index(seq_ax)),
                        )
                    elif mode == "finetune":
                        # table = raw (N, 2, L) token rows; full trunk + head
                        # runs (and trains) on the batch's unique news
                        cand_vecs, his_vecs = _batch_news_vecs_tokens(
                            text_encoder, news_params, table,
                            batch["candidates"], batch["history"], enc_rng,
                            cap=cap,
                        )
                    else:
                        cand_vecs, his_vecs = _batch_news_vecs(
                            model, news_params, table,
                            batch["candidates"], batch["history"],
                            cap=cap,
                            chunk=cfg.data.gather_chunk,
                            fused=fuse_gather,
                            gather_fn=table_gather,
                            n_news=(
                                sharded_table.num_rows
                                if sharded_table is not None else None
                            ),
                        )
                    if n_seq > 1:
                        # candidate encoding is replicated across seq shards;
                        # scale so the post-grad psum counts it exactly once
                        cand_vecs = _scale_grad(cand_vecs, 1.0 / n_seq)
                    scores = model.apply(
                        {"params": {"user_encoder": user_params}},
                        cand_vecs,
                        his_vecs,
                        train=True,
                        rngs={"dropout": dropout_rng},
                    )
                    return score_loss(
                        scores, batch["labels"], cfg.model.sigmoid_before_ce
                    )

                loss, (user_g, news_g) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
                    state.user_params, state.news_params
                )
                if n_seq > 1:
                    # each seq shard holds a partial param grad (its history
                    # slice); sum -> full grad, replicated over seq
                    user_g = jax.tree_util.tree_map(
                        lambda g: lax.psum(g, seq_ax), user_g
                    )
                    news_g = jax.tree_util.tree_map(
                        lambda g: lax.psum(g, seq_ax), news_g
                    )
            if noise_fn is not None:
                if news_g is None:
                    (user_g,) = noise_fn((user_g,), noise_rng)
                else:
                    user_g, news_g = noise_fn((user_g, news_g), noise_rng)
            # sentry sees the PER-CLIENT grads (post-noise, pre-sync): the
            # synced mean is what steps the optimizer, but a diverging or
            # poisoned client is only visible before the collective blends
            # its gradient into the cohort's
            sentry_grads = (user_g, news_g)
            user_g = strategy.sync_grads(user_g, sync_axes)
            u_updates, opt_user = opt_user_tx.update(user_g, state.opt_user, state.user_params)
            if chaos:
                # fault AT the update boundary: the sentry below sees the
                # faulted update, so detection (and the quarantine path)
                # fires exactly as it would on a real bad client
                u_updates = _apply_update_fault(
                    u_updates, batch["chaos.code"], batch["chaos.scale"]
                )
            n_updates = None
            if news_g is None:
                new_news_params, opt_news = state.news_params, state.opt_news
            else:
                news_g = strategy.sync_grads(news_g, sync_axes)
                n_updates, opt_news = opt_news_tx.update(
                    news_g, state.opt_news, state.news_params
                )
                if chaos:
                    n_updates = _apply_update_fault(
                        n_updates, batch["chaos.code"], batch["chaos.scale"]
                    )
                new_news_params = jax.tree_util.tree_map(
                    lambda p, u: p + u, state.news_params, n_updates
                )
            sentry_updates = (u_updates, n_updates)
            new_state = state.replace(
                step=state.step + 1,
                user_params=jax.tree_util.tree_map(
                    lambda p, u: p + u, state.user_params, u_updates
                ),
                news_params=new_news_params,
                opt_user=opt_user,
                opt_news=opt_news,
                rng=rng,
            )

        elif mode == "decoupled":
            # table is the (N, D) news-vector table; user tower trains on
            # gathered vectors, embedding grads accumulate per-nid
            cand_vecs0 = table[batch["candidates"]]
            his_vecs0 = table[batch["history"]]

            def loss_fn(user_params, cand_vecs, his_vecs):
                scores = model.apply(
                    {"params": {"user_encoder": user_params}},
                    cand_vecs,
                    his_vecs,
                    train=True,
                    rngs={"dropout": dropout_rng},
                )
                return score_loss(scores, batch["labels"], cfg.model.sigmoid_before_ce)

            loss, (user_g, cand_g, his_g) = jax.value_and_grad(
                loss_fn, argnums=(0, 1, 2)
            )(state.user_params, cand_vecs0, his_vecs0)

            if noise_fn is not None:
                user_g, cand_g, his_g = noise_fn((user_g, cand_g, his_g), noise_rng)
            sentry_grads = (user_g, cand_g, his_g)

            # per-nid scatter-add (reference process_news_grad, main.py:20-42)
            d = cand_g.shape[-1]
            ids = jnp.concatenate(
                [batch["candidates"].reshape(-1), batch["history"].reshape(-1)]
            )
            grads_flat = jnp.concatenate(
                [cand_g.reshape(-1, d), his_g.reshape(-1, d)]
            )
            accum = state.news_grad_accum.at[ids].add(grads_flat)

            user_g = strategy.sync_grads(user_g, sync_axes)
            u_updates, opt_user = opt_user_tx.update(user_g, state.opt_user, state.user_params)
            if chaos:
                u_updates = _apply_update_fault(
                    u_updates, batch["chaos.code"], batch["chaos.scale"]
                )
            sentry_updates = (u_updates,)
            new_state = state.replace(
                step=state.step + 1,
                user_params=jax.tree_util.tree_map(
                    lambda p, u: p + u, state.user_params, u_updates
                ),
                opt_user=opt_user,
                rng=rng,
                news_grad_accum=accum,
            )
        else:
            raise ValueError(f"unknown step mode {mode!r}")

        mean_loss = lax.pmean(loss, axis_name=sync_axes)
        metrics = {"loss": loss, "mean_loss": mean_loss}
        if sentry:
            grad_norm = _tree_global_norm(*sentry_grads)
            update_norm = _tree_global_norm(*sentry_updates)
            param_norm = _tree_global_norm(
                new_state.user_params, new_state.news_params
            )
            finite = (
                jnp.isfinite(loss)
                & jnp.isfinite(grad_norm)
                & jnp.isfinite(update_norm)
                & jnp.isfinite(param_norm)
            )
            metrics["health.grad_norm"] = grad_norm
            metrics["health.update_norm"] = update_norm
            metrics["health.param_norm"] = param_norm
            # int32 sentinel, not bool: scan stacks it over steps and the
            # host sums it — "how many step×client cells went non-finite"
            metrics["health.nonfinite"] = 1 - finite.astype(jnp.int32)
            if dp_stats is not None:
                metrics["health.clip_rate"] = dp_stats["clip_rate"]
                metrics["health.clip_max_norm"] = dp_stats["max_norm"]
        capped = (
            cap
            and not use_dpsgd
            and (mode == "joint" or (mode == "finetune" and n_seq == 1))
        )
        if capped:
            # ids are data, not params — computed outside the grad closure;
            # any nonzero total means the cap corrupted this step. (Under
            # DP-SGD the cap is inert — each example encodes its own ids —
            # and the seq-parallel finetune path encodes rows separately,
            # bypassing the capped joint dedup — so no flag there.)
            flag = unique_overflow(
                batch["candidates"], batch["history"],
                cap,
                # sharded table: the LOCAL block's dim 0 is rows/shard, not
                # the catalog — the dedup bound must use the global count
                sharded_table.num_rows if sharded_table is not None
                else table.shape[0],
            )
            if n_seq > 1:
                # each seq shard dedups its own history slice, so overflow
                # is per-shard; without this sum the P(clients) out-spec
                # (check_vma=False) would report only seq-shard 0's flag and
                # silently swallow corruption on the others
                flag = lax.psum(flag, seq_ax)
            metrics["unique_overflow"] = lax.psum(flag, axis_name=sync_axes)
        return new_state, metrics

    if n_seq > 1:
        # history's last dim lives sharded over the seq axis; the step then
        # requires exactly the canonical batch keys (shard_fed_batch's layout)
        batch_spec: Any = {
            "candidates": P(axis),
            "history": P(axis, None, seq_ax),
            "labels": P(axis),
        }
    else:
        batch_spec = P(axis)

    return local_step, k, batch_spec, axis


def build_fed_train_step(
    model: NewsRecommender,
    cfg: ExperimentConfig,
    strategy: FedStrategy,
    mesh: Mesh,
    mode: str | None = None,
    noise_fn: Callable[[Any, jax.Array], Any] | None = None,
    donate_batch: bool = False,
    sharded_table: Any | None = None,
    state_shardings: Any | None = None,
) -> Callable:
    """Compile the per-batch federated train step.

    Returns ``step(stacked_state, batch_arrays, feature_table) ->
    (new_stacked_state, metrics)`` where ``batch_arrays`` is a dict of
    ``(num_clients, B, ...)`` arrays sharded over ``clients`` and
    ``feature_table`` is replicated — token states for ``joint`` mode, the
    news-vector table for ``decoupled`` mode. Step math and the LDP/DP
    hooks are documented on ``_build_local_step``.

    ``donate_batch`` additionally donates the batch buffers (the Trainer
    device_puts fresh arrays every dispatch, so XLA may reclaim them as
    scratch once consumed); leave False when re-dispatching the same batch
    arrays (bench.py's chain timer does).

    ``sharded_table`` (a ``shard.table.TableSpec``): the feature table is
    row-sharded over the clients axis instead of replicated, gathered
    in-step by the owner-bucketed exchange. ``state_shardings`` (from
    ``shard.policy.fsdp_state_shardings``): the returned state re-commits
    to the at-rest FSDP layout inside the same program. Both default to
    None = the byte-identical pre-shard program.
    """
    local_step, k, batch_spec, axis = _build_local_step(
        model, cfg, strategy, mesh, mode, noise_fn, sharded_table
    )
    table_spec = P(axis) if sharded_table is not None else P()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis), batch_spec, table_spec),
        out_specs=(P(axis), P(axis)),
        check_vma=False,
    )
    def sharded_step(stacked_state, batch, table):
        return _cohort_call(local_step, k, 2, stacked_state, batch, table)

    return jax.jit(
        _reshard_state_out(sharded_step, state_shardings),
        donate_argnums=(0, 1) if donate_batch else (0,),
    )


def _prepend_none(spec: Any) -> Any:
    """P(axis, ...) -> P(None, axis, ...): same layout under a leading
    (unsharded) steps dimension."""
    if isinstance(spec, dict):
        return {kk: _prepend_none(v) for kk, v in spec.items()}
    return P(None, *spec)


def build_fed_train_scan(
    model: NewsRecommender,
    cfg: ExperimentConfig,
    strategy: FedStrategy,
    mesh: Mesh,
    mode: str | None = None,
    noise_fn: Callable[[Any, jax.Array], Any] | None = None,
    donate_batch: bool = False,
    sharded_table: Any | None = None,
    state_shardings: Any | None = None,
) -> Callable:
    """Epoch-in-jit: ``lax.scan`` the train step over a STACK of batches.

    ``scan_fn(stacked_state, stacked_batches, table) -> (state, metrics)``
    where every batch array carries a leading ``(steps,)`` dimension
    (``stack_batches`` + ``shard_scan_batches``) and the returned metrics
    do too. One XLA dispatch executes the whole chain — the TPU-first
    answer to per-step dispatch overhead, which dominates small-batch
    throughput on remote-dispatch links (measured 2026-07-31: a B=64 step
    over the axon tunnel is ~21 ms wall vs ~25 ms for 16x the work at
    B=1024; the reference pays per-batch Python+DDP dispatch by
    construction, ``main.py:55-91``). Identical math to the per-step form:
    the body IS the same ``_build_local_step`` closure, so a fix to the
    step math lands in both.
    """
    local_step, k, batch_spec, axis = _build_local_step(
        model, cfg, strategy, mesh, mode, noise_fn, sharded_table
    )
    table_spec = P(axis) if sharded_table is not None else P()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis), _prepend_none(batch_spec), table_spec),
        out_specs=(P(axis), _prepend_none(P(axis))),
        check_vma=False,
    )
    def sharded_scan(stacked_state, batches, table):
        def one(carry, batch):
            new_state, metrics = _cohort_call(local_step, k, 2, carry, batch, table)
            return new_state, metrics

        return lax.scan(one, stacked_state, batches)

    return jax.jit(
        _reshard_state_out(sharded_scan, state_shardings),
        donate_argnums=(0, 1) if donate_batch else (0,),
    )


def stack_batches(batches: list) -> dict:
    """Stack per-step batch dicts into (steps, ...) arrays for
    ``build_fed_train_scan``."""
    return {
        kk: np.stack([b[kk] for b in batches]) for kk in batches[0]
    }


def shard_scan_batches(mesh: Mesh, stacked: dict, cfg: ExperimentConfig) -> dict:
    """Device-put stacked (steps, num_clients, ...) batch arrays: the
    per-key ``parallel.mesh.fed_batch_spec`` layout under a leading
    (unsharded) steps dimension."""
    return _shard_stacked_batches(mesh, stacked, cfg, depth=1)


def _shard_stacked_batches(
    mesh: Mesh, stacked: dict, cfg: ExperimentConfig, depth: int
) -> dict:
    """THE device-put for batch stacks: the per-key fed layout under
    ``depth`` leading unsharded dims (1 = epoch scan, 2 = round scan)."""
    from jax.sharding import NamedSharding

    from fedrec_tpu.parallel.mesh import fed_batch_spec

    def spec_of(kk):
        s = fed_batch_spec(kk, cfg, mesh)
        for _ in range(depth):
            s = _prepend_none(s)
        return s

    return {
        kk: jax.device_put(np.asarray(v), NamedSharding(mesh, spec_of(kk)))
        for kk, v in stacked.items()
    }


def build_fed_round_scan(
    model: NewsRecommender,
    cfg: ExperimentConfig,
    strategy: FedStrategy,
    mesh: Mesh,
    mode: str | None = None,
    noise_fn: Callable[[Any, jax.Array], Any] | None = None,
    donate_batch: bool = False,
    sharded_table: Any | None = None,
    state_shardings: Any | None = None,
) -> Callable:
    """Rounds-in-jit: whole federated ROUNDS in one XLA dispatch.

    ``round_scan(stacked_state, batches, table, weights) ->
    (state, metrics)`` where every batch array carries a leading
    ``(rounds, steps)`` pair (``stack_rounds`` + ``shard_round_batches``)
    and ``weights`` is a ``(rounds, num_clients)`` participation matrix
    applied at each round's end through ``strategy.sync_params``. This
    compiles the round loop the reference drives from Python over gloo —
    per-epoch ``all_reduce(param)/world_size``
    (``Parameter_Averaging_main.py:137-151``) and the server's
    broadcast/gather round loop (``server.py:72-105``) — into a single
    program: one dispatch per R rounds instead of R·S per-batch dispatches,
    the next rung above ``build_fed_train_scan`` on remote-dispatch links
    (its measured win: +17% at B=64 over the axon tunnel, 2026-08-01).

    The step body IS the same ``_build_local_step`` closure and the sync
    uses the ONE ``cohort_axes`` policy, so the math is identical to the
    Trainer's host-driven rounds (pinned in ``tests/test_scan.py``).
    ``Local``/``GradAvg`` strategies make the round-end sync a no-op,
    turning this into a plain multi-epoch-in-jit.
    """
    local_step, k, batch_spec, axis = _build_local_step(
        model, cfg, strategy, mesh, mode, noise_fn, sharded_table
    )
    table_spec = P(axis) if sharded_table is not None else P()
    _, sync_axes = cohort_axes(cfg, mesh)
    local_round_sync = _make_local_sync(strategy, sync_axes, cfg.fed.robust, cfg.fed)
    codec_sync = compressed_sync_active(cfg, strategy)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(axis),
            _prepend_none(_prepend_none(batch_spec)),
            table_spec,
            _prepend_none(P(axis)),
        ),
        out_specs=(P(axis), _prepend_none(_prepend_none(P(axis)))),
        check_vma=False,
    )
    def sharded_rounds(stacked_state, batches, table, weights):
        def one_step(carry, batch):
            return _cohort_call(local_step, k, 2, carry, batch, table)

        def one_round(carry, xs):
            r_batches, w = xs
            # the codec sync compresses each client's ROUND DELTA, so it
            # needs the round-entry params — captured from the carry here,
            # exactly the trees the Trainer captures host-side for the
            # host-driven path
            entry_u, entry_n = carry.user_params, carry.news_params
            st, ms = lax.scan(one_step, carry, r_batches)
            if codec_sync:
                st = _cohort_call(
                    local_round_sync, k, 4, st, w, entry_u, entry_n
                )
            else:
                st = _cohort_call(local_round_sync, k, 2, st, w)
            return st, ms

        return lax.scan(one_round, stacked_state, (batches, weights))

    return jax.jit(
        _reshard_state_out(sharded_rounds, state_shardings),
        donate_argnums=(0, 1) if donate_batch else (0,),
    )


def stack_rounds(round_batches: list) -> dict:
    """Stack a list of per-round batch lists into (rounds, steps, ...)
    arrays for ``build_fed_round_scan`` — literally two layers of
    ``stack_batches``."""
    return stack_batches([stack_batches(r) for r in round_batches])


def shard_round_batches(mesh: Mesh, stacked: dict, cfg: ExperimentConfig) -> dict:
    """Device-put (rounds, steps, num_clients, ...) batch arrays with the
    per-key fed layout under two leading unsharded dims."""
    return _shard_stacked_batches(mesh, stacked, cfg, depth=2)


def build_news_update_step(
    model: NewsRecommender,
    cfg: ExperimentConfig,
    mesh: Mesh,
    strategy: FedStrategy | None = None,
    state_shardings: Any | None = None,
) -> Callable:
    """Epoch-end news-head update for ``decoupled`` mode.

    Replays each client's accumulated per-nid embedding gradients through the
    text head with ``jax.vjp`` — semantically the reference's
    ``update_news_grad`` (``model.py:72-90``: forward touched news, then
    ``news_vecs.backward(news_grad)``, then Adam step) — and refreshes the
    news-vector table. All news rows participate (untouched rows have zero
    accumulated grad, contributing nothing, so no dynamic-shape "touched
    only" gather is needed).

    Under ``GradAvg`` the resulting head gradient is ``pmean``-ed across
    clients before the Adam step: because the accumulator and vjp are linear,
    averaging once here is mathematically identical to averaging the per-step
    embedding grads (DDP parity, reference ``Gradient_Averaging_main.py:119``)
    at a fraction of the collective cost.
    """
    _, opt_news_tx = make_optimizers(cfg)
    axis = cfg.fed.mesh_axis
    strategy = strategy or FedStrategy()
    k, sync_axes = cohort_axes(cfg, mesh)

    def local_update(state: ClientState, token_states: jnp.ndarray):
        def encode(news_params):
            return encode_all_news(model, news_params, token_states)

        vecs, vjp = jax.vjp(encode, state.news_params)
        (head_g,) = vjp(state.news_grad_accum)
        head_g = strategy.sync_grads(head_g, sync_axes)
        n_updates, opt_news = opt_news_tx.update(
            head_g, state.opt_news, state.news_params
        )
        new_params = jax.tree_util.tree_map(
            lambda p, u: p + u, state.news_params, n_updates
        )
        new_vecs = encode(new_params)
        new_state = state.replace(
            news_params=new_params,
            opt_news=opt_news,
            news_grad_accum=jnp.zeros_like(state.news_grad_accum),
        )
        return new_state, new_vecs

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=(P(axis), P(axis)),
        check_vma=False,
    )
    def sharded_update(stacked_state, token_states):
        return _cohort_call(local_update, k, 1, stacked_state, token_states)

    return jax.jit(
        _reshard_state_out(sharded_update, state_shardings),
        donate_argnums=(0,),
    )


def compressed_sync_active(cfg: ExperimentConfig, strategy: FedStrategy) -> bool:
    """True when the round-end sync runs the update-codec body — which
    takes the round-ENTRY params as extra arguments (deltas are what the
    codec compresses). ``dcn_compress='none'`` keeps the pre-codec sync
    program byte-for-byte (the bit-identity contract)."""
    return (
        getattr(cfg.fed, "dcn_compress", "none") != "none"
        and strategy.sync_params_every_round
    )


def _make_local_sync(
    strategy: FedStrategy, sync_axes: Any, robust: Any = None,
    fed_cfg: Any = None, leaf_codecs: list | None = None,
) -> Callable:
    """THE round-end parameter-sync body — shared by ``build_param_sync``
    (host-driven rounds) and ``build_fed_round_scan`` (rounds-in-jit) so
    the two programs can never diverge on what a round-end sync means.
    Optimizer states stay local (the reference likewise only averages
    parameters).

    ``robust`` (a ``fed.robust`` config section) swaps the weighted mean
    for a Byzantine-robust aggregator when ``method != "mean"`` — both
    towers aggregate as ONE tree so the clip method's global norm spans
    the whole client update (``fedrec_tpu.fed.robust``). Strategies that
    never sync params (local/grad_avg) stay untouched.

    ``fed_cfg`` (the ``fed`` config section) selects the update codec
    (``dcn_compress``). With a codec active the body signature grows to
    ``(state, w, entry_user, entry_news)`` — the client's round-ENTRY
    params — and the sync becomes the compressed-uplink model
    (``fedrec_tpu.comms``):

      1. ``delta_c = params_c - entry_c`` (each client's round update —
         DP clip+noise already happened per step, BEFORE any encode);
      2. ``acc_c = delta_c + residual_c`` (error feedback, biased codecs);
      3. ``decoded_c = decode(encode(acc_c))`` in-graph — the arithmetic
         twin of the wire codec; ``residual_c' = acc_c - decoded_c`` for
         participants (non-participants transmitted nothing and keep
         their residual);
      4. DECODE-BEFORE-REDUCE: the aggregator — weighted mean OR any
         ``fed.robust`` method — runs over the decoded dense deltas, so
         trimmed-mean/median judge clients, not quantization noise;
      5. every client adopts ``entry + aggregate`` (entries are the common
         post-sync global in any participating round); a round where no
         client reports keeps local params, the ``weighted_param_avg``
         contract.

    ``leaf_codecs`` (``fed.dcn_compress='auto'``): a pinned per-leaf codec
    map — one concrete codec per flattened leaf of the ``(user, news)``
    contribution tree, overriding the tree-wide codec. Error feedback then
    applies PER LEAF, only where the leaf's codec supports it (the
    capability table); sketch leaves stay unbiased and bank nothing.
    """
    method = getattr(robust, "method", "mean") if robust is not None else "mean"
    codec = getattr(fed_cfg, "dcn_compress", "none") if fed_cfg is not None else "none"
    if codec != "none" and strategy.sync_params_every_round:
        from fedrec_tpu.comms import (
            codec_caps,
            codec_uses_feedback,
            jax_encode_decode,
            validate_codec,
        )
        from fedrec_tpu.fed.strategies import weighted_param_avg

        if leaf_codecs is None and codec != "auto":
            validate_codec(codec)
        use_ef = codec_uses_feedback(codec, fed_cfg.dcn_error_feedback)
        ratio = fed_cfg.dcn_topk_ratio
        sk_width = getattr(fed_cfg, "dcn_sketch_width", 0.1)
        sk_seed = getattr(fed_cfg, "dcn_sketch_seed", 0)
        if method != "mean":
            from fedrec_tpu.fed.robust import (
                robust_aggregate,
                validate_robust_method,
            )

            validate_robust_method(method)

        def local_sync(state: ClientState, w: jnp.ndarray, entry_u, entry_n):
            entry = (entry_u, entry_n)
            theta = (state.user_params, state.news_params)
            delta = jax.tree_util.tree_map(
                lambda t, e: t.astype(jnp.float32) - e.astype(jnp.float32),
                theta, entry,
            )
            flat_d, treedef = jax.tree_util.tree_flatten(delta)
            # codec="auto" with no pinned map yet = the warmup window:
            # an all-"none" map (dense sync through the codec program
            # shape, so the later pin only swaps leaf constants)
            tree_wide = "none" if codec == "auto" else codec
            per_leaf = (
                [tree_wide] * len(flat_d)
                if leaf_codecs is None
                else [validate_codec(c) for c in leaf_codecs]
            )
            if len(per_leaf) != len(flat_d):
                raise ValueError(
                    f"per-leaf codec map has {len(per_leaf)} entries but "
                    f"the contribution tree has {len(flat_d)} leaves"
                )
            # EF applies per leaf, only where the leaf's codec is biased
            # (supports_error_feedback); unbiased leaves bank nothing
            ef_flags = [
                use_ef and codec_caps(c).supports_error_feedback
                for c in per_leaf
            ]
            flat_r = (
                jax.tree_util.tree_leaves(state.ef_residual)
                if use_ef
                else [None] * len(flat_d)
            )
            decs, new_rs = [], []
            for i, (d, c) in enumerate(zip(flat_d, per_leaf)):
                a = d + flat_r[i] if ef_flags[i] else d
                dec = jax_encode_decode(
                    a, c, ratio,
                    sketch_width=sk_width, sketch_seed=sk_seed, leaf_id=i,
                )
                decs.append(dec)
                if use_ef:
                    # a weight-0 client transmitted nothing this round:
                    # its residual carries over unchanged (its delta is
                    # discarded with its participation, not banked)
                    new_rs.append(
                        jnp.where(w > 0, a - dec, flat_r[i])
                        if ef_flags[i]
                        else flat_r[i]
                    )
            decoded = jax.tree_util.tree_unflatten(treedef, decs)
            new_residual = (
                jax.tree_util.tree_unflatten(treedef, new_rs)
                if use_ef
                else None
            )
            if method != "mean":
                agg = robust_aggregate(
                    decoded, w, sync_axes,
                    method=method, trim_k=robust.trim_k,
                    clip_norm=robust.clip_norm,
                )
            else:
                agg = weighted_param_avg(decoded, w, sync_axes)
            any_p = lax.psum(
                (w > 0).astype(jnp.float32), axis_name=sync_axes
            ) > 0
            new_user, new_news = jax.tree_util.tree_map(
                lambda e, a, t: jnp.where(
                    any_p, (e.astype(jnp.float32) + a).astype(t.dtype), t
                ),
                entry, agg, theta,
            )
            kwargs: dict = {"user_params": new_user, "news_params": new_news}
            if new_residual is not None:
                kwargs["ef_residual"] = new_residual
            return state.replace(**kwargs)

        return local_sync

    if method != "mean" and strategy.sync_params_every_round:
        from fedrec_tpu.fed.robust import robust_aggregate, validate_robust_method

        validate_robust_method(method)

        def local_sync(state: ClientState, w: jnp.ndarray):
            new_user, new_news = robust_aggregate(
                (state.user_params, state.news_params),
                w,
                sync_axes,
                method=method,
                trim_k=robust.trim_k,
                clip_norm=robust.clip_norm,
            )
            return state.replace(user_params=new_user, news_params=new_news)

        return local_sync

    def local_sync(state: ClientState, w: jnp.ndarray):
        new_user = strategy.sync_params(state.user_params, w, sync_axes)
        new_news = strategy.sync_params(state.news_params, w, sync_axes)
        return state.replace(user_params=new_user, news_params=new_news)

    return local_sync


def build_param_sync(
    cfg: ExperimentConfig,
    mesh: Mesh,
    strategy: FedStrategy | None = None,
    state_shardings: Any | None = None,
    leaf_codecs: list | None = None,
) -> Callable:
    """Round-end parameter aggregation, dispatched through the strategy.

    ``sync(stacked_state, weights) -> stacked_state`` where ``weights`` is a
    (num_clients,) mask/weight vector. With ``ParamAvg``, equal weights
    reproduce the reference's ``all_reduce(param)/world_size`` FedAvg
    (``Parameter_Averaging_main.py:144-148``); masks implement client-subset
    rounds. ``Local``/``GradAvg`` leave parameters untouched. Optimizer
    states stay local (the reference likewise only averages parameters).
    """
    axis = cfg.fed.mesh_axis
    strategy = strategy or ParamAvg()
    k, sync_axes = cohort_axes(cfg, mesh)
    local_sync = _make_local_sync(
        strategy, sync_axes, cfg.fed.robust, cfg.fed, leaf_codecs=leaf_codecs
    )

    if compressed_sync_active(cfg, strategy):
        # codec body: ``sync(state, weights, entry_user, entry_news)`` —
        # the caller supplies the round-ENTRY param trees (stacked per
        # client), captured before the round's first (buffer-donating)
        # step dispatch
        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis)),
            out_specs=P(axis),
            check_vma=False,
        )
        def sharded_sync_c(stacked_state, weights, entry_u, entry_n):
            return _cohort_call(
                local_sync, k, 4, stacked_state, weights, entry_u, entry_n
            )

        return jax.jit(_reshard_state_out(sharded_sync_c, state_shardings))

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )
    def sharded_sync(stacked_state, weights):
        return _cohort_call(local_sync, k, 2, stacked_state, weights)

    # NOT donated (unlike the train step): sync runs once per round, so the
    # transient double-buffer is cheap, and callers legitimately hold the
    # pre-sync state for comparisons (e.g. the local-strategy identity test)
    return jax.jit(_reshard_state_out(sharded_sync, state_shardings))


# --------------------------------------------------------------- eval step
def build_eval_step(model: NewsRecommender, cfg: ExperimentConfig) -> Callable:
    """Per-impression validation metrics on device.

    ``evaluate(user_params, news_vecs_table, batch) -> dict of (B,) arrays``
    scoring candidates by dot product (reference ``Trainer.validate``,
    ``client.py:149-171``). Returns PER-IMPRESSION vectors (incl. per-row
    loss) so the caller can trim batch padding before averaging — fixing
    both the reference's last-sample-only bug (``client.py:171``) and the
    wrap-around-pad double count of a naive batch mean.
    """

    def evaluate(user_params, news_vecs, batch):
        cand_vecs = news_vecs[batch["candidates"]]
        his_vecs = news_vecs[batch["history"]]
        user_vec = model.apply(
            {"params": {"user_encoder": user_params}},
            his_vecs,
            method=NewsRecommender.encode_user,
        )
        scores = score_candidates(cand_vecs, user_vec)
        out = dict(ranking_metrics_batch(scores))
        out["loss"] = score_loss(
            scores, batch["labels"], cfg.model.sigmoid_before_ce, reduce=False
        )
        return out

    return jax.jit(evaluate)


def _full_eval_body(
    model: NewsRecommender, quality: tuple | None = None
) -> Callable:
    """Per-impression full-pool scoring — the ONE definition both the
    unsharded and the mesh-sharded eval step wrap (a fix applied to the
    scoring math can never diverge the two paths).

    ``quality`` = ``(score_bins, score_range, ece_bins)`` additionally
    returns the fixed-shape quality partial sums
    (:func:`fedrec_tpu.eval.metrics.quality_stats_batch` — score
    histograms + reliability bins, no host syncs) from the SAME scores;
    the batch then carries a ``keep`` (B,) weight vector zeroing padded
    impressions.  ``quality=None`` builds the exact pre-quality program.
    """
    from fedrec_tpu.eval.metrics import full_pool_metrics_batch, quality_stats_batch

    def evaluate(user_params, news_vecs, batch):
        his_vecs = news_vecs[batch["history"]]
        user_vec = model.apply(
            {"params": {"user_encoder": user_params}},
            his_vecs,
            method=NewsRecommender.encode_user,
        )  # (B, D)
        pos_scores = jnp.einsum("bd,bd->b", news_vecs[batch["pos"]], user_vec)
        neg_scores = jnp.einsum("bpd,bd->bp", news_vecs[batch["neg_pools"]], user_vec)
        out = full_pool_metrics_batch(pos_scores, neg_scores, batch["neg_mask"])
        if quality is not None:
            score_bins, score_range, ece_bins = quality
            out.update(quality_stats_batch(
                pos_scores, neg_scores, batch["neg_mask"], batch["keep"],
                score_bins, score_range, ece_bins,
            ))
        return out

    return evaluate


def build_full_eval_step(
    model: NewsRecommender, cfg: ExperimentConfig, quality: tuple | None = None
) -> Callable:
    """Deterministic FULL-POOL evaluation step.

    ``evaluate(user_params, news_vecs_table, batch) -> dict of (B,) arrays``
    where ``batch`` holds per-impression ``pos`` (B,), padded negative pools
    ``neg_pools`` (B, P) with ``neg_mask`` (B, P), and ``history`` (B, H).
    Scores every real pool negative against the one positive — the protocol
    behind the reference's published MIND table (``evaluation_split``,
    reference ``evaluation_functions.py:33-47``), with no sampling noise.
    ``quality`` (see :func:`_full_eval_body`) adds the fixed-shape
    quality partial sums to the outputs.
    """
    return jax.jit(_full_eval_body(model, quality))


def build_full_eval_step_sharded(
    model: NewsRecommender, cfg: ExperimentConfig, mesh: Mesh,
    quality: tuple | None = None,
) -> Callable:
    """:func:`build_full_eval_step` sharded over EVERY mesh axis.

    Each of the mesh's devices scores ``B / mesh.size`` impressions against
    the replicated news-vector table; per-impression metrics come back
    sharded and the caller's host mean is unchanged. Same per-impression
    math as the unsharded step (the shard body IS it), so the published-
    table protocol stays exact while the full-pool pass — the eval
    bottleneck at MIND scale — takes ``1/mesh.size`` of the wall time.
    Callers must keep the batch axis divisible by ``mesh.size`` (the
    Trainer rounds its eval block size accordingly).

    With ``quality`` set, the per-shard quality partial sums are
    ``psum``-reduced across the mesh inside the shard body and come back
    replicated (out-spec ``P()``), so the host accumulates the same
    global sums it would from the unsharded step.
    """
    axes = tuple(mesh.axis_names)
    if quality is None:
        sharded = partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(), P(axes)),
            out_specs=P(axes),
            check_vma=False,
        )(_full_eval_body(model))
        return jax.jit(sharded)

    from fedrec_tpu.eval.metrics import QUALITY_SUM_KEYS

    body = _full_eval_body(model, quality)

    def body_psum(user_params, news_vecs, batch):
        out = body(user_params, news_vecs, batch)
        for k in QUALITY_SUM_KEYS:
            out[k] = jax.lax.psum(out[k], axes)
        return out

    out_specs = {
        **{k: P(axes) for k in ("auc", "mrr", "ndcg5", "ndcg10")},
        **{k: P() for k in QUALITY_SUM_KEYS},
    }
    sharded = partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(axes)),
        out_specs=out_specs,
        check_vma=False,
    )(body_psum)
    return jax.jit(sharded)
