"""Orbax checkpointing: ``{client states, round}`` with auto-resume.

Parity target: the reference Trainer's ``_save_snapshot``/``_load_snapshot``
(``{MODEL_STATE, EPOCHS_RUN}`` to ``snapshot.pt``, auto-resume when the file
exists, saved every ``save_every`` epochs — reference ``main.py:112-133,138-139``).
Here the snapshot is the full federated pytree — per-client parameters AND
optimizer states AND PRNG keys — so a resumed run is bit-identical to an
uninterrupted one, which the reference's params-only snapshot is not (its
Adam moments reset on resume; ledger).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp


class SnapshotIntegrityError(RuntimeError):
    """A restored snapshot failed verification (non-finite parameters):
    the on-disk bytes parsed but the state is not trainable."""


def verify_state_tree(state: Any, samples_per_leaf: int = 256) -> None:
    """Integrity check on a restored state pytree: a strided sample of
    every float PARAMETER leaf must be finite.  Params only — a healthy
    quarantine-era snapshot may legitimately carry non-finite optimizer
    moments for an excluded client, but non-finite *parameters* can never
    be right (every client adopts the finite aggregate at round end).
    Raises :class:`SnapshotIntegrityError` on the first bad leaf."""
    subtrees = []
    for name in ("user_params", "news_params"):
        sub = getattr(state, name, None)
        if sub is None and isinstance(state, dict):
            sub = state.get(name)
        if sub is not None:
            subtrees.append((name, sub))
    if not subtrees:  # unknown layout: check everything float
        subtrees = [("state", state)]
    for name, sub in subtrees:
        for path, leaf in jax.tree_util.tree_flatten_with_path(sub)[0]:
            arr = np.asarray(leaf)
            if not np.issubdtype(arr.dtype, np.floating):
                continue
            flat = arr.reshape(-1)
            stride = max(1, flat.size // samples_per_leaf)
            if not np.isfinite(flat[::stride]).all():
                raise SnapshotIntegrityError(
                    f"non-finite values in restored {name}"
                    f"{jax.tree_util.keystr(path)}"
                )


def gather_for_save(state: Any) -> Any:
    """Make every leaf of a state pytree checkpoint-safe regardless of its
    device layout — the gather-on-save half of the sharded-checkpoint
    contract (``shard.fsdp`` / ``shard.table``; the restore half is the
    template-driven ``restore`` + the Trainer's ``_place_state``
    re-commit).

    Fully-addressable leaves (host arrays, replicated device arrays, and
    single-host sharded arrays) pass through untouched — orbax serializes
    them as-is, so the no-shard path is byte-identical to the pre-shard
    snapshot format. A NON-fully-addressable leaf (a multi-host mesh
    holding only its slice of the fsdp axis) is gathered to a host copy
    via ``process_allgather`` first; without this, orbax's save would
    require every process to hold every shard and fail.
    """

    def one(x: Any) -> Any:
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            return multihost_utils.process_allgather(x)
        return x

    return jax.tree_util.tree_map(one, state)


class SnapshotManager:
    def __init__(self, directory: str | Path, max_to_keep: int = 3):
        self.directory = Path(directory).absolute()
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )
        # the round restore() actually landed on — may be OLDER than
        # latest_round() when the newest snapshot was corrupt/torn and
        # restore fell back to a previous retained one
        self.last_restored_round: int | None = None

    def _settled_step(self, round_idx: int | None) -> int | None:
        """The one reader-side settle point: waits out any in-flight async
        save, then resolves ``None`` to the latest step."""
        self.manager.wait_until_finished()
        return self.manager.latest_step() if round_idx is None else round_idx

    def latest_round(self) -> int | None:
        return self._settled_step(None)

    def save(self, round_idx: int, state: Any, wait: bool = False) -> None:
        """Persist the full state pytree for ``round_idx``.

        Async by default: orbax snapshots device buffers synchronously (the
        values are consistent) but performs the serialization/IO in the
        background, overlapping with the next round's compute instead of
        stalling the step stream. Readers (``latest_round``/``restore``) and
        ``close`` settle in-flight saves first, so no torn snapshot is ever
        observable. ``wait=True`` restores the blocking behavior.

        Sharded leaves round-trip: non-fully-addressable arrays gather to
        host first (:func:`gather_for_save`), and ``restore`` hands back
        whatever layout the caller's template asks for — a ``shard.fsdp``
        run resumes bit-identically (``tests/test_shard_fsdp.py``).
        """
        self.manager.save(
            round_idx, args=ocp.args.StandardSave(gather_for_save(state))
        )
        if wait:
            self.manager.wait_until_finished()

    def restore_raw(self, round_idx: int | None = None) -> Any:
        """Restore WITHOUT a template: the saved pytree as host arrays, any
        leading client dim intact. Serving uses this — it must not need the
        training run's mesh (or even its device count) to read parameters."""
        step = self._settled_step(round_idx)
        if step is None:
            raise FileNotFoundError(f"no snapshot under {self.directory}")
        return self.manager.restore(step, args=ocp.args.StandardRestore())

    def restore(
        self,
        state_template: Any,
        round_idx: int | None = None,
        verify: bool = True,
    ) -> Any:
        """Restore into the structure of ``state_template`` (shapes/dtypes).

        Integrity-checked: the loaded pytree is verified (structure via the
        template restore itself; finite-ness of a sampled subset of every
        parameter leaf via :func:`verify_state_tree`).  When the LATEST
        snapshot is corrupt or torn — a crash mid-write, a truncated file,
        a bad disk — restore falls back to the previous retained snapshot
        instead of crashing the resume; ``self.last_restored_round``
        records which round actually loaded (callers must resume from
        ``last_restored_round + 1``, not ``latest_round() + 1``).  An
        explicit ``round_idx`` disables the fallback (the caller asked for
        that exact snapshot).
        """
        step = self._settled_step(round_idx)
        if step is None:
            raise FileNotFoundError(f"no snapshot under {self.directory}")
        abstract = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct, state_template
        )
        if round_idx is not None:
            candidates = [step]
        else:
            candidates = sorted(
                (s for s in self.manager.all_steps() if s <= step), reverse=True
            ) or [step]
        last_err: Exception | None = None
        for s in candidates:
            try:
                out = self.manager.restore(
                    s, args=ocp.args.StandardRestore(abstract)
                )
                if verify:
                    verify_state_tree(out)
                self.last_restored_round = int(s)
                if s != candidates[0]:
                    print(
                        f"[checkpoint] fell back to the round-{s} snapshot "
                        f"(newest at round {candidates[0]} is corrupt: "
                        f"{type(last_err).__name__})"
                    )
                return out
            except Exception as e:  # noqa: BLE001 — each retained snapshot
                # gets its chance; the LAST error is re-raised below
                last_err = e
                print(
                    f"[checkpoint] snapshot at round {s} failed to "
                    f"restore/verify ({type(e).__name__}: {e}); "
                    + ("trying the previous retained snapshot"
                       if s != candidates[-1] else "no older snapshot left")
                )
        raise RuntimeError(
            f"every retained snapshot under {self.directory} failed to "
            f"restore (rounds {candidates}); the checkpoint directory is "
            "unusable — point train.snapshot_dir somewhere fresh"
        ) from last_err

    def wait(self) -> None:
        """Settle in-flight async saves (call before process exit)."""
        self.manager.wait_until_finished()

    def close(self) -> None:
        self.manager.close()  # orbax settles in-flight saves itself


def coordinator_globals(directory: str | Path) -> list[Path]:
    """The coordinator deployment's global-model snapshots
    (``global_round_N.msgpack``, flax-serialized ``{user, news, round}``),
    oldest to newest. The single source of the filename contract — the
    coordinator's writer/retention and the serving CLI's reader both use it.
    Files whose suffix is not an integer (operator backups like
    ``global_round_19_backup.msgpack``) are ignored, not crashed on.
    """
    out = []
    for p in Path(directory).glob("global_round_*.msgpack"):
        r = global_round_of(p)
        if r is not None:
            out.append((r, p))
    return [p for _, p in sorted(out)]


def global_round_of(path: Path) -> int | None:
    try:
        return int(path.stem.rsplit("_", 1)[1])
    except ValueError:
        return None


def atomic_write_bytes(path: Path, blob: bytes) -> None:
    """Write-then-rename so concurrent readers never see a torn file."""
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_bytes(blob)
    os.replace(tmp, path)


NEWS_TABLE_CHECKPOINT = "news_table.npy"


def save_table_checkpoint(directory: str | Path, rows: Any) -> Path:
    """Persist the full (host-gathered, unpadded) news/token table next to
    the snapshots — the recovery source for a sharded-catalog shrink: a
    lost host takes its ``shard.table`` row blocks with it, and the
    re-formed world reloads those rows from HERE instead of losing them
    (``shard.table.recover_table_rows``).  Atomic, like every snapshot
    artifact.  The table is frozen in table/head modes, so one write per
    run suffices (callers skip the write when the file exists)."""
    import io

    buf = io.BytesIO()
    np.save(buf, np.asarray(rows))
    path = Path(directory) / NEWS_TABLE_CHECKPOINT
    path.parent.mkdir(parents=True, exist_ok=True)
    atomic_write_bytes(path, buf.getvalue())
    return path


def load_table_checkpoint(directory: str | Path) -> np.ndarray | None:
    """Inverse of :func:`save_table_checkpoint`; ``None`` when absent or
    unreadable (recovery then falls back to the original token source —
    a torn table checkpoint must not kill a resume)."""
    path = Path(directory) / NEWS_TABLE_CHECKPOINT
    if not path.exists():
        return None
    try:
        return np.load(path)
    except (OSError, ValueError) as e:
        print(
            f"[checkpoint] table checkpoint {path.name} unreadable "
            f"({type(e).__name__}: {e}); ignoring it"
        )
        return None


POPULATION_SIDECAR = "population_state.msgpack"


def population_state_bytes(
    sampler_state: dict,
    ledger_state: dict,
    slot_occupants: np.ndarray,
    slot_writeback: np.ndarray,
    round_idx: int,
) -> bytes:
    """Serialize the cohort engine's schedule-defining state — the
    sampler's fairness counters, the participation ledger (incl.
    quarantine expiries), and the current slot occupancy — as the
    ``population_state.msgpack`` snapshot sidecar. Round-tagged like the
    FedOpt sidecar so a loader can detect a sidecar that does not match
    the snapshot it resumes from. Restoring it makes the post-resume
    cohort SCHEDULE identical to an uninterrupted run
    (``tests/test_population.py``); per-client optimizer sidecars are
    deliberately not included (cross-device clients are cheap to restart
    from the template — documented in docs/OPERATIONS.md)."""
    from flax import serialization

    return serialization.to_bytes({
        "sampler": sampler_state,
        "ledger": ledger_state,
        "slot_occupants": np.asarray(slot_occupants, np.int64),
        "slot_writeback": np.asarray(slot_writeback, bool),
        "round": np.int64(round_idx),
    })


def load_population_state(blob: bytes) -> dict:
    """Inverse of :func:`population_state_bytes` (msgpack is
    self-describing, so no template is needed)."""
    from flax import serialization

    state = serialization.msgpack_restore(blob)
    state["round"] = int(state["round"])
    return state
