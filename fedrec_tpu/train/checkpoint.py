"""Orbax checkpointing: ``{client states, round}`` with auto-resume.

Parity target: the reference Trainer's ``_save_snapshot``/``_load_snapshot``
(``{MODEL_STATE, EPOCHS_RUN}`` to ``snapshot.pt``, auto-resume when the file
exists, saved every ``save_every`` epochs — reference ``main.py:112-133,138-139``).
Here the snapshot is the full federated pytree — per-client parameters AND
optimizer states AND PRNG keys — so a resumed run is bit-identical to an
uninterrupted one, which the reference's params-only snapshot is not (its
Adam moments reset on resume; ledger).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp


class SnapshotManager:
    def __init__(self, directory: str | Path, max_to_keep: int = 3):
        self.directory = Path(directory).absolute()
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def _settled_step(self, round_idx: int | None) -> int | None:
        """The one reader-side settle point: waits out any in-flight async
        save, then resolves ``None`` to the latest step."""
        self.manager.wait_until_finished()
        return self.manager.latest_step() if round_idx is None else round_idx

    def latest_round(self) -> int | None:
        return self._settled_step(None)

    def save(self, round_idx: int, state: Any, wait: bool = False) -> None:
        """Persist the full state pytree for ``round_idx``.

        Async by default: orbax snapshots device buffers synchronously (the
        values are consistent) but performs the serialization/IO in the
        background, overlapping with the next round's compute instead of
        stalling the step stream. Readers (``latest_round``/``restore``) and
        ``close`` settle in-flight saves first, so no torn snapshot is ever
        observable. ``wait=True`` restores the blocking behavior.
        """
        self.manager.save(round_idx, args=ocp.args.StandardSave(state))
        if wait:
            self.manager.wait_until_finished()

    def restore_raw(self, round_idx: int | None = None) -> Any:
        """Restore WITHOUT a template: the saved pytree as host arrays, any
        leading client dim intact. Serving uses this — it must not need the
        training run's mesh (or even its device count) to read parameters."""
        step = self._settled_step(round_idx)
        if step is None:
            raise FileNotFoundError(f"no snapshot under {self.directory}")
        return self.manager.restore(step, args=ocp.args.StandardRestore())

    def restore(self, state_template: Any, round_idx: int | None = None) -> Any:
        """Restore into the structure of ``state_template`` (shapes/dtypes)."""
        step = self._settled_step(round_idx)
        if step is None:
            raise FileNotFoundError(f"no snapshot under {self.directory}")
        abstract = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct, state_template
        )
        return self.manager.restore(step, args=ocp.args.StandardRestore(abstract))

    def wait(self) -> None:
        """Settle in-flight async saves (call before process exit)."""
        self.manager.wait_until_finished()

    def close(self) -> None:
        self.manager.close()  # orbax settles in-flight saves itself


def coordinator_globals(directory: str | Path) -> list[Path]:
    """The coordinator deployment's global-model snapshots
    (``global_round_N.msgpack``, flax-serialized ``{user, news, round}``),
    oldest to newest. The single source of the filename contract — the
    coordinator's writer/retention and the serving CLI's reader both use it.
    Files whose suffix is not an integer (operator backups like
    ``global_round_19_backup.msgpack``) are ignored, not crashed on.
    """
    out = []
    for p in Path(directory).glob("global_round_*.msgpack"):
        r = global_round_of(p)
        if r is not None:
            out.append((r, p))
    return [p for _, p in sorted(out)]


def global_round_of(path: Path) -> int | None:
    try:
        return int(path.stem.rsplit("_", 1)[1])
    except ValueError:
        return None


def atomic_write_bytes(path: Path, blob: bytes) -> None:
    """Write-then-rename so concurrent readers never see a torn file."""
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_bytes(blob)
    os.replace(tmp, path)
