"""Training state: parameters + dual optimizer states + PRNG, as one pytree.

The reference keeps two inner Adam optimizers on the model object (lr 5e-5,
reference ``model.py:22-23``) plus a vestigial outer SGD (``main.py:171``) —
here the state is an explicit immutable pytree: ``{user, news}`` parameter
subtrees with separate optax states (preserving the two-optimizer structure,
minus the dead outer SGD — ledger item), a per-client PRNG key, and the
news-embedding-gradient accumulator for the decoupled (reference-parity)
update path (``model.py:97-109`` ``collect``).

Federated simulation stacks one ``ClientState`` per client along a leading
axis that is sharded over the mesh's ``clients`` axis.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import struct

from fedrec_tpu.config import ExperimentConfig
from fedrec_tpu.models import NewsRecommender


@struct.dataclass
class ClientState:
    step: jnp.ndarray                 # int32 scalar
    user_params: Any                  # user-encoder subtree
    news_params: Any                  # text-head subtree
    opt_user: Any                     # optax state for user_params
    opt_news: Any                     # optax state for news_params
    rng: jax.Array                    # per-client PRNG key
    news_grad_accum: jnp.ndarray      # (N_news, D) embedding-grad scatter target
    # per-client error-feedback residual for the biased update codecs
    # (fed.dcn_compress = sign1bit/topk with fed.dcn_error_feedback): a
    # (user_params, news_params)-shaped pytree holding the mass the last
    # lossy encode dropped, re-entering the next round's update. A scalar
    # zero placeholder when the active codec keeps no residual — the state
    # template (and so snapshots and the population sidecar) stay one
    # structure per config. Listed in fed.population.SIDECAR_FIELDS, so it
    # LRU/disk-spills with the optimizer moments and resets on quarantine
    # heal (a healed client must not replay a poisoned residual).
    ef_residual: Any = None

    def full_params(self) -> dict:
        """Reassemble the flax variables dict for ``model.apply``."""
        return {"params": {"user_encoder": self.user_params, "text_head": self.news_params}}


def make_optimizers(cfg: ExperimentConfig) -> tuple[optax.GradientTransformation, optax.GradientTransformation]:
    def _make(lr: float) -> optax.GradientTransformation:
        if cfg.optim.lr_schedule not in ("constant", "cosine"):
            raise ValueError(
                f"unknown lr_schedule {cfg.optim.lr_schedule!r} "
                "(constant|cosine)"
            )
        sched: float | optax.Schedule = lr
        if cfg.optim.lr_schedule == "cosine" and cfg.optim.decay_steps > 0:
            # cosine decay over the run's optimizer-step budget (the caller
            # sets decay_steps = rounds * local_epochs * steps_per_epoch;
            # decay_steps=0 means constant, per the config contract).
            # Matters most for DP-SGD: injected-noise variance scales with
            # lr^2, so a small late lr averages the noise out while the
            # large early lr does the escaping (docs/DP.md)
            sched = optax.cosine_decay_schedule(
                lr, cfg.optim.decay_steps, alpha=cfg.optim.lr_min_frac
            )
        txs = []
        if cfg.optim.grad_clip_norm > 0:
            txs.append(optax.clip_by_global_norm(cfg.optim.grad_clip_norm))
        if cfg.optim.optimizer == "adam":
            txs.append(optax.adam(sched))
        elif cfg.optim.optimizer == "sgd":
            txs.append(optax.sgd(sched))
        else:
            raise ValueError(f"unknown optimizer {cfg.optim.optimizer!r}")
        return optax.chain(*txs)

    return _make(cfg.optim.user_lr), _make(cfg.optim.news_lr)


def init_client_state(
    model: NewsRecommender,
    cfg: ExperimentConfig,
    rng: jax.Array,
    num_news: int,
    title_len: int | None = None,
) -> ClientState:
    """Initialize one client's state (shapes from config; no data needed)."""
    title_len = title_len or cfg.data.max_title_len
    init_rng, state_rng = jax.random.split(rng)
    dummy_states = jnp.zeros((1, title_len, cfg.model.bert_hidden), cfg.model.dtype)
    dummy_cand = jnp.zeros((1, 1 + cfg.data.npratio, cfg.model.news_dim), cfg.model.dtype)
    dummy_his = jnp.zeros((1, cfg.data.max_his_len, cfg.model.news_dim), cfg.model.dtype)
    variables = model.init(
        init_rng, dummy_states, dummy_cand, dummy_his,
        method=NewsRecommender.init_both_towers,
    )
    user_params = variables["params"]["user_encoder"]
    if cfg.model.text_encoder_mode == "finetune":
        # news tower = full TextEncoder (trunk + head), trained in-loop
        # (BASELINE config 5); pretrained trunk weights can be grafted in
        # afterwards via models.bert.load_hf_state_dict
        from fedrec_tpu.models.bert import make_text_encoder

        te = make_text_encoder(cfg.model)
        dummy_tokens = jnp.zeros((1, 2, title_len), jnp.int32)
        news_params = te.init(init_rng, dummy_tokens)["params"]
    else:
        news_params = variables["params"]["text_head"]
    opt_user_tx, opt_news_tx = make_optimizers(cfg)
    from fedrec_tpu.comms import codec_uses_feedback

    if codec_uses_feedback(cfg.fed.dcn_compress, cfg.fed.dcn_error_feedback):
        ef_residual = jax.tree_util.tree_map(
            lambda x: jnp.zeros(x.shape, jnp.float32),
            (user_params, news_params),
        )
    else:
        ef_residual = jnp.zeros((), jnp.float32)
    return ClientState(
        step=jnp.zeros((), jnp.int32),
        user_params=user_params,
        news_params=news_params,
        opt_user=opt_user_tx.init(user_params),
        opt_news=opt_news_tx.init(news_params),
        rng=state_rng,
        news_grad_accum=jnp.zeros((num_news, cfg.model.news_dim), jnp.float32),
        ef_residual=ef_residual,
    )


def stack_states(states: list[ClientState]) -> ClientState:
    """Stack per-client states along a new leading (clients) axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)


def replicate_state(state: ClientState, num_clients: int, rng: jax.Array) -> ClientState:
    """One init broadcast to all clients, with distinct per-client PRNG keys.

    All clients start from identical parameters — matching the reference,
    where the server broadcasts the initial model before round 1
    (``server.py:76-77``).
    """
    stacked = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (num_clients,) + x.shape), state
    )
    return stacked.replace(rng=jax.random.split(rng, num_clients))
