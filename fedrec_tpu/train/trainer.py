"""The single Trainer — ends the reference's 4-way copy-paste.

The reference duplicates its Trainer + train step + grad processors across
``main.py:95-139``, ``Gradient_Averaging_main.py:96-149``,
``Parameter_Averaging_main.py:96-151`` and ``client.py:105-189`` with small
diffs (SURVEY.md section 1, "Key structural fact"). Here one Trainer drives
every mode; the differences are a ``FedStrategy`` object and config flags.

Round structure (generalizes all reference drivers):

  for round in rounds:                      # server.py:72 round loop
      draw participation mask               # fixes Final_Report VII.a dropout
      for local_epoch in local_epochs:      # client local training
          for batch in sharded batches:     # jitted SPMD step, ICI collectives
              step()
          if decoupled: news_update()       # model.py:66-90 update() parity
      if strategy.sync_params_every_round:
          param_sync(mask)                  # Parameter_Averaging_main.py:144-148
      evaluate(); log; snapshot every save_every  # main.py:138-139
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from fedrec_tpu.config import ExperimentConfig
from fedrec_tpu.data.batcher import IndexedSamples, TrainBatcher, index_samples
from fedrec_tpu.data.mind import MindData
from fedrec_tpu.data.prefetch import maybe_prefetch
from fedrec_tpu.fed.strategies import get_strategy
from fedrec_tpu.models import NewsRecommender
from fedrec_tpu.parallel.mesh import (
    client_sharding,
    fed_mesh,
    shard_fed_batch,
)
from fedrec_tpu.train.checkpoint import SnapshotManager
from fedrec_tpu.train.state import init_client_state, replicate_state
from fedrec_tpu.train.step import (
    build_eval_step,
    build_fed_round_scan,
    build_fed_train_step,
    build_full_eval_step,
    build_full_eval_step_sharded,
    build_news_update_step,
    build_fed_train_scan,
    build_param_sync,
    compressed_sync_active,
    encode_all_news,
    encode_all_news_sharded,
    shard_round_batches,
    shard_scan_batches,
    stack_batches,
    stack_rounds,
)
from fedrec_tpu.obs import (
    CompileWatchdog,
    FlightRecorder,
    HealthMonitor,
    TrainingHealthError,
    dump_artifacts,
    get_registry,
    get_tracer,
    rotate_jsonl,
    sample_device_memory,
)
from fedrec_tpu.utils.logging import MetricLogger
from fedrec_tpu.utils.profiling import profile_if


@dataclass
class RoundResult:
    round_idx: int
    train_loss: float
    val_metrics: dict[str, float] = field(default_factory=dict)


class RoundRecovery(Exception):
    """Internal control flow for quarantine-and-rollback recovery
    (``fed.robust.recover``): raised by the round-end health check instead
    of the hard abort, caught by ``Trainer.run``, which quarantines the
    offending client, restores the round-entry state, and replays."""

    def __init__(self, trigger: dict):
        super().__init__(
            f"recoverable health trigger [{trigger.get('kind')}] "
            f"client {trigger.get('client')} round {trigger.get('round')}"
        )
        self.trigger = trigger


class Trainer:
    """Federated trainer over a clients mesh.

    ``token_states``: (N_news, L, bert_hidden) cached frozen-trunk token
    states (see ``fedrec_tpu.models.bert`` for producing them from a real
    DistilBERT, or pass synthetic states for smoke runs).
    """

    def __init__(
        self,
        cfg: ExperimentConfig,
        data: MindData,
        token_states: np.ndarray,
        snapshot_dir: str | None = None,
    ):
        self.cfg = cfg
        self.data = data
        self.model = NewsRecommender(cfg.model)
        self.strategy = get_strategy(cfg.fed.strategy)
        # ---- robustness (fed.robust + chaos): validate up front — a robust
        # method or recovery mode that would silently never apply is a
        # misconfiguration, not a preference (same policy as server_opt)
        from fedrec_tpu.fed.robust import validate_robust_method

        rb = cfg.fed.robust
        validate_robust_method(rb.method)
        if rb.method != "mean" and not self.strategy.sync_params_every_round:
            raise ValueError(
                f"fed.robust.method={rb.method!r} requires a strategy that "
                "syncs params every round (param_avg or coordinator); "
                f"fed.strategy={cfg.fed.strategy!r} never aggregates params, "
                "so the robust aggregator would silently never run"
            )
        if rb.recover:
            if not self.strategy.sync_params_every_round:
                raise ValueError(
                    "fed.robust.recover=true requires a param-syncing "
                    "strategy (param_avg or coordinator): quarantine works "
                    "by zeroing the client's aggregation weight"
                )
            if not cfg.obs.health.sentry:
                raise ValueError(
                    "fed.robust.recover=true requires obs.health.sentry: "
                    "recovery is driven by the in-graph health vectors"
                )
        # ---- update-compression codec (fed.dcn_compress, fedrec_tpu.comms):
        # validated up front like robust/server_opt — a codec that would
        # silently never run is a misconfiguration, not a preference.
        # "auto" is the adaptive per-leaf mode: a concrete codec per leaf
        # is pinned from warmup telemetry (see _pin_auto_codec_map).
        from fedrec_tpu.comms import codec_caps, validate_codec

        if cfg.fed.dcn_compress != "auto":
            validate_codec(cfg.fed.dcn_compress)
        if (
            cfg.fed.dcn_compress != "none"
            and not self.strategy.sync_params_every_round
        ):
            raise ValueError(
                f"fed.dcn_compress={cfg.fed.dcn_compress!r} requires a "
                "strategy that syncs params every round (param_avg or "
                f"coordinator); fed.strategy={cfg.fed.strategy!r} never "
                "ships a round update, so the codec would silently never "
                "run (per-step grad_avg traffic is not compressed)"
            )
        if cfg.shard.fsdp > 1 and cfg.fed.dcn_compress == "topk":
            raise ValueError(
                "fed.dcn_compress='topk' with shard.fsdp>1 is not "
                "supported: the per-tensor top-k selection materializes "
                "every gathered dense delta at the sync boundary, exactly "
                "the full-size residency shard.fsdp exists to avoid — use "
                "int8/sign1bit or shard.fsdp=1"
            )
        if (
            rb.method != "mean"
            and cfg.fed.dcn_compress not in ("none", "auto")
            and not codec_caps(cfg.fed.dcn_compress).decodes_per_contribution
        ):
            raise ValueError(
                f"fed.robust.method={rb.method!r} needs per-contribution "
                f"decode, which codec {cfg.fed.dcn_compress!r} cannot "
                "provide (its contributions only exist pre-aggregated: "
                "order statistics judge CLIENTS, and sketch collisions mix "
                "every client's coordinates before any decode exists); use "
                "one of the decodable codecs (int8/sign1bit/topk) or "
                "fed.robust.method='mean'"
            )
        if cfg.fed.dcn_compress == "auto":
            if cfg.train.rounds_per_scan > 1:
                raise ValueError(
                    "fed.dcn_compress='auto' is incompatible with "
                    "train.rounds_per_scan > 1: pinning the per-leaf codec "
                    "map after warmup rebuilds the compiled sync, which "
                    "cannot happen inside a compiled round chain"
                )
            if rb.method != "mean":
                raise ValueError(
                    "fed.dcn_compress='auto' requires "
                    "fed.robust.method='mean': the pinned per-leaf map may "
                    "select a linear sketch, whose contributions only exist "
                    "pre-aggregated (no per-contribution decode for order "
                    "statistics)"
                )
            if cfg.fed.dcn_auto_warmup < 1:
                raise ValueError(
                    f"fed.dcn_auto_warmup={cfg.fed.dcn_auto_warmup} must "
                    "be >= 1: the per-leaf map derives from at least one "
                    "observed round delta"
                )
            if cfg.shard.fsdp > 1:
                raise ValueError(
                    "fed.dcn_compress='auto' with shard.fsdp>1 is not "
                    "supported: the pinned map may select 'topk', which "
                    "materializes every gathered dense delta at the sync "
                    "boundary — pin a concrete fsdp-safe codec "
                    "(int8/sign1bit/countsketch/randproj) instead"
                )
        # ---- aggregation topology (agg.*, fedrec_tpu.agg): validated up
        # front like robust/codec — a mode that would silently never apply
        # is a misconfiguration, not a preference
        if cfg.agg.mode not in ("flat", "hierarchical", "async"):
            raise ValueError(
                f"unknown agg.mode {cfg.agg.mode!r}; expected 'flat', "
                "'hierarchical', or 'async'"
            )
        if cfg.agg.tree_fanout < 2:
            raise ValueError(
                f"agg.tree_fanout={cfg.agg.tree_fanout} must be >= 2"
            )
        if cfg.agg.staleness_cap < 0:
            raise ValueError(
                f"agg.staleness_cap={cfg.agg.staleness_cap} must be >= 0"
            )
        if cfg.agg.quorum < 0 or cfg.agg.quorum > cfg.fed.num_clients:
            raise ValueError(
                f"agg.quorum={cfg.agg.quorum} must be in "
                f"[0, fed.num_clients={cfg.fed.num_clients}] "
                "(0 = all-reporting)"
            )
        if cfg.agg.mode != "flat" and not self.strategy.sync_params_every_round:
            raise ValueError(
                f"agg.mode={cfg.agg.mode!r} requires a strategy that syncs "
                "params every round (param_avg or coordinator); "
                f"fed.strategy={cfg.fed.strategy!r} never aggregates, so "
                "the aggregation topology would silently never apply"
            )
        if cfg.agg.mode == "async":
            if cfg.train.rounds_per_scan > 1:
                raise ValueError(
                    "agg.mode='async' is incompatible with "
                    "train.rounds_per_scan > 1: the buffered quorum commit "
                    "is a host-side round-boundary operation and cannot run "
                    "inside a compiled round chain"
                )
            if cfg.fed.dcn_compress == "auto":
                raise ValueError(
                    "agg.mode='async' is incompatible with "
                    "fed.dcn_compress='auto': buffered entries may outlive "
                    "the warmup window, so the per-leaf map could change "
                    "between a push and its fold — pin a concrete codec "
                    "(every registered codec composes: linear sketches "
                    "fold in sketch space, per-contribution codecs decode "
                    "at push time with per-edge error feedback)"
                )
            # every CONCRETE codec composes with the buffered commit —
            # the capability table says how: is_linear folds in sketch
            # space under the same staleness weights; otherwise
            # decodes_per_contribution decodes at push time (per-edge EF
            # residuals ride the buffer sidecar)
        # the host-side tiered reduce only engages for NON-linear robust
        # methods: a tree of (sum(w*x), sum(w)) partials with one final
        # divide IS the flat weighted mean algebraically, so
        # hierarchical+mean lowers to the unchanged in-graph collective
        # and stays bit-identical by construction (tests/test_agg.py)
        self._agg_async = cfg.agg.mode == "async"
        self._agg_hier_host = (
            cfg.agg.mode == "hierarchical" and rb.method != "mean"
        )
        self._agg_version = 0
        self.agg_buffer = None
        if self._agg_async:
            from fedrec_tpu.agg import AggBuffer, CommitPolicy

            self.agg_buffer = AggBuffer()
            self._agg_policy = CommitPolicy(
                quorum=cfg.agg.quorum, staleness_cap=cfg.agg.staleness_cap
            )
        self.chaos = None
        if cfg.chaos.enabled:
            from fedrec_tpu.fed.chaos import FaultPlan

            self.chaos = FaultPlan(cfg.chaos, cfg.fed.num_clients)
        # quarantine ledger: client -> rounds left excluded; retries count
        # rollback/replay attempts for the CURRENT round (reset on advance)
        self._quarantine: dict[int, int] = {}
        self._round_retries = 0
        self._recovery_state = None
        self._recovery_opt_state = None
        self.server_opt = None
        if cfg.fed.server_opt != "none":
            if not self.strategy.sync_params_every_round:
                # fail fast (ADVICE r2, mirroring validate_compress): the
                # server optimizer steps round deltas at param-sync time, so
                # under local/grad_avg a requested FedAdam would silently
                # never run
                raise ValueError(
                    f"fed.server_opt={cfg.fed.server_opt!r} requires a "
                    "strategy that syncs params every round (param_avg or "
                    f"coordinator); fed.strategy={cfg.fed.strategy!r} never "
                    "would apply it"
                )
            from fedrec_tpu.fed.strategies import ServerOptimizer

            self.server_opt = ServerOptimizer(
                cfg.fed.server_opt, cfg.fed.server_lr, cfg.fed.server_momentum
            )
        self.mesh = fed_mesh(cfg)
        self.mode = {"table": "decoupled", "head": "joint", "finetune": "finetune"}.get(
            cfg.model.text_encoder_mode, "joint"
        )
        if cfg.train.eval_protocol not in ("sampled", "full", "last4"):
            raise ValueError(
                f"unknown train.eval_protocol {cfg.train.eval_protocol!r}; "
                "expected 'sampled', 'full', or 'last4'"
            )

        self.text_encoder = None
        self.news_tokens: jnp.ndarray | None = None
        if self.mode == "finetune":
            # in-loop trunk training reads raw token rows, not cached states
            from fedrec_tpu.models.bert import make_text_encoder

            self.text_encoder = make_text_encoder(cfg.model)
            self.news_tokens = jnp.asarray(data.news_tokens, jnp.int32)
            self.token_states = None
        else:
            self.token_states = jnp.asarray(
                token_states, dtype=jnp.dtype(cfg.model.dtype)
            )

        # ---- sharding subsystem (fedrec_tpu.shard, docs/DESIGN.md §5i):
        # (1) shard.table — the token-state catalog row-sharded over the
        # client mesh axis; steps gather via the owner-bucketed all_to_all
        # exchange, so catalog capacity scales with devices. (2) shard.fsdp
        # — at-rest client state (params + optimizer moments + accumulators)
        # sharded across the fsdp mesh axis per the size-aware policy,
        # derived from the ABSTRACT state via jax.eval_shape so placement
        # is known before any builder compiles. Both default off, and off
        # means the byte-identical pre-shard programs.
        self.table_spec = None
        if cfg.shard.table:
            from fedrec_tpu.shard.table import ShardedNewsTable, TableSpec

            if self.token_states is not None:
                tab = ShardedNewsTable.create(
                    self.token_states, self.mesh, cfg.fed.mesh_axis
                )
                self.token_states = tab.rows
                self.table_spec = tab.spec
            else:
                # finetune mode holds a token table, not cached states; the
                # step builder below fails fast on the mode — this spec
                # exists only to reach that guard
                n = int(self.news_tokens.shape[0])
                s = int(self.mesh.shape[cfg.fed.mesh_axis])
                self.table_spec = TableSpec(
                    cfg.fed.mesh_axis, s, -(-n // s), n
                )
        self._state_shardings = None
        if cfg.shard.fsdp > 1:
            from fedrec_tpu.shard.policy import fsdp_state_shardings

            abstract_state = jax.eval_shape(
                lambda: replicate_state(
                    init_client_state(
                        self.model, cfg, jax.random.PRNGKey(cfg.train.seed),
                        data.num_news, data.title_len,
                    ),
                    cfg.fed.num_clients,
                    jax.random.PRNGKey(cfg.train.seed + 1),
                )
            )
            self._state_shardings = fsdp_state_shardings(
                abstract_state, self.mesh, cfg
            )

        train_ix = index_samples(data.train_samples, data.nid2index, cfg.data.max_his_len)
        if cfg.data.num_shards > 1:
            # coordinator deployment: this process trains only its disjoint
            # shard (reference DistributedSampler-by-rank, main.py:166)
            from fedrec_tpu.data.batcher import process_shard_indices

            train_ix = train_ix.take(
                process_shard_indices(
                    len(train_ix), cfg.data.num_shards,
                    cfg.data.shard_index, cfg.data.seed,
                )
            )
        # true local sample count — what fed.weight_by_samples must weigh
        self.num_local_samples = len(train_ix)
        batcher_cls = TrainBatcher
        if cfg.data.native_loader:
            from fedrec_tpu.data import native_batcher

            if native_batcher.is_available():
                batcher_cls = native_batcher.NativeTrainBatcher
            else:
                print("[trainer] native loader unavailable; using Python batcher")
        self.batcher = batcher_cls(
            train_ix,
            cfg.data.batch_size,
            cfg.data.npratio,
            shuffle=cfg.data.shuffle,
            drop_remainder=cfg.data.drop_remainder,
            seed=cfg.data.seed,
        )
        self.valid_ix: IndexedSamples | None = None
        if data.valid_samples:
            self.valid_ix = index_samples(
                data.valid_samples, data.nid2index, cfg.data.max_his_len
            )
        self.train_ix = train_ix  # the population's shard substrate

        # ---- cross-device cohort engine (fed.population): logical-client
        # population sampled onto the fixed device slots each round.
        # _pop_engine: any population config (bookkeeping + quorum/deadline);
        # _pop_sampling: population STRICTLY above the slot count — real
        # per-round sampling with per-client data shards and sidecar
        # load/unload. population == slots is the degenerate (cross-silo)
        # config: identity cohorts, the legacy data path, bit-identical
        # trajectory (tests/test_population.py).
        from pathlib import Path as _Path

        pcfg = cfg.fed.population
        self._pop_engine = pcfg.num_clients > 0
        self._pop_sampling = pcfg.num_clients > cfg.fed.num_clients
        self.population = None
        self.cohort_sampler = None
        self._current_plan = None
        self._pop_pending: dict[int, tuple] = {}
        self._pop_attempts: dict[int, int] = {}
        self.cohort_history: list[tuple[int, tuple]] = []
        self._slot_occupants = np.arange(cfg.fed.num_clients, dtype=np.int64)
        self._slot_writeback = np.ones(cfg.fed.num_clients, bool)
        self._recovery_occupants = None
        self._pop_template = None
        if self._pop_engine:
            from fedrec_tpu.fed.population import ClientPopulation
            from fedrec_tpu.fed.sampling import (
                CohortSampler,
                validate_sampler_mode,
            )

            validate_sampler_mode(pcfg.sampler)
            if pcfg.num_clients < cfg.fed.num_clients:
                raise ValueError(
                    f"fed.population.num_clients={pcfg.num_clients} is below "
                    f"the device-slot count fed.num_clients="
                    f"{cfg.fed.num_clients}; the population must cover every "
                    "slot (== slots is the degenerate cross-silo config)"
                )
            if pcfg.over_select < 1.0:
                raise ValueError(
                    f"fed.population.over_select={pcfg.over_select} must be "
                    ">= 1.0 (1.0 = no over-selection)"
                )
            if pcfg.client_state not in ("persist", "reset"):
                raise ValueError(
                    f"fed.population.client_state={pcfg.client_state!r}; "
                    "expected 'persist' or 'reset'"
                )
            if pcfg.min_reports > cfg.fed.num_clients:
                raise ValueError(
                    f"fed.population.min_reports={pcfg.min_reports} exceeds "
                    f"the slot count {cfg.fed.num_clients}: the quorum could "
                    "never be met"
                )
            if self._pop_sampling:
                if not self.strategy.sync_params_every_round:
                    raise ValueError(
                        "fed.population sampling (num_clients above the slot "
                        "count) requires a param-syncing strategy (param_avg "
                        "or coordinator): sampled-in clients adopt the "
                        f"global at round end; fed.strategy="
                        f"{cfg.fed.strategy!r} never distributes one"
                    )
                if cfg.fed.participation < 1.0:
                    raise ValueError(
                        "fed.participation < 1.0 composes with the FIXED "
                        "cohort only; under fed.population sampling the "
                        "cohort draw IS the participation policy — leave "
                        "fed.participation at 1.0"
                    )
            spill = pcfg.spill_dir or None
            if not spill:
                snap = snapshot_dir or cfg.train.snapshot_dir
                spill = str(_Path(snap) / "popspill") if snap else None
            self.population = ClientPopulation(
                pcfg.num_clients,
                len(train_ix),
                data_seed=cfg.data.seed,
                batch_size=cfg.data.batch_size if self._pop_sampling else 0,
                resident_cap=pcfg.resident_cap,
                spill_dir=spill,
            )
            self.cohort_sampler = CohortSampler(
                pcfg.num_clients,
                pcfg.sampler,
                pcfg.seed,
                sample_counts=self.population.sample_counts,
            )

        # jitted programs. Batch-buffer donation (train.donate_batch) is
        # safe HERE because every dispatch device_puts fresh arrays; the
        # builders default it off for direct callers that reuse batches.
        self.train_step = build_fed_train_step(
            self.model, cfg, self.strategy, self.mesh, mode=self.mode,
            donate_batch=cfg.train.donate_batch,
            sharded_table=self.table_spec,
            state_shardings=self._state_shardings,
        )
        # epoch-in-jit chains (train.scan_steps > 1): one dispatch per
        # scan_steps batches; the tail of an epoch uses train_step
        self.train_scan = (
            build_fed_train_scan(
                self.model, cfg, self.strategy, self.mesh, mode=self.mode,
                donate_batch=cfg.train.donate_batch,
                sharded_table=self.table_spec,
                state_shardings=self._state_shardings,
            )
            if cfg.train.scan_steps > 1
            else None
        )
        # rounds-in-jit (train.rounds_per_scan > 1): whole rounds — every
        # local epoch plus the round-end sync — in one compiled dispatch.
        # run() chunks rounds so chunk boundaries always land on eval/save
        # cadence rounds; trajectory equality is pinned in tests/test_scan.py.
        self.round_scan = None
        if cfg.train.rounds_per_scan > 1:
            if self.mode == "decoupled":
                raise ValueError(
                    "train.rounds_per_scan > 1 is not supported with "
                    "model.text_encoder_mode='table' (decoupled mode): the "
                    "epoch-end news_update/table refresh is a host-driven "
                    "program between epochs. Use mode 'head' or 'finetune', "
                    "or train.scan_steps for epoch-in-jit."
                )
            if self.server_opt is not None:
                raise ValueError(
                    "train.rounds_per_scan > 1 is incompatible with "
                    "fed.server_opt: FedOpt steps round deltas host-side at "
                    "every round boundary. Disable one of the two."
                )
            self.round_scan = build_fed_round_scan(
                self.model, cfg, self.strategy, self.mesh, mode=self.mode,
                donate_batch=cfg.train.donate_batch,
                sharded_table=self.table_spec,
                state_shardings=self._state_shardings,
            )
        self.news_update = build_news_update_step(
            self.model, cfg, self.mesh, self.strategy,
            state_shardings=self._state_shardings,
        )
        # fed.dcn_compress="auto": until the warmup window pins the real
        # map, the codec-sync body runs with an all-"none" map (dense sync
        # through the codec program SHAPE, so the pin only swaps leaf
        # constants, never the calling convention) — _make_local_sync
        # derives that warmup default from codec="auto" + leaf_codecs=None
        self.param_sync = build_param_sync(
            cfg, self.mesh, self.strategy,
            state_shardings=self._state_shardings,
        )
        # codec syncs take the round-ENTRY params (the delta base) as extra
        # args — captured per round before the first buffer-donating step
        self._sync_takes_entry = compressed_sync_active(cfg, self.strategy)
        self.eval_step = build_eval_step(self.model, cfg)
        # full-pool eval sharded over the mesh when there is one: same
        # per-impression math, 1/mesh.size of the eval wall time (the
        # full-pool pass is the eval bottleneck at MIND scale)
        self.full_eval_step = (
            build_full_eval_step_sharded(self.model, cfg, self.mesh)
            if self.mesh.size > 1
            else build_full_eval_step(self.model, cfg)
        )
        # quality-instrumented twin (obs.quality.enabled): same scoring
        # math plus fixed-shape score/calibration partial sums. A separate
        # compiled program so the DISABLED path keeps the exact pre-quality
        # program (byte-identical trajectories, tests/test_quality.py).
        self.full_eval_step_q = None
        if cfg.obs.quality.enabled:
            qspec = (
                int(cfg.obs.quality.score_bins),
                float(cfg.obs.quality.score_range),
                int(cfg.obs.quality.ece_bins),
            )
            self.full_eval_step_q = (
                build_full_eval_step_sharded(
                    self.model, cfg, self.mesh, quality=qspec
                )
                if self.mesh.size > 1
                else build_full_eval_step(self.model, cfg, quality=qspec)
            )

        # state (pre-sharded so the first step doesn't retrace)
        state0 = init_client_state(
            self.model,
            cfg,
            jax.random.PRNGKey(cfg.train.seed),
            data.num_news,
            data.title_len,
        )
        stacked = replicate_state(
            state0, cfg.fed.num_clients, jax.random.PRNGKey(cfg.train.seed + 1)
        )
        self.state = self._place_state(stacked)
        if self._pop_engine:
            # the pristine sidecar template a never-before-selected (or
            # quarantine-healed) logical client starts from: slot 0's
            # freshly-initialized non-param leaves, captured BEFORE any
            # restore/training touches the state (rng is re-derived per
            # client in _template_sidecar)
            from fedrec_tpu.fed.population import SIDECAR_FIELDS

            host0 = jax.tree_util.tree_map(np.asarray, self.state)
            self._pop_template = {
                f: jax.tree_util.tree_map(
                    lambda x: np.array(x[0]), getattr(host0, f)
                )
                for f in SIDECAR_FIELDS
            }

        self.start_round = 0
        self.snapshots: SnapshotManager | None = None
        if snapshot_dir or cfg.train.snapshot_dir:
            self.snapshots = SnapshotManager(snapshot_dir or cfg.train.snapshot_dir)
            if cfg.train.resume and self.snapshots.latest_round() is not None:
                # validate BEFORE the current cfg is persisted below — the
                # incumbent config.json is the record of what the snapshot
                # was trained with, and must be read before being replaced
                self._check_snapshot_config(cfg)
                try:
                    self.state = self.snapshots.restore(self.state)
                except Exception as e:
                    # the raw orbax tree-structure error names pytree paths,
                    # not the config knob that caused them (ADVICE r3) —
                    # name the likely culprits
                    raise RuntimeError(
                        f"snapshot restore from {self.snapshots.directory} "
                        f"failed ({type(e).__name__}; chained below). If the "
                        "error names pytree paths/shapes, the usual cause is "
                        "a model-config change since the snapshot was "
                        "written (model.user_tower picks a different "
                        "parameter family; news_dim/num_heads/trunk_* change "
                        "shapes) — compare the snapshot's config.json with "
                        "this run's --set flags. Otherwise the checkpoint "
                        "itself may be incomplete or corrupt; point "
                        "train.snapshot_dir at a fresh directory to start "
                        "over."
                    ) from e
                # re-commit to the at-rest layout: a snapshot gathered to
                # host on save (shard.fsdp) must land back sharded
                self.state = self._place_state(self.state)
                # last_restored_round, not latest_round(): a corrupt newest
                # snapshot falls back to the previous retained one, and the
                # resumed counter must match the state that actually loaded
                restored = self.snapshots.last_restored_round
                if restored is None:
                    restored = int(self.snapshots.latest_round())
                self.start_round = int(restored) + 1
                print(f"[trainer] resumed from snapshot at round {self.start_round - 1}")
                if self.server_opt is not None:
                    # FedOpt buffers live host-side; restore the sidecar so
                    # a resumed run is bit-identical to an uninterrupted one
                    sidecar = self.snapshots.directory / "server_opt_state.msgpack"
                    if not sidecar.exists():
                        print(
                            "[trainer] WARNING: resuming a fed.server_opt run "
                            f"without {sidecar.name} — momentum/adaptivity "
                            "buffers restart from zero, so the resumed "
                            "trajectory will differ from an uninterrupted one"
                        )
                    if sidecar.exists():
                        loaded_round = self.server_opt.load_state(
                            sidecar.read_bytes(), self._client0_params()
                        )
                        if loaded_round != self.start_round - 1:
                            print(
                                f"[trainer] server_opt sidecar from round "
                                f"{loaded_round} != snapshot round "
                                f"{self.start_round - 1}; momentum may be "
                                "skewed for the first resumed round"
                            )
                if self._pop_engine:
                    # the cohort engine's schedule-defining state: sampler
                    # fairness counters + participation ledger + slot
                    # occupancy — restoring it makes rounds r+1.. sample
                    # IDENTICAL cohorts to an uninterrupted run
                    from fedrec_tpu.train.checkpoint import (
                        POPULATION_SIDECAR,
                        load_population_state,
                    )

                    pop_sidecar = self.snapshots.directory / POPULATION_SIDECAR
                    if pop_sidecar.exists():
                        pst = load_population_state(pop_sidecar.read_bytes())
                        self.cohort_sampler.load_state_dict(pst["sampler"])
                        self.population.ledger.load_state_dict(pst["ledger"])
                        self._slot_occupants = np.asarray(
                            pst["slot_occupants"], np.int64
                        )
                        self._slot_writeback = np.asarray(
                            pst["slot_writeback"], bool
                        )
                        if pst["round"] != self.start_round - 1:
                            print(
                                f"[trainer] population sidecar from round "
                                f"{pst['round']} != snapshot round "
                                f"{self.start_round - 1}; the cohort "
                                "schedule may be skewed for the first "
                                "resumed rounds"
                            )
                    elif self._pop_sampling:
                        print(
                            "[trainer] WARNING: resuming a fed.population "
                            f"run without {POPULATION_SIDECAR} — the "
                            "sampler/ledger restart fresh, so the resumed "
                            "cohort schedule will differ from an "
                            "uninterrupted run"
                        )
                if self._agg_async:
                    # pending late contributions survive the restart; a
                    # missing/foreign/mismatched sidecar starts empty
                    # (late updates are droppable by design — the commit
                    # version still resumes so staleness stays coherent)
                    from fedrec_tpu.agg.buffer import (
                        AGG_BUFFER_SIDECAR,
                        AggBuffer,
                    )

                    agg_sidecar = self.snapshots.directory / AGG_BUFFER_SIDECAR
                    if agg_sidecar.exists():
                        try:
                            buf, tag, ver = AggBuffer.load_state(
                                agg_sidecar.read_bytes()
                            )
                        except ValueError as e:
                            print(
                                "[trainer] ignoring unreadable agg-buffer "
                                f"sidecar: {e}"
                            )
                        else:
                            self._agg_version = ver
                            if tag == self.start_round - 1:
                                self.agg_buffer = buf
                                if len(buf):
                                    print(
                                        f"[trainer] restored {len(buf)} "
                                        "pending async contribution(s) at "
                                        f"commit version {ver}"
                                    )
                            else:
                                print(
                                    "[trainer] agg-buffer sidecar from round "
                                    f"{tag} != snapshot round "
                                    f"{self.start_round - 1}; starting with "
                                    "an empty buffer (pending late updates "
                                    "dropped)"
                                )
                    else:
                        print(
                            "[trainer] resuming an agg.mode=async run "
                            f"without {AGG_BUFFER_SIDECAR} — pending late "
                            "contributions (if any) are lost and the commit "
                            "version restarts"
                        )
            try:
                # resolved config rides with the snapshots so serving can
                # rebuild the exact model without the operator re-typing
                # every --set (fedrec-recommend reads it back; ADVICE r2).
                # Atomic: a concurrently-serving fedrec-recommend must never
                # read a torn file. Written AFTER the resume path above so
                # the incumbent config.json — the record of what an existing
                # snapshot was trained with — is validated before replacement
                from fedrec_tpu.train.checkpoint import atomic_write_bytes

                atomic_write_bytes(
                    self.snapshots.directory / "config.json",
                    cfg.to_json().encode(),
                )
            except OSError as e:
                print(f"[trainer] could not persist config.json: {e}")

        self.best_snapshots: SnapshotManager | None = None
        self._best_auc: float | None = None
        if self.snapshots is not None and cfg.train.keep_best:
            import json as _json

            best_dir = self.snapshots.directory / "best"
            self.best_snapshots = SnapshotManager(best_dir, max_to_keep=1)
            marker = best_dir / "best.json"
            if marker.exists():
                # resumed run: the incumbent best must never be replaced
                # by a worse later round
                try:
                    m = _json.loads(marker.read_text())
                    best_round, best_auc = int(m["round"]), float(m["auc"])
                except (OSError, ValueError, KeyError, TypeError):
                    best_round = best_auc = None
                stored = self.best_snapshots.latest_round()
                if best_round is not None and stored == best_round:
                    self._best_auc = best_auc
                elif stored is not None or best_round is not None:
                    # torn state (crash between the snapshot save and the
                    # marker write): the stored snapshot's AUC is unknown,
                    # so let the next improvement rewrite both coherently
                    print(
                        "[trainer] best-snapshot marker/round mismatch "
                        f"(marker {best_round}, stored {stored}); best-AUC "
                        "tracking restarts this run"
                    )

        # ---- observability (fedrec_tpu.obs): registry instruments, host
        # spans, and the obs.dir artifact trio (metrics.jsonl / trace.json /
        # prometheus.txt). The registry/tracer always record in memory;
        # files only when obs.dir is set.
        from pathlib import Path

        self._obs_dir: Path | None = None
        jsonl_path = None
        if cfg.obs.dir:
            self._obs_dir = Path(cfg.obs.dir)
            self._obs_dir.mkdir(parents=True, exist_ok=True)
            jsonl_path = str(self._obs_dir / "metrics.jsonl")
        self.registry = get_registry()
        self.tracer = get_tracer()
        self.tracer.capacity = cfg.obs.trace_capacity
        # fleet correlation keys (fedrec_tpu.obs.fleet): every span,
        # registry snapshot and MetricLogger record carries worker/rank
        # labels so multi-process artifacts are joinable — the
        # coordinator CLI stamps the stable elastic identity first and
        # this is then a no-op
        from fedrec_tpu.obs.fleet import ensure_fleet_identity

        ensure_fleet_identity(
            worker=str(jax.process_index()), rank=jax.process_index()
        )
        # wire-layer observability (obs.wire): envelope on/off + offset
        # window for every TCP exchange this process makes
        from fedrec_tpu.obs.wire import configure_wire

        configure_wire(
            enabled=cfg.obs.wire.enabled, window=cfg.obs.wire.window
        )
        self._m_rounds = self.registry.counter(
            "train.rounds_total", "federated rounds completed"
        )
        self._m_steps = self.registry.counter(
            "train.steps_total", "train-step batches dispatched"
        )
        self._m_round_loss = self.registry.gauge(
            "train.round_loss", "mean train loss of the last round"
        )
        self._m_round_secs = self.registry.histogram(
            "train.round_seconds", "wall seconds per federated round",
            buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                     100.0, 250.0, 500.0, 1000.0),
        )
        self._m_overflow = self.registry.counter(
            "train.cap_overflow_total",
            "unique-news cap overflow count (client-summed over steps; "
            "nonzero aborts the round)",
        )
        # per-step fusion gauge: how many fused Pallas hot-path kernels the
        # compiled step launches (model.fuse_hot_path; 2 = gather+encode
        # AND attention+pool+score, 1 = scoring kernel only — cnn text
        # head keeps the dense gather — 0 = dense step). A reader of a
        # prometheus scrape can tell WHICH program produced the step
        # timings next to it (docs/OBSERVABILITY.md).
        fuse_on = getattr(cfg.model, "fuse_hot_path", False)
        fused_n = 0
        if fuse_on:
            # the gather+encode kernel runs only where the frozen-table
            # gather exists: joint mode ("head") with the additive head
            fused_n = 1 + int(
                cfg.model.text_encoder_mode == "head"
                and getattr(cfg.model, "text_head_arch", "additive")
                == "additive"
            )
        self._g_fused = self.registry.gauge(
            "model.fused_hot_path_kernels",
            "fused Pallas kernels per train step (0 = dense path)",
        )
        self._g_fused.set(fused_n)
        # ---- sharding instruments (fedrec_tpu.shard; fedrec-obs report's
        # Sharding section): always registered, zero-valued when the
        # subsystem is off so the section simply doesn't render
        self._g_fsdp_shards = self.registry.gauge(
            "shard.fsdp_shards",
            "fsdp mesh-axis size the at-rest state shards over (1 = "
            "replicated layout)",
        )
        self._g_fsdp_shards.set(float(max(cfg.shard.fsdp, 1)))
        self._g_state_bytes = self.registry.gauge(
            "shard.state_bytes_per_device",
            "at-rest client-state bytes ONE device holds under the active "
            "sharding policy (params + optimizer moments + accumulators)",
        )
        self._g_table_rows = self.registry.gauge(
            "shard.table_rows_per_device",
            "news-catalog rows resident per device (= catalog rows under "
            "the replicated layout; padded_rows / shards under shard.table)",
        )
        self._g_table_occ = self.registry.gauge(
            "shard.table_occupancy",
            "real catalog rows / padded sharded rows (1.0 = no padding "
            "waste; only below 1 when devices don't divide the catalog)",
        )
        self._g_remote_rows = self.registry.gauge(
            "shard.remote_gather_rows",
            "worst-case rows crossing the interconnect per sharded-gather "
            "step across the mesh (shards x unique slots; 0 = table "
            "replicated, no remote gather)",
        )
        self._m_a2a_bytes = self.registry.counter(
            "shard.a2a_bytes_total",
            "modeled owner-bucketed all_to_all bytes of the sharded-table "
            "gather (id buckets out + answer rows back, whole mesh), "
            "advanced per dispatched step",
        )
        self._a2a_bytes_per_step = 0
        if self.table_spec is not None:
            from fedrec_tpu.shard.table import a2a_bytes_per_gather
            from fedrec_tpu.train.step import resolve_unique_cap

            spec = self.table_spec
            b = cfg.data.batch_size
            worst = b * (1 + cfg.data.npratio + cfg.data.max_his_len)
            uniq = min(worst, spec.num_rows)
            cap = resolve_unique_cap(cfg, b)
            if cap:
                uniq = min(uniq, cap)
            self._a2a_bytes_per_step = a2a_bytes_per_gather(
                uniq, tuple(self.token_states.shape[1:]),
                self.token_states.dtype, spec,
            )
            self._g_table_rows.set(float(spec.rows_per_shard))
            self._g_table_occ.set(spec.num_rows / spec.padded_rows)
            self._g_remote_rows.set(float(spec.num_shards * uniq))
        elif self.token_states is not None:
            self._g_table_rows.set(float(self.token_states.shape[0]))
            self._g_table_occ.set(1.0)
        if self._state_shardings is not None:
            from fedrec_tpu.shard.policy import shard_bytes_per_device

            self._g_state_bytes.set(
                float(shard_bytes_per_device(self.state, self._state_shardings))
            )
        else:
            # replicated layout: every leaf is still dim-0 split over the
            # clients axis (client_sharding), so ONE device's share is the
            # total over that axis size — the same per-device accounting
            # the fsdp branch reports, keeping the gauge comparable when
            # an operator flips shard.fsdp on
            total = sum(
                float(np.prod(x.shape)) * x.dtype.itemsize
                for x in jax.tree_util.tree_leaves(self.state)
            )
            self._g_state_bytes.set(
                total / int(self.mesh.shape[cfg.fed.mesh_axis])
            )
        # ---- robustness instruments (fedrec-obs report's Robustness
        # section reads these): always registered — zero-valued when the
        # features are off, so the section simply doesn't render
        self._m_robust_rounds = self.registry.counter(
            "fed.robust_rounds_total",
            "round-end aggregations performed, labeled by robust method",
            labels=("method",),
        )
        self._m_quarantines = self.registry.counter(
            "fed.quarantines_total",
            "clients quarantined by the recovery path (weight 0 for "
            "fed.robust.quarantine_rounds rounds)",
        )
        self._m_rollbacks = self.registry.counter(
            "fed.rollbacks_total",
            "round rollback/replay cycles performed by the recovery path",
        )
        self._g_quarantined = self.registry.gauge(
            "fed.quarantine_active", "clients currently quarantined"
        )
        self._m_chaos = self.registry.counter(
            "chaos.faults_total",
            "faults injected by the chaos FaultPlan, labeled by kind "
            "(drop/straggle/nan/scale/flip); rollback replays re-count",
            labels=("kind",),
        )
        # ---- aggregation-topology instruments (fedrec_tpu.agg; the fleet
        # report's Aggregation section): always registered, zero-valued
        # under agg.mode='flat' so the section simply doesn't render
        self._m_agg_commits = self.registry.counter(
            "agg.commits_total",
            "async quorum commits performed (global version bumps)",
        )
        self._m_agg_late = self.registry.counter(
            "agg.late_folds_total",
            "buffered contributions folded with staleness > 0",
        )
        self._m_agg_stale = self.registry.counter(
            "agg.stale_drops_total",
            "buffered contributions dropped past agg.staleness_cap",
        )
        self._g_agg_staleness = self.registry.gauge(
            "agg.staleness",
            "mean staleness (commits behind) of the last commit's folds",
        )
        self._g_agg_version = self.registry.gauge(
            "agg.adopted_version",
            "global model version this worker last adopted (async commit "
            "counter; 0 until a first commit) — the fleet stalled-commit "
            "rule watches it against train.rounds_total",
        )
        # a restored agg-buffer sidecar already adopted a version above
        self._g_agg_version.set(float(self._agg_version))
        self._g_agg_quorum_wait = self.registry.gauge(
            "agg.quorum_wait_ms",
            "first-report -> quorum-close time of the last async commit "
            "(what the commit waited, vs the barrier's slowest reporter)",
        )
        self._g_agg_gate_saved = self.registry.gauge(
            "agg.gate_saved_ms",
            "slowest-report latency minus the quorum-close latency of the "
            "last async commit — the barrier wait the quorum removed",
        )
        self._g_agg_pending = self.registry.gauge(
            "agg.buffer_pending",
            "contributions in the async buffer awaiting a later commit",
        )
        self._g_agg_tier_ms = self.registry.gauge(
            "agg.tier_reduce_ms",
            "per-level-max tier-reduce time of the last hierarchical "
            "round, summed over levels (the tree's parallel critical path)",
        )
        # ---- cohort-engine instruments (fedrec-obs report's Participation
        # section): zero-valued when fed.population is off
        self._g_pop_size = self.registry.gauge(
            "fed.population_clients",
            "configured logical-client population (0 = cross-silo)",
        )
        self._g_pop_size.set(float(cfg.fed.population.num_clients))
        self._g_cohort_sampled = self.registry.gauge(
            "fed.cohort_sampled",
            "clients drawn for the current round, over-selection included",
        )
        self._g_cohort_reporting = self.registry.gauge(
            "fed.cohort_reporting",
            "clients whose round weight survived dropout and the deadline",
        )
        self._m_pop_drops = self.registry.counter(
            "fed.pop_dropouts_total",
            "sampled clients that dropped out of their round",
        )
        self._m_deadline_cuts = self.registry.counter(
            "fed.deadline_cuts_total",
            "clients cut at the round deadline (weight 0, work discarded)",
        )
        self._m_quorum_replays = self.registry.counter(
            "fed.quorum_replays_total",
            "rounds discarded below min_reports and replayed with a "
            "fresh cohort draw",
        )
        self._m_cohort_swaps = self.registry.counter(
            "fed.cohort_slot_swaps_total",
            "device-slot sidecar load/unload operations (cohort churn)",
        )
        self._g_pop_coverage = self.registry.gauge(
            "fed.population_coverage",
            "fraction of the population selected at least once",
        )
        # ---- communication instruments (fed.dcn_compress,
        # fedrec_tpu.comms): byte counters labeled by path — "cohort" is
        # the in-graph simulated client uplink (bytes measured from a real
        # wire-codec encode of the param trees, not dtype arithmetic),
        # "dcn" the coordinator's actual cross-host gather (counted in
        # parallel.multihost). Registered always; zero-valued (and the
        # report section silent) when no codec is active.
        self._m_bytes_up = self.registry.counter(
            "fed.dcn_bytes_up_total",
            "client->server round-update bytes shipped, by path "
            "(cohort = simulated in-graph uplink, dcn = real cross-host "
            "gather)",
            labels=("path",),
        )
        self._m_bytes_down = self.registry.counter(
            "fed.dcn_bytes_down_total",
            "server->client fan-out bytes (full precision in every mode), "
            "by path",
            labels=("path",),
        )
        self._g_comp_ratio = self.registry.gauge(
            "fed.dcn_compression_ratio",
            "dense/encoded byte ratio of one client's round-update payload "
            "under the active codec",
        )
        self._codec_bytes_per_client: int | None = None
        self._dense_bytes_per_client: int | None = None
        # fed.dcn_compress="auto": the per-leaf codec map, pinned once
        # after the warmup window (None while warming up — the sync body
        # runs with an all-"none" map until the pin, then recompiles)
        self._auto_leaf_codecs: list | None = None
        if cfg.fed.dcn_compress not in ("none", "auto"):
            self._price_codec()
        # spent-epsilon trajectory: one gauge per round, next to loss/AUC.
        # Only the rigorous mechanism gets a trajectory — ldp_news carries
        # no (epsilon, delta) statement to spend against (docs/DP.md).
        self._eps_schedule = None
        if (
            cfg.privacy.enabled
            and cfg.privacy.mechanism == "dpsgd"
            and cfg.privacy.sigma > 0
        ):
            from fedrec_tpu.privacy import round_epsilon_schedule

            # num_local_samples is this process's shard — the same n the
            # CLI drivers calibrated sigma against (cli/run.py passes the
            # full corpus, cli/coordinator.py its local shard)
            self._eps_schedule = round_epsilon_schedule(cfg, self.num_local_samples)
            self._m_eps = self.registry.gauge(
                "privacy.epsilon_spent",
                "(epsilon, delta)-DP spent after the completed rounds",
            )

        self.logger = MetricLogger(
            use_wandb=cfg.train.wandb,
            project=cfg.train.wandb_project,
            run_name=cfg.train.run_name,
            jsonl_path=jsonl_path,
            registry=self.registry,
            jsonl_max_mb=cfg.obs.jsonl_max_mb,
        )
        # round-cadence fleet telemetry (obs.fleet.collector): registry
        # snapshots + completed spans pushed to the fleet collector; push
        # failures are counted, never raised, and the obs.dir artifacts
        # stay the lossless offline source
        self.fleet_pusher = None
        if cfg.obs.fleet.collector:
            from fedrec_tpu.obs.fleet import FleetPusher

            self.fleet_pusher = FleetPusher(
                cfg.obs.fleet.collector,
                registry=self.registry,
                tracer=self.tracer,
                timeout_s=cfg.obs.fleet.push_timeout_s,
                push_every=cfg.obs.fleet.push_every,
            )

        # ---- training-health flight recorder (fedrec_tpu.obs.health) +
        # device watchdogs (fedrec_tpu.obs.device). The monitor digests the
        # in-graph sentry's per-client health vectors at round cadence; the
        # recorder keeps the last-N batches + the round-entry state so a
        # non-finite trigger dumps a replayable forensic bundle.
        hcfg = cfg.obs.health
        self.health = HealthMonitor(hcfg, registry=self.registry)
        # ---- model-quality observability (fedrec_tpu.obs.quality): the
        # sliced-eval publisher + per-client quality digest. Slice
        # definitions are built lazily at the first eval (valid_ix is
        # fixed for the run) and reused by every eval and the banked
        # quality gate.
        self.quality = None
        self._slice_defs = None
        if cfg.obs.quality.enabled:
            from fedrec_tpu.obs.quality import QualityMonitor

            self.quality = QualityMonitor(cfg.obs.quality, registry=self.registry)
        self.flightrec: FlightRecorder | None = None
        if self._obs_dir is not None and hcfg.flight_recorder:
            self.flightrec = FlightRecorder(
                ring_size=hcfg.ring_size,
                dump_policy=hcfg.dump_policy,
                dump_table_max_mb=hcfg.dump_table_max_mb,
            )
        # ---- performance observability (fedrec_tpu.obs.perf): live MFU /
        # samples-per-sec / roofline-verdict gauges off the round's span
        # timings, compile-cost telemetry via the watchdog hook, HBM
        # attribution at round cadence, triggered capture windows.
        # Default OFF — nothing below is constructed and the watchdog
        # keeps its exact pre-perf behavior (cost_cb=None).
        self.perf = None
        self._perf_last_batch = None
        # retain the last sharded batch ONLY when the HBM-attribution
        # pass will actually read it — a pinned (steps, clients, B, ...)
        # stack with no consumer would hold a chunk of device memory
        # across rounds for nothing
        self._perf_keep_batch = False
        if cfg.obs.perf.enabled:
            from fedrec_tpu.obs.perf import PerfMonitor

            self.perf = PerfMonitor(
                cfg.obs.perf, cfg, data.num_news,
                registry=self.registry, tracer=self.tracer,
                obs_dir=self._obs_dir,
            )
            self._perf_keep_batch = cfg.obs.perf.hbm_components
        # ---- continuous watch layer (fedrec_tpu.obs.watch): declarative
        # SLO burn rates + the streaming anomaly detector + the unified
        # alert lifecycle, evaluated once per round in _after_round with
        # the round's MetricLogger record. Default OFF — nothing below is
        # constructed, no alert.* instrument registers, and the legacy
        # trigger paths keep their exact pre-watch behavior (the
        # byte-identity pin in tests/test_watch.py).
        self.watch = None
        if cfg.obs.slo.enabled:
            from fedrec_tpu.obs.watch import Watch

            self.watch = Watch(
                cfg.obs.slo, cfg.obs.watch,
                registry=self.registry, tracer=self.tracer,
                jsonl_path=jsonl_path,
                jsonl_max_mb=cfg.obs.jsonl_max_mb,
            )
            if self.perf is not None:
                self.watch.bind_perf(self.perf)
            if self.fleet_pusher is not None:
                # alert transition records ride the existing telemetry
                # envelope so the collector sees every worker's alerts
                self.fleet_pusher.engine = self.watch.engine
        self.watchdog = CompileWatchdog(
            registry=self.registry,
            storm_threshold=hcfg.storm_threshold,
            storm_window_s=hcfg.storm_window_s,
            cost_cb=(
                self.perf.cost
                if self.perf is not None and cfg.obs.perf.compile_cost
                else None
            ),
        )
        self.watchdog.install()
        # every jitted program goes through the watchdog so each XLA
        # compile carries (callable, arg shapes) provenance — the steady-
        # shape paths must show exactly one compile per signature
        self.train_step = self.watchdog.watch(self.train_step, "train_step")
        if self.train_scan is not None:
            self.train_scan = self.watchdog.watch(self.train_scan, "train_scan")
        if self.round_scan is not None:
            self.round_scan = self.watchdog.watch(self.round_scan, "round_scan")
        self.eval_step = self.watchdog.watch(self.eval_step, "eval_step")
        self.full_eval_step = self.watchdog.watch(
            self.full_eval_step, "full_eval_step"
        )
        if self.full_eval_step_q is not None:
            self.full_eval_step_q = self.watchdog.watch(
                self.full_eval_step_q, "full_eval_step_q"
            )
        self.param_sync = self.watchdog.watch(self.param_sync, "param_sync")

        self._table: jnp.ndarray | None = None  # decoupled-mode news-vec table
        self._adopt_fn = None  # lazy compiled set_global_params program
        self.last_per_client_metrics: list[dict[str, float]] | None = None

    # ------------------------------------------------------------------
    def _check_snapshot_config(self, cfg) -> None:
        """Fail with a guided message when resuming under a model config
        whose parameter tree cannot match the snapshot's (ADVICE r3: the
        raw orbax tree-structure error names pytree paths, not the knob).
        Reads the config.json the snapshot-writing run persisted; absent or
        unreadable → silently skip (the restore itself still validates
        structure, and older snapshot dirs predate config.json).
        """
        import json as _json

        cfg_path = self.snapshots.directory / "config.json"
        try:
            saved = _json.loads(cfg_path.read_text()).get("model", {})
        except (OSError, ValueError):
            return
        # the knobs that change the parameter TREE (family or shapes) —
        # a mismatch is certain restore failure, so fail with guidance.
        # trunk_* shape the tree only when the snapshot actually holds trunk
        # params (text_encoder_mode="finetune", train/state.py); bert_hidden
        # only when a text head exists (mode != "table", where news vecs are
        # a precomputed table and no bert-width param is in the tree)
        tree_knobs = [
            "user_tower", "news_dim", "num_heads", "head_dim", "query_dim",
            "text_encoder_mode",
        ]
        saved_mode = saved.get("text_encoder_mode")
        if saved_mode != "table":
            # the text-head family + its conv width shape the text_head
            # subtree exactly like user_tower shapes the user_encoder one
            tree_knobs += ["bert_hidden", "text_head_arch", "cnn_kernel"]
        if saved_mode == "finetune":
            tree_knobs += [
                "trunk_layers", "trunk_heads", "trunk_ffn", "trunk_vocab",
            ]
        diffs = [
            (k, saved[k], getattr(cfg.model, k))
            for k in tree_knobs
            if k in saved and saved[k] != getattr(cfg.model, k)
        ]
        if diffs:
            detail = "; ".join(
                f"model.{k}: snapshot={s!r} vs this run={c!r}"
                for k, s, c in diffs
            )
            raise ValueError(
                f"cannot resume from {self.snapshots.directory}: the "
                f"snapshot was trained under a different model config "
                f"({detail}). Re-run with the snapshot's settings (its "
                "config.json has the full record) or point "
                "train.snapshot_dir at a fresh directory."
            )

    def _place_state(self, state: Any) -> Any:
        """Commit a full state pytree to its at-rest layout: the per-leaf
        FSDP shardings when ``shard.fsdp > 1`` (``shard.policy``), else the
        classic leading-dim client sharding — THE one placement rule, used
        by init, restore and adopt so a resumed run can never come back in
        a layout the compiled programs would silently re-shard every step."""
        if self._state_shardings is not None:
            return jax.tree_util.tree_map(
                lambda x, s: jax.device_put(jnp.asarray(x), s),
                state, self._state_shardings,
            )
        sharding = client_sharding(self.mesh, self.cfg.fed.mesh_axis)
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(jnp.asarray(x), sharding), state
        )

    def _client0_params(self) -> tuple[Any, Any]:
        u = jax.tree_util.tree_map(lambda x: x[0], self.state.user_params)
        n = jax.tree_util.tree_map(lambda x: x[0], self.state.news_params)
        return u, n

    def _price_codec(self) -> None:
        """Price the per-client uplink from ONE real wire encode (payload
        sizes are static per codec × shapes) and publish the overall +
        per-leaf compression-ratio cells. Re-run when the ``auto``
        per-leaf map pins (the payload sizes change with the map)."""
        from fedrec_tpu.comms import (
            encode_tree,
            leaf_names,
            payload_nbytes,
            tree_dense_nbytes,
        )

        cfg = self.cfg
        host_params = jax.tree_util.tree_map(
            np.asarray, self._client0_params()
        )
        enc = encode_tree(
            host_params,
            cfg.fed.dcn_compress,
            cfg.fed.dcn_topk_ratio,
            sketch_width=cfg.fed.dcn_sketch_width,
            sketch_seed=cfg.fed.dcn_sketch_seed,
            leaf_codecs=self._auto_leaf_codecs,
        )
        self._codec_bytes_per_client = enc.nbytes()
        self._dense_bytes_per_client = tree_dense_nbytes(host_params)
        self._g_comp_ratio.set(
            self._dense_bytes_per_client
            / max(self._codec_bytes_per_client, 1)
        )
        ratio_leaf = self.registry.gauge(
            "fed.dcn_compression_ratio_leaf",
            "dense/encoded byte ratio of one round-update tensor, by leaf",
            labels=("leaf",),
        )
        for name, payload, shape in zip(
            leaf_names(host_params), enc.payloads, enc.shapes
        ):
            dense_b = 4 * int(np.prod(shape)) if shape else 4
            ratio_leaf.set(
                dense_b / max(payload_nbytes(payload), 1), leaf=name
            )

    # tensors at or below this size stay uncompressed under "auto":
    # scalars/norm vectors, where codec overhead exceeds the dense bytes
    _AUTO_DENSE_FLOOR = 64

    def _pin_auto_codec_map(self, round_idx: int, sync_entry: Any) -> None:
        """``fed.dcn_compress='auto'``: derive the per-leaf codec map from
        the warmup window's GLOBAL round delta (round-entry global vs the
        post-sync global — identical on every process, so the pin needs no
        broadcast and replays deterministically from the seed), rebuild
        the compiled sync around it, re-price the uplink, and record the
        map in provenance (``codec_map.json`` beside the obs artifacts).

        Selection per leaf: tensors ≤ the dense floor stay "none"
        (codec overhead exceeds the payload); otherwise the measured
        reconstruction error of topk (at ``fed.dcn_topk_ratio``) and
        countsketch (at ``fed.dcn_sketch_width``) on the warmup delta
        decides — sparse, concentrated deltas reconstruct better under
        topk; dense towers under the sketch. Held fixed thereafter."""
        from fedrec_tpu.comms import decode_leaf, encode_leaf, leaf_names

        cfg = self.cfg
        entry0 = jax.tree_util.tree_map(
            lambda x: np.asarray(x[0], np.float32), sync_entry
        )
        post0 = jax.tree_util.tree_map(
            lambda x: np.asarray(x, np.float32), self._client0_params()
        )
        delta = jax.tree_util.tree_map(lambda p, e: p - e, post0, entry0)
        flat, _ = jax.tree_util.tree_flatten(delta)
        names = leaf_names(delta)
        chosen: list[str] = []
        detail: list[dict] = []
        for i, (name, d) in enumerate(zip(names, flat)):
            if d.size <= self._AUTO_DENSE_FLOOR:
                chosen.append("none")
                detail.append({"leaf": name, "codec": "none", "n": int(d.size)})
                continue
            errs = {}
            for cand in ("topk", "countsketch"):
                rec = decode_leaf(
                    encode_leaf(
                        d, cand, cfg.fed.dcn_topk_ratio,
                        sketch_width=cfg.fed.dcn_sketch_width,
                        sketch_seed=cfg.fed.dcn_sketch_seed,
                        leaf_id=i,
                    ),
                    cand, d.shape,
                    sketch_seed=cfg.fed.dcn_sketch_seed, leaf_id=i,
                )
                errs[cand] = float(np.sqrt(np.mean((rec - d) ** 2)))
            pick = "topk" if errs["topk"] <= errs["countsketch"] else "countsketch"
            chosen.append(pick)
            detail.append({
                "leaf": name, "codec": pick, "n": int(d.size),
                "rmse_topk": errs["topk"],
                "rmse_countsketch": errs["countsketch"],
            })
        self._auto_leaf_codecs = chosen
        # rebuild the compiled sync around the pinned map (same calling
        # convention — the warmup body already ran the 4-arg codec shape)
        from fedrec_tpu.train.step import build_param_sync

        self.param_sync = self.watchdog.watch(
            build_param_sync(
                cfg, self.mesh, self.strategy,
                state_shardings=self._state_shardings,
                leaf_codecs=chosen,
            ),
            "param_sync",
        )
        self._price_codec()
        summary = {
            "pinned_at_round": int(round_idx),
            "warmup_rounds": int(cfg.fed.dcn_auto_warmup),
            "sketch_width": float(cfg.fed.dcn_sketch_width),
            "sketch_seed": int(cfg.fed.dcn_sketch_seed),
            "topk_ratio": float(cfg.fed.dcn_topk_ratio),
            "map": {n: c for n, c in zip(names, chosen)},
            "detail": detail,
        }
        import json

        if self._obs_dir is not None:
            with open(self._obs_dir / "codec_map.json", "w") as f:
                json.dump(summary, f, indent=2, sort_keys=True)
        if self.logger is not None:
            # a JSON string survives the logger's stringification — the
            # report parses it back into the auto_codec_map row
            self.logger.log(round_idx, {
                "dcn_auto_map_pinned": json.dumps(
                    {n: c for n, c in zip(names, chosen)}, sort_keys=True
                ),
            })
        counts: dict[str, int] = {}
        for c in chosen:
            counts[c] = counts.get(c, 0) + 1
        print(
            f"[trainer] fed.dcn_compress=auto pinned per-leaf codec map "
            f"after round {round_idx}: "
            + ", ".join(f"{c}×{k}" for c, k in sorted(counts.items()))
            + (
                f" (codec_map.json in {self._obs_dir})"
                if self._obs_dir is not None else ""
            ),
            flush=True,
        )

    def _client_params(self, client: int) -> tuple[Any, Any]:
        u = jax.tree_util.tree_map(lambda x: x[client], self.state.user_params)
        n = jax.tree_util.tree_map(lambda x: x[client], self.state.news_params)
        return u, n

    def _clients_in_sync(self) -> bool:
        """True when every client holds bitwise-identical parameters.

        Decides whether evaluation may use the client-0 fast path: after a
        ``param_avg``/coordinator sync (everyone adopts the aggregate) and
        under ``grad_avg`` (per-step pmean keeps clients in lockstep) this
        is True; under ``local`` — or after a zero-participation round,
        which keeps local params — clients diverge and client 0 would NOT
        be "the model" (VERDICT r2 Weak #3)."""
        leaves = jax.tree_util.tree_leaves(
            (self.state.user_params, self.state.news_params)
        )
        # ONE readback: each host sync costs a full tunnel round-trip
        # (~65 ms on axon — see bench.py measure()), so per-leaf bools
        # would turn this cheap check into seconds of RTT
        return bool(jnp.all(jnp.stack([jnp.all(x == x[0:1]) for x in leaves])))

    def _corpus_for(self, news_params: Any, client: int) -> jnp.ndarray:
        # only the decoupled mode caches a (client-0) table that a non-zero
        # client must bypass; every other path is client-agnostic
        if client != 0 and self.mode == "decoupled":
            return self._encode_states(news_params)
        return self._encode_corpus(news_params)

    def _aggregate_eval(self, eval_one) -> dict[str, float]:
        """Client-0 metrics when clients are in sync; otherwise the MEAN of
        per-client metrics (the documented aggregate — the reference's
        semantics are per-client validation, ``client.py:149-171``). The
        per-client breakdown is kept on ``self.last_per_client_metrics``."""
        if self.cfg.fed.num_clients == 1 or self._clients_in_sync():
            self.last_per_client_metrics = None
            return eval_one(0)
        per = [eval_one(c) for c in range(self.cfg.fed.num_clients)]
        self.last_per_client_metrics = per
        return {k: float(np.mean([m[k] for m in per])) for k in per[0]}

    def adopt_state(self, state: Any) -> None:
        """Install a restored full state pytree (params + opt + PRNG) with
        the trainer's at-rest layout (``_place_state``) — the multi-process
        resume path, where snapshots are flax-serialized per host rather
        than orbax-managed."""
        self.state = self._place_state(state)
        self._table = None  # params changed; a cached decoupled table is stale

    def population_sidecar_bytes(self, round_idx: int) -> bytes | None:
        """The cohort engine's schedule-defining state (sampler fairness
        counters + participation ledger + slot occupancy), serialized for
        persistence — or ``None`` when no population engine is active.
        The orbax path writes ``population_state.msgpack`` itself
        (:meth:`_after_round`); the coordinator deployment persists this
        per WORKER next to its local msgpack snapshot so an elastic
        epoch change can carry participation history across the
        re-formed world."""
        if not self._pop_engine:
            return None
        from fedrec_tpu.train.checkpoint import population_state_bytes

        return population_state_bytes(
            self.cohort_sampler.state_dict(),
            self.population.ledger.state_dict(),
            self._slot_occupants,
            self._slot_writeback,
            round_idx,
        )

    def adopt_population_sidecar(self, blob: bytes, resize: bool = False) -> int:
        """Restore a population sidecar; returns its round tag.

        ``resize=False`` demands exact population/slot agreement (the
        fixed-world resume). ``resize=True`` is elastic-membership
        continuity: the LEDGER adopts with prefix-copy resize semantics
        (:meth:`ParticipationLedger.load_state_dict`), while sampler
        fairness state and slot occupancy are adopted only when their
        shapes still match — an epoch's re-deal otherwise restarts them
        fresh (documented divergence: the cohort *schedule* re-anchors at
        the new world, the participation *history* does not reset)."""
        if not self._pop_engine:
            raise ValueError(
                "adopt_population_sidecar needs an active fed.population "
                "engine (fed.population.num_clients > 0)"
            )
        from fedrec_tpu.train.checkpoint import load_population_state

        pst = load_population_state(blob)
        try:
            self.cohort_sampler.load_state_dict(pst["sampler"])
        except ValueError:
            if not resize:
                raise
            print(
                "[trainer] population sampler state does not fit the "
                "re-formed world; fairness counters restart fresh "
                "(ledger continuity is preserved)"
            )
        self.population.ledger.load_state_dict(pst["ledger"], resize=resize)
        occ = np.asarray(pst["slot_occupants"], np.int64)
        wb = np.asarray(pst["slot_writeback"], bool)
        if occ.shape == self._slot_occupants.shape:
            self._slot_occupants = occ.copy()
            self._slot_writeback = wb.copy()
        elif not resize:
            raise ValueError(
                f"population sidecar slot count {occ.shape} does not match "
                f"the configured {self._slot_occupants.shape} slots"
            )
        return int(pst["round"])

    def set_global_params(self, user_params: Any, news_params: Any) -> None:
        """Adopt externally-aggregated parameters on every local client.

        Used by the coordinator deployment: the server's weight fan-out
        (reference ``server.py:76-77`` / ``client.py:261-264``) lands here.
        """
        # ONE compiled program replaces a per-leaf broadcast+device_put storm:
        # each mesh shard swaps its param slices for the (replicated) new
        # globals, so the state keeps its client sharding and the round
        # boundary issues a single dispatch (the transfer storm both wastes
        # TPU dispatch and, on single-core XLA:CPU rigs, can starve the next
        # round's collective rendezvous into its termination deadline)
        if self._adopt_fn is None:
            from functools import partial

            from fedrec_tpu.compat import shard_map
            from jax.sharding import PartitionSpec as P

            axis = self.cfg.fed.mesh_axis

            @partial(
                shard_map,
                mesh=self.mesh,
                in_specs=(P(axis), P(), P()),
                out_specs=P(axis),
                check_vma=False,
            )
            def adopt(stacked, u, n):
                # the block may hold a COHORT of k clients (clients > devices,
                # see train.step.cohort_axes) — every client in the block
                # adopts the globals; opt states and rngs stay per-client.
                # (The block-of-1 x[0]/x[None] form this replaces silently
                # collapsed cohort states to one client.)
                kb = stacked.step.shape[0]
                bu = jax.tree_util.tree_map(
                    lambda x: jnp.broadcast_to(x, (kb,) + x.shape), u
                )
                bn = jax.tree_util.tree_map(
                    lambda x: jnp.broadcast_to(x, (kb,) + x.shape), n
                )
                return stacked.replace(user_params=bu, news_params=bn)

            self._adopt_fn = jax.jit(adopt, donate_argnums=(0,))
        self.state = self._adopt_fn(
            self.state,
            jax.tree_util.tree_map(jnp.asarray, user_params),
            jax.tree_util.tree_map(jnp.asarray, news_params),
        )
        if self.mode == "decoupled":
            self._refresh_table()

    def _replicate_table(self, table: jnp.ndarray) -> jnp.ndarray:
        """Pin a news-vector table to the one replicated layout the train
        step expects (in_spec ``P()``). The decoupled round alternates table
        sources (sharded refresh vs per-client update slice); without a
        common layout each source would key its own compile of the step."""
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(table, NamedSharding(self.mesh, PartitionSpec()))

    def _refresh_table(self) -> jnp.ndarray:
        _, news_params = self._client0_params()
        self._table = self._encode_states(news_params)
        return self._table

    def _encode_states(self, news_params) -> jnp.ndarray:
        """Cached-trunk corpus encode, sharded over all mesh devices when
        there are several (per-round refresh is the eval-path bottleneck at
        corpus scale). The result is pinned replicated so every consumer —
        train step (in_spec ``P()``), per-batch eval gathers, serving
        export — pays the post-encode all-gather exactly once here."""
        if self.table_spec is not None:
            # sharded catalog: the at-rest rows are already P(clients) and
            # padded, so the sharded encode reshards nothing; only the REAL
            # rows leave (eval/serving index by catalog id)
            vecs = encode_all_news_sharded(
                self.model, news_params, self.token_states, self.mesh
            )
            return self._replicate_table(vecs[: self.table_spec.num_rows])
        if self.mesh.size > 1:
            return self._replicate_table(
                encode_all_news_sharded(
                    self.model, news_params, self.token_states, self.mesh
                )
            )
        return encode_all_news(self.model, news_params, self.token_states)

    def _encode_corpus(self, news_params) -> jnp.ndarray:
        """(N, D) news-vector table from client params, any text-encoder mode."""
        if self.mode == "finetune":
            from fedrec_tpu.train.step import encode_corpus_tokens

            return encode_corpus_tokens(self.text_encoder, news_params, self.news_tokens)
        if self.mode == "decoupled" and self._table is not None:
            # the round loop (news_update / _refresh_table / set_global_params)
            # just rebuilt this table from the same client-0 params — a second
            # full-corpus encode per eval round would double the exact cost
            # the sharded encode exists to cut
            return self._table
        return self._encode_states(news_params)

    def export_for_serving(self) -> tuple[Any, jnp.ndarray]:
        """``(user_params, (N, D) news-vector table)`` of client 0 — the
        handoff to :mod:`fedrec_tpu.serve` (after ``param_avg``/coordinator
        aggregation all clients hold identical parameters). Warns loudly
        when clients have diverged (``local``, zero-participation round):
        client 0 is then ONE client's model, not "the model" — same
        resolution rule as :meth:`evaluate` (VERDICT r2 Weak #3)."""
        if self.cfg.fed.num_clients > 1 and not self._clients_in_sync():
            print(
                "[trainer] WARNING: exporting client 0 for serving while "
                "clients hold DIVERGED parameters (local strategy or an "
                "unsynced round) — run a param sync first, or serve "
                "per-client models deliberately"
            )
        user_params, news_params = self._client0_params()
        return user_params, self._encode_corpus(news_params)

    def _feature_table(self) -> jnp.ndarray:
        if self.mode == "finetune":
            return self.news_tokens
        if self.mode == "joint":
            return self.token_states
        if self._table is None:
            self._refresh_table()
        return self._table

    # ------------------------------------------------------------------
    def _epoch_batches_source(self, epoch_idx: int):
        """One local epoch's stacked (slots, B, ...) batches. Fixed world:
        the legacy batcher re-deals the whole (local) corpus over the
        client slots each epoch. Sampled world (``fed.population`` above
        the slot count): slot *j* iterates the CURRENT cohort's client
        ``j``'s own static shard — data follows the client, the premise of
        cross-device federation."""
        if self._pop_sampling:
            return self.population.cohort_epoch_batches(
                self._current_plan.slot_clients, self.train_ix,
                self.cfg.data, epoch_idx,
            )
        return self.batcher.epoch_batches_sharded(
            self.cfg.fed.num_clients, epoch_idx
        )

    def _epoch_batch_iter(self, epoch_idx: int, extra: dict | None = None):
        """Epoch batches as step-ready dicts, built ahead on a bounded
        producer thread when ``data.prefetch_batches`` > 0 — batch t+1
        assembles (shuffle, negative sampling, packing) while step t runs
        on device, closing the dispatch gap the step_profile host-pipeline
        rows measure. Off (0) = plain inline iteration, identical batches
        either way (tests/test_prefetch.py). ``extra`` (the round's chaos
        fault vectors) is merged into every batch dict."""
        extra = extra or {}
        return maybe_prefetch(
            self._epoch_batches_source(epoch_idx),
            self.cfg.data.prefetch_batches,
            transform=lambda b: {
                "candidates": b.candidates,
                "history": b.history,
                "labels": b.labels,
                **extra,
            },
        )

    # ------------------------------------------------- health / forensics
    def _host_state(self) -> Any:
        """Host (numpy) copy of the full stacked client state — the flight
        recorder's chunk-entry checkpoint. Device buffers may be donated
        away by the time a trigger fires, so the copy is eager."""
        return jax.tree_util.tree_map(np.asarray, self.state)

    def _entry_state(self) -> Any:
        """The round/chunk-entry state the flight recorder keeps — None
        when obs.health.snapshot_state is off (the per-round D2H copy is
        the recorder's dominant cost at large model x cohort scale; dumps
        then carry the batch ring but cannot replay)."""
        return self._host_state() if self.cfg.obs.health.snapshot_state else None

    def _dump_meta(self) -> dict:
        return {
            "num_news": self.data.num_news,
            "title_len": self.data.title_len,
            "mode": self.mode,
            "num_local_samples": self.num_local_samples,
        }

    def _check_health(
        self,
        start_round: int,
        health_rows: list[dict] | None = None,
        metrics3d: dict | None = None,
        round_losses: tuple | list = (),
    ) -> None:
        """Digest one round's (or chunk's) fetched sentry arrays through the
        HealthMonitor; on a trigger, dump the flight recorder and (for a
        non-finite sentinel under abort_on_nonfinite) raise
        TrainingHealthError. One sync point per round — the arrays were
        produced asynchronously alongside the loss readback."""
        if not self.cfg.obs.health.sentry:
            return
        if metrics3d is not None:
            arrays = {
                k: np.asarray(v)
                for k, v in metrics3d.items()
                if k.startswith("health.")
            }
        elif health_rows:
            c = self.cfg.fed.num_clients
            keys = health_rows[0].keys()
            arrays = {
                k: np.concatenate(
                    [np.asarray(r[k]).reshape(-1, c) for r in health_rows]
                )[None]
                for k in keys
            }
        else:
            return
        if not arrays:
            return
        trigger = self.health.check(
            start_round, arrays, list(round_losses),
            ignore_clients=set(self._quarantine),
        )
        if self.watch is not None:
            # unified trigger path: the health monitor's verdicts pulse
            # through the alert engine (scored at the round's evaluate)
            self.watch.ingest_health_trigger(trigger)
            self.watch.ingest_health_outliers(self.health.last_outliers)
        # ---- quarantine-and-rollback (fed.robust.recover): a non-finite
        # update or an outlier client becomes a RECOVERABLE trigger while
        # retries remain — run() quarantines the client, restores the
        # round-entry state, and replays. Quarantined clients were already
        # excluded above, so a replay cannot re-trigger on the same client;
        # retries bound how many DISTINCT bad clients one round may shed
        # before the existing dump-and-abort takes over.
        rb = self.cfg.fed.robust
        if rb.recover:
            cand = (
                trigger
                if trigger is not None and trigger.get("kind") == "nonfinite"
                else None
            )
            if cand is None and self.health.last_outliers:
                cand = {
                    "kind": "outlier",
                    **max(
                        self.health.last_outliers,
                        key=lambda o: o["update_norm"],
                    ),
                }
            if (
                cand is not None
                and cand.get("client") is not None
                and self._round_retries < rb.max_retries
            ):
                raise RoundRecovery(cand)
        if trigger is None:
            return
        dump_dir = self._dump_flightrec(trigger)
        kind = trigger["kind"]
        where = f"round {trigger.get('round')}"
        if trigger.get("step") is not None:
            where += f" step {trigger['step']} client {trigger.get('client')}"
        detail = trigger.get("detail") or {
            k: trigger[k] for k in ("round_loss", "trailing_mean")
            if k in trigger
        }
        if dump_dir:
            hint = (
                f" Forensics dumped to {dump_dir} — confirm with "
                f"`fedrec-obs replay {dump_dir}`."
            )
        elif self.flightrec is not None:
            hint = (
                " Flight-recorder dump suppressed by "
                f"obs.health.dump_policy={self.cfg.obs.health.dump_policy!r}"
                f" (earlier dump: {self.flightrec.last_dump_dir})."
            )
        else:
            hint = (
                " Set obs.dir (+ obs.health.flight_recorder) for a "
                "replayable dump."
            )
        msg = (
            f"training-health trigger [{kind}] at {where}: {detail}.{hint}"
        )
        if kind == "nonfinite" and self.cfg.obs.health.abort_on_nonfinite:
            raise TrainingHealthError(msg)
        print(f"[trainer] WARNING: {msg}")

    # ------------------------------------------- quarantine & rollback
    def train_round_recovering(self, round_idx: int) -> RoundResult:
        """One host-driven round under the quarantine/rollback policy —
        the coordinator driver's per-round entry point (``run`` applies
        the same policy around whole chunks). Without
        ``fed.robust.recover`` this is exactly :meth:`train_round`."""
        from fedrec_tpu.fed.population import QuorumFailure

        while True:
            self._capture_recovery_state()
            try:
                result = self.train_round(round_idx)
            except RoundRecovery as e:
                self._rollback_and_quarantine(e.trigger, round_idx)
                continue
            except QuorumFailure as e:
                self._handle_quorum_failure(e, round_idx)
                continue
            self._round_retries = 0
            self._commit_population(round_idx)
            self._tick_quarantine()
            return result

    def _capture_recovery_state(self) -> None:
        """Snapshot the rollback target at round/chunk entry: the full
        client state (host copy), plus the FedOpt buffers — the server
        optimizer steps at round end, so replaying a rolled-back round
        without restoring them would double-apply momentum."""
        if not self.cfg.fed.robust.recover:
            return
        self._recovery_state = self._host_state()
        if self._pop_engine:
            # occupancy must roll back WITH the state: a replayed round
            # re-installs its cohort against the restored slots, and a
            # stale occupancy map would write one client's sidecar back
            # under another's id
            self._recovery_occupants = (
                self._slot_occupants.copy(),
                self._slot_writeback.copy(),
            )
        if self.server_opt is not None:
            import copy

            self._recovery_opt_state = copy.deepcopy(self.server_opt._state)

    def _rollback_and_quarantine(self, trigger: dict, round_idx: int) -> None:
        """Apply one recovery cycle (``fed.robust.recover``): quarantine the
        offending client, restore the round-entry state, and let ``run``
        replay the round. Published to the registry and stamped into the
        trace as a ``rollback`` event; the replayed round's ``fed_round``
        span carries the active quarantine set."""
        cfg = self.cfg
        client = int(trigger["client"])
        kind = str(trigger.get("kind"))
        self._round_retries += 1
        logical = None
        if self._pop_sampling and self._current_plan is not None:
            # the sentry flags a SLOT; quarantine the LOGICAL client that
            # occupied it this round — the sampler excludes it from draws
            # until the expiry round, and its (possibly poisoned) sidecar
            # is reset so the healed rejoin restarts from the template
            logical = int(self._current_plan.slot_clients[client])
            self.population.ledger.quarantine(
                logical, round_idx + cfg.fed.robust.quarantine_rounds
            )
            self.population.reset_sidecar(logical)
            self._pop_pending = {
                k: v for k, v in self._pop_pending.items() if k < round_idx
            }
            self._g_quarantined.set(
                float(len(self.population.ledger.quarantined))
            )
        else:
            self._quarantine[client] = max(
                self._quarantine.get(client, 0),
                cfg.fed.robust.quarantine_rounds,
            )
            self._g_quarantined.set(float(len(self._quarantine)))
        self._m_quarantines.inc()
        self._m_rollbacks.inc()
        self.tracer.add_span(
            "rollback", dur_s=0.0,
            round=int(trigger.get("round") or round_idx),
            client=client if logical is None else logical,
            kind=kind, retry=self._round_retries,
        )
        who = (
            f"client {client}" if logical is None
            else f"logical client {logical} (slot {client})"
        )
        print(
            f"[trainer] WARNING: health trigger [{kind}] on {who} "
            f"at round {trigger.get('round')} — quarantining it for "
            f"{cfg.fed.robust.quarantine_rounds} round(s), rolling back to "
            f"the round-{round_idx} entry state and replaying (retry "
            f"{self._round_retries}/{cfg.fed.robust.max_retries})"
        )
        self.adopt_state(self._recovery_state)
        if self._pop_engine and self._recovery_occupants is not None:
            self._slot_occupants = self._recovery_occupants[0].copy()
            self._slot_writeback = self._recovery_occupants[1].copy()
            if logical is not None:
                # the quarantined client's sidecar was reset above; without
                # this, the replay's _install_cohort would write its
                # restored (possibly poisoned) sidecar straight back and
                # the healed rejoin would NOT restart from the template
                self._slot_writeback[self._slot_occupants == logical] = False
        if self.server_opt is not None:
            import copy

            self.server_opt._state = copy.deepcopy(self._recovery_opt_state)

    def _round_span_args(self) -> dict:
        """Extra fed_round span attributes while recovery is active, so the
        trace shows which rounds ran with clients excluded / as replays."""
        args: dict = {}
        if self._quarantine:
            args["quarantined"] = sorted(self._quarantine)
        if self._pop_sampling and self._current_plan is not None:
            args["cohort"] = int(self._current_plan.slot_real.sum())
            if self.population.ledger.quarantined:
                args["quarantined"] = sorted(
                    self.population.ledger.quarantined
                )
        if self._round_retries:
            args["replay_retry"] = self._round_retries
        if self._codec_bytes_per_client is not None:
            # byte attrs ride the fed_round span: what ONE client's update
            # costs on the wire under the active codec, vs dense
            args["codec"] = self.cfg.fed.dcn_compress
            args["codec_bytes_per_client"] = self._codec_bytes_per_client
            args["dense_bytes_per_client"] = self._dense_bytes_per_client
        return args

    def _tick_quarantine(self) -> None:
        """Advance the quarantine ledger by one completed round; expired
        clients rejoin HEALED (params reset to the global, optimizer
        moments zeroed) — their own state may still be NaN-poisoned, and
        un-healed Adam moments would re-trigger the same quarantine the
        moment it expires."""
        if not self._quarantine:
            return
        expired = []
        for c in list(self._quarantine):
            self._quarantine[c] -= 1
            if self._quarantine[c] <= 0:
                expired.append(c)
                del self._quarantine[c]
        self._g_quarantined.set(float(len(self._quarantine)))
        for c in expired:
            self._heal_client(c)

    def _heal_client(self, client: int) -> None:
        cfg = self.cfg
        donor = next(
            (
                c
                for c in range(cfg.fed.num_clients)
                if c != client and c not in self._quarantine
            ),
            None,
        )
        if donor is None:
            return

        def fix(tree, from_donor: bool):
            def one(x):
                x = np.array(x)
                if x.ndim >= 1 and x.shape[0] == cfg.fed.num_clients:
                    x[client] = x[donor] if from_donor else 0
                return x

            return jax.tree_util.tree_map(one, tree)

        host = self._host_state()
        self.adopt_state(
            host.replace(
                user_params=fix(host.user_params, True),
                news_params=fix(host.news_params, True),
                opt_user=fix(host.opt_user, False),
                opt_news=fix(host.opt_news, False),
                news_grad_accum=fix(host.news_grad_accum, False),
                # a healed client must not replay a poisoned codec
                # residual — same contract as the optimizer moments
                ef_residual=fix(host.ef_residual, False),
            )
        )
        print(
            f"[trainer] quarantine expired for client {client}: rejoined "
            "with global params and fresh optimizer state"
        )

    def _dump_flightrec(self, trigger: dict):
        if self.flightrec is None:
            return None
        try:
            table = np.asarray(self._feature_table())
        except Exception:  # noqa: BLE001 — forensics must not mask the trigger
            table = None
        try:
            return self.flightrec.dump(
                self._obs_dir / "flightrec",
                trigger,
                cfg=self.cfg,
                registry=self.registry,
                table=table,
                meta=self._dump_meta(),
            )
        except Exception as e:  # noqa: BLE001
            print(f"[trainer] flight-recorder dump failed: "
                  f"{type(e).__name__}: {e}")
            return None

    def _flightrec_on_exception(self, e: BaseException) -> None:
        """Last-chance forensics: a run dying to an exception that never
        reached a round-end health check (dispatch error, cap-overflow
        abort) still dumps its batch ring + chunk-entry state."""
        if self.flightrec is None or self.flightrec.dump_count > 0:
            return
        if not isinstance(e, Exception):
            return  # KeyboardInterrupt/SystemExit: exit fast, no dump
        self._dump_flightrec({
            "kind": "exception",
            "error": type(e).__name__,
            "message": str(e)[:500],
            "round": None,
            "step": None,
        })

    def _mask_rng(self, round_idx: int) -> jax.Array:
        """THE per-round participation-mask key — host-driven rounds and
        rounds-in-jit chunks both derive masks from this one expression, so
        the chunked path's identical-trajectory contract cannot be broken
        by editing one copy."""
        return jax.random.PRNGKey(
            hash((self.cfg.train.seed, round_idx)) & 0x7FFFFFFF
        )

    def _round_weights(self, round_idx: int) -> np.ndarray:
        """THE per-round aggregation weights — host-driven rounds and
        rounds-in-jit chunks share this one composition:

        * fixed-world (no ``fed.population``): participation mask × chaos
          slot drop/straggle mask × quarantine exclusion — without chaos
          or quarantine exactly the participation mask (value-identical
          to the pre-robust trajectory);
        * cohort engine: the plan's per-slot report simulation (pads,
          per-round dropouts, deadline cuts — :func:`plan_round_weights`)
          × the same participation/chaos-slot composition, with the
          quorum policy enforced on the FINAL reporting count (a
          :class:`QuorumFailure` here is raised before any state
          mutation, so the discarded round IS its entry state).
        """
        cfg = self.cfg
        from fedrec_tpu.fed.strategies import participation_mask

        plan = self._current_plan if self._pop_engine else None
        events = None
        if plan is not None:
            from fedrec_tpu.fed.population import plan_round_weights

            w, events = plan_round_weights(
                plan, round_idx, cfg.fed.population.round_deadline_ms,
                chaos=self.chaos,
            )
            if round_idx == plan.round_idx and plan.start_dropped.size:
                # start-drops never reached a slot; the ledger still owes
                # them a dropped round (over-selection's raison d'etre)
                events["dropped"] = np.unique(
                    np.concatenate([events["dropped"], plan.start_dropped])
                )
            if cfg.fed.participation < 1.0:
                # degenerate-population composition: the legacy fraction
                # still applies when the cohort is the fixed world
                w = w * np.asarray(
                    participation_mask(
                        self._mask_rng(round_idx), cfg.fed.num_clients,
                        cfg.fed.participation,
                    ),
                    np.float32,
                )
        else:
            w = np.asarray(
                participation_mask(
                    self._mask_rng(round_idx), cfg.fed.num_clients,
                    cfg.fed.participation,
                ),
                np.float32,
            )
        if self.chaos is not None:
            rf = self.chaos.round_faults(round_idx)
            w = w * rf.weight_mask
            for kind, count in (
                ("drop", len(rf.dropped)), ("straggle", len(rf.straggled)),
            ):
                if count:
                    self._m_chaos.inc(count, kind=kind)
            for kind, _client in rf.injected:
                self._m_chaos.inc(kind=kind)
            if rf.straggled and cfg.chaos.straggle_ms > 0:
                import time as _time

                _time.sleep(cfg.chaos.straggle_ms / 1e3)
        if not self._pop_sampling:
            # slot-keyed quarantine (legacy + degenerate population); the
            # sampling engine excludes quarantined LOGICAL clients at the
            # cohort draw instead
            for c in self._quarantine:
                if 0 <= c < w.shape[0]:
                    w[c] = 0.0
        if plan is not None:
            from fedrec_tpu.fed.population import QuorumFailure

            # ledger truth = the FINAL weights (slot chaos included)
            keep = (w > 0) & plan.slot_real
            events["reported"] = np.unique(plan.slot_clients[keep])
            # any real client whose weight hit zero for a reason the
            # pop-level simulation didn't see (slot chaos, participation
            # mask, slot quarantine) still owes the ledger a dropped
            # round — otherwise selected > reported+dropped+cut and the
            # sizing runbook's dropout metrics under-count real churn
            lost = (
                set(np.unique(plan.slot_clients[plan.slot_real & ~keep]).tolist())
                - set(events["reported"].tolist())
                - set(np.asarray(events["deadline_cut"]).tolist())
                - set(np.asarray(events["dropped"]).tolist())
            )
            if lost:
                events["dropped"] = np.unique(np.concatenate([
                    np.asarray(events["dropped"], np.int64),
                    np.asarray(sorted(lost), np.int64),
                ]))
            self._pop_pending[round_idx] = (plan, events)
            reporting = int(events["reported"].size)
            self._g_cohort_reporting.set(float(reporting))
            mr = cfg.fed.population.min_reports
            if 0 < mr and reporting < mr:
                raise QuorumFailure(
                    plan.round_idx, round_idx, reporting, mr, plan.attempt
                )
        return w

    # ------------------------------------------------- cohort engine
    def _ensure_cohort(self, round_idx: int) -> None:
        """Sample and install the cohort for ``round_idx`` (the draw
        anchor — a rounds-in-jit chunk keeps one cohort for its whole
        span, re-rolling only the per-round report weights). Re-entrant:
        a rollback or quorum replay re-derives the plan — same
        ``(seed, round, attempt)`` minus newly-quarantined clients —
        and the install no-ops when the occupancy is unchanged."""
        if not self._pop_engine:
            return
        from fedrec_tpu.fed.population import build_cohort_plan

        pcfg = self.cfg.fed.population
        exclude = (
            self.population.ledger.active_quarantine(round_idx)
            if self._pop_sampling
            else ()
        )
        plan = build_cohort_plan(
            self.cohort_sampler,
            self.cfg.fed.num_clients,
            round_idx,
            pcfg.over_select,
            chaos=self.chaos,
            exclude=exclude,
            attempt=self._pop_attempts.get(round_idx, 0),
            pack=self._pop_sampling,
        )
        self._current_plan = plan
        self._g_cohort_sampled.set(float(len(plan.sampled)))
        if self._pop_sampling:
            self._install_cohort(plan)

    def _template_sidecar(self, client_id: int) -> dict:
        """The pristine sidecar a first-time (or healed) client starts
        from: zeroed optimizer moments + step 0 + a per-client PRNG fold
        (logical clients get their own deterministic noise streams,
        disjoint from the slot-init splits)."""
        t = {
            f: jax.tree_util.tree_map(np.array, v)
            for f, v in self._pop_template.items()
        }
        t["rng"] = np.asarray(
            jax.random.fold_in(
                jax.random.PRNGKey(self.cfg.train.seed + 1),
                (1 << 24) + int(client_id),
            )
        )
        return t

    def _install_cohort(self, plan) -> None:
        """Load/unload around the round: write rotating-out occupants'
        sidecars (optimizer states, PRNG, step, grad accumulator) back to
        the population store, load the incoming clients' sidecars (or the
        template on first selection) into their slots. Parameters are NOT
        touched — after a param-avg sync every slot holds the global, which
        is exactly what a sampled-in client adopts. Pad slots (weight 0)
        load their duplicate's sidecar but never write back."""
        from fedrec_tpu.fed.population import SIDECAR_FIELDS

        slots = self.cfg.fed.num_clients
        persist = self.cfg.fed.population.client_state == "persist"
        new_occ = np.asarray(plan.slot_clients, np.int64)
        new_wb = (plan.slot_real & persist).astype(bool)
        changed = [
            j for j in range(slots) if self._slot_occupants[j] != new_occ[j]
        ]
        if not changed:
            self._slot_writeback = new_wb
            return
        # only the sidecar subtrees cross the host boundary — params and
        # the rest of the state never change across an install (the
        # post-sync global IS what a sampled-in client adopts), so a
        # cohort swap costs sidecar-sized transfers, not a full-model
        # D2H/H2D round-trip per round. np.array: writable host copies.
        fields = {
            f: jax.tree_util.tree_map(np.array, getattr(self.state, f))
            for f in SIDECAR_FIELDS
        }
        if persist:
            # write back EVERY persisted occupant, not only changed slots:
            # a client can stay at its old index as a weight-0 pad while
            # being re-packed real into a DIFFERENT slot — the store copy
            # must be its freshest sidecar or the new slot loads stale
            # moments and the round's training is silently discarded
            for j in range(slots):
                if self._slot_writeback[j]:
                    self.population.put_sidecar(
                        int(self._slot_occupants[j]),
                        {
                            f: jax.tree_util.tree_map(
                                lambda x, _j=j: x[_j].copy(), fields[f]
                            )
                            for f in SIDECAR_FIELDS
                        },
                    )
        for j in changed:
            cid = int(new_occ[j])
            sc = self.population.get_sidecar(cid) if persist else None
            if sc is None:
                sc = self._template_sidecar(cid)
            for f in SIDECAR_FIELDS:
                def put(dst, src, _j=j):
                    dst[_j] = src
                    return dst

                jax.tree_util.tree_map(put, fields[f], sc[f])
        self._m_cohort_swaps.inc(len(changed))
        if self._state_shardings is not None:
            # fsdp at rest: each sidecar field re-commits to its policy
            # layout, not the flat client sharding
            self.state = self.state.replace(**{
                f: jax.tree_util.tree_map(
                    lambda x, s: jax.device_put(jnp.asarray(x), s),
                    fields[f], getattr(self._state_shardings, f),
                )
                for f in SIDECAR_FIELDS
            })
        else:
            sharding = client_sharding(self.mesh, self.cfg.fed.mesh_axis)
            self.state = self.state.replace(**{
                f: jax.tree_util.tree_map(
                    lambda x: jax.device_put(jnp.asarray(x), sharding),
                    fields[f],
                )
                for f in SIDECAR_FIELDS
            })
        self._slot_occupants = new_occ.copy()
        self._slot_writeback = new_wb

    def _commit_population(self, round_idx: int) -> None:
        """Commit one COMPLETED round into the sampler's fairness state
        and the participation ledger — called only once the round's
        results are accepted, so rolled-back and quorum-discarded rounds
        never skew the schedule."""
        if not self._pop_engine:
            return
        pending = self._pop_pending.pop(round_idx, None)
        if pending is None:
            return
        plan, events = pending
        self.cohort_sampler.record(plan.sampled)
        self.population.ledger.commit(plan.sampled, events)
        for key, ctr in (
            ("dropped", self._m_pop_drops),
            ("deadline_cut", self._m_deadline_cuts),
        ):
            n = int(np.asarray(events.get(key, ())).size)
            if n:
                ctr.inc(n)
        self._pop_attempts.pop(round_idx, None)
        self._g_pop_coverage.set(self.population.ledger.coverage())
        if self._pop_sampling:
            self._g_quarantined.set(
                float(len(self.population.ledger.quarantined))
            )
        self.cohort_history.append(
            (
                round_idx,
                tuple(int(c) for c in plan.slot_clients[plan.slot_real]),
            )
        )

    def _handle_quorum_failure(self, e, round_idx: int) -> None:
        """One quorum-replay cycle: discard the round's pending ledger
        events, bump the draw attempt for the anchor round (fresh cohort
        + fresh fault dice next pass), abort once retries are exhausted.
        The failure is raised before any dispatch, so 'replay from the
        round-entry state' needs no state restore — the entry state was
        never left."""
        pcfg = self.cfg.fed.population
        self._pop_pending = {
            k: v for k, v in self._pop_pending.items() if k < round_idx
        }
        attempts = self._pop_attempts.get(e.anchor_round, 0) + 1
        self._m_quorum_replays.inc()
        self.tracer.add_span(
            "quorum_replay", dur_s=0.0, round=e.round_idx,
            reporting=e.reporting, attempt=attempts,
        )
        # a re-draw only helps if SOMETHING consumes the attempt counter:
        # the cohort draw (sampled world) or the population-level fault
        # dice. In the degenerate world without those, every replay
        # recomputes byte-identical weights (slot chaos and the
        # participation mask are keyed on round only) — burning retries
        # would just delay the same abort.
        ch = self.cfg.chaos
        attempt_sensitive = self._pop_sampling or (
            self.chaos is not None
            and (
                ch.pop_drop_rate > 0
                or ch.pop_flaky_fraction > 0
                or (ch.pop_straggle_ms > 0 and pcfg.round_deadline_ms > 0)
            )
        )
        if attempts > pcfg.quorum_retries or not attempt_sensitive:
            futile = (
                "" if attempt_sensitive else
                " (a fixed-world cohort with no population-level fault "
                "dice replays identically — retries skipped)"
            )
            raise RuntimeError(
                f"round {e.round_idx} failed quorum "
                f"({e.reporting} reporting < min_reports="
                f"{pcfg.min_reports}) on {attempts} consecutive cohort "
                f"draws{futile} — the population's dropout rate cannot "
                "sustain this quorum. Lower fed.population.min_reports, "
                "raise over_select, or relax the deadline "
                "(docs/OPERATIONS.md, 'sizing a cohort')."
            ) from e
        self._pop_attempts[e.anchor_round] = attempts
        print(
            f"[trainer] WARNING: quorum failure at round {e.round_idx} "
            f"({e.reporting} < {pcfg.min_reports}); discarding the round "
            f"and replaying with a fresh cohort draw (attempt {attempts}/"
            f"{pcfg.quorum_retries})"
        )

    def _count_uplink(self, weights_np: np.ndarray) -> None:
        """Bank one synced round's (or one chunk row's) modeled wire
        traffic: each REPORTING client ships one encoded update up, every
        client receives one dense fan-out down. Bytes come from a real
        wire-codec encode of the param trees (init-time; payload sizes are
        static per codec × shapes). No-op without an active codec."""
        if self._codec_bytes_per_client is None:
            return
        w = np.asarray(weights_np).reshape(-1, self.cfg.fed.num_clients)
        reporting = int((w > 0).sum())
        rounds = int(w.shape[0])
        self._m_bytes_up.inc(
            float(self._codec_bytes_per_client * reporting), path="cohort"
        )
        self._m_bytes_down.inc(
            float(
                self._dense_bytes_per_client
                * self.cfg.fed.num_clients
                * rounds
            ),
            path="cohort",
        )

    def _uplink_span_args(self, weights_np: np.ndarray) -> dict:
        """Byte attrs for the aggregate span under an active codec."""
        if self._codec_bytes_per_client is None:
            return {}
        w = np.asarray(weights_np).reshape(-1, self.cfg.fed.num_clients)
        return {
            "codec": self.cfg.fed.dcn_compress,
            "bytes_up": int(self._codec_bytes_per_client * (w > 0).sum()),
            "bytes_down": int(
                self._dense_bytes_per_client
                * self.cfg.fed.num_clients
                * w.shape[0]
            ),
        }

    def _count_steps(self, n: int) -> None:
        """Step counter + the sharded-gather wire model: every dispatched
        step moves one owner-bucketed exchange across the mesh when the
        catalog is sharded (``shard.a2a_bytes_total``; 0 bytes/step when
        ``shard.table`` is off)."""
        self._m_steps.inc(n)
        if self._a2a_bytes_per_step:
            self._m_a2a_bytes.inc(float(n * self._a2a_bytes_per_step))

    def _perf_sample_components(self, round_idx: int) -> None:
        """HBM attribution at round cadence (obs.perf.hbm_components):
        bucket ``jax.live_arrays()`` bytes into params / optimizer /
        news_table / batch / other gauges.  Classification is by leaf
        identity against the CURRENT state pytrees, so donated buffers
        (no longer live) simply drop out."""
        if self.perf is None or not self.cfg.obs.perf.hbm_components:
            return
        from fedrec_tpu.obs.perf import live_array_components

        st = self.state
        table = self.token_states
        if table is None:
            table = self.news_tokens if self.mode == "finetune" else self._table
        live_array_components(
            {
                "params": (st.user_params, st.news_params),
                "optimizer": (st.opt_user, st.opt_news),
                "news_table": table,
                "batch": self._perf_last_batch,
            },
            registry=self.registry,
            tracer=self.tracer,
            fed_round=round_idx,
        )

    def _chaos_batch_keys(self, round_idx: int) -> dict | None:
        """Per-client fault vectors every chaos-enabled batch must carry
        (``train.step`` applies them at the update boundary)."""
        return (
            self.chaos.batch_keys(round_idx) if self.chaos is not None else None
        )

    def train_round(self, round_idx: int) -> RoundResult:
        """One host-driven federated round, wrapped in a ``fed_round`` host
        span AND a ``jax.profiler.StepTraceAnnotation`` carrying the same
        round number — so the obs trace and a captured device trace
        (train.profile) are correlatable round-for-round."""
        import time as _time

        t0 = _time.perf_counter()
        # cohort first (and before the span, whose args describe it): the
        # draw + sidecar install define who this round even is
        self._ensure_cohort(round_idx)
        if self.perf is not None:
            self.perf.begin_round()
        with self.tracer.span(
            "fed_round", step_num=round_idx, num_rounds=1,
            **self._round_span_args(),
        ), jax.profiler.StepTraceAnnotation("fed_round", step_num=round_idx):
            result = self._train_round_inner(round_idx)
            # HBM gauges at the round boundary, attributed (as an instant
            # event) to this fed_round span; no-op on allocator-less CPU
            sample_device_memory(
                self.registry, self.tracer, fed_round=round_idx
            )
            self._perf_sample_components(round_idx)
        wall = _time.perf_counter() - t0
        self._m_round_secs.observe(wall)
        if self.perf is not None:
            self.perf.observe_round(round_idx, 1, wall)
        return result

    def _train_round_inner(self, round_idx: int) -> RoundResult:
        cfg = self.cfg
        weights_np = self._round_weights(round_idx)
        weights = jnp.asarray(weights_np)
        chaos_extra = self._chaos_batch_keys(round_idx)
        sync_entry = None
        if self._sync_takes_entry:
            # the codec sync compresses ROUND DELTAS, so it needs the
            # round-entry param trees. Copied (not referenced): the step
            # dispatches below donate the state buffers, so a live alias
            # would be invalidated by the first step of the epoch.
            sync_entry = jax.tree_util.tree_map(
                jnp.copy, (self.state.user_params, self.state.news_params)
            )
        if self.flightrec is not None:
            self.flightrec.start_chunk(
                round_idx, self._entry_state(),
                {round_idx: weights_np},
            )

        round_start_global = None
        if (
            self.server_opt is not None
            or self._agg_async
            or self._agg_hier_host
        ):
            # all clients hold identical params at round entry (initial
            # replication / previous sync); client 0 IS the global model.
            # Materialized to host: the server step is a round-boundary op,
            # and the readback doubles as a barrier that keeps the device
            # program queue shallow (async dispatch of per-round reshard +
            # broadcast programs can otherwise pile up far enough to trip
            # XLA:CPU's 40 s collective-rendezvous termination deadline)
            round_start_global = jax.tree_util.tree_map(
                np.asarray, self._client0_params()
            )

        losses = []
        raw_losses = []  # per-client loss cells: the NaN-robust fallback
        overflows = []  # device arrays; read once at round end (no per-step sync)
        # sentry aux vectors, same deal: appended as device arrays, one
        # host fetch at the round-end health check
        health_rows: list[dict] = []
        scan_s = cfg.train.scan_steps if self.train_scan is not None else 1

        tracer = self.tracer

        def keep_metrics(metrics) -> None:
            losses.append(metrics["mean_loss"])
            raw_losses.append(metrics["loss"])
            if "unique_overflow" in metrics:
                overflows.append(metrics["unique_overflow"])
            row = {k: v for k, v in metrics.items() if k.startswith("health.")}
            if row:
                health_rows.append(row)

        def dispatch(group: list, table) -> None:
            self._count_steps(len(group))
            if len(group) == scan_s and scan_s > 1:
                with tracer.span("h2d", n=len(group)):
                    stacked = shard_scan_batches(
                        self.mesh, stack_batches(group), cfg
                    )
                if self._perf_keep_batch:
                    self._perf_last_batch = stacked
                with tracer.span("dispatch", kind="scan_chain", n=len(group)):
                    self.state, metrics = self.train_scan(
                        self.state, stacked, table
                    )
            else:  # per-batch path; also the short epoch tail under scan
                for g in group:
                    with tracer.span("h2d", n=1):
                        sharded = shard_fed_batch(self.mesh, g, cfg)
                    if self._perf_keep_batch:
                        self._perf_last_batch = sharded
                    with tracer.span("dispatch", kind="step", n=1):
                        self.state, metrics = self.train_step(
                            self.state, sharded, table
                        )
                    keep_metrics(metrics)
                return
            keep_metrics(metrics)  # scan chain: (scan_s, clients) entries

        step_in_round = 0
        for local_epoch in range(cfg.fed.local_epochs):
            epoch_idx = round_idx * cfg.fed.local_epochs + local_epoch
            table = self._feature_table()
            group: list = []
            it = self._epoch_batch_iter(epoch_idx, chaos_extra)
            src = iter(it)
            try:
                while True:
                    # the consumer-side wait IS the batch-build cost when
                    # prefetch is off, and the residual (unhidden) build
                    # cost when it is on — either way the span to watch
                    t_build = tracer.now()
                    try:
                        batch = next(src)
                    except StopIteration:
                        break
                    tracer.add_span(
                        "batch_build", dur_s=tracer.now() - t_build,
                        epoch=epoch_idx,
                    )
                    if self.flightrec is not None:
                        self.flightrec.record(
                            batch, round_idx, epoch_idx, step_in_round
                        )
                    step_in_round += 1
                    group.append(batch)
                    if len(group) == scan_s:
                        dispatch(group, table)
                        group = []
            finally:
                # a dispatch error mid-epoch must not leak the producer
                # thread (Prefetcher.close is idempotent; bare generators
                # close harmlessly)
                close = getattr(it, "close", None)
                if close is not None:
                    close()
            if group:
                dispatch(group, table)
            if self.mode == "decoupled":
                self.state, tables = self.news_update(self.state, self.token_states)
                self._table = self._replicate_table(
                    jax.tree_util.tree_map(lambda x: x[0], tables)
                )

        if self.strategy.sync_params_every_round and (
            self._agg_async or self._agg_hier_host
        ):
            # host-side aggregation topologies (agg.mode): the in-graph
            # param_sync never runs — per-client params come to host and
            # the commit/tree reduce replaces the flat collective.
            # (hierarchical + method="mean" is NOT this path: it lowers to
            # the unchanged flat collective below, bit-identical.)
            with tracer.span(
                "aggregate", round=round_idx, method=cfg.fed.robust.method,
                mode=cfg.agg.mode, **self._uplink_span_args(weights_np),
            ):
                # drain the round's step backlog via a data dependency
                # before the cross-device host gather (same XLA:CPU
                # rendezvous-deadline rationale as the FedOpt branch)
                if losses:
                    jax.block_until_ready(losses[-1])
                if self._agg_async:
                    self._agg_async_commit(
                        round_idx, weights_np, round_start_global
                    )
                else:
                    self._agg_hier_sync(
                        round_idx, weights_np, round_start_global
                    )
            self._m_robust_rounds.inc(method=cfg.fed.robust.method)
            self._count_uplink(weights_np)
        elif self.strategy.sync_params_every_round:
            with tracer.span(
                "aggregate", round=round_idx, method=cfg.fed.robust.method,
                **self._uplink_span_args(weights_np),
            ):
                if sync_entry is not None:
                    self.state = self.param_sync(
                        self.state, weights, *sync_entry
                    )
                else:
                    self.state = self.param_sync(self.state, weights)
            self._m_robust_rounds.inc(method=cfg.fed.robust.method)
            self._count_uplink(weights_np)
            if (
                cfg.fed.dcn_compress == "auto"
                and self._auto_leaf_codecs is None
                and sync_entry is not None
                and round_idx + 1 >= cfg.fed.dcn_auto_warmup
            ):
                self._pin_auto_codec_map(round_idx, sync_entry)
            if self.server_opt is not None:
                # FedOpt: the weighted mean is a proposal, not the new model —
                # the server optimizer steps the global from round_start
                # toward it (set_global_params rebroadcasts to all clients
                # and refreshes the decoupled table).
                # Drain the round's step backlog FIRST via a data dependency:
                # the client-0 slice below is a cross-device gather, and
                # dispatching it behind a full epoch of queued steps leaves
                # its rendezvous open for the whole backlog — on a time-
                # sliced XLA:CPU rig that trips the 40 s collective
                # termination deadline (observed; steps drain incrementally
                # through per-value readbacks everywhere else).
                if losses:
                    jax.block_until_ready(losses[-1])
                mean = jax.tree_util.tree_map(np.asarray, self._client0_params())
                new_u, new_n = self.server_opt.step(round_start_global, mean)
                self.set_global_params(
                    jax.tree_util.tree_map(jnp.asarray, new_u),
                    jax.tree_util.tree_map(jnp.asarray, new_n),
                )
            elif self.mode == "decoupled":
                self._refresh_table()

        # flat mean over every (step, client) cell: scan chains contribute one
        # (scan_steps, clients) entry and per-batch steps one (clients,) entry,
        # so a mean-of-entry-means would overweight the epoch tail
        train_loss = self._round_loss_mean(
            np.concatenate([np.asarray(l).reshape(-1) for l in losses]),
            np.concatenate([np.asarray(l).reshape(-1) for l in raw_losses]),
        )
        # sentry digest FIRST: a non-finite sentinel is the root cause the
        # operator needs (and dumps the flight recorder) before any other
        # abort gets to describe the same broken round differently
        self._check_health(
            round_idx, health_rows=health_rows, round_losses=[train_loss]
        )
        if overflows:
            # per entry: max over clients (replicated psum total per step),
            # then sum over the entry's steps — a scan chain contributes a
            # (scan_steps, clients) array and must count EACH overflowed step
            total = int(
                np.sum([np.asarray(o).max(axis=-1).sum() for o in overflows])
            )
            if total > 0:
                self._m_overflow.inc(total)
                raise RuntimeError(self._overflow_message(total))
        result = RoundResult(round_idx, train_loss)
        self._eval_if_due(result)
        return result

    # ------------------------------------------- aggregation topologies
    def _agg_param_stacks(self) -> tuple[Any, Any]:
        """Every client's (user, news) params to host as (C, ...) leaf
        stacks — the raw material of the host-side topologies (the state
        keeps its leading clients axis, so one fetch covers the cohort)."""
        return jax.tree_util.tree_map(
            np.asarray, (self.state.user_params, self.state.news_params)
        )

    def _agg_hier_sync(
        self, round_idx: int, weights_np: np.ndarray, round_start_global: Any
    ) -> None:
        """Hierarchical robust sync (agg.mode='hierarchical' with a
        non-mean fed.robust method): the cohort's contributions reduce up
        an agg.tree_fanout tree, the robust method applied PER TIER — the
        trajectory this produces genuinely diverges from the flat robust
        reduce (documented in docs/DESIGN.md; bounded-delta pinned).  The
        topology is rebuilt from the live cohort every round, so a
        membership shrink/rejoin reforms the tree by construction."""
        from fedrec_tpu.agg.hierarchy import (
            tree_critical_path_ms,
            tree_reduce_np,
        )

        cfg = self.cfg
        if float(np.sum(weights_np)) == 0.0:
            return  # nobody reported: every client keeps its local params
        stacks = self._agg_param_stacks()
        stats: dict = {}
        reduced = tree_reduce_np(
            stacks,
            weights_np,
            cfg.agg.tree_fanout,
            cfg.fed.robust.method,
            trim_k=cfg.fed.robust.trim_k,
            clip_norm=cfg.fed.robust.clip_norm,
            fallback_tree=round_start_global,
            stats=stats,
        )
        self._g_agg_tier_ms.set(tree_critical_path_ms(stats))
        new_u, new_n = reduced
        if self.server_opt is not None:
            # FedOpt sees the tree's output exactly where it saw the flat
            # mean: a proposal the server optimizer steps toward
            new_u, new_n = self.server_opt.step(
                round_start_global, (new_u, new_n)
            )
        self.set_global_params(
            jax.tree_util.tree_map(jnp.asarray, new_u),
            jax.tree_util.tree_map(jnp.asarray, new_n),
        )

    def _agg_async_commit(
        self, round_idx: int, weights_np: np.ndarray, round_start_global: Any
    ) -> None:
        """In-process buffered quorum commit (agg.mode='async' on a cohort
        deployment): per-slot report latencies come from the SAME seeded
        chaos distribution the population engine uses, the agg.quorum
        earliest reporters commit NOW, and the stragglers' deltas land in
        the buffer to fold staleness-weighted into the next commit — the
        cohort-simulation twin of the agg/server.py wire deployment."""
        from fedrec_tpu.agg.buffer import BufferEntry
        from fedrec_tpu.agg.commit import encode_contribution, fold_commit
        from fedrec_tpu.fed.chaos import population_report

        cfg = self.cfg
        part = np.flatnonzero(weights_np > 0)
        if part.size == 0:
            return  # nobody reported: no commit, clients keep local params
        client_ids = np.asarray(self._slot_occupants)
        _, latency = population_report(self.chaos, round_idx, client_ids)
        latency = np.asarray(latency, np.float64)

        base_leaves, treedef = jax.tree_util.tree_flatten(round_start_global)
        stack_leaves = jax.tree_util.tree_flatten(self._agg_param_stacks())[0]

        k = self._agg_policy.quorum_for(int(part.size))
        order = part[np.argsort(latency[part], kind="stable")]
        on_time, late = order[:k], order[k:]
        quorum_lat = float(latency[order[k - 1]])
        max_lat = float(latency[order[-1]])

        codec = cfg.fed.dcn_compress

        def entry(slot: int) -> BufferEntry:
            wid = str(int(client_ids[slot]))
            leaves = [
                np.asarray(s[slot] - b)
                for s, b in zip(stack_leaves, base_leaves)
            ]
            ecodec = "none"
            if codec != "none":
                # per-contribution codecs decode at push with this
                # edge's banked error-feedback residual (riding the
                # buffer sidecar, so it survives checkpoint/restore);
                # linear sketches buffer raw and fold in sketch space
                banked = (
                    self.agg_buffer.residual_for(wid)
                    if cfg.fed.dcn_error_feedback
                    else None
                )
                leaves, ecodec, new_res, _ = encode_contribution(
                    leaves,
                    codec,
                    topk_ratio=cfg.fed.dcn_topk_ratio,
                    sketch_width=cfg.fed.dcn_sketch_width,
                    sketch_seed=cfg.fed.dcn_sketch_seed,
                    residual_leaves=banked,
                )
                if new_res is not None and cfg.fed.dcn_error_feedback:
                    self.agg_buffer.bank_residual(
                        wid, self._agg_version, new_res
                    )
            return BufferEntry(
                worker=wid,
                round=round_idx,
                epoch=self.agg_buffer.epoch,
                based_on=self._agg_version,
                weight=float(weights_np[slot]),
                arrival_ms=float(latency[slot]),
                leaves=leaves,
                codec=ecodec,
            )

        # prior rounds' stragglers fold into THIS commit (staleness >= 1)
        commit_entries = self.agg_buffer.take_all()
        commit_entries += [entry(int(s)) for s in on_time]
        # the stragglers' entries MUST capture the pre-commit version:
        # their deltas are against round_start_global, so based_on has to
        # be the version that global carried — building them after the
        # bump would under-count their staleness by one commit (full
        # instead of 1/(1+s) weight, cap off by one)
        late_entries = [entry(int(s)) for s in late]
        new_leaves, stats = fold_commit(
            base_leaves,
            commit_entries,
            self._agg_version,
            self._agg_policy,
            method=cfg.fed.robust.method,
            trim_k=cfg.fed.robust.trim_k,
            clip_norm=cfg.fed.robust.clip_norm,
            sketch_seed=cfg.fed.dcn_sketch_seed,
        )
        self._agg_version = stats.version
        self._g_agg_version.set(float(stats.version))
        for e in late_entries:
            self.agg_buffer.add(e)

        self._m_agg_commits.inc()
        self._m_agg_late.inc(float(stats.late_folds))
        self._m_agg_stale.inc(float(stats.stale_drops))
        self._g_agg_staleness.set(stats.mean_staleness)
        self._g_agg_quorum_wait.set(quorum_lat - float(latency[order[0]]))
        self._g_agg_gate_saved.set(max_lat - quorum_lat)
        self._g_agg_pending.set(float(len(self.agg_buffer)))

        new_u, new_n = jax.tree_util.tree_unflatten(treedef, new_leaves)
        if self.server_opt is not None:
            # identical update semantics: the commit output is a proposal,
            # exactly like the flat weighted mean (a zero-staleness
            # all-reporting commit IS that mean)
            new_u, new_n = self.server_opt.step(
                round_start_global, (new_u, new_n)
            )
        self.set_global_params(
            jax.tree_util.tree_map(jnp.asarray, new_u),
            jax.tree_util.tree_map(jnp.asarray, new_n),
        )

    @staticmethod
    def _round_loss_mean(mean_cells: np.ndarray, loss_cells: np.ndarray) -> float:
        """The round's train loss. Healthy rounds: the flat mean over the
        in-graph pmean cells — bit-identical to pre-robust reporting. When
        any cell is non-finite (a chaos/quarantined client), the pmean is
        NaN for EVERY client (the collective blends the poison), so the
        metric falls back to the mean over the finite PER-CLIENT loss
        cells: a NaN client's cells are the health sentry's signal
        (counted there), not the cohort's progress metric."""
        mean_cells = mean_cells.reshape(-1)
        if np.isfinite(mean_cells).all():
            return float(mean_cells.mean())
        loss_cells = loss_cells.reshape(-1)
        finite = loss_cells[np.isfinite(loss_cells)]
        return float(finite.mean()) if finite.size else float("nan")

    def _overflow_message(self, total: int) -> str:
        cfg = self.cfg
        policy = (
            f"data.unique_news_cap_buckets={cfg.data.unique_news_cap_buckets!r}"
            if cfg.data.unique_news_cap_buckets
            else f"data.unique_news_cap={cfg.data.unique_news_cap}"
        )
        return (
            f"{policy} overflowed on {total} step(s) this round — the "
            "capped unique-news dedup dropped ids and the gradients are "
            "invalid. Raise the cap (or set it to 0 for the exact "
            "worst-case bound)."
        )

    def _eval_if_due(self, result: RoundResult) -> None:
        """Round-cadence evaluation (train.eval_every), shared by the
        host-driven round and the rounds-in-jit chunk tail."""
        if self.valid_ix is None:
            return
        if (result.round_idx + 1) % self.cfg.train.eval_every != 0:
            return
        protocol = self.cfg.train.eval_protocol  # validated in __init__
        with self.tracer.span(
            "eval", round=result.round_idx, protocol=protocol
        ):
            # sliced-eval telemetry rides the full-pool protocols only —
            # the sampled protocol re-draws negatives per epoch, so its
            # per-slice numbers would carry sampling noise the banked
            # quality gate could never threshold against
            q = None
            if self.quality is not None and protocol in ("full", "last4"):
                q = self._begin_quality_eval()
            if protocol == "full":
                result.val_metrics = self.evaluate_full(_quality=q)
            elif protocol == "last4":
                result.val_metrics = self.evaluate_full(last_k=4, _quality=q)
            else:
                result.val_metrics = self.evaluate()
            if q is not None:
                self._finish_quality_eval(
                    result.round_idx, q, result.val_metrics
                )

    # ----------------------------------------------------- rounds-in-jit
    def _round_is_boundary(self, round_idx: int) -> bool:
        """True when host-side work is due AFTER this round — evaluation
        (eval_every), a snapshot (save_every / final round), or the end of
        training — so a compiled round chunk must not run past it."""
        cfg = self.cfg
        if round_idx >= cfg.fed.rounds - 1:
            return True
        if self.valid_ix is not None and (round_idx + 1) % cfg.train.eval_every == 0:
            return True
        if self.snapshots is not None and (round_idx + 1) % cfg.train.save_every == 0:
            return True
        return False

    def _round_chunk(self, round_idx: int) -> int:
        """How many rounds starting at ``round_idx`` may run in one
        compiled chunk: up to ``train.rounds_per_scan``, never crossing a
        cadence boundary (so checkpoint/eval behavior is byte-identical to
        the host-driven loop) — nor a quarantine expiry: the chunk's
        weights stack is built at entry, so a chunk outliving a quarantine
        would exclude the client past its configured
        ``fed.robust.quarantine_rounds`` and delay its healed rejoin."""
        if self.round_scan is None:
            return 1
        cap = self.cfg.train.rounds_per_scan
        if self._quarantine:
            cap = min(cap, min(self._quarantine.values()))
        n = 1
        while (
            n < cap
            and round_idx + n < self.cfg.fed.rounds
            and not self._round_is_boundary(round_idx + n - 1)
        ):
            n += 1
        return n

    def _train_rounds_scan(self, round_idx: int, num_rounds: int) -> list[RoundResult]:
        """Execute ``num_rounds`` whole federated rounds in ONE compiled
        dispatch via ``build_fed_round_scan`` — every local epoch's steps
        plus each round-end participation-weighted sync. The host builds
        the (rounds, steps, clients, ...) batch stack up front — straight
        off the batcher, no prefetcher: with a single dispatch at the end
        there is no device work to overlap the build with — so the device
        sees zero host round-trips until the chunk's final readback.

        Identical trajectory to ``train_round`` driven ``num_rounds``
        times: same step body, same sync policy, same per-round
        participation masks (same rng derivation) — pinned in
        ``tests/test_scan.py``.
        """
        import time as _time

        t0 = _time.perf_counter()
        # one cohort per CHUNK (the chunk's batch stack and state are fixed
        # at entry; per-round report weights still re-roll inside) — cohort
        # rotation under rounds-in-jit happens at chunk cadence, a
        # documented divergence from the host-driven per-round rotation
        self._ensure_cohort(round_idx)
        if self.perf is not None:
            self.perf.begin_round()
        chunk_span = self.tracer.span(
            "fed_round", step_num=round_idx, num_rounds=num_rounds,
            **self._round_span_args(),
        )
        chunk_annotation = jax.profiler.StepTraceAnnotation(
            "fed_round", step_num=round_idx
        )
        with chunk_span, chunk_annotation:
            results = self._train_rounds_scan_inner(round_idx, num_rounds)
            sample_device_memory(
                self.registry, self.tracer, fed_round=round_idx
            )
            self._perf_sample_components(round_idx)
        # the chunk is one dispatch; attribute its wall time evenly so the
        # per-round histogram stays comparable across dispatch modes
        wall = _time.perf_counter() - t0
        per_round = wall / num_rounds
        for _ in range(num_rounds):
            self._m_round_secs.observe(per_round)
        if self.perf is not None:
            # one digest per chunk (the chunk IS one dispatch); the log
            # keys ride every round of the chunk via _after_round
            self.perf.observe_round(round_idx, num_rounds, wall)
        return results

    def _train_rounds_scan_inner(
        self, round_idx: int, num_rounds: int
    ) -> list[RoundResult]:
        cfg = self.cfg
        tracer = self.tracer
        weights = np.stack([
            self._round_weights(r)
            for r in range(round_idx, round_idx + num_rounds)
        ])
        table = self._feature_table()
        if self.flightrec is not None:
            self.flightrec.start_chunk(
                round_idx, self._entry_state(),
                {round_idx + i: weights[i] for i in range(num_rounds)},
            )

        with tracer.span(
            "batch_build", kind="round_stack", rounds=num_rounds
        ):
            round_lists: list[list[dict]] = []
            steps: int | None = None
            for r in range(round_idx, round_idx + num_rounds):
                batches: list[dict] = []
                chaos_extra = self._chaos_batch_keys(r) or {}
                for local_epoch in range(cfg.fed.local_epochs):
                    epoch_idx = r * cfg.fed.local_epochs + local_epoch
                    # sampled world: slot j iterates the CHUNK cohort's
                    # client j's own shard (same source as the host-driven
                    # path — _ensure_cohort above fixed the occupancy)
                    for b in self._epoch_batches_source(epoch_idx):
                        batch = {
                            "candidates": b.candidates,
                            "history": b.history,
                            "labels": b.labels,
                            **chaos_extra,
                        }
                        if self.flightrec is not None:
                            self.flightrec.record(
                                batch, r, epoch_idx, len(batches)
                            )
                        batches.append(batch)
                if steps is None:
                    steps = len(batches)
                elif len(batches) != steps:
                    # static (rounds, steps) shapes are the contract; a
                    # varying per-epoch step count cannot stack
                    raise RuntimeError(
                        f"rounds-in-jit needs a constant steps-per-round, got "
                        f"{steps} then {len(batches)}"
                    )
                round_lists.append(batches)
            if not steps:
                raise ValueError(
                    "no batches: dataset smaller than num_clients*batch_size"
                )

        with tracer.span("h2d", n=num_rounds * steps):
            stacked = shard_round_batches(
                self.mesh, stack_rounds(round_lists), cfg
            )
        if self._perf_keep_batch:
            self._perf_last_batch = stacked
        self._count_steps(num_rounds * steps)
        with tracer.span(
            "dispatch", kind="round_chunk", rounds=num_rounds, steps=steps
        ):
            self.state, metrics = self.round_scan(
                self.state, stacked, table, jnp.asarray(weights)
            )
        if self.strategy.sync_params_every_round:
            self._m_robust_rounds.inc(num_rounds, method=cfg.fed.robust.method)
            self._count_uplink(weights)

        mean_loss = np.asarray(metrics["mean_loss"])  # (rounds, steps, clients)
        raw_loss = np.asarray(metrics["loss"])
        results = []
        for i in range(num_rounds):
            # same reduction as the host-driven round's loss bookkeeping
            results.append(
                RoundResult(
                    round_idx + i,
                    self._round_loss_mean(mean_loss[i], raw_loss[i]),
                )
            )
        # sentry digest first (see _train_round_inner): the health arrays
        # are already (rounds, steps, clients) in the chunk's metrics
        self._check_health(
            round_idx, metrics3d=metrics,
            round_losses=[r.train_loss for r in results],
        )
        if "unique_overflow" in metrics:
            # (rounds, steps, clients): max over clients (replicated psum
            # total), then count every overflowed step in the chunk
            total = int(
                np.asarray(metrics["unique_overflow"]).max(axis=-1).sum()
            )
            if total > 0:
                self._m_overflow.inc(total)
                raise RuntimeError(self._overflow_message(total))
        # only the chunk's last round can sit on an eval boundary
        # (_round_chunk guarantees it); earlier rounds get no metrics, same
        # as host-driven rounds off the eval cadence
        self._eval_if_due(results[-1])
        return results

    def evaluate(self, client: int | None = None) -> dict[str, float]:
        """Mean validation metrics over all impressions (fixes the reference's
        last-sample-only bug, ``client.py:171``).

        ``client=None`` (default) resolves the evaluation target explicitly:
        the client-0 fast path when all clients are in sync, else the mean
        of per-client metrics (see :meth:`_aggregate_eval` — VERDICT r2
        Weak #3). Pass an explicit ``client`` index to score one client.

        Candidates are 1 positive + ``npratio`` sampled negatives (the
        reference's per-epoch ``validate``, ``client.py:149-171``); batches
        keep one static shape, with the final batch's wrap-around padding
        trimmed from the mean. For the deterministic published-table protocol
        use :meth:`evaluate_full`.
        """
        assert self.valid_ix is not None, "no validation samples"
        if client is None:
            return self._aggregate_eval(lambda c: self.evaluate(client=c))
        user_params, news_params = self._client_params(client)
        table = self._corpus_for(news_params, client)
        n = len(self.valid_ix)
        bsz = min(n, 256)
        vb = TrainBatcher(
            self.valid_ix,
            batch_size=bsz,
            npratio=self.cfg.data.npratio,
            shuffle=False,
            drop_remainder=False,
            seed=0,
        )
        sums: dict[str, float] = {}
        count = 0
        for batch in vb.epoch_batches(0):
            out = self.eval_step(
                user_params,
                table,
                {
                    "candidates": batch.candidates,
                    "history": batch.history,
                    "labels": batch.labels,
                },
            )
            valid_n = min(bsz, n - count)  # trim wrap-around pad rows
            for k, v in out.items():
                sums[k] = sums.get(k, 0.0) + float(jnp.sum(v[:valid_n]))
            count += valid_n
        return {k: v / count for k, v in sums.items()}

    def evaluate_full(
        self,
        last_k: int | None = None,
        client: int | None = None,
        _quality: dict | None = None,
    ) -> dict[str, float]:
        """Deterministic evaluation over each impression's FULL negative pool.

        The protocol behind the reference's published MIND table (AUC 68.42
        etc. — full-pool ``evaluation_split``, reference
        ``evaluation_functions.py:33-47``). ``last_k`` keeps only each pool's
        LAST k negatives — ``last_k=4`` reproduces the reference client's
        deterministic per-round validation slice (``client.py:159-160``).

        ``client=None`` resolves like :meth:`evaluate`: client-0 fast path
        when clients are in sync, else mean of per-client metrics.

        Impressions with an empty (post-slice) pool are skipped, as the
        reference's try/except does. One compile: static (B, P) shapes with
        padding masked out of every mean.

        ``_quality`` (``_begin_quality_eval``'s session dict) routes the
        pass through the quality-instrumented eval step and folds each
        batch's per-impression metrics into the slice accumulator and the
        score/calibration sums.  Diverged cohorts accumulate EVERY
        client's pass into the one session — each client scores the same
        impression set, so pooling equals the mean-of-means the corpus
        metric reports.  ``None`` (the default, and always when
        ``obs.quality.enabled=false``) runs the pre-quality program
        untouched.
        """
        assert self.valid_ix is not None, "no validation samples"
        if client is None:
            return self._aggregate_eval(
                lambda c: self.evaluate_full(
                    last_k=last_k, client=c, _quality=_quality
                )
            )
        user_params, news_params = self._client_params(client)
        table = self._corpus_for(news_params, client)

        ix = self.valid_ix
        n = len(ix)
        pools = ix.neg_pools
        lens = ix.neg_lens.astype(np.int64)
        if last_k is not None:
            # keep each pool's last k real negatives, left-aligned: row i
            # becomes pools[i, max(0, len-k) : len] (+ right padding)
            p = min(last_k, pools.shape[1])
            start = np.maximum(lens - p, 0)[:, None]
            idx = np.minimum(start + np.arange(p)[None, :], pools.shape[1] - 1)
            pools = np.take_along_axis(pools, idx, axis=1)
            lens = np.minimum(lens, p)
        P = max(1, pools.shape[1])
        mask = (np.arange(P)[None, :] < lens[:, None]).astype(np.float32)

        bsz = min(n, 256)
        if self.mesh.size > 1:
            # the sharded step splits the batch axis over the mesh evenly
            bsz = max(self.mesh.size, bsz - bsz % self.mesh.size)
        pad = (-n) % bsz
        def _pad(a):
            return np.concatenate([a, np.repeat(a[:1], pad, axis=0)]) if pad else a

        pos_a = _pad(ix.pos)
        pools_a = _pad(pools.astype(np.int32))
        mask_a = _pad(mask)
        his_a = _pad(ix.history)
        keep_a = _pad((lens > 0).astype(np.float32))
        if pad:
            keep_a[n:] = 0.0  # padded rows never count

        step = self.full_eval_step if _quality is None else self.full_eval_step_q
        if _quality is not None:
            # one pass per evaluated client: _finish_quality_eval divides
            # the pooled counts back down so published impression counts
            # stay per-validation-set (the n the noise threshold is quoted
            # against), not ×clients on a diverged cohort
            _quality["passes"] = _quality.get("passes", 0) + 1
        sums = {k: 0.0 for k in ("auc", "mrr", "ndcg5", "ndcg10")}
        kept = 0.0
        for b in range(0, n + pad, bsz):
            sl = slice(b, b + bsz)
            batch = {
                "pos": pos_a[sl],
                "neg_pools": pools_a[sl],
                "neg_mask": mask_a[sl],
                "history": his_a[sl],
            }
            if _quality is not None:
                batch["keep"] = keep_a[sl]
            out = step(user_params, table, batch)
            w = keep_a[sl]
            for k in sums:
                sums[k] += float(jnp.sum(out[k] * w))
            kept += float(w.sum())
            if _quality is not None:
                from fedrec_tpu.eval.metrics import QUALITY_SUM_KEYS

                _quality["acc"].add(
                    b, {k: np.asarray(out[k]) for k in sums}, np.asarray(w)
                )
                qs = _quality["sums"]
                for k in QUALITY_SUM_KEYS:
                    qs[k] = qs.get(k, 0.0) + np.asarray(out[k], np.float64)
        if kept == 0:
            raise ValueError("no impression has a non-empty negative pool")
        return {k: v / kept for k, v in sums.items()}

    # ------------------------------------------------------- quality layer
    def _begin_quality_eval(self) -> dict:
        """One sliced-eval session: the slice accumulator (definitions
        built once per run — fixed, seeded) plus the score/calibration
        partial-sum dict the eval loop folds batches into."""
        from fedrec_tpu.obs.quality import (
            SlicedEvalAccumulator,
            build_slice_defs,
        )

        if self._slice_defs is None:
            self._slice_defs = build_slice_defs(
                self.valid_ix, self.cfg.obs.quality
            )
        return {
            "acc": SlicedEvalAccumulator(self._slice_defs, len(self.valid_ix)),
            "sums": {},
        }

    def _finish_quality_eval(
        self, round_idx: int, q: dict, val_metrics: dict[str, float]
    ) -> None:
        """Publish the session: per-slice gauges (+ skip counter), the
        corpus quartet under ``slice="all"``, the score/calibration
        digest, and the per-client quality-outlier digest (informational —
        composes with quarantine's ignore set, never triggers it)."""
        slices, skipped = q["acc"].finalize()
        # a diverged cohort pooled every client's pass into the session:
        # the weighted MEANS are invariant (each pass covers the same
        # impression set), but the raw counts/sums are ×passes — scale
        # them back so every published n means validation impressions
        passes = max(int(q.get("passes", 1)), 1)
        if passes > 1:
            for m in slices.values():
                m["count"] /= passes
            q["sums"] = {k: v / passes for k, v in q["sums"].items()}
        self.quality.publish_slices(slices, skipped)
        # the category family partitions the impression set, so its counts
        # sum to the kept (scoreable) total — the honest n for slice="all"
        kept = sum(
            m["count"] for n, m in slices.items() if n.startswith("category=")
        ) or float(len(self.valid_ix))
        self.quality.publish_corpus(val_metrics, count=kept)
        if q["sums"]:
            self.quality.publish_distribution(q["sums"])
        if self.cfg.obs.quality.per_client:
            outliers = self.quality.digest_clients(
                round_idx,
                self.last_per_client_metrics,
                ignore_clients=set(self._quarantine),
                shared=val_metrics,
            )
            # surfaced on the HealthMonitor next to the norm-based flags
            # (one triage surface); informational — never a trigger
            self.health.last_quality_outliers = outliers
            if self.watch is not None:
                self.watch.ingest_quality_outliers(outliers)

    # ------------------------------------------------------------------
    def run(self) -> list[RoundResult]:
        cfg = self.cfg
        history: list[RoundResult] = []
        from fedrec_tpu.fed.population import QuorumFailure

        # train.profile traces land inside obs.dir when one is configured
        # (discoverable next to the artifact trio) instead of the
        # hardcoded /tmp default; the logdir is pointed to from
        # metrics.jsonl either way a trace was captured
        profile_logdir = (
            str(self._obs_dir / "jax_profile")
            if cfg.train.profile and self._obs_dir is not None
            else None
        )
        try:
            with profile_if(cfg.train.profile, profile_logdir) as plogdir:
                if plogdir is not None and self._obs_dir is not None:
                    import time as _time

                    from fedrec_tpu.obs.perf import append_jsonl_record

                    append_jsonl_record(self._obs_dir / "metrics.jsonl", {
                        "kind": "profile_trace",
                        "logdir": plogdir,
                        "ts": _time.time(),
                    })
                round_idx = self.start_round
                while round_idx < cfg.fed.rounds:
                    # rounds-in-jit: chunks of up to train.rounds_per_scan
                    # rounds in one dispatch, always breaking at eval/save
                    # cadence boundaries so the host-side bookkeeping below
                    # sees exactly the rounds it would host-driven
                    chunk = self._round_chunk(round_idx)
                    if self.perf is not None:
                        # capture windows open at the dispatch boundary —
                        # a window intersecting this round/chunk starts a
                        # jax.profiler trace under obs.dir
                        self.perf.capture_before_round(round_idx, chunk)
                    # rollback target: the state every client held at
                    # round/chunk entry — one blocking host copy per round
                    # is the price of replayability (same cost profile as
                    # obs.health.snapshot_state); no-op unless recover
                    self._capture_recovery_state()
                    try:
                        if chunk > 1:
                            results = self._train_rounds_scan(round_idx, chunk)
                        else:
                            results = [self.train_round(round_idx)]
                    except RoundRecovery as e:
                        self._rollback_and_quarantine(e.trigger, round_idx)
                        continue  # replay the same round/chunk
                    except QuorumFailure as e:
                        # raised BEFORE any dispatch (weights are built at
                        # round/chunk entry), so the round's entry state
                        # was never left — replay is a fresh cohort draw
                        self._handle_quorum_failure(e, round_idx)
                        continue
                    self._round_retries = 0
                    for result in results:
                        history.append(result)
                        # commit BEFORE _after_round: a save-cadence
                        # snapshot's population sidecar must describe the
                        # schedule INCLUDING this round
                        self._commit_population(result.round_idx)
                        self._after_round(result)
                        self._tick_quarantine()
                    if self.perf is not None:
                        # the window closes AFTER the round's host-side
                        # bookkeeping so checkpoint/eval cost is captured
                        self.perf.capture_after_round(
                            round_idx + len(results) - 1
                        )
                    round_idx += len(results)
            if self.snapshots is not None:
                self.snapshots.wait()  # settle async saves before handing back
        except BaseException as e:
            # forensics on EVERY failing exit path: an exception that never
            # reached a round-end health check (dispatch error, cap
            # overflow) still dumps the batch ring + chunk-entry state
            self._flightrec_on_exception(e)
            raise
        finally:
            # a still-open perf capture window must stop (and write its
            # pointer record) on every exit path, before the artifact
            # dump below appends the final registry snapshot — and the
            # retained HBM-attribution batch must not outlive the run
            if self.perf is not None:
                self.perf.close()
                self._perf_last_batch = None
            # artifacts on EVERY exit path: a run that died to a cap
            # overflow (or any mid-round error) is exactly the run whose
            # trace/registry state is needed — and the failing round never
            # reached its _after_round snapshot
            if self._obs_dir is not None:
                try:
                    paths = dump_artifacts(
                        self._obs_dir, registry=self.registry,
                        tracer=self.tracer,
                    )
                    print(
                        f"[trainer] obs artifacts: {paths['metrics']} "
                        f"{paths['trace']} {paths['prometheus']}"
                    )
                except Exception as e:  # noqa: BLE001 — never mask the training error
                    print(f"[trainer] could not write obs artifacts: "
                          f"{type(e).__name__}: {e}")
            if self.fleet_pusher is not None:
                # final push on every exit path (never raises; a dead
                # collector only counts a failure)
                self.fleet_pusher.push(final=True)
            try:
                self.logger.finish()
            except Exception as e:  # noqa: BLE001 — a wandb flush error must
                # not displace the exception that actually ended training
                print(f"[trainer] logger.finish failed: "
                      f"{type(e).__name__}: {e}")
        return history

    def _after_round(self, result: RoundResult) -> None:
        """Per-round host bookkeeping: metric logging, best-AUC snapshot,
        cadence snapshots (+ FedOpt sidecar)."""
        cfg = self.cfg
        round_idx = result.round_idx
        self._m_rounds.inc()
        self._m_round_loss.set(result.train_loss)
        log = {"round": round_idx, "training_loss": result.train_loss}
        if self._eps_schedule is not None:
            # rounds completed so far INCLUDING resumed ones: the privacy
            # budget composes over the whole trajectory, not this process's
            # uptime
            eps = self._eps_schedule(round_idx + 1)
            self._m_eps.set(eps)
            log["privacy.epsilon_spent"] = round(eps, 6)
        if self.perf is not None and self.perf.last_round is not None:
            # the latest round/chunk digest rides the per-round record —
            # the MFU trend fedrec-obs perf renders (a chunk's rounds all
            # carry the chunk digest; num_rounds disambiguates in-trace)
            log.update({
                k: v for k, v in self.perf.last_round.items() if k != "round"
            })
        if result.val_metrics:
            # ONE key scheme (val_<metric>), Prometheus-sanitizable as-is —
            # the historical valid_auc/valid_mrr vs val_ndcg@5 mix forced
            # every reader to know both spellings and the '@' keys to be
            # mangled on exposition. fedrec-obs report keeps a legacy-key
            # fallback so pre-rename artifacts still render.
            named = {
                "validation_loss": result.val_metrics.get("loss"),
                "val_auc": result.val_metrics.get("auc"),
                "val_mrr": result.val_metrics.get("mrr"),
                "val_ndcg5": result.val_metrics.get("ndcg5"),
                "val_ndcg10": result.val_metrics.get("ndcg10"),
            }
            # the full-pool protocols have no loss key — omit, don't
            # log null
            log.update({k: v for k, v in named.items() if v is not None})
        self.logger.log(round_idx, log)
        auc = (
            result.val_metrics.get("auc")
            if result.val_metrics else None
        )
        if (
            self.best_snapshots is not None
            and auc is not None
            and (self._best_auc is None or auc > self._best_auc)
        ):
            import json as _json

            from fedrec_tpu.train.checkpoint import atomic_write_bytes

            # a failed best-write must not kill training (the
            # round-cadence config.json persistence has the same
            # policy) and must not advance _best_auc — a later
            # round between the persisted and the failed best
            # still deserves a save
            try:
                # blocking: the marker must never describe a
                # snapshot that is still in flight
                with self.tracer.span(
                    "checkpoint", round=round_idx, kind="best"
                ):
                    self.best_snapshots.save(
                        round_idx, self.state, wait=True
                    )
                atomic_write_bytes(
                    self.best_snapshots.directory / "best.json",
                    _json.dumps(
                        {"round": round_idx, "auc": float(auc)}
                    ).encode(),
                )
                atomic_write_bytes(
                    self.best_snapshots.directory / "config.json",
                    cfg.to_json().encode(),
                )
                self._best_auc = float(auc)
            except OSError as e:
                print(
                    f"[trainer] could not persist best snapshot "
                    f"at round {round_idx}: {e}"
                )
        if self.snapshots is not None and (
            (round_idx + 1) % cfg.train.save_every == 0
            or round_idx == cfg.fed.rounds - 1
        ):
            # blocking save under FedOpt: the sidecar must never be
            # newer than the orbax snapshot it pairs with (a crash
            # between an async save and the sidecar write would
            # resume round-r momentum against round r-k params)
            with self.tracer.span(
                "checkpoint", round=round_idx, kind="cadence"
            ):
                # blocking also under the cohort engine: the population
                # sidecar (like FedOpt's) must never be newer than the
                # snapshot it pairs with, or a crash between the two
                # resumes round-r cohort schedule against round r-k params
                self.snapshots.save(
                    round_idx, self.state,
                    wait=self.server_opt is not None or self._pop_engine
                    or self._agg_async,
                )
                if self.server_opt is not None:
                    from fedrec_tpu.train.checkpoint import atomic_write_bytes

                    atomic_write_bytes(
                        self.snapshots.directory / "server_opt_state.msgpack",
                        self.server_opt.state_bytes(round_idx),
                    )
                if self._agg_async:
                    # buffered late contributions pair with THIS snapshot:
                    # same blocking discipline as the FedOpt sidecar (the
                    # sidecar must never be newer than the snapshot, or a
                    # crash between the two would fold round-r late deltas
                    # against round r-k params on resume)
                    from fedrec_tpu.agg.buffer import AGG_BUFFER_SIDECAR
                    from fedrec_tpu.train.checkpoint import atomic_write_bytes

                    atomic_write_bytes(
                        self.snapshots.directory / AGG_BUFFER_SIDECAR,
                        self.agg_buffer.state_bytes(
                            round_idx, self._agg_version
                        ),
                    )
                if self._pop_engine:
                    from fedrec_tpu.train.checkpoint import (
                        POPULATION_SIDECAR,
                        atomic_write_bytes,
                        population_state_bytes,
                    )

                    atomic_write_bytes(
                        self.snapshots.directory / POPULATION_SIDECAR,
                        population_state_bytes(
                            self.cohort_sampler.state_dict(),
                            self.population.ledger.state_dict(),
                            self._slot_occupants,
                            self._slot_writeback,
                            round_idx,
                        ),
                    )
                if self.table_spec is not None and self.token_states is not None:
                    # sharded-catalog recovery source: the TRUE rows,
                    # host-gathered, written ONCE (the table is frozen in
                    # table/head modes) — a shrink that loses a shard's
                    # row blocks reloads them from here instead of losing
                    # them (shard.table.recover_table_rows)
                    from fedrec_tpu.train.checkpoint import (
                        NEWS_TABLE_CHECKPOINT,
                        gather_for_save,
                        save_table_checkpoint,
                    )

                    tbl_path = (
                        self.snapshots.directory / NEWS_TABLE_CHECKPOINT
                    )
                    if not tbl_path.exists():
                        rows = np.asarray(
                            gather_for_save(self.token_states)
                        )[: self.table_spec.num_rows]
                        save_table_checkpoint(self.snapshots.directory, rows)
        if (
            self._obs_dir is not None
            and (round_idx + 1) % max(cfg.obs.snapshot_every, 1) == 0
        ):
            # size-based rotation before the append (obs.jsonl_max_mb):
            # snapshots are the event log's bulk on long runs
            rotate_jsonl(self._obs_dir / "metrics.jsonl", cfg.obs.jsonl_max_mb)
            self.registry.write_snapshot(self._obs_dir / "metrics.jsonl")
        if self.watch is not None:
            # one watch tick per round, fed the round's log record, BEFORE
            # the fleet push so this round's transitions ride this push
            self.watch.evaluate(record=log)
        if self.fleet_pusher is not None:
            self.fleet_pusher.maybe_push(round_idx)
