from fedrec_tpu.train.state import ClientState, init_client_state, stack_states
from fedrec_tpu.train.step import (
    build_eval_step,
    build_fed_train_scan,
    build_fed_train_step,
    build_full_eval_step,
    build_full_eval_step_sharded,
    build_news_update_step,
    build_param_sync,
    encode_all_news,
    encode_all_news_sharded,
    shard_scan_batches,
    stack_batches,
)

__all__ = [
    "ClientState",
    "build_eval_step",
    "build_full_eval_step",
    "build_full_eval_step_sharded",
    "build_fed_train_scan",
    "build_fed_train_step",
    "build_news_update_step",
    "build_param_sync",
    "encode_all_news",
    "encode_all_news_sharded",
    "init_client_state",
    "shard_scan_batches",
    "stack_batches",
    "stack_states",
]
