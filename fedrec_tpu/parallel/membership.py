"""Elastic membership: epoch-based world formation over heartbeat leases.

The coordinator deployment's world size was STATIC: ``--num-processes N``
is baked into the jax.distributed rendezvous, so a dead peer either
degrades every survivor to standalone training (N independent forks — the
pre-elastic behavior documented in ``parallel/multihost.py``) or, under
``--supervise``, the whole world respawn-loops until the dead peer comes
back. Neither shrinks. Production federated systems treat membership as
dynamic (PAPERS.md: "Scaling Federated Learning for Fine-tuning of Large
Language Models"); this module supplies the missing control plane:

* :class:`MembershipServer` — a tiny threaded TCP JSON-lines service (the
  same wire idiom as the serving admin channel) owning a **monotonically
  increasing membership epoch**. Each worker holds a heartbeat **lease**;
  an expired lease marks the current epoch stale. Epoch *e+1* forms from
  the workers that have (re-)joined: immediately when the full target
  complement is back, or after ``formation_grace_ms`` with at least
  ``min_world`` joiners (the **shrink-and-continue** path). A join that
  arrives while an epoch is healthy flags a **reform**, which the epoch's
  rank-0 worker broadcasts to the whole world at the next round boundary
  (the rejoin path — see ``CoordinatorRuntime.start_round``).

* :class:`MembershipClient` — blocking calls (``join``/``heartbeat``/
  ``leave``/``status``) plus a daemon lease-renewal thread. The join
  assignment carries ``(epoch, rank, world, coordinator_address)``; the
  coordinator address is the rank-0 worker's OWN pre-bound candidate, a
  FRESH port per epoch, so a respawned worker can never re-exec into the
  previous (dying) world's rendezvous — the failure the pre-elastic
  supervisor could only retry through.

Ranks are dense ``0..world-1``, assigned by sorting stable worker ids
(numeric ids numerically), so a surviving worker keeps the lowest ranks
and the server role (rank 0) moves only when the previous rank-0 died.
Worker identity is the supervisor-stable ``--process-id``; snapshots are
keyed by it (``local_state_w<ID>``), not by the per-epoch rank.

The degenerate contract: a deployment that never passes ``--membership``
never touches this module — byte-identical behavior to the fixed world.

Run standalone (the elastic smoke's service process)::

    python -m fedrec_tpu.parallel.membership 127.0.0.1:9123 \
        --target-world 4 --lease-ms 6000 --formation-grace-ms 4000
"""

from __future__ import annotations

import socket
import threading
import time
from dataclasses import dataclass, field


def _now() -> float:
    return time.monotonic()


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port on ``host`` — the joiner's coordinator
    candidate. The tiny bind->release race (another process grabbing the
    port before jax binds it) is covered by the bounded rendezvous retry:
    a failed bring-up re-joins and draws a fresh port."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


@dataclass(frozen=True)
class EpochAssignment:
    """One worker's seat in one membership epoch."""

    epoch: int
    rank: int
    world: int
    coordinator: str        # host:port of THIS epoch's jax rendezvous
    lease_ms: float
    heartbeat_ms: float

    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch, "rank": self.rank, "world": self.world,
            "coordinator": self.coordinator, "lease_ms": self.lease_ms,
            "heartbeat_ms": self.heartbeat_ms,
        }


def _rank_order(worker_ids) -> list[str]:
    """Dense rank assignment: numeric ids sort numerically (worker "0"
    keeps rank 0 while it lives), non-numeric ids lexically after."""
    def key(w: str):
        try:
            return (0, int(w), w)
        except ValueError:
            return (1, 0, w)

    return sorted(worker_ids, key=key)


@dataclass
class _Member:
    worker: str
    expires_at: float
    rank: int


@dataclass
class _Joiner:
    worker: str
    coord_candidate: str
    arrived_at: float
    event: threading.Event = field(default_factory=threading.Event)
    assignment: EpochAssignment | None = None


class MembershipServer:
    """The epoch/lease bookkeeper. One instance per federation.

    Thread model: one listener thread accepts connections and answers each
    request inline (requests are tiny; ``join`` parks the connection's
    thread on an event until formation), plus one reaper thread that
    expires leases and closes formation windows. All state behind one
    lock; formation is the only compound transition.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        target_world: int = 0,
        min_world: int | None = None,
        lease_ms: float | None = None,
        heartbeat_ms: float | None = None,
        formation_grace_ms: float | None = None,
        collector=None,
        obs_dir: str | None = None,
    ):
        self.host = host
        self.port = port
        self.target_world = int(target_world)
        # fleet telemetry riding the membership port (one control-plane
        # address per federation): a fedrec_tpu.obs.fleet
        # TelemetryCollector answers telemetry_push/telemetry_status here
        self.collector = collector
        # the service's OWN obs artifact trio (metrics.jsonl/trace.json/
        # prometheus.txt) — its shrink/rejoin/lease counters used to be
        # visible only second-hand through worker mirror gauges
        self.obs_dir = obs_dir
        # None = adopt from the first join request that carries a policy
        # (the workers' shared ``fed.elastic`` section is then the ONE
        # source of lease/formation policy); an explicit server-side value
        # wins over every joiner
        self._min_world = min_world
        self._lease_ms = lease_ms
        self._heartbeat_ms = heartbeat_ms
        self._formation_grace_ms = formation_grace_ms
        self._lock = threading.Lock()
        self.epoch = -1                       # no world formed yet
        self._members: dict[str, _Member] = {}
        self._joiners: dict[str, _Joiner] = {}
        self._window_opened: float | None = None
        self._reform_needed = False
        # ---- counters the status/report surface exposes
        self.shrinks = 0
        self.rejoins = 0
        self.lease_misses = 0
        self.epoch_history: list[dict] = []   # [{"epoch": e, "world": n}]
        self._srv: socket.socket | None = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._instrument()

    # ------------------------------------------------- effective policy
    @property
    def min_world(self) -> int:
        return max(int(self._min_world or 1), 1)

    @property
    def lease_ms(self) -> float:
        return float(self._lease_ms or 15000.0)

    @property
    def heartbeat_ms(self) -> float:
        return float(self._heartbeat_ms or 5000.0)

    @property
    def formation_grace_ms(self) -> float:
        return float(self._formation_grace_ms or 10000.0)

    def _adopt_policy_locked(self, policy: dict) -> None:
        """Fill any server-side ``None`` policy knob from a joiner's
        ``fed.elastic`` section — first writer wins, explicit server
        flags always win (they are not ``None``)."""
        if self._lease_ms is None and policy.get("lease_ms"):
            self._lease_ms = float(policy["lease_ms"])
        if self._heartbeat_ms is None and policy.get("heartbeat_ms"):
            self._heartbeat_ms = float(policy["heartbeat_ms"])
        if (
            self._formation_grace_ms is None
            and policy.get("formation_grace_ms")
        ):
            self._formation_grace_ms = float(policy["formation_grace_ms"])
        if self._min_world is None and policy.get("min_world"):
            self._min_world = int(policy["min_world"])

    # --------------------------------------------------------------- obs
    def _instrument(self) -> None:
        """The service's registry instruments — REAL monotonic counters
        in its own process (the worker-side mirror gauges these replace
        under-reported across worker respawns; see
        docs/OBSERVABILITY.md, Membership)."""
        from fedrec_tpu.obs import get_registry, get_tracer

        reg = get_registry()
        self._tracer = get_tracer()
        self._m_shrinks = reg.counter(
            "fed.membership_shrinks_total",
            "epochs that formed SMALLER than their predecessor "
            "(shrink-and-continue events; service-owned)",
        )
        self._m_rejoins = reg.counter(
            "fed.membership_rejoins_total",
            "workers that re-entered a later epoch after missing one "
            "(service-owned)",
        )
        self._m_lease_misses = reg.counter(
            "fed.membership_lease_misses_total",
            "heartbeat leases the service expired — the failure detector "
            "firing (service-owned)",
        )
        self._g_epoch = reg.gauge(
            "fed.membership_epoch",
            "membership epoch this worker's world formed at",
        )
        self._g_world = reg.gauge(
            "fed.membership_world",
            "world size of this worker's membership epoch",
        )

    def dump_obs(self) -> None:
        """Write/refresh the service's artifact trio (no-op without
        ``obs_dir``); called on membership-state changes by the
        standalone main loop and on shutdown, so the membership timeline
        is inspectable while the federation is still running.  The event
        log is size-rotated (one ``.1`` level, same policy as
        ``obs.jsonl_max_mb``) so a long-lived control plane cannot grow
        it without bound."""
        if not self.obs_dir:
            return
        from pathlib import Path

        from fedrec_tpu.obs import dump_artifacts, rotate_jsonl

        try:
            rotate_jsonl(Path(self.obs_dir) / "metrics.jsonl", 64.0)
            dump_artifacts(self.obs_dir)
        except OSError:
            pass  # a full disk must not take the control plane down

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "MembershipServer":
        srv = socket.create_server((self.host, self.port))
        srv.settimeout(0.5)
        self._srv = srv
        self.port = srv.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True)
        r = threading.Thread(target=self._reaper_loop, daemon=True)
        t.start()
        r.start()
        self._threads = [t, r]
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2.0)
        # release any parked joiners so their worker threads exit
        with self._lock:
            for j in self._joiners.values():
                j.event.set()
        self.dump_obs()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # ------------------------------------------------------------- serving
    def _accept_loop(self) -> None:
        assert self._srv is not None
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        # ONE wire-protocol implementation for the control plane: the
        # shared JSON-lines exchange (obs.fleet also fronts the telemetry
        # collector with it, so the two servers cannot drift). The long
        # timeout is membership-specific: a ``join`` parks the
        # connection's thread until epoch formation.
        from fedrec_tpu.obs.fleet import serve_json_line

        serve_json_line(conn, self._handle, timeout_s=300.0)

    def _handle(self, req: dict) -> dict:
        cmd = req.get("cmd")
        if cmd in ("telemetry_push", "telemetry_status"):
            if self.collector is None:
                return {
                    "error": "no telemetry collector attached — start the "
                             "service with --telemetry-dir (or run a "
                             "standalone obs.fleet CollectorServer)"
                }
            return self.collector.handle(req)
        if cmd == "heartbeat":
            return self._heartbeat(str(req["worker"]), int(req.get("epoch", -1)))
        if cmd == "join":
            return self._join(
                str(req["worker"]), str(req.get("coord", "")),
                req.get("policy") or {},
            )
        if cmd == "leave":
            return self._leave(str(req["worker"]))
        if cmd == "status":
            return self.status()
        return {"error": f"unknown cmd {cmd!r}"}

    # ------------------------------------------------------------ protocol
    def _heartbeat(self, worker: str, epoch: int) -> dict:
        with self._lock:
            m = self._members.get(worker)
            if m is not None and epoch == self.epoch:
                m.expires_at = _now() + self.lease_ms / 1e3
            # a heartbeat from a stale epoch gets reform=True: that worker
            # missed a formation and must leave/rejoin
            reform = self._reform_needed or epoch != self.epoch
            return {"epoch": self.epoch, "reform": bool(reform)}

    def _join(self, worker: str, coord: str, policy: dict) -> dict:
        self._tracer.instant("membership_worker_join", worker=str(worker))
        with self._lock:
            self._adopt_policy_locked(policy)
            j = _Joiner(worker=worker, coord_candidate=coord, arrived_at=_now())
            self._joiners[worker] = j
            # joining supersedes any live lease (the worker left its world)
            self._members.pop(worker, None)
            if self._members and self.epoch >= 0:
                # someone knocking while members are still live: a NEW
                # worker wanting in, or a member's fast respawn whose old
                # incarnation died before its lease expired — either way
                # the live world must reform at its next round boundary
                # (during a mass reformation this is a no-op: the flag is
                # already set and formation clears it)
                self._reform_needed = True
            if self._window_opened is None:
                self._window_opened = _now()
            self._maybe_form_locked()
        # park outside the lock until formation (or stop/supersession)
        deadline = _now() + 3600.0
        while not j.event.wait(timeout=0.2):
            if self._stop.is_set() or _now() > deadline:
                return {"error": "membership server stopping"}
            with self._lock:
                if self._joiners.get(worker) is not j:
                    # the worker timed out client-side and re-joined: the
                    # NEW join owns the seat; this connection's thread must
                    # exit instead of polling the lock for up to an hour
                    return {"error": "join superseded by a newer join "
                                     "from this worker"}
                self._maybe_form_locked()
        if j.assignment is None:
            return {"error": "membership server stopping"}
        return j.assignment.to_dict()

    def _leave(self, worker: str) -> dict:
        with self._lock:
            self._members.pop(worker, None)
            j = self._joiners.pop(worker, None)
            if j is not None:
                j.event.set()
            # a clean leave of the FINAL member is a finished run, not a
            # death: no reform, no shrink accounting
            return {"ok": True, "epoch": self.epoch}

    # ----------------------------------------------------------- formation
    def _expected_world(self) -> int:
        """How many joiners formation waits for before the grace window
        closes: the full target complement (every configured worker back)
        or, once a smaller epoch exists, everyone known-alive."""
        if self.target_world > 0:
            return self.target_world
        return max(len(self._members) + len(self._joiners), self.min_world)

    def _maybe_form_locked(self) -> None:
        n = len(self._joiners)
        if n == 0 or self._window_opened is None:
            return
        window_s = self.formation_grace_ms / 1e3
        full = n >= self._expected_world()
        # live members that have NOT re-joined yet: forming now would
        # orphan them mid-round — wait for them to reach their boundary
        # (their leases go stale if they died; the reaper prunes them)
        missing_live = [w for w in self._members if w not in self._joiners]
        if not full and (missing_live or _now() - self._window_opened < window_s):
            return
        if n < self.min_world:
            return
        self._form_locked()

    def _form_locked(self) -> None:
        joiners = dict(self._joiners)
        order = _rank_order(joiners)
        prev = self.epoch_history[-1] if self.epoch_history else None
        prev_world = prev["world"] if prev else 0
        prev_set = set(prev.get("workers", ())) if prev else set()
        self.epoch += 1
        world = len(order)
        coordinator = joiners[order[0]].coord_candidate or "127.0.0.1:0"
        expires = _now() + self.lease_ms / 1e3
        self._members = {
            w: _Member(worker=w, expires_at=expires, rank=r)
            for r, w in enumerate(order)
        }
        if self.epoch > 0:
            if world < prev_world:
                self.shrinks += 1
                self._m_shrinks.inc()
            rejoined = set(order) - prev_set
            if prev_set and rejoined:
                self.rejoins += len(rejoined)
                self._m_rejoins.inc(len(rejoined))
        self.epoch_history.append(
            {"epoch": self.epoch, "world": world, "workers": list(order)}
        )
        self._g_epoch.set(float(self.epoch))
        self._g_world.set(float(world))
        # the formation instant is the merged fleet trace's membership
        # timeline (kill -> shrink -> rejoin reads straight off the track)
        self._tracer.instant(
            "membership_epoch_formed",
            epoch=self.epoch, world=world, workers=list(order),
        )
        self._joiners.clear()
        self._window_opened = None
        self._reform_needed = False
        for r, w in enumerate(order):
            j = joiners[w]
            j.assignment = EpochAssignment(
                epoch=self.epoch, rank=r, world=world,
                coordinator=coordinator, lease_ms=self.lease_ms,
                heartbeat_ms=self.heartbeat_ms,
            )
            j.event.set()

    def _reaper_loop(self) -> None:
        while not self._stop.wait(
            # per-iteration: the lease policy may arrive with the first join
            timeout=max(self.lease_ms / 4e3, 0.05)
        ):
            with self._lock:
                now = _now()
                dead = [w for w, m in self._members.items()
                        if m.expires_at < now]
                for w in dead:
                    del self._members[w]
                    self.lease_misses += 1
                    self._m_lease_misses.inc()
                    self._tracer.instant(
                        "membership_lease_expired", worker=str(w)
                    )
                    self._reform_needed = True
                self._maybe_form_locked()

    # -------------------------------------------------------------- status
    def status(self) -> dict:
        with self._lock:
            return {
                "epoch": self.epoch,
                "world": len(self._members),
                "members": sorted(self._members),
                "pending": sorted(self._joiners),
                "reform_pending": bool(self._reform_needed),
                "shrinks": self.shrinks,
                "rejoins": self.rejoins,
                "lease_misses": self.lease_misses,
                "epoch_history": [
                    {"epoch": h["epoch"], "world": h["world"]}
                    for h in self.epoch_history
                ],
            }


# ------------------------------------------------------------------ client
class MembershipError(RuntimeError):
    """The membership service refused or could not answer a request."""


class MembershipClient:
    """One worker's view of the membership service.

    All calls are one-shot request/response over a fresh TCP connection
    (the service is a control plane at round cadence, not a data path).
    ``start_heartbeat`` runs the lease-renewal daemon; ``reform_pending``
    is the latched flag the epoch's rank-0 worker reads at each round
    boundary to trigger the reformation broadcast.
    """

    def __init__(
        self,
        address: str,
        worker_id: str,
        join_timeout_s: float = 180.0,
        rpc_timeout_s: float = 10.0,
    ):
        host, port = address.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.worker_id = str(worker_id)
        self.join_timeout_s = float(join_timeout_s)
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.assignment: EpochAssignment | None = None
        self._reform = threading.Event()
        self._stop = threading.Event()
        self._hb_thread: threading.Thread | None = None
        self.heartbeat_failures = 0

    # ---------------------------------------------------------------- rpcs
    def _call(self, req: dict, timeout_s: float | None = None) -> dict:
        timeout = timeout_s if timeout_s is not None else self.rpc_timeout_s
        # the shared client wire helper (obs.fleet also pushes telemetry
        # with it): transport failures surface as OSError, protocol /
        # {"error": ...} replies as ValueError -> MembershipError.  The
        # helper also carries the obs.wire trace-context envelope, so
        # every control-plane edge (join/heartbeat/leave) gets per-edge
        # RTT + clock-offset telemetry for free
        from fedrec_tpu.obs.fleet import request_json_line

        try:
            return request_json_line(self.host, self.port, req, timeout)
        except ValueError as e:
            raise MembershipError(str(e)) from e

    def _local_host_toward_service(self) -> str:
        """The local interface address that ROUTES TO the membership
        service — the right host to advertise in this worker's
        jax-rendezvous candidate. Loopback only when the service itself is
        on loopback; on a multi-machine federation this is the worker's
        routable address, so a non-rank-0 peer can actually reach the
        epoch's coordinator."""
        try:
            with socket.create_connection(
                (self.host, self.port), timeout=self.rpc_timeout_s
            ) as s:
                return s.getsockname()[0]
        except OSError:
            return "127.0.0.1"

    def join(
        self,
        coord_candidate: str | None = None,
        policy: dict | None = None,
    ) -> EpochAssignment:
        """Block until the next epoch forms and return this worker's seat.
        ``coord_candidate`` is a ``host:port`` this worker could host the
        jax rendezvous on (rank 0's candidate wins); defaults to a fresh
        port on the interface that routes to the membership service.
        ``policy`` carries the worker's ``fed.elastic`` lease/formation
        knobs — a server started without explicit flags adopts the first
        joiner's policy, so the config section is the one source of truth
        in the common deployment."""
        if coord_candidate is None:
            adv = self._local_host_toward_service()
            coord_candidate = f"{adv}:{free_port(adv)}"
        resp = self._call(
            {
                "cmd": "join", "worker": self.worker_id,
                "coord": coord_candidate, "policy": policy or {},
            },
            timeout_s=self.join_timeout_s,
        )
        self.assignment = EpochAssignment(
            epoch=int(resp["epoch"]), rank=int(resp["rank"]),
            world=int(resp["world"]), coordinator=str(resp["coordinator"]),
            lease_ms=float(resp["lease_ms"]),
            heartbeat_ms=float(resp["heartbeat_ms"]),
        )
        self._reform.clear()
        return self.assignment

    def heartbeat(self) -> dict:
        epoch = self.assignment.epoch if self.assignment else -1
        resp = self._call(
            {"cmd": "heartbeat", "worker": self.worker_id, "epoch": epoch}
        )
        if resp.get("reform"):
            self._reform.set()
        return resp

    def leave(self) -> None:
        try:
            self._call({"cmd": "leave", "worker": self.worker_id})
        except (OSError, MembershipError):
            pass  # a dead service cannot block a clean exit

    def status(self) -> dict:
        return self._call({"cmd": "status"})

    # ----------------------------------------------------------- heartbeat
    def start_heartbeat(self) -> None:
        """Renew the lease every ``heartbeat_ms`` on a daemon thread,
        beginning with an IMMEDIATE renewal: leases start ticking at epoch
        formation, and the jax rendezvous between join and the first
        round (transport probe included) can outlast ``lease_ms`` — call
        this right after :meth:`join`, before the rendezvous, or a slow
        bring-up reads as a death and reforms the world it just formed.
        A failed renewal counts ``heartbeat_failures`` (the worker-side
        ``fed.lease_heartbeat_failures`` gauge) but never raises — a
        transiently unreachable service must not kill training; the
        server-side lease expiry is the authoritative failure detector."""
        if self._hb_thread is not None:
            return
        interval = (
            self.assignment.heartbeat_ms / 1e3 if self.assignment else 5.0
        )

        def loop():
            while True:
                try:
                    self.heartbeat()
                except (OSError, MembershipError, ValueError):
                    self.heartbeat_failures += 1
                if self._stop.wait(timeout=interval):
                    return

        self._hb_thread = threading.Thread(target=loop, daemon=True)
        self._hb_thread.start()

    @property
    def reform_pending(self) -> bool:
        return self._reform.is_set()

    def close(self) -> None:
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
            self._hb_thread = None


def elastic_policy(elastic_cfg) -> dict:
    """The ``fed.elastic`` knobs a worker ships in its join request."""
    return {
        "lease_ms": float(elastic_cfg.lease_ms),
        "heartbeat_ms": float(elastic_cfg.heartbeat_ms),
        "formation_grace_ms": float(elastic_cfg.formation_grace_ms),
        "min_world": int(elastic_cfg.min_world),
    }


def publish_membership_metrics(
    assignment: EpochAssignment | None = None,
    client: "MembershipClient | None" = None,
    reforms: int = 0,
) -> None:
    """THE one registration site for the worker-side membership metrics
    (docs/OBSERVABILITY.md, Membership): the epoch/world gauges from this
    worker's seat, this worker's failed lease renewals, and its reform
    departures.  The service-owned totals (shrinks / rejoins / lease
    misses) live as REAL counters in the service's own obs artifact trio
    (``--obs-dir`` on the standalone service) — the pre-PR-13 workaround
    of mirroring them into each worker as gauges is retired: worker
    registries restart on respawn while the service's history does not,
    and the fleet report reads the service's artifacts directly.
    """
    from fedrec_tpu.obs import get_registry

    reg = get_registry()
    if assignment is not None:
        reg.gauge(
            "fed.membership_epoch",
            "membership epoch this worker's world formed at",
        ).set(float(assignment.epoch))
        reg.gauge(
            "fed.membership_world",
            "world size of this worker's membership epoch",
        ).set(float(assignment.world))
    if client is not None:
        reg.gauge(
            "fed.lease_heartbeat_failures",
            "lease renewals THIS worker failed to deliver",
        ).set(float(client.heartbeat_failures))
    if reforms:
        reg.counter(
            "fed.membership_reforms_total",
            "reformation departures this worker performed (save, leave, "
            "rejoin at the next epoch)",
        ).inc(float(reforms))


def main(argv: list[str] | None = None) -> int:
    """Standalone service process (the elastic smoke's control plane)."""
    import argparse

    parser = argparse.ArgumentParser(
        description="fedrec elastic-membership service"
    )
    parser.add_argument("address", metavar="HOST:PORT")
    parser.add_argument("--target-world", type=int, default=0,
                        help="full complement: forming waits for this many "
                             "joiners before the grace window applies")
    # policy flags default to None = adopt the first joiner's fed.elastic
    # section; pass them explicitly to pin policy server-side
    parser.add_argument("--min-world", type=int, default=None)
    parser.add_argument("--lease-ms", type=float, default=None)
    parser.add_argument("--heartbeat-ms", type=float, default=None)
    parser.add_argument("--formation-grace-ms", type=float, default=None)
    parser.add_argument("--obs-dir", default=None,
                        help="write the service's OWN obs artifact trio "
                             "here (refreshed every few seconds and on "
                             "shutdown) — the authoritative membership "
                             "timeline the fleet report/trace reads; name "
                             "it worker_membership under the fleet's "
                             "shared obs root so fedrec-obs fleet "
                             "discovers it")
    parser.add_argument("--telemetry-dir", default=None,
                        help="also act as the fleet telemetry collector "
                             "(fedrec_tpu.obs.fleet) on THIS port: "
                             "workers' obs.fleet.collector pushes land "
                             "as worker_* dirs under this directory")
    parser.add_argument("--watch", action="store_true",
                        help="evaluate the fleet-level watch rules "
                             "(fedrec_tpu.obs.watch.FleetRules: persistent "
                             "straggler, world below target, quorum-wait "
                             "growth, stalled commit) against incoming "
                             "telemetry pushes and the membership world; "
                             "alert records land under the telemetry dir's "
                             "worker_fleet/ (needs --telemetry-dir)")
    args = parser.parse_args(argv)
    host, port = args.address.rsplit(":", 1)
    collector = None
    if args.telemetry_dir:
        from fedrec_tpu.obs.fleet import TelemetryCollector

        collector = TelemetryCollector(args.telemetry_dir)
    rules = None
    if args.watch and collector is not None:
        from pathlib import Path

        from fedrec_tpu.obs.watch import FleetRules

        fleet_dir = Path(args.telemetry_dir) / "worker_fleet"
        fleet_dir.mkdir(parents=True, exist_ok=True)
        rules = FleetRules(
            target_world=args.target_world,
            jsonl_path=fleet_dir / "metrics.jsonl",
        )
        collector.rules = rules
    if args.obs_dir:
        from fedrec_tpu.obs.fleet import set_fleet_identity

        set_fleet_identity(worker="membership")
    server = MembershipServer(
        host=host, port=int(port),
        target_world=args.target_world, min_world=args.min_world,
        lease_ms=args.lease_ms, heartbeat_ms=args.heartbeat_ms,
        formation_grace_ms=args.formation_grace_ms,
        collector=collector, obs_dir=args.obs_dir,
    ).start()
    print(f"[membership] serving on {server.address}", flush=True)

    # a SIGTERM'd service (the smoke's cleanup kill) must still run the
    # finally below — the final artifact dump is the membership timeline
    import signal

    def _term(signum, frame):  # noqa: ARG001 — signal handler signature
        raise SystemExit(0)

    try:
        signal.signal(signal.SIGTERM, _term)
    except (ValueError, OSError):
        pass  # not the main thread / unsupported platform: best effort
    try:
        # change-driven artifact refresh: a snapshot line per membership
        # EVENT (join/leave/expiry/formation), not per poll tick — an
        # idle federation's event log stays flat
        last_status = None
        while True:
            time.sleep(5)
            status = (
                server.status() if (args.obs_dir or rules is not None)
                else None
            )
            if rules is not None and status is not None:
                # the world-below-target rule only the membership service
                # can evaluate: it owns the authoritative world count
                rules.observe_world(status["world"])
            if args.obs_dir and status != last_status:
                server.dump_obs()
                last_status = status
    except (KeyboardInterrupt, SystemExit):
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
