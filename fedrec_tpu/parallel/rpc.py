"""Resilient fleet RPC — the retry/deadline/circuit-breaker front for the
JSON-lines wire.

Every cross-process service in the package (commit authority, membership,
telemetry collector, serving admin) speaks the one-shot JSON-lines
exchange of :func:`fedrec_tpu.obs.fleet.request_json_line`.  That helper
is deliberately a SINGLE attempt: it raises ``OSError`` on any transport
failure.  At churn scale a single attempt is the wrong contract — a 100
worker fleet sees torn connections, authority restarts and transient
partitions as the steady state, and ROADMAP item 1(c) requires workers to
ride them out.  This module is the one place the failure-handling policy
lives, absorbing the backoff idiom ``serving/client.py`` pioneered so the
two wire clients cannot drift:

* :func:`backoff_delay_s` — full-jitter exponential backoff (AWS-style):
  ``U(0, min(cap, base * 2^attempt))``.  The jitter matters as much as the
  exponent: a restarted authority must not meet every worker's retry in
  one synchronized stampede.
* :class:`RpcPolicy` — split connect/read timeouts (a dead host fails in
  ``connect_timeout_s``, a slow fold gets the full ``read_timeout_s``),
  a per-op retry budget (``op_attempts`` overrides ``attempts``) and the
  backoff shape, as one value object built from ``agg.worker_*`` knobs.
* :class:`CircuitBreaker` — after ``threshold`` consecutive transport
  failures the edge "opens": calls fail fast (no connect timeout burned)
  until ``reset_s`` passes, then a single half-open probe decides whether
  to close again.  Keeps a worker's round loop training at full speed
  while the authority is gone instead of stalling every round on the
  full retry budget.
* :class:`FleetRpc` — one edge's retrying client over
  ``request_json_line``.  Transport failures (``OSError``) are retried
  inside the budget; application error replies (``ValueError``) are NOT —
  the peer is alive and answered, retrying would re-ask the same bad
  question.  ``last_ok``/:meth:`FleetRpc.unreachable_for` feed the caller's
  degrade decision: an async worker keeps training within
  ``agg.worker_unreachable_budget_s`` of wire silence, then raises
  :class:`AuthorityUnreachable` and exits :data:`RC_DEGRADED` (rc-75, the
  PR-5 supervisor's retryable code) instead of crashing.
* :func:`new_push_id` — the client-generated idempotency token a push
  carries: retries of the SAME contribution reuse the id, the authority's
  ledger folds it at most once (``AggBuffer``'s same-(worker, round)
  replacement already made retries weight-safe; the id makes re-delivery
  after a commit safe too).

Per-edge accounting rides the shared wire metrics:
``wire.retries_total`` (re-attempts after a transport failure),
``wire.circuit_open_total`` (closed->open transitions) and
``wire.circuit_state`` (0 closed / 1 half-open / 2 open), all labelled by
peer — docs/OPERATIONS.md §3h reads them back during an incident.
"""

from __future__ import annotations

import random
import time
import uuid
from dataclasses import dataclass, field

__all__ = [
    "RC_DEGRADED",
    "AuthorityUnreachable",
    "CircuitBreaker",
    "CircuitOpen",
    "FleetRpc",
    "RpcPolicy",
    "backoff_delay_s",
    "new_push_id",
]

# the PR-5 supervisor's retryable exit code: a worker that degrades out of
# its unreachable budget exits with this so the supervisor respawns it
# (against the restarted authority) instead of counting a crash
RC_DEGRADED = 75


class AuthorityUnreachable(RuntimeError):
    """The wire stayed dead past the caller's staleness budget: training
    on would accumulate unfoldable staleness, so the worker should exit
    ``RC_DEGRADED`` for the supervisor to respawn."""

    returncode = RC_DEGRADED


class CircuitOpen(OSError):
    """Fail-fast refusal while the edge's circuit breaker is open — an
    ``OSError`` so every retry/degrade path treats it as the transport
    failure it stands in for (without burning a connect timeout)."""


def backoff_delay_s(
    attempt: int,
    base_ms: float = 50.0,
    max_ms: float = 2000.0,
    rng: random.Random | None = None,
) -> float:
    """Full-jitter exponential backoff (AWS-style): a delay drawn from
    ``U(0, min(max_ms, base_ms * 2^attempt))``, in seconds.  Shared by
    :class:`FleetRpc` and ``serving.client.ServingClient`` so the two
    wire clients' retry shapes cannot drift."""
    cap = min(float(max_ms), float(base_ms) * (2.0 ** max(int(attempt), 0)))
    u = rng.uniform(0.0, cap) if rng is not None else random.uniform(0.0, cap)
    return u / 1e3


def new_push_id(worker: str, round_idx: int) -> str:
    """A client-generated idempotency token for one contribution push.
    Generated ONCE per (worker, round) contribution and reused verbatim
    on every retry — the authority's push ledger guarantees a given id
    folds at most once, so duplicated delivery (retry after a lost ack,
    chaos duplication) can never double a worker's weight."""
    return f"{worker}:{int(round_idx)}:{uuid.uuid4().hex[:12]}"


@dataclass
class RpcPolicy:
    """One edge's failure-handling shape (``agg.worker_*`` in config)."""

    connect_timeout_s: float = 5.0    # dial budget (dead host fails fast)
    read_timeout_s: float = 60.0      # per-exchange socket deadline
    attempts: int = 4                 # default per-op attempt budget
    backoff_base_ms: float = 50.0
    backoff_max_ms: float = 2000.0
    # per-op overrides of `attempts` — e.g. a bounded poll loop retries
    # itself, so `global` can run a leaner budget than `push`
    op_attempts: dict = field(default_factory=dict)
    breaker_threshold: int = 5        # consecutive failures before opening
    breaker_reset_s: float = 10.0     # open -> half-open probe interval
    seed: int | None = None           # jitter stream (decorrelate workers)

    def attempts_for(self, op: str) -> int:
        return max(1, int(self.op_attempts.get(op, self.attempts)))


class CircuitBreaker:
    """Closed -> open after ``threshold`` CONSECUTIVE failures; open
    refuses instantly for ``reset_s``; then one half-open probe is let
    through and its outcome closes or re-opens the circuit."""

    def __init__(self, threshold: int = 5, reset_s: float = 10.0):
        self.threshold = max(int(threshold), 1)
        self.reset_s = float(reset_s)
        self.consec_failures = 0
        self.opens = 0                 # closed->open transitions (lifetime)
        self._opened_at: float | None = None
        self._probing = False

    @property
    def state(self) -> str:
        if self._opened_at is None:
            return "closed"
        if self._probing or (
            time.monotonic() - self._opened_at >= self.reset_s
        ):
            return "half-open"
        return "open"

    def allow(self) -> bool:
        """Whether a call may go out now.  In the half-open window the
        FIRST caller becomes the probe; siblings keep failing fast until
        its outcome is known."""
        if self._opened_at is None:
            return True
        if self._probing:
            return False
        if time.monotonic() - self._opened_at >= self.reset_s:
            self._probing = True
            return True
        return False

    def success(self) -> None:
        self.consec_failures = 0
        self._opened_at = None
        self._probing = False

    def failure(self) -> None:
        self.consec_failures += 1
        was_open = self._opened_at is not None
        if self._probing or (
            not was_open and self.consec_failures >= self.threshold
        ):
            # a failed probe re-opens with a fresh reset window; a closed
            # breaker crossing the threshold opens for the first time
            if not was_open:
                self.opens += 1
            self._opened_at = time.monotonic()
            self._probing = False


class FleetRpc:
    """Retrying JSON-lines client for ONE edge (host:port), fronting
    :func:`~fedrec_tpu.obs.fleet.request_json_line` with the policy's
    backoff/deadline/breaker behavior.  Thread-compatible for the
    churn-soak's logical workers: each worker owns its own instance, so
    per-edge counters stay per-worker."""

    def __init__(
        self,
        host: str,
        port: int,
        policy: RpcPolicy | None = None,
    ):
        self.host = str(host)
        self.port = int(port)
        self.policy = policy or RpcPolicy()
        self._rng = random.Random(self.policy.seed)
        self.breaker = CircuitBreaker(
            self.policy.breaker_threshold, self.policy.breaker_reset_s
        )
        self._born = time.monotonic()
        self.last_ok: float | None = None   # monotonic ts of last success
        # local accounting (the soak's logical workers synthesize their
        # per-worker telemetry snapshots from these, since a shared
        # process registry cannot keep 100 workers' edges apart)
        self.ok = 0
        self.errors = 0
        self.retries = 0
        self.op_errors: dict[str, int] = {}
        self.op_ok: dict[str, int] = {}

    # ------------------------------------------------------------ metrics
    @property
    def peer(self) -> str:
        return f"{self.host}:{self.port}"

    def _m_retry(self, op: str) -> None:
        from fedrec_tpu.obs import get_registry

        get_registry().counter(
            "wire.retries_total",
            "request re-attempts after a transport failure per edge (the "
            "resilient-RPC budget at work; 0 on a healthy wire)",
            labels=("peer", "op"),
        ).inc(peer=self.peer, op=op)

    def _m_breaker(self, opened: bool) -> None:
        from fedrec_tpu.obs import get_registry

        reg = get_registry()
        if opened:
            reg.counter(
                "wire.circuit_open_total",
                "circuit-breaker closed->open transitions per edge (the "
                "peer stayed dead past the consecutive-failure threshold)",
                labels=("peer",),
            ).inc(peer=self.peer)
        reg.gauge(
            "wire.circuit_state",
            "circuit-breaker state per edge: 0 closed, 1 half-open, "
            "2 open (open = calls fail fast, training continues degraded)",
            labels=("peer",),
        ).set(
            {"closed": 0.0, "half-open": 1.0, "open": 2.0}[
                self.breaker.state
            ],
            peer=self.peer,
        )

    # --------------------------------------------------------------- call
    def unreachable_for(self) -> float:
        """Seconds since the last successful exchange on this edge (since
        construction when none succeeded yet) — the caller's degrade
        clock (``agg.worker_unreachable_budget_s``)."""
        anchor = self.last_ok if self.last_ok is not None else self._born
        return time.monotonic() - anchor

    def call(self, req: dict, op: str | None = None) -> dict:
        """One exchange with retry.  Raises ``OSError`` once the attempt
        budget is spent (or instantly while the breaker is open) and
        ``ValueError`` on an application error reply (never retried: the
        peer answered)."""
        from fedrec_tpu.obs.fleet import request_json_line

        op = op or str(req.get("cmd", "req"))
        budget = self.policy.attempts_for(op)
        last_err: OSError | None = None
        for attempt in range(budget):
            if not self.breaker.allow():
                self.errors += 1
                self.op_errors[op] = self.op_errors.get(op, 0) + 1
                self._m_breaker(opened=False)
                raise CircuitOpen(
                    f"circuit open for {self.peer} (op={op}): "
                    f"{self.breaker.consec_failures} consecutive failures, "
                    f"probing again in <= {self.breaker.reset_s:g}s"
                )
            try:
                resp = request_json_line(
                    self.host, self.port, req,
                    timeout_s=self.policy.read_timeout_s,
                    connect_timeout_s=self.policy.connect_timeout_s,
                    op=op,
                )
            except OSError as e:
                last_err = e
                before = self.breaker.opens
                self.breaker.failure()
                self.errors += 1
                self.op_errors[op] = self.op_errors.get(op, 0) + 1
                self._m_breaker(opened=self.breaker.opens > before)
                if attempt + 1 < budget and self.breaker.state != "open":
                    self.retries += 1
                    self._m_retry(op)
                    time.sleep(backoff_delay_s(
                        attempt, self.policy.backoff_base_ms,
                        self.policy.backoff_max_ms, self._rng,
                    ))
                    continue
                raise
            except ValueError:
                # the peer is alive and answered — liveness for the
                # breaker and the degrade clock, but the error propagates
                self.breaker.success()
                self.last_ok = time.monotonic()
                self._m_breaker(opened=False)
                raise
            self.breaker.success()
            self.ok += 1
            self.op_ok[op] = self.op_ok.get(op, 0) + 1
            self.last_ok = time.monotonic()
            self._m_breaker(opened=False)
            return resp
        raise last_err if last_err is not None else OSError(
            f"no attempt budget for op {op!r}"
        )

    # ---------------------------------------------------------- telemetry
    def wire_snapshot_rows(self) -> dict:
        """This edge's per-op request/error totals in registry-snapshot
        row shape (``wire.requests_total`` / ``wire.errors_total``) — the
        churn soak's logical workers feed these to the fleet watch rules
        as their per-worker telemetry snapshots."""
        def rows(table: dict[str, int]) -> list[dict]:
            return [
                {"labels": {"peer": self.peer, "op": o}, "value": float(n)}
                for o, n in sorted(table.items())
            ]

        return {
            "wire.requests_total": {
                "kind": "counter", "values": rows(self.op_ok),
            },
            "wire.errors_total": {
                "kind": "counter", "values": rows(self.op_errors),
            },
        }
