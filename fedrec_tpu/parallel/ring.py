"""Sequence / context parallelism: ring attention and Ulysses all-to-all.

The reference has no long-context machinery at all — its attention builds one
dense ``(bz, heads, 50, 50)`` score tensor (reference ``attention.py:38-44``)
and caps history at 50 items (reference ``dataset.py:9``). This module makes
long click-histories a first-class capability of the TPU framework: shard the
sequence axis over a ``seq`` mesh axis and attend with XLA collectives over
ICI, so neither the score matrix nor the full K/V sequence ever materializes
on one chip.

Two interchangeable strategies, both called inside ``shard_map`` with the
sequence dimension sharded over ``axis_name``:

* ``ring_attention`` — blockwise online-softmax (flash) accumulation while
  K/V blocks rotate around the ring via ``lax.ppermute``. Per-step compute
  overlaps with the neighbor exchange; memory is O(L/n) per chip.
* ``ulysses_attention`` — ``lax.all_to_all`` reshards from sequence-sharded
  to head-sharded, runs local dense attention over the full sequence for a
  head subset, and reshards back. One collective pair per call; requires
  ``num_heads % axis_size == 0``.

Numerics: true max-stabilized softmax with multiplicative key-mask semantics
matching ``models.attention._masked_normalize`` (stable path) including its
``+1e-8`` denominator epsilon, so a sequence-parallel run is bit-comparable
to the single-chip stable path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30  # finite "-inf": keeps fully-masked blocks NaN-free


def _zeros_with_vma_of(ref: jnp.ndarray, shape: tuple, fill: float = 0.0) -> jnp.ndarray:
    """A constant-filled array typed with ``ref``'s varying-manual-axes.

    shard_map (JAX >= 0.8) tracks which mesh axes a value varies over in its
    aval; a loop carry initialized from a plain constant is "unvarying" while
    the body's output varies over every axis the operands do (e.g. both
    ``clients`` and ``seq`` in a dp x sp layout), which fails scan's
    carry-type check. Multiplying by a zero slice of ``ref`` broadcasts the
    constant AND unions in ``ref``'s vma — version-portable, and XLA folds
    the arithmetic away.
    """
    zero = (ref * 0).sum(tuple(range(ref.ndim)))  # scalar 0 carrying ref's vma
    return jnp.full(shape, fill, dtype=ref.dtype) + zero.astype(ref.dtype)


def _scale(dk: int, dtype) -> jnp.ndarray:
    return jnp.asarray(1.0 / (dk**0.5), dtype=dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: jnp.ndarray | None = None,
    axis_name: str = "seq",
) -> jnp.ndarray:
    """Ring flash attention over a sequence-sharded mesh axis.

    Args:
      q: ``(..., Lq_shard, H, Dk)`` local query block.
      k, v: ``(..., Lk_shard, H, Dk/Dv)`` local key/value blocks.
      mask: optional ``(..., Lk_shard)`` key mask (1 = attend) for the local
        block; rotates around the ring together with K/V.
      axis_name: mesh axis the sequence is sharded over.

    Returns ``(..., Lq_shard, H, Dv)`` — exactly dense attention over the
    full (gathered) sequence, computed without ever gathering it.
    """
    n = lax.psum(1, axis_name)
    *batch, lq, h, dk = q.shape
    dv = v.shape[-1]
    scale = _scale(dk, q.dtype)

    has_mask = mask is not None
    if has_mask:
        mask = mask.astype(q.dtype)

    perm = [(j, (j + 1) % n) for j in range(n)]

    # anchor: scalar zero carrying the UNION of q/k/v/mask vmas — what the
    # body outputs
    anchor = (q * 0).sum() + (k * 0).sum() + (v * 0).sum()
    if has_mask:
        anchor = anchor + (mask * 0).sum()
    m0 = _zeros_with_vma_of(anchor, (*batch, h, lq), fill=_NEG)
    l0 = _zeros_with_vma_of(anchor, (*batch, h, lq))
    o0 = _zeros_with_vma_of(anchor, (*batch, lq, h, dv))

    def body(i, carry):
        k_b, v_b, mask_b, m, l, o = carry
        s = jnp.einsum("...qhd,...khd->...hqk", q, k_b) * scale
        if has_mask:
            s = jnp.where(mask_b[..., None, None, :] > 0, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        if has_mask:
            p = p * mask_b[..., None, None, :]
        corr = jnp.exp(m - m_new)  # (..., H, Lq)
        l = l * corr + jnp.sum(p, axis=-1)
        # corr broadcast to o's (..., Lq, H, Dv) layout
        corr_o = jnp.moveaxis(corr, -2, -1)[..., None]  # (..., Lq, H, 1)
        o = o * corr_o + jnp.einsum("...hqk,...khd->...qhd", p, v_b)

        def rotate(blocks):
            kb, vb, mb = blocks
            kb = lax.ppermute(kb, axis_name, perm)
            vb = lax.ppermute(vb, axis_name, perm)
            if has_mask:  # maskless path skips the mask hop entirely
                mb = lax.ppermute(mb, axis_name, perm)
            return kb, vb, mb

        # the last iteration's rotation would be discarded — skip the ICI hop
        k_b, v_b, mask_b = lax.cond(
            i < n - 1, rotate, lambda b: b, (k_b, v_b, mask_b)
        )
        return k_b, v_b, mask_b, m_new, l, o

    # maskless path rotates only K/V; a dummy scalar keeps the carry shape
    mask_carry = mask if has_mask else anchor
    _, _, _, _, l, o = lax.fori_loop(
        0, n, body, (k, v, mask_carry, m0, l0, o0)
    )
    denom = jnp.moveaxis(l, -2, -1)[..., None] + 1e-8  # (..., Lq, H, 1)
    return o / denom


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: jnp.ndarray | None = None,
    axis_name: str = "seq",
) -> jnp.ndarray:
    """All-to-all (DeepSpeed-Ulysses-style) sequence-parallel attention.

    Same shard layout and semantics as ``ring_attention``; requires the head
    count to divide evenly by the axis size. Reshards seq->heads, attends
    densely over the full sequence locally, reshards back.
    """
    n = lax.psum(1, axis_name)
    *batch, lq, h, dk = q.shape
    if h % n != 0:
        raise ValueError(f"num_heads={h} not divisible by axis size {n}")
    nb = len(batch)

    def to_heads(x):
        # (..., L_shard, H, D) -> (..., L, H/n, D)
        return lax.all_to_all(
            x, axis_name, split_axis=nb + 1, concat_axis=nb, tiled=True
        )

    q_g, k_g, v_g = to_heads(q), to_heads(k), to_heads(v)
    if mask is not None:
        mask_g = lax.all_gather(
            mask.astype(q.dtype), axis_name, axis=nb, tiled=True
        )
        bias = jnp.where(mask_g[..., None, None, :] > 0, 0.0, _NEG).astype(q.dtype)
    else:
        mask_g = None
        bias = None

    s = jnp.einsum("...qhd,...khd->...hqk", q_g, k_g) * _scale(dk, q.dtype)
    if bias is not None:
        s = s + bias
    s = s - jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s)
    if mask_g is not None:
        p = p * mask_g[..., None, None, :]
    p = p / (jnp.sum(p, axis=-1, keepdims=True) + 1e-8)
    o = jnp.einsum("...hqk,...khd->...qhd", p, v_g)
    # (..., L, H/n, D) -> (..., L_shard, H, D)
    return lax.all_to_all(o, axis_name, split_axis=nb, concat_axis=nb + 1, tiled=True)


def seq_parallel_pool(
    x: jnp.ndarray,
    logits: jnp.ndarray,
    mask: jnp.ndarray | None = None,
    axis_name: str = "seq",
) -> jnp.ndarray:
    """Additive-attention pooling across a sequence-sharded axis.

    ``x``: ``(..., L_shard, D)`` values; ``logits``: ``(..., L_shard)``
    unnormalized attention scores (the local ``fc2(tanh(fc1 x))`` output);
    ``mask``: optional ``(..., L_shard)``. Normalization (max + denominator)
    runs over the GLOBAL sequence via ``lax.pmax``/``lax.psum``; returns the
    pooled ``(..., D)`` vector, identical on every ``seq`` shard.
    """
    if mask is not None:
        logits = jnp.where(mask > 0, logits, _NEG)
    # max-shift is softmax-invariant -> no gradient flows through it (pmax has
    # no AD rule anyway)
    g_max = lax.pmax(
        jnp.max(jax.lax.stop_gradient(logits), axis=-1), axis_name
    )  # (...)
    w = jnp.exp(logits - g_max[..., None])
    if mask is not None:
        w = w * mask.astype(w.dtype)
    denom = lax.psum(jnp.sum(w, axis=-1), axis_name) + 1e-8
    local = jnp.einsum("...l,...ld->...d", w, x)
    return lax.psum(local, axis_name) / denom[..., None]
