"""Device mesh + sharding helpers: the TPU-native communication backend.

This replaces the reference's entire L5 layer — torchrun/c10d rendezvous +
gloo ``init_process_group``/``all_reduce``/``broadcast`` + raw TCP side
channel (reference ``main.py:144``, ``Parameter_Averaging_main.py:146``,
``server.py:74-98``, ``client.py:191-210,256-264``) — with a
``jax.sharding.Mesh`` over a ``clients`` axis:

  * one federated client == one mesh slot (TPU core / pod chip)
  * grad / param averaging == ``lax.pmean`` over the axis, riding ICI
  * server broadcast / gather == sharding-induced XLA collectives; no file
    transfer channel exists because arrays are natively exchangeable
  * multi-host rendezvous == ``jax.distributed.initialize`` (see
    ``fedrec_tpu.parallel.multihost``)

On a single host the same code runs against N virtual CPU devices
(``--xla_force_host_platform_device_count=N``) — the JAX-native analogue of
the reference's localhost-gloo simulation (reference ``README.md:27-34``).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CLIENT_AXIS = "clients"
# the parameter-sharding axis (shard.fsdp > 1): client state at rest is
# sharded across it per fedrec_tpu.shard.policy; compute gathers on entry
FSDP_AXIS = "fsdp"


def client_mesh(
    num_clients: int,
    axis: str = CLIENT_AXIS,
    local: bool = True,
    max_devices: int | None = None,
) -> Mesh:
    """1-D mesh over the federated-client axis.

    ``local=True`` (default) builds the mesh from this process's addressable
    devices — correct for single-host simulation and for the coordinator
    deployment where each host trains its own clients and syncs over DCN.
    ``local=False`` uses the global device list for a single-controller
    multi-host SPMD mesh (all hosts must then feed globally-sharded arrays).

    When ``num_clients`` exceeds the device count, the mesh spans every
    device and each device hosts a COHORT of ``num_clients / n_devices``
    clients (the train/sync steps vmap over the in-device cohort and run
    collectives over ``(cohort, mesh)`` jointly — see
    ``fedrec_tpu.train.step.LOCAL_AXIS``). This is how a 32-client
    federation (BASELINE.json north star) runs on fewer chips, the
    TPU-native analogue of oversubscribing torchrun ranks onto one node
    (reference ``README.md:27-34``). Requires divisibility; on CPU test
    rigs use ``--xla_force_host_platform_device_count``.

    ``max_devices`` caps the device pool (mainly for equivalence tests:
    the same client count with different cohort factors).
    """
    devices = jax.local_devices() if local else jax.devices()
    if max_devices is not None:
        devices = devices[:max_devices]
    if num_clients <= len(devices):
        size = num_clients
    elif num_clients % len(devices) == 0:
        size = len(devices)
    else:
        raise ValueError(
            f"num_clients={num_clients} exceeds {len(devices)} available "
            "devices and is not divisible by the device count (cohort "
            "sharding needs equal cohorts); set XLA_FLAGS="
            "--xla_force_host_platform_device_count for simulation"
        )
    mesh_devices = mesh_utils.create_device_mesh(
        (size,), devices=devices[:size]
    )
    return Mesh(mesh_devices, (axis,))


def fed_mesh(cfg: Any, local: bool = True) -> Mesh:
    """Mesh for an ExperimentConfig: 1-D ``(clients,)``, 2-D
    ``(clients, seq)`` when ``fed.seq_shards > 1`` (long-history sequence
    parallelism — each client's history attention spans ``seq_shards`` chips
    via ring/Ulysses collectives, see ``fedrec_tpu.parallel.ring``), or 2-D
    ``(clients, fsdp)`` when ``shard.fsdp > 1`` (at-rest parameter/optimizer
    sharding per ``fedrec_tpu.shard.policy``; ``fsdp=1`` builds the exact
    1-D mesh, so the degenerate config is bit-identical to pure data
    parallelism by construction).
    """
    n_cli, n_seq = cfg.fed.num_clients, cfg.fed.seq_shards
    n_fsdp = getattr(getattr(cfg, "shard", None), "fsdp", 1)
    if n_fsdp > 1:
        if n_seq > 1:
            raise ValueError(
                f"shard.fsdp={n_fsdp} with fed.seq_shards={n_seq} is not "
                "supported: both claim the mesh's second axis — unset one "
                "of the two"
            )
        return _two_axis_mesh(
            cfg, n_cli, n_fsdp, FSDP_AXIS, "shard.fsdp", local
        )
    if n_seq <= 1:
        return client_mesh(n_cli, cfg.fed.mesh_axis, local=local)
    if cfg.data.max_his_len % n_seq != 0:
        raise ValueError(
            f"data.max_his_len={cfg.data.max_his_len} must be divisible by "
            f"fed.seq_shards={n_seq} to shard the history axis"
        )
    return _two_axis_mesh(
        cfg, n_cli, n_seq, cfg.fed.seq_axis, "fed.seq_shards", local
    )


def _two_axis_mesh(
    cfg: Any,
    n_cli: int,
    n_second: int,
    second_axis: str,
    flag: str,
    local: bool,
) -> Mesh:
    """A 2-D ``(clients, <second>)`` mesh with the same cohort policy as
    :func:`client_mesh` on the clients axis — shared by the seq-parallel
    and fsdp layouts so slot/cohort arithmetic cannot diverge."""
    devices = jax.local_devices() if local else jax.devices()
    cli_slots = len(devices) // n_second
    if cli_slots < 1:
        raise ValueError(
            f"{flag}={n_second} exceeds {len(devices)} devices; "
            "set XLA_FLAGS=--xla_force_host_platform_device_count for simulation"
        )
    if n_cli <= cli_slots:
        size = n_cli
    elif n_cli % cli_slots == 0:
        size = cli_slots  # cohorts: size*n_second devices, n_cli/size per slot
    else:
        raise ValueError(
            f"num_clients={n_cli} exceeds the {cli_slots} client slots of a "
            f"{len(devices)}-device mesh with {flag}={n_second} and is not "
            "divisible by the slot count (cohort sharding needs equal "
            "cohorts); set XLA_FLAGS=--xla_force_host_platform_device_count"
        )
    mesh_devices = mesh_utils.create_device_mesh(
        (size, n_second), devices=devices[: size * n_second]
    )
    return Mesh(mesh_devices, (cfg.fed.mesh_axis, second_axis))


def fed_batch_spec(key: str, cfg: Any, mesh: Mesh) -> P:
    """The ONE per-key batch layout rule: dim 0 over the clients axis;
    ``history``'s last dim additionally over the seq axis when sequence
    parallelism is on. Used by ``shard_fed_batch`` and (under a prepended
    steps dim) by ``train.step.shard_scan_batches`` — change it here and
    both input paths follow."""
    if (
        cfg.fed.seq_shards > 1
        and cfg.fed.seq_axis in mesh.axis_names
        and key == "history"
    ):
        return P(cfg.fed.mesh_axis, None, cfg.fed.seq_axis)
    return P(cfg.fed.mesh_axis)


def shard_fed_batch(mesh: Mesh, batch: dict, cfg: Any) -> dict:
    """Shard a train batch for ``fed_mesh`` per ``fed_batch_spec``."""
    if cfg.fed.seq_shards <= 1 or cfg.fed.seq_axis not in mesh.axis_names:
        return shard_batch(mesh, batch, cfg.fed.mesh_axis)
    return {
        k: jax.device_put(
            np.asarray(v), NamedSharding(mesh, fed_batch_spec(k, cfg, mesh))
        )
        for k, v in batch.items()
    }


def client_sharding(mesh: Mesh, axis: str = CLIENT_AXIS) -> NamedSharding:
    """Leading-axis sharding: array dim 0 is the per-client dim."""
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch: Any, axis: str = CLIENT_AXIS) -> Any:
    """Device-put a pytree of (num_clients, ...) arrays with dim 0 sharded."""
    sharding = client_sharding(mesh, axis)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(np.asarray(x), sharding), batch
    )
