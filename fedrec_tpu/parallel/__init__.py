from fedrec_tpu.parallel.mesh import (
    FSDP_AXIS,
    client_mesh,
    client_sharding,
    fed_mesh,
    replicated_sharding,
    shard_batch,
    shard_fed_batch,
)
from fedrec_tpu.parallel.ring import (
    ring_attention,
    seq_parallel_pool,
    ulysses_attention,
)

__all__ = [
    "FSDP_AXIS",
    "client_mesh",
    "client_sharding",
    "fed_mesh",
    "replicated_sharding",
    "shard_batch",
    "shard_fed_batch",
    "ring_attention",
    "seq_parallel_pool",
    "ulysses_attention",
]
