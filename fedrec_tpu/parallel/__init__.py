from fedrec_tpu.parallel.mesh import (
    client_mesh,
    client_sharding,
    replicated_sharding,
    shard_batch,
)

__all__ = [
    "client_mesh",
    "client_sharding",
    "replicated_sharding",
    "shard_batch",
]
