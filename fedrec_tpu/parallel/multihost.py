"""Multi-host rendezvous + coordinator round control over DCN.

Replaces the reference's entire hub-and-spoke deployment plumbing:

  * torchrun c10d rendezvous (``--rdzv-backend=c10d --rdzv-endpoint=...``,
    reference ``README.md:27-46``) -> ``jax.distributed.initialize``.
  * Server weight broadcast per round (``server.py:74-77`` broadcasting every
    parameter tensor from rank 1) -> one
    ``multihost_utils.broadcast_one_to_all`` of the whole parameter pytree.
  * Client -> server full ``state_dict`` streamed over raw TCP sockets in
    1 KB chunks, ~268 MB/client/round (``client.py:191-210``,
    ``server.py:80-98``, Final_Report.pdf VII.b) -> ``process_allgather``:
    arrays are natively exchangeable through XLA's collectives, so the file
    side channel (an artifact of gloo's tensor-only API) simply disappears —
    and only the ~2M trainable params travel, never the frozen trunk.
  * The 1.0/0.0 continue/stop flag broadcast (``server.py:74,105``,
    ``client.py:256-258``) -> ``broadcast_round_flag``.

Fault tolerance: ``aggregate_from_hosts`` takes a participation weight per
process, so a round aggregates over whichever clients reported — the
reference instead hangs until its 2-day gloo timeout if any client dies
(``client.py:227``, Final_Report.pdf VII.a).
"""

from __future__ import annotations

import atexit
import os
import threading
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import multihost_utils


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    initialization_timeout: float | None = None,
) -> tuple[int, int]:
    """Join the multi-host world; returns (process_id, num_processes).

    All arguments default to cluster auto-detection (TPU pod metadata); set
    them explicitly for manual bring-up, e.g. CPU-based integration tests.

    ``jax_enable_recoverability`` is enabled: without it the coordination
    service propagates any task failure as fatal to every non-leader.
    NOTE the remaining platform constraint: the runtime client's error
    poller still TERMINATES the process (XLA ``client.h:80``) when the
    coordination service itself goes away (it lives in process 0), and a
    degraded client's disconnect blocks behind the broken world. Degraded
    mode in a long-lived deployment must therefore LEAVE the runtime —
    the coordinator CLI re-execs a degraded client as a standalone
    continuation from its local snapshot (see
    ``fedrec_tpu.cli.coordinator``).
    """
    try:
        jax.config.update("jax_enable_recoverability", True)
    except AttributeError:  # older jax without the flag: keep prior behavior
        pass
    # Backend must not be touched before jax.distributed.initialize, so key
    # off the requested platform rather than jax.default_backend().
    platforms = os.environ.get("JAX_PLATFORMS", "") or str(
        getattr(jax.config, "jax_platforms", None) or ""
    )
    first = platforms.split(",")[0].strip().lower()
    if first in ("cpu", ""):
        # XLA:CPU has no native multiprocess collectives ("Multiprocess
        # computations aren't implemented on the CPU backend") — route them
        # through gloo so CPU worlds (a default-backend CPU host as much
        # as an explicit JAX_PLATFORMS=cpu one; test_elastic,
        # test_supervisor) exercise the real cross-process path. With an
        # accelerator present ("" resolves to tpu/gpu) the setting is
        # inert: it only selects the CPU backend's collectives impl.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):  # older jax / no gloo build
            pass
    kwargs: dict = {}
    if initialization_timeout:
        # bounded bring-up for supervised relaunches: a respawn racing a
        # dying world must FAIL (and be retried by its supervisor) rather
        # than sit in jax's default 5-minute rendezvous wait
        kwargs["initialization_timeout"] = int(initialization_timeout)
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
    except TypeError:  # older jax without initialization_timeout
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    return jax.process_index(), jax.process_count()


def broadcast_params(params: Any, is_source: bool | None = None) -> Any:
    """Server -> all clients weight fan-out (reference ``server.py:76-77``)."""
    return multihost_utils.broadcast_one_to_all(params, is_source=is_source)


def broadcast_round_flag(keep_going: bool) -> bool:
    """Continue/stop control flag (reference ``server.py:74,105``)."""
    flag = multihost_utils.broadcast_one_to_all(
        jnp.asarray(1.0 if keep_going else 0.0)
    )
    return bool(float(flag) != 0.0)


def broadcast_round_index(round_idx: int) -> int:
    """Server -> clients round counter; -1 = stop.

    Subsumes the reference's 1.0/0.0 flag (``server.py:74,105``) AND pins
    every host to the server's round index — a client resumed from a stale
    (or missing) local snapshot would otherwise run a different counter than
    the server: different batch seeds, misaligned save cadence, mislabeled
    global snapshots.
    """
    v = multihost_utils.broadcast_one_to_all(jnp.asarray(round_idx, jnp.int32))
    return int(v)


def validate_compress(compress: str) -> str:
    """Fail FAST on a bad mode: raised lazily inside the aggregation
    collective, a typo would be misread by the watchdog as a peer failure
    and silently degrade every host to standalone training."""
    if compress not in ("none", "int8"):
        raise ValueError(f"unknown compress mode {compress!r}; 'none' | 'int8'")
    return compress


def quantize_leaf(p: Any) -> tuple[np.ndarray, np.float32]:
    """Symmetric per-tensor int8 quantization: ``p ~= q * scale``.

    Max-abs scaling to 127 levels; an all-zero tensor gets scale 0 (its
    dequantization is exactly zero). Worst-case element error is scale/2 =
    max|p|/254 — ~0.2% of the tensor's dynamic range.
    """
    p = np.asarray(p, np.float32)
    amax = float(np.max(np.abs(p))) if p.size else 0.0
    scale = np.float32(amax / 127.0)
    if scale == 0.0:
        return np.zeros(p.shape, np.int8), scale
    q = np.clip(np.rint(p / scale), -127, 127).astype(np.int8)
    return q, scale


def dequantize_weighted_mean(
    gathered_q: np.ndarray, gathered_scales: np.ndarray, weights: np.ndarray
) -> np.ndarray:
    """(P, ...) int8 contributions + (P,) scales + (P,) weights -> weighted
    mean ``sum_i w_i * q_i * s_i / sum_i w_i`` (caller guards total > 0)."""
    coeff = (weights * gathered_scales / np.sum(weights)).astype(np.float32)
    return np.einsum("p,p...->...", coeff, gathered_q.astype(np.float32))


def aggregate_from_hosts(
    params: Any,
    weight: float = 1.0,
    compress: str = "none",
    base: Any = None,
    robust: Any = None,
) -> Any:
    """Participation-weighted FedAvg across processes.

    Each process contributes its local parameter pytree with ``weight``
    (0 = this client sat the round out). Every process receives the
    aggregate — the allgather-based replacement for the server's
    TCP-gather + key-wise mean (``server.py:37-55,102``).

    ``compress='int8'`` quantizes the client->server payload (symmetric
    per-tensor int8 + one f32 scale), cutting the gather traffic 4x on top
    of the trainable-towers-only design. The server->client fan-out
    (:func:`broadcast_params`) stays full precision — quantizing the global
    model would bias every client's training, while quantizing the per-round
    CONTRIBUTIONS only adds zero-mean rounding noise to the mean.

    ``robust`` (a ``fed.robust`` config section with ``method != "mean"``)
    swaps the weighted mean for a Byzantine-robust reduction
    (:func:`fedrec_tpu.fed.robust.robust_reduce_tree_np`) applied to the
    (P, ...) stacks ``process_allgather`` already materializes — the
    cross-HOST counterpart of the in-graph cohort aggregators, so a
    poisoned *process* cannot move the coordinator's global either.
    Robust methods require ``compress='none'``: trimming per coordinate
    after int8 rounding would judge quantization noise, not clients.

    ``base`` (int8 mode only): a pytree every process holds identically —
    the round-start global from the server fan-out. When given, the round
    DELTAS ``params - base`` are quantized instead of the absolute tensors
    (ADVICE r2): one round's delta spans a far smaller range than the
    parameters, so the same 127 levels bound the per-element error by
    ``max|delta|/254`` instead of ``max|param|/254`` — and a single outlier
    WEIGHT no longer degrades the whole tensor's resolution, only an
    outlier single-round UPDATE would. The weighted mean commutes with the
    shift: ``mean_w(params) == base + mean_w(params - base)`` exactly.
    """
    validate_compress(compress)
    w_arr = np.asarray(weight, np.float32)
    method = getattr(robust, "method", "mean") if robust is not None else "mean"
    if method != "mean":
        from fedrec_tpu.fed.robust import (
            robust_reduce_tree_np,
            validate_robust_method,
        )

        validate_robust_method(method)
        if compress != "none":
            raise ValueError(
                f"fed.robust.method={method!r} requires "
                "fed.dcn_compress='none': coordinate-wise robust reduction "
                "over int8-quantized contributions would trim quantization "
                "noise, not clients"
            )
        raw = jax.tree_util.tree_map(lambda p: np.asarray(p, np.float32), params)
        gathered, weights = multihost_utils.process_allgather((raw, w_arr))
        if float(np.sum(weights)) == 0.0:
            return params  # nobody reported; keep local (no NaNs)
        reduced = robust_reduce_tree_np(
            gathered, np.asarray(weights), method,
            trim_k=robust.trim_k, clip_norm=robust.clip_norm,
            fallback_tree=raw,  # m==0 coordinates keep local (in-graph parity)
        )
        return jax.tree_util.tree_map(
            lambda m, p: jnp.asarray(np.asarray(m, np.asarray(p).dtype)),
            reduced, params,
        )
    if compress == "int8":
        flat, treedef = jax.tree_util.tree_flatten(params)
        if base is not None:
            base_flat = jax.tree_util.tree_leaves(base)
            flat = [
                np.asarray(p, np.float32) - np.asarray(b, np.float32)
                for p, b in zip(flat, base_flat)
            ]
        pairs = [quantize_leaf(p) for p in flat]
        q = jax.tree_util.tree_unflatten(treedef, [x[0] for x in pairs])
        scales = jax.tree_util.tree_unflatten(treedef, [x[1] for x in pairs])
        # ONE collective for payload + scales + weight: fewer DCN round
        # trips, and no window where a peer death strands the runtime
        # between matched gathers
        gathered_q, gathered_s, weights = multihost_utils.process_allgather(
            (q, scales, w_arr)
        )
        total = float(np.sum(weights))
        if total == 0.0:
            return params  # nobody reported; keep local (no NaNs)
        mean = jax.tree_util.tree_map(
            lambda gq, gs: dequantize_weighted_mean(
                np.asarray(gq), np.asarray(gs), np.asarray(weights)
            ),
            gathered_q,
            gathered_s,
        )
        if base is not None:
            return jax.tree_util.tree_map(
                lambda m, b: jnp.asarray(m + np.asarray(b, np.float32)),
                mean, base,
            )
        return jax.tree_util.tree_map(jnp.asarray, mean)
    weighted = jax.tree_util.tree_map(lambda p: np.asarray(p) * weight, params)
    gathered, weights = multihost_utils.process_allgather((weighted, w_arr))
    total = float(np.sum(weights))
    if total == 0.0:
        return params  # nobody reported; keep local (no NaNs)
    return jax.tree_util.tree_map(lambda g: jnp.asarray(np.sum(g, axis=0) / total), gathered)


class CoordinatorRuntime:
    """Host-level round loop for the true client/server deployment.

    Process 0 acts as the aggregation server (the reference uses global rank
    1 as the source, ``client.py:257`` — an arbitrary choice; we use 0).
    Single-process fallback: all methods degrade to no-ops so the same
    driver script runs standalone.

    Unplanned-failure tolerance (``collective_timeout_s``): every DCN
    collective runs under a watchdog. A dead peer hangs the collective for
    every survivor (and would hang every subsequent one too), so on the
    first timeout or collective error the runtime flips to ``degraded``
    mode: all later calls take the local path and the host finishes its
    remaining rounds standalone. The reference instead blocks until its
    2-day gloo timeout and then dies (``client.py:227``,
    Final_Report.pdf VII.a). Planned per-round sit-outs don't need this —
    they are weight-0 participation in :meth:`aggregate`.

    Slow (not dead) peers: a host that stalls past the watchdog and then
    recovers degrades via its OWN watchdog at its next collective and
    finishes standalone — with one platform caveat. The JAX coordination
    service lives in process 0 (like torchrun's c10d rendezvous), so if the
    SERVER has already degraded and exited by the time a slow client wakes,
    the client's distributed runtime fatally terminates it: a bounded
    crash, never a wedge. Both directions are pinned by
    ``test_coordinator_slow_server_recovers`` /
    ``test_coordinator_slow_client_bounded_termination``.
    """

    def __init__(
        self,
        collective_timeout_s: float | None = None,
        compress: str = "none",
        robust: Any = None,
        round_deadline_s: float | None = None,
    ):
        self.process_id = jax.process_index()
        self.num_processes = jax.process_count()
        self.collective_timeout_s = collective_timeout_s
        # cross-device round deadline (fed.population.round_deadline_ms):
        # bounds the round-end AGGREGATION gather specifically — a peer
        # that has not contributed by the deadline has missed the round.
        # A missed gather degrades this host to standalone (collectives
        # are ordered; a partial gather cannot be resumed), but bounded:
        # the reference instead blocks until its 2-day gloo timeout.
        self.round_deadline_s = round_deadline_s
        self.deadline_misses = 0
        self.degraded_by_timeout = False
        self.compress = validate_compress(compress)
        self.robust = robust  # fed.robust section; None/mean = plain FedAvg
        self.degraded = False
        self._shutdown_done = False
        if self.num_processes > 1:
            # registered AFTER jax.distributed.initialize's own atexit hook,
            # so ours runs FIRST (LIFO): even a driver that never calls
            # finalize() gets the synchronized teardown below instead of
            # the destructor race
            atexit.register(self._synchronized_shutdown)

    @property
    def is_server(self) -> bool:
        return self.process_id == 0

    def _collective(
        self,
        fn: Callable[[], Any],
        fallback: Callable[[], Any],
        timeout_s: float | None = None,
        kind: str = "collective",
    ) -> Any:
        """Run one DCN collective under the watchdog; local fallback after
        the world is known-broken. ``timeout_s`` overrides the runtime
        watchdog for THIS call (the round-deadline bound on the aggregate
        gather); ``kind`` labels the failure for the operator. The
        abandoned worker thread stays blocked in the dead collective — it
        is a daemon and never rejoined."""
        if self.degraded:
            return fallback()
        timeout = timeout_s if timeout_s is not None else self.collective_timeout_s
        if not timeout:
            return fn()
        box: list = []
        errs: list = []

        def target():
            try:
                box.append(fn())
            except Exception as exc:  # collective error == peer failure
                errs.append(exc)

        t = threading.Thread(target=target, daemon=True)
        t.start()
        t.join(timeout)
        if t.is_alive() or errs:
            timed_out = t.is_alive()
            why = f"error: {errs[0]!r}" if errs else (
                f"timeout after {timeout}s"
            )
            print(
                f"[multihost] process {self.process_id}: {kind} failed "
                f"({why}); degrading to standalone training for the "
                "remaining rounds"
            )
            self.degraded = True
            self.degraded_by_timeout = timed_out
            return fallback()
        return box[0]

    def start_round(self, round_idx: int, total_rounds: int) -> int:
        """Negotiate the next round: returns the SERVER's round index, or -1
        to stop. Clients must adopt the returned counter (their own may be
        stale after a partial-snapshot resume). Locally (single process or
        degraded) it is the caller's own counter that decides."""
        local = round_idx if round_idx < total_rounds else -1
        if self.num_processes == 1:
            return local
        return self._collective(
            lambda: broadcast_round_index(local if self.is_server else 0),
            lambda: local,
        )

    def sync_from_server(self, params: Any) -> Any:
        if self.num_processes == 1:
            return params
        return self._collective(
            lambda: broadcast_params(params, is_source=self.is_server),
            lambda: params,
        )

    def aggregate(
        self, params: Any, participated: bool = True, weight: float = 1.0,
        base: Any = None,
    ) -> Any:
        """Weighted FedAvg across processes. ``weight`` is this process's
        aggregation mass (e.g. its example count for classic FedAvg);
        non-participants contribute 0 regardless. ``base`` (the round-start
        global every process holds) switches int8 compression to tighter
        delta quantization — see :func:`aggregate_from_hosts`.

        When ``round_deadline_s`` is set, THIS collective — the round's
        report-collection point — is bounded by it (taking precedence over
        the general watchdog): a gather still incomplete at the deadline
        counts a ``deadline_miss``, keeps local params for the round, and
        degrades the host (collectives are ordered, so a partial gather
        cannot be resumed — bounded, never wedged)."""
        if self.num_processes == 1:
            return params
        w = float(weight) if participated else 0.0
        deadline = self.round_deadline_s
        before = self.degraded
        out = self._collective(
            lambda: aggregate_from_hosts(
                params, w, compress=self.compress, base=base,
                robust=self.robust,
            ),
            lambda: params,
            timeout_s=deadline if deadline else None,
            kind=(
                f"round aggregation (deadline {deadline}s)"
                if deadline else "collective"
            ),
        )
        if (
            deadline and self.degraded and not before
            and self.degraded_by_timeout  # an ERROR is a peer failure,
        ):                                # not a deadline cut
            self.deadline_misses += 1
            from fedrec_tpu.obs import get_registry

            get_registry().counter(
                "fed.dcn_deadline_misses_total",
                "round-end DCN gathers cut at the round deadline "
                "(host degraded to standalone)",
            ).inc()
        return out

    def _synchronized_shutdown(self) -> None:
        """Healthy-world teardown: barrier, clients disconnect, server last.

        ``jax_enable_recoverability`` makes the default shutdown barrier
        non-blocking for recoverable tasks (the runtime warns exactly
        this), so without an explicit sync the LEADER can exit and tear
        down the coordination service while slower peers' disconnect RPCs
        are still in flight — their C++ client then fatally terminates
        them at interpreter teardown (observed on a healthy 4-process
        run). Sequence here: one collective barrier (under the watchdog,
        so a peer that died right at exit degrades us instead of hanging),
        then non-server processes disconnect immediately while the server
        grants a grace period before taking the service down with it.
        """
        if self._shutdown_done or self.degraded or self.num_processes == 1:
            return
        self._shutdown_done = True
        if not self.collective_timeout_s:
            # even without a configured watchdog, the exit barrier must be
            # BOUNDED: a peer that crashed mid-round (uncaught exception)
            # would otherwise deadlock this process's interpreter exit
            self.collective_timeout_s = 60.0
        self._collective(
            lambda: multihost_utils.sync_global_devices("fedrec_shutdown"),
            lambda: None,
        )
        if self.degraded:
            return  # barrier failed; degraded teardown path owns the exit
        try:
            if self.is_server:
                # let every client's disconnect land before the service dies
                time.sleep(3.0)
            jax.distributed.shutdown()
        except Exception as exc:  # noqa: BLE001 — exit must stay clean
            print(
                f"[multihost] process {self.process_id}: distributed "
                f"shutdown raised {exc!r} (ignored)"
            )

    def finalize(self, exit_code: int = 0) -> None:
        """Call after the round loop, once all artifacts are flushed.

        Healthy world: synchronized teardown (see
        :meth:`_synchronized_shutdown`), then return normally.

        Degraded mode: the coordination service is broken — any shutdown
        barrier (including the one the distributed client's destructor
        runs at interpreter teardown) either hangs or terminates the
        process with a fatal coordination-service error. The only clean
        exit is to skip teardown entirely via ``os._exit``.
        """
        if not self.degraded:
            self._synchronized_shutdown()
        if not self.degraded:  # may have flipped during the shutdown barrier
            return
        import os
        import sys

        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(exit_code)
