"""Multi-host rendezvous + coordinator round control over DCN.

Replaces the reference's entire hub-and-spoke deployment plumbing:

  * torchrun c10d rendezvous (``--rdzv-backend=c10d --rdzv-endpoint=...``,
    reference ``README.md:27-46``) -> ``jax.distributed.initialize``.
  * Server weight broadcast per round (``server.py:74-77`` broadcasting every
    parameter tensor from rank 1) -> one
    ``multihost_utils.broadcast_one_to_all`` of the whole parameter pytree.
  * Client -> server full ``state_dict`` streamed over raw TCP sockets in
    1 KB chunks, ~268 MB/client/round (``client.py:191-210``,
    ``server.py:80-98``, Final_Report.pdf VII.b) -> ``process_allgather``:
    arrays are natively exchangeable through XLA's collectives, so the file
    side channel (an artifact of gloo's tensor-only API) simply disappears —
    and only the ~2M trainable params travel, never the frozen trunk.
  * The 1.0/0.0 continue/stop flag broadcast (``server.py:74,105``,
    ``client.py:256-258``) -> ``broadcast_round_flag``.

Fault tolerance: ``aggregate_from_hosts`` takes a participation weight per
process, so a round aggregates over whichever clients reported — the
reference instead hangs until its 2-day gloo timeout if any client dies
(``client.py:227``, Final_Report.pdf VII.a).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import multihost_utils


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> tuple[int, int]:
    """Join the multi-host world; returns (process_id, num_processes).

    All arguments default to cluster auto-detection (TPU pod metadata); set
    them explicitly for manual bring-up, e.g. CPU-based integration tests.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_index(), jax.process_count()


def broadcast_params(params: Any, is_source: bool | None = None) -> Any:
    """Server -> all clients weight fan-out (reference ``server.py:76-77``)."""
    return multihost_utils.broadcast_one_to_all(params, is_source=is_source)


def broadcast_round_flag(keep_going: bool) -> bool:
    """Continue/stop control flag (reference ``server.py:74,105``)."""
    flag = multihost_utils.broadcast_one_to_all(
        jnp.asarray(1.0 if keep_going else 0.0)
    )
    return bool(float(flag) != 0.0)


def aggregate_from_hosts(params: Any, weight: float = 1.0) -> Any:
    """Participation-weighted FedAvg across processes.

    Each process contributes its local parameter pytree with ``weight``
    (0 = this client sat the round out). Every process receives the
    aggregate — the allgather-based replacement for the server's
    TCP-gather + key-wise mean (``server.py:37-55,102``).
    """
    weighted = jax.tree_util.tree_map(lambda p: np.asarray(p) * weight, params)
    gathered = multihost_utils.process_allgather(weighted)  # leading axis = process
    weights = multihost_utils.process_allgather(np.asarray(weight, np.float32))
    total = float(np.sum(weights))
    if total == 0.0:
        return params  # nobody reported; keep local (no NaNs)
    return jax.tree_util.tree_map(lambda g: jnp.asarray(np.sum(g, axis=0) / total), gathered)


class CoordinatorRuntime:
    """Host-level round loop for the true client/server deployment.

    Process 0 acts as the aggregation server (the reference uses global rank
    1 as the source, ``client.py:257`` — an arbitrary choice; we use 0).
    Single-process fallback: all methods degrade to no-ops so the same
    driver script runs standalone.
    """

    def __init__(self):
        self.process_id = jax.process_index()
        self.num_processes = jax.process_count()

    @property
    def is_server(self) -> bool:
        return self.process_id == 0

    def start_round(self, round_idx: int, total_rounds: int) -> bool:
        if self.num_processes == 1:
            return round_idx < total_rounds
        return broadcast_round_flag(round_idx < total_rounds)

    def sync_from_server(self, params: Any) -> Any:
        if self.num_processes == 1:
            return params
        return broadcast_params(params, is_source=self.is_server)

    def aggregate(self, params: Any, participated: bool = True) -> Any:
        if self.num_processes == 1:
            return params
        return aggregate_from_hosts(params, 1.0 if participated else 0.0)
