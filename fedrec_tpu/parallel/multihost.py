"""Multi-host rendezvous + coordinator round control over DCN.

Replaces the reference's entire hub-and-spoke deployment plumbing:

  * torchrun c10d rendezvous (``--rdzv-backend=c10d --rdzv-endpoint=...``,
    reference ``README.md:27-46``) -> ``jax.distributed.initialize``.
  * Server weight broadcast per round (``server.py:74-77`` broadcasting every
    parameter tensor from rank 1) -> one
    ``multihost_utils.broadcast_one_to_all`` of the whole parameter pytree.
  * Client -> server full ``state_dict`` streamed over raw TCP sockets in
    1 KB chunks, ~268 MB/client/round (``client.py:191-210``,
    ``server.py:80-98``, Final_Report.pdf VII.b) -> ``process_allgather``:
    arrays are natively exchangeable through XLA's collectives, so the file
    side channel (an artifact of gloo's tensor-only API) simply disappears —
    and only the ~2M trainable params travel, never the frozen trunk.
  * The 1.0/0.0 continue/stop flag broadcast (``server.py:74,105``,
    ``client.py:256-258``) -> ``broadcast_round_flag``.

Fault tolerance: ``aggregate_from_hosts`` takes a participation weight per
process, so a round aggregates over whichever clients reported — the
reference instead hangs until its 2-day gloo timeout if any client dies
(``client.py:227``, Final_Report.pdf VII.a).
"""

from __future__ import annotations

import atexit
import os
import random
import threading
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import multihost_utils

# start_round's out-of-band control value: the server broadcasts it at a
# round boundary when the elastic membership layer needs the world to
# REFORM (a peer rejoined, or the server's lease watchdog flagged a loss).
# Every worker receiving it saves a hand-off snapshot and leaves the world
# so the next membership epoch can form; -1 keeps meaning "stop".
REFORM_SIGNAL = -2


def _attempt_address(addr: str | None, attempt: int) -> str | None:
    """Rendezvous address for retry ``attempt``: the configured port plus
    the attempt index. Every peer derives the SAME schedule, so after a
    failed bring-up the whole world realigns on a fresh port — the broken
    attempt's coordination service and gloo pairs are abandoned in place
    (shutting them down is what fatally terminates the process, XLA
    ``client.h:80``; see ``_abandon_broken_world``)."""
    if addr is None or attempt == 0:
        return addr
    host, port = addr.rsplit(":", 1)
    return f"{host}:{int(port) + attempt}"


def _probe_transport(timeout_s: float) -> None:
    """One bounded warm-up collective after rendezvous: the gloo TCP pairs
    connect lazily at the FIRST collective, which is where the known
    transport flake ("pair.cc: Connection closed by peer") surfaces — not
    at ``jax.distributed.initialize``. Probing here turns that flake into
    a retryable bring-up failure instead of a mid-training world break.
    The peer whose pair broke sees the error; every other peer's probe
    hangs and times out — so ALL peers fail the attempt and realign on
    the next attempt's address."""
    box: list = []
    errs: list = []

    def target():
        try:
            box.append(
                multihost_utils.sync_global_devices("fedrec_transport_probe")
            )
        except Exception as exc:  # noqa: BLE001 — transport probe failure
            errs.append(exc)

    t = threading.Thread(target=target, daemon=True)
    t.start()
    t.join(timeout_s)
    if errs:
        raise RuntimeError(f"transport probe failed: {errs[0]!r}")
    if t.is_alive():
        raise RuntimeError(
            f"transport probe timed out after {timeout_s}s (a peer's gloo "
            "pair likely broke; retrying the rendezvous)"
        )


def _abandon_broken_world() -> None:
    """Detach from a broken bring-up WITHOUT calling shutdown: the
    shutdown barrier on a broken world is exactly the observed fatal path
    (``client.h:80`` terminates the process when the disconnect RPC cannot
    complete). The old client/service objects are leaked in place — their
    heartbeats keep each other content on the abandoned port while the
    retry rendezvouses on the next one — and the backend cache is cleared
    so the next device use rebuilds gloo pairs against the new client."""
    from jax._src import distributed as _dist

    state = _dist.global_state
    state.client = None
    state.service = None
    state.preemption_sync_manager = None
    try:
        from jax.extend import backend as _backend

        _backend.clear_backends()
    except Exception:  # noqa: BLE001 — backends may not exist yet
        pass


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    initialization_timeout: float | None = None,
    rendezvous_retries: int = 2,
    probe_timeout_s: float = 30.0,
) -> tuple[int, int]:
    """Join the multi-host world; returns (process_id, num_processes).

    All arguments default to cluster auto-detection (TPU pod metadata); set
    them explicitly for manual bring-up, e.g. CPU-based integration tests.

    Bring-up is RETRIED (``rendezvous_retries`` extra attempts, jittered
    backoff): on the CPU/gloo path each attempt ends with a bounded
    warm-up collective (:func:`_probe_transport`) so the known gloo
    transport flake — a TCP pair dying at the first collective, which
    used to fail ``test_multihost_world`` and block the shard smoke's
    2-process step leg — fails the ATTEMPT instead of the run. Attempt
    *k* rendezvouses on ``port + k`` (every peer derives the same
    schedule) because a broken attempt's coordination service cannot be
    safely shut down or re-bound (see :func:`_abandon_broken_world`).

    ``jax_enable_recoverability`` is enabled: without it the coordination
    service propagates any task failure as fatal to every non-leader.
    NOTE the remaining platform constraint: the runtime client's error
    poller still TERMINATES the process (XLA ``client.h:80``) when the
    coordination service itself goes away (it lives in process 0), and a
    degraded client's disconnect blocks behind the broken world. Degraded
    mode in a long-lived deployment must therefore LEAVE the runtime —
    the coordinator CLI re-execs a degraded client as a standalone
    continuation from its local snapshot (see
    ``fedrec_tpu.cli.coordinator``).
    """
    try:
        jax.config.update("jax_enable_recoverability", True)
    except AttributeError:  # older jax without the flag: keep prior behavior
        pass
    # Backend must not be touched before jax.distributed.initialize, so key
    # off the requested platform rather than jax.default_backend().
    platforms = os.environ.get("JAX_PLATFORMS", "") or str(
        getattr(jax.config, "jax_platforms", None) or ""
    )
    first = platforms.split(",")[0].strip().lower()
    if first in ("cpu", ""):
        # XLA:CPU has no native multiprocess collectives ("Multiprocess
        # computations aren't implemented on the CPU backend") — route them
        # through gloo so CPU worlds (a default-backend CPU host as much
        # as an explicit JAX_PLATFORMS=cpu one; test_elastic,
        # test_supervisor) exercise the real cross-process path. With an
        # accelerator present ("" resolves to tpu/gpu) the setting is
        # inert: it only selects the CPU backend's collectives impl.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):  # older jax / no gloo build
            pass
    kwargs: dict = {}
    if initialization_timeout:
        # bounded bring-up for supervised relaunches: a respawn racing a
        # dying world must FAIL (and be retried by its supervisor) rather
        # than sit in jax's default 5-minute rendezvous wait
        kwargs["initialization_timeout"] = int(initialization_timeout)
    # the probe only makes sense for an explicit multi-process CPU/gloo
    # bring-up: auto-detected TPU pods keep their native ICI transport
    # (and their init-time behavior) untouched
    probe = (
        coordinator_address is not None
        and (num_processes or 1) > 1
        and first in ("cpu", "")
    )
    attempts = max(int(rendezvous_retries), 0) + 1 if probe else 1
    rng = random.Random(os.getpid())
    for attempt in range(attempts):
        addr = _attempt_address(coordinator_address, attempt)
        # an INITIALIZE failure raises immediately in every attempt: it is
        # NOT collective (e.g. one respawn racing a dying world fails
        # alone), so retrying it in-process would walk this peer down the
        # port schedule while the others wait at the base port — that
        # retry belongs to the supervisor. Only the PROBE below — a
        # collective every peer fails together — advances the schedule.
        try:
            jax.distributed.initialize(
                coordinator_address=addr,
                num_processes=num_processes,
                process_id=process_id,
                **kwargs,
            )
        except TypeError:  # older jax without initialization_timeout
            jax.distributed.initialize(
                coordinator_address=addr,
                num_processes=num_processes,
                process_id=process_id,
            )
        if not probe:
            break
        try:
            _probe_transport(probe_timeout_s)
            break
        except RuntimeError as exc:
            if attempt + 1 >= attempts:
                raise
            _abandon_broken_world()
            delay = min(1.0 * (attempt + 1), 5.0) * (0.5 + rng.random())
            print(
                f"[multihost] transport probe attempt {attempt + 1}/"
                f"{attempts} failed ({exc}); re-rendezvous on "
                f"{_attempt_address(coordinator_address, attempt + 1)} "
                f"in {delay:.1f}s",
                flush=True,
            )
            time.sleep(delay)
    return jax.process_index(), jax.process_count()


def broadcast_params(params: Any, is_source: bool | None = None) -> Any:
    """Server -> all clients weight fan-out (reference ``server.py:76-77``)."""
    return multihost_utils.broadcast_one_to_all(params, is_source=is_source)


def broadcast_round_flag(keep_going: bool) -> bool:
    """Continue/stop control flag (reference ``server.py:74,105``)."""
    flag = multihost_utils.broadcast_one_to_all(
        jnp.asarray(1.0 if keep_going else 0.0)
    )
    return bool(float(flag) != 0.0)


def broadcast_round_index(round_idx: int) -> int:
    """Server -> clients round counter; -1 = stop.

    Subsumes the reference's 1.0/0.0 flag (``server.py:74,105``) AND pins
    every host to the server's round index — a client resumed from a stale
    (or missing) local snapshot would otherwise run a different counter than
    the server: different batch seeds, misaligned save cadence, mislabeled
    global snapshots.
    """
    v = multihost_utils.broadcast_one_to_all(jnp.asarray(round_idx, jnp.int32))
    return int(v)


def validate_compress(compress: str) -> str:
    """Fail FAST on a bad codec name (delegates to the
    :mod:`fedrec_tpu.comms` registry): raised lazily inside the aggregation
    collective, a typo would be misread by the watchdog as a peer failure
    and silently degrade every host to standalone training."""
    from fedrec_tpu.comms import validate_codec

    return validate_codec(compress)


def _bank_dcn_bytes(
    up: int = 0, down: int = 0, dense: int = 0, encoded: int = 0
) -> None:
    """Publish REAL cross-host wire bytes into the metrics registry
    (path="dcn" — the Trainer's simulated in-graph uplink uses
    path="cohort"). Bytes are measured from the encoded buffers the
    collective actually ships, not dtype arithmetic."""
    from fedrec_tpu.obs import get_registry

    reg = get_registry()
    if up:
        reg.counter(
            "fed.dcn_bytes_up_total",
            "client->server round-update bytes shipped, by path",
            labels=("path",),
        ).inc(float(up), path="dcn")
    if down:
        reg.counter(
            "fed.dcn_bytes_down_total",
            "server->client fan-out bytes (full precision), by path",
            labels=("path",),
        ).inc(float(down), path="dcn")
    if dense and encoded:
        reg.gauge(
            "fed.dcn_compression_ratio",
            "dense/encoded byte ratio of one client's round-update payload",
        ).set(dense / encoded)


def _allgather_stacked(tree_and_weight: tuple) -> tuple:
    """``process_allgather`` with the leading (P,) process dim GUARANTEED:
    in a single-process world the gather is an identity (no stacking), so
    the P=1 case — exercised directly by unit tests, and by a degraded
    host finishing standalone — gets the dim added by hand. Weights come
    back as a (P,) float32 vector either way."""
    gathered, weights = multihost_utils.process_allgather(tree_and_weight)
    w = np.asarray(weights, np.float32)
    if w.ndim == 0:
        gathered = jax.tree_util.tree_map(
            lambda x: np.asarray(x)[None], gathered
        )
        w = w[None]
    return gathered, w


def aggregate_from_hosts(
    params: Any,
    weight: float = 1.0,
    compress: str = "none",
    base: Any = None,
    robust: Any = None,
    codec_state: Any = None,
    topk_ratio: float = 0.01,
    error_feedback: bool = True,
    agg: Any = None,
    sketch_width: float = 0.1,
    sketch_seed: int = 0,
) -> Any:
    """Participation-weighted FedAvg across processes.

    Each process contributes its local parameter pytree with ``weight``
    (0 = this client sat the round out). Every process receives the
    aggregate — the allgather-based replacement for the server's
    TCP-gather + key-wise mean (``server.py:37-55,102``).

    ``compress`` selects an update codec from :mod:`fedrec_tpu.comms`
    (``int8`` | ``sign1bit`` | ``topk``): the client->server payload is the
    ENCODED contribution — real int8/bit-packed/index+value buffers through
    ``process_allgather`` — while the server->client fan-out
    (:func:`broadcast_params`) stays full precision (quantizing the global
    model would bias every client's training; compressing only the
    per-round CONTRIBUTIONS adds bounded reconstruction error to the mean,
    and the biased codecs bank that error per process via ``codec_state``).

    DECODE-BEFORE-REDUCE vs SUM-THEN-DECODE: the per-contribution codecs
    (int8/sign1bit/topk) densify every gathered contribution per process
    before ANY reduction, so ``robust`` (a ``fed.robust`` section with
    ``method != "mean"``) composes with them — trimmed-mean/median judge
    clients, not quantization noise. The LINEAR sketches (countsketch /
    randproj) take the other branch: the allgather ships fixed-size
    sketch images, the weighted sum runs in sketch space, and ONE decode
    happens at the root (``decode(Σ enc(xᵢ)) == Σ x̂ᵢ`` by linearity).
    That branch is mean-only — a summed sketch has no per-contribution
    decode, so order statistics fail fast (the capability table in
    :mod:`fedrec_tpu.comms` marks the boundary).

    ``base``: a pytree every process holds identically — the round-start
    global from the server fan-out. With a codec active the round DELTAS
    ``params - base`` are encoded instead of the absolute tensors
    (ADVICE r2): one round's delta spans a far smaller range than the
    parameters, so the codec's levels bound the per-element error by the
    DELTA's range. The weighted mean commutes with the shift:
    ``mean_w(params) == base + mean_w(params - base)``.

    ``codec_state`` (:class:`fedrec_tpu.comms.CodecState`): this process's
    error-feedback residual for the biased codecs (sign1bit/topk with
    ``error_feedback``) — the mass the encode drops is added to the NEXT
    round's contribution. Updated in place; only when this process
    participates (``weight > 0``; a sit-out transmitted nothing).

    DP ordering: clipping + noise happened per step inside training, so
    the delta this function encodes is already privatized — encode runs
    strictly AFTER the mechanism, ε-accounting untouched.

    ``agg`` (an ``agg`` config section): ``mode="hierarchical"`` reduces
    the gathered (P, ...) stacks up an ``agg.tree_fanout`` tree instead
    of one flat robust sweep — the robust method applies PER TIER, the
    tree reforms from the CURRENT gathered world every round (membership
    shrink/rejoin needs no topology invalidation), and the per-level-max
    timing lands in the ``agg.tier_reduce_ms`` gauge.  With
    ``method="mean"`` the hierarchical mode deliberately takes the flat
    einsum below: a tree of partial sums IS the flat weighted mean
    algebraically, so lowering it keeps bit-identity (docs/DESIGN.md).
    Codec composition is decode-before-reduce as always: the tiers see
    densified contributions, so every decodable codec composes with the
    hierarchical reduce exactly as with the flat one.
    """
    validate_compress(compress)
    w_arr = np.asarray(weight, np.float32)
    method = getattr(robust, "method", "mean") if robust is not None else "mean"
    hier = getattr(agg, "mode", "flat") == "hierarchical" and method != "mean"

    def _robust_reduce(stacks, w_np, fallback):
        """The one robust-reduction seam: flat sweep, or the tiered tree
        when agg.mode='hierarchical' (mean never lands here — it lowers
        to the flat einsum/sum paths, bit-identical by algebra)."""
        from fedrec_tpu.fed.robust import robust_reduce_tree_np

        if not hier:
            return robust_reduce_tree_np(
                stacks, w_np, method,
                trim_k=robust.trim_k, clip_norm=robust.clip_norm,
                fallback_tree=fallback,
            )
        from fedrec_tpu.agg.hierarchy import (
            tree_critical_path_ms,
            tree_reduce_np,
        )

        stats: dict = {}
        reduced = tree_reduce_np(
            stacks, w_np, int(getattr(agg, "tree_fanout", 2)), method,
            trim_k=robust.trim_k, clip_norm=robust.clip_norm,
            fallback_tree=fallback, stats=stats,
        )
        from fedrec_tpu.obs import get_registry

        get_registry().gauge(
            "agg.tier_reduce_ms",
            "per-level-max tier-reduce time of the last hierarchical "
            "round, summed over levels (the tree's parallel critical path)",
        ).set(tree_critical_path_ms(stats))
        return reduced

    if method != "mean":
        from fedrec_tpu.fed.robust import validate_robust_method

        validate_robust_method(method)
        if compress != "none":
            from fedrec_tpu.comms import codec_caps

            if not codec_caps(compress).decodes_per_contribution:
                raise ValueError(
                    f"fed.robust.method={method!r} needs per-contribution "
                    f"decode, which codec {compress!r} cannot provide (its "
                    "contributions only exist pre-aggregated: order "
                    "statistics like trimmed-mean/median judge CLIENTS, and "
                    "sketch collisions mix every client's coordinates before "
                    "any decode exists); use one of the decodable codecs "
                    "(int8/sign1bit/topk) or fed.robust.method='mean'"
                )

    if compress != "none":
        from fedrec_tpu.comms import (
            codec_caps,
            codec_uses_feedback,
            decode_gathered,
            decode_leaf,
            decode_tree,
            encode_tree,
            leaf_names,
            payload_nbytes,
            sum_payloads,
            tree_dense_nbytes,
            tree_rmse,
        )

        raw = jax.tree_util.tree_map(
            lambda p: np.asarray(p, np.float32), params
        )
        if base is not None:
            contrib = jax.tree_util.tree_map(
                lambda p, b: p - np.asarray(b, np.float32), raw, base
            )
        else:
            contrib = raw
        use_ef = codec_uses_feedback(compress, error_feedback)
        if use_ef and codec_state is not None and codec_state.residual is not None:
            acc = jax.tree_util.tree_map(
                lambda c, r: c + np.asarray(r, np.float32),
                contrib, codec_state.residual,
            )
        else:
            acc = contrib
        enc = encode_tree(
            acc, compress, topk_ratio,
            sketch_width=sketch_width, sketch_seed=sketch_seed,
        )
        own_decoded = decode_tree(enc)
        if use_ef and codec_state is not None and float(w_arr) > 0:
            codec_state.residual = jax.tree_util.tree_map(
                lambda a, d: a - d, acc, own_decoded
            )
        any_sketch = any(
            not codec_caps(enc.leaf_codec(i)).decodes_per_contribution
            for i in range(len(enc.payloads))
        )
        # ONE collective for payload + weight: fewer DCN round trips, and
        # no window where a peer death strands the runtime between
        # matched gathers
        gathered, weights = _allgather_stacked((enc.payloads, w_arr))
        _bank_dcn_bytes(
            up=enc.nbytes(),
            dense=tree_dense_nbytes(acc),
            encoded=enc.nbytes(),
        )
        from fedrec_tpu.obs import get_registry

        reg = get_registry()
        ratio_leaf = reg.gauge(
            "fed.dcn_compression_ratio_leaf",
            "dense/encoded byte ratio of one round-update tensor, by leaf",
            labels=("leaf",),
        )
        for name, payload, shape in zip(
            leaf_names(acc), enc.payloads, enc.shapes
        ):
            dense_b = 4 * int(np.prod(shape)) if shape else 4
            enc_b = max(payload_nbytes(payload), 1)
            ratio_leaf.set(dense_b / enc_b, leaf=name)
        if any_sketch:
            # measured reconstruction error of THIS process's own sketch
            # round-trip — the live signal an operator tunes
            # fed.dcn_sketch_width against (docs/OPERATIONS.md §3d)
            reg.gauge(
                "fed.dcn_sketch_rmse",
                "RMSE of this process's sketch round-trip (decode(encode(x))"
                " vs x), pooled over all sketched coordinates",
            ).set(tree_rmse(own_decoded, acc))
        total = float(np.sum(weights))
        if total == 0.0:
            return params  # nobody reported; keep local (no NaNs)
        w_np = np.asarray(weights)
        if method != "mean":
            # all leaves decodable here (the sketch fail-fast above):
            # m==0 coordinates keep this host's own decoded
            # contribution (the in-graph fallback contract)
            stacks = decode_gathered(gathered, enc)  # (P, *shape) dense
            reduced = _robust_reduce(stacks, w_np, own_decoded)
        else:
            coeff = (np.where(w_np > 0, w_np, 0.0) / total).astype(np.float32)
            mask_p = w_np > 0

            def _mask_rows(v):
                # zero-WEIGHT contributions are masked out of the sum, not
                # multiplied in: a quarantined process's NaN payload must
                # contribute nothing, not NaN (weighted_param_avg parity)
                a = np.asarray(v, np.float32)
                m = mask_p.reshape((-1,) + (1,) * (a.ndim - 1))
                return np.where(m, a, 0.0)

            out_leaves = []
            for i, (payload, shape) in enumerate(
                zip(gathered, enc.shapes)
            ):
                lc = enc.leaf_codec(i)
                masked = {k: _mask_rows(v) for k, v in payload.items()}
                if not codec_caps(lc).decodes_per_contribution:
                    # SUM-THEN-DECODE: weighted mean in sketch space,
                    # ONE decode at the root — by linearity this IS the
                    # mean of the per-contribution decodes
                    summed = sum_payloads(masked, coeff)
                    out_leaves.append(
                        decode_leaf(
                            summed, lc, shape,
                            sketch_seed=enc.sketch_seed, leaf_id=i,
                        )
                    )
                else:
                    rows = np.stack([
                        decode_leaf(
                            {k: v[p] for k, v in masked.items()},
                            lc, shape,
                            sketch_seed=enc.sketch_seed, leaf_id=i,
                        )
                        for p in range(len(w_np))
                    ])
                    out_leaves.append(
                        np.einsum("p,p...->...", coeff, rows)
                    )
            reduced = jax.tree_util.tree_unflatten(enc.treedef, out_leaves)
        if base is not None:
            reduced = jax.tree_util.tree_map(
                lambda m, b: m + np.asarray(b, np.float32), reduced, base
            )
        return jax.tree_util.tree_map(
            lambda m, p: jnp.asarray(np.asarray(m, np.asarray(p).dtype)),
            reduced, params,
        )

    if method != "mean":
        raw = jax.tree_util.tree_map(lambda p: np.asarray(p, np.float32), params)
        gathered, weights = _allgather_stacked((raw, w_arr))
        from fedrec_tpu.comms import tree_dense_nbytes

        _bank_dcn_bytes(up=tree_dense_nbytes(raw))
        if float(np.sum(weights)) == 0.0:
            return params  # nobody reported; keep local (no NaNs)
        # m==0 coordinates keep local (in-graph parity)
        reduced = _robust_reduce(gathered, np.asarray(weights), raw)
        return jax.tree_util.tree_map(
            lambda m, p: jnp.asarray(np.asarray(m, np.asarray(p).dtype)),
            reduced, params,
        )
    weighted = jax.tree_util.tree_map(lambda p: np.asarray(p) * weight, params)
    from fedrec_tpu.comms import tree_dense_nbytes

    _bank_dcn_bytes(up=tree_dense_nbytes(weighted))
    gathered, weights = _allgather_stacked((weighted, w_arr))
    total = float(np.sum(weights))
    if total == 0.0:
        return params  # nobody reported; keep local (no NaNs)
    return jax.tree_util.tree_map(lambda g: jnp.asarray(np.sum(g, axis=0) / total), gathered)


class CoordinatorRuntime:
    """Host-level round loop for the true client/server deployment.

    Process 0 acts as the aggregation server (the reference uses global rank
    1 as the source, ``client.py:257`` — an arbitrary choice; we use 0).
    Single-process fallback: all methods degrade to no-ops so the same
    driver script runs standalone.

    Unplanned-failure tolerance (``collective_timeout_s``): every DCN
    collective runs under a watchdog. A dead peer hangs the collective for
    every survivor (and would hang every subsequent one too), so on the
    first timeout or collective error the runtime flips to ``degraded``
    mode: all later calls take the local path and the host finishes its
    remaining rounds standalone. The reference instead blocks until its
    2-day gloo timeout and then dies (``client.py:227``,
    Final_Report.pdf VII.a). Planned per-round sit-outs don't need this —
    they are weight-0 participation in :meth:`aggregate`.

    Slow (not dead) peers: a host that stalls past the watchdog and then
    recovers degrades via its OWN watchdog at its next collective and
    finishes standalone — with one platform caveat. The JAX coordination
    service lives in process 0 (like torchrun's c10d rendezvous), so if the
    SERVER has already degraded and exited by the time a slow client wakes,
    the client's distributed runtime fatally terminates it: a bounded
    crash, never a wedge. Both directions are pinned by
    ``test_coordinator_slow_server_recovers`` /
    ``test_coordinator_slow_client_bounded_termination``.
    """

    def __init__(
        self,
        collective_timeout_s: float | None = None,
        compress: str = "none",
        robust: Any = None,
        round_deadline_s: float | None = None,
        topk_ratio: float = 0.01,
        error_feedback: bool = True,
        membership: Any = None,
        epoch: int = 0,
        agg: Any = None,
        sketch_width: float = 0.1,
        sketch_seed: int = 0,
    ):
        self.process_id = jax.process_index()
        self.num_processes = jax.process_count()
        # elastic membership (fedrec_tpu.parallel.membership): the client
        # whose lease-renewal thread latches reform_pending, and this
        # world's membership epoch. None = the fixed pre-elastic world —
        # start_round then never emits REFORM_SIGNAL (degenerate contract).
        self.membership = membership
        self.epoch = int(epoch)
        self.collective_timeout_s = collective_timeout_s
        # cross-device round deadline (fed.population.round_deadline_ms):
        # bounds the round-end AGGREGATION gather specifically — a peer
        # that has not contributed by the deadline has missed the round.
        # A missed gather degrades this host to standalone (collectives
        # are ordered; a partial gather cannot be resumed), but bounded:
        # the reference instead blocks until its 2-day gloo timeout.
        self.round_deadline_s = round_deadline_s
        self.deadline_misses = 0
        self.degraded_by_timeout = False
        self.compress = validate_compress(compress)
        self.robust = robust  # fed.robust section; None/mean = plain FedAvg
        self.agg = agg  # agg section; hierarchical = per-tier robust reduce
        self.topk_ratio = topk_ratio
        self.error_feedback = error_feedback
        self.sketch_width = sketch_width
        self.sketch_seed = sketch_seed
        # this process's error-feedback residual for the biased codecs
        # (sign1bit/topk): the wire endpoint's EF state, persisted by the
        # coordinator CLI at save cadence so a resumed run keeps carrying
        # the dropped mass (a fresh/restarted process starts from zero —
        # the same bounded-staleness contract as a fresh logical client)
        from fedrec_tpu.comms import CodecState, codec_uses_feedback

        self.codec_state = (
            CodecState()
            if codec_uses_feedback(self.compress, error_feedback)
            else None
        )
        self.degraded = False
        self._shutdown_done = False
        if self.num_processes > 1:
            # registered AFTER jax.distributed.initialize's own atexit hook,
            # so ours runs FIRST (LIFO): even a driver that never calls
            # finalize() gets the synchronized teardown below instead of
            # the destructor race
            atexit.register(self._synchronized_shutdown)

    @property
    def is_server(self) -> bool:
        return self.process_id == 0

    def _collective(
        self,
        fn: Callable[[], Any],
        fallback: Callable[[], Any],
        timeout_s: float | None = None,
        kind: str = "collective",
    ) -> Any:
        """Run one DCN collective under the watchdog; local fallback after
        the world is known-broken. ``timeout_s`` overrides the runtime
        watchdog for THIS call (the round-deadline bound on the aggregate
        gather); ``kind`` labels the failure for the operator. The
        abandoned worker thread stays blocked in the dead collective — it
        is a daemon and never rejoined."""
        if self.degraded:
            return fallback()
        timeout = timeout_s if timeout_s is not None else self.collective_timeout_s
        if not timeout:
            return fn()
        box: list = []
        errs: list = []

        def target():
            try:
                box.append(fn())
            except Exception as exc:  # collective error == peer failure
                errs.append(exc)

        t = threading.Thread(target=target, daemon=True)
        t.start()
        t.join(timeout)
        if t.is_alive() or errs:
            timed_out = t.is_alive()
            why = f"error: {errs[0]!r}" if errs else (
                f"timeout after {timeout}s"
            )
            print(
                f"[multihost] process {self.process_id}: {kind} failed "
                f"({why}); degrading to standalone training for the "
                "remaining rounds"
            )
            self.degraded = True
            self.degraded_by_timeout = timed_out
            return fallback()
        return box[0]

    def start_round(self, round_idx: int, total_rounds: int) -> int:
        """Negotiate the next round: returns the SERVER's round index, -1
        to stop, or :data:`REFORM_SIGNAL` when the elastic membership
        layer wants the world to reform at this boundary (a rejoining
        peer, or the server's lease watchdog flagged a loss the
        collectives have not hit yet). Clients must adopt the returned
        counter (their own may be stale after a partial-snapshot resume).
        Locally (single process or degraded) it is the caller's own
        counter that decides.

        The reform decision is the SERVER's and travels in the SAME
        broadcast as the round counter — one collective, so every worker
        leaves at the identical boundary instead of discovering the
        reform at skewed heartbeat times and stranding each other's
        collectives mid-round (the reformation barrier)."""
        local = round_idx if round_idx < total_rounds else -1
        if (
            self.membership is not None
            and self.is_server
            and local >= 0
            and self.membership.reform_pending
        ):
            local = REFORM_SIGNAL
        if self.num_processes == 1:
            return local
        return self._collective(
            lambda: broadcast_round_index(local if self.is_server else 0),
            lambda: local,
        )

    def sync_from_server(self, params: Any) -> Any:
        if self.num_processes == 1:
            return params
        out = self._collective(
            lambda: broadcast_params(params, is_source=self.is_server),
            lambda: params,
        )
        if not self.degraded:
            from fedrec_tpu.comms import tree_dense_nbytes

            # the fan-out is full precision in every codec mode (pinned:
            # compressing the GLOBAL would bias every client's training)
            _bank_dcn_bytes(down=tree_dense_nbytes(params))
        return out

    def aggregate(
        self, params: Any, participated: bool = True, weight: float = 1.0,
        base: Any = None,
    ) -> Any:
        """Weighted FedAvg across processes. ``weight`` is this process's
        aggregation mass (e.g. its example count for classic FedAvg);
        non-participants contribute 0 regardless. ``base`` (the round-start
        global every process holds) switches int8 compression to tighter
        delta quantization — see :func:`aggregate_from_hosts`.

        When ``round_deadline_s`` is set, THIS collective — the round's
        report-collection point — is bounded by it (taking precedence over
        the general watchdog): a gather still incomplete at the deadline
        counts a ``deadline_miss``, keeps local params for the round, and
        degrades the host (collectives are ordered, so a partial gather
        cannot be resumed — bounded, never wedged)."""
        if self.num_processes == 1:
            return params
        w = float(weight) if participated else 0.0
        deadline = self.round_deadline_s
        before = self.degraded
        out = self._collective(
            lambda: aggregate_from_hosts(
                params, w, compress=self.compress, base=base,
                robust=self.robust, codec_state=self.codec_state,
                topk_ratio=self.topk_ratio,
                error_feedback=self.error_feedback, agg=self.agg,
                sketch_width=self.sketch_width,
                sketch_seed=self.sketch_seed,
            ),
            lambda: params,
            timeout_s=deadline if deadline else None,
            kind=(
                f"round aggregation (deadline {deadline}s)"
                if deadline else "collective"
            ),
        )
        if (
            deadline and self.degraded and not before
            and self.degraded_by_timeout  # an ERROR is a peer failure,
        ):                                # not a deadline cut
            self.deadline_misses += 1
            from fedrec_tpu.obs import get_registry

            get_registry().counter(
                "fed.dcn_deadline_misses_total",
                "round-end DCN gathers cut at the round deadline "
                "(host degraded to standalone)",
            ).inc()
        return out

    def _synchronized_shutdown(self) -> None:
        """Healthy-world teardown: barrier, clients disconnect, server last.

        ``jax_enable_recoverability`` makes the default shutdown barrier
        non-blocking for recoverable tasks (the runtime warns exactly
        this), so without an explicit sync the LEADER can exit and tear
        down the coordination service while slower peers' disconnect RPCs
        are still in flight — their C++ client then fatally terminates
        them at interpreter teardown (observed on a healthy 4-process
        run). Sequence here: one collective barrier (under the watchdog,
        so a peer that died right at exit degrades us instead of hanging),
        then non-server processes disconnect immediately while the server
        grants a grace period before taking the service down with it.
        """
        if self._shutdown_done or self.degraded or self.num_processes == 1:
            return
        self._shutdown_done = True
        if not self.collective_timeout_s:
            # even without a configured watchdog, the exit barrier must be
            # BOUNDED: a peer that crashed mid-round (uncaught exception)
            # would otherwise deadlock this process's interpreter exit
            self.collective_timeout_s = 60.0
        self._collective(
            lambda: multihost_utils.sync_global_devices("fedrec_shutdown"),
            lambda: None,
        )
        if self.degraded:
            return  # barrier failed; degraded teardown path owns the exit
        try:
            if self.is_server:
                # let every client's disconnect land before the service dies
                time.sleep(3.0)
            jax.distributed.shutdown()
        except Exception as exc:  # noqa: BLE001 — exit must stay clean
            print(
                f"[multihost] process {self.process_id}: distributed "
                f"shutdown raised {exc!r} (ignored)"
            )

    def finalize(self, exit_code: int = 0) -> None:
        """Call after the round loop, once all artifacts are flushed.

        Healthy world: synchronized teardown (see
        :meth:`_synchronized_shutdown`), then return normally.

        Degraded mode: the coordination service is broken — any shutdown
        barrier (including the one the distributed client's destructor
        runs at interpreter teardown) either hangs or terminates the
        process with a fatal coordination-service error. The only clean
        exit is to skip teardown entirely via ``os._exit``.
        """
        if not self.degraded:
            self._synchronized_shutdown()
        if not self.degraded:  # may have flipped during the shutdown barrier
            return
        import os
        import sys

        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(exit_code)
