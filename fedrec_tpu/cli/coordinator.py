"""Coordinator deployment driver — the reference's client.py/server.py pair.

One script for both roles (the reference needs two divergent scripts plus a
raw-TCP side channel; see SURVEY.md section 2.3). Each participating host
runs:

  python -m fedrec_tpu.cli.coordinator ROUNDS BATCH SAVE_EVERY \
      --coordinator HOST:PORT --num-processes N --process-id I \
      [--dp-epsilon 10] [--server-trains] [--set section.key=value ...]

Process 0 is the aggregation server (reference uses rank 1,
``client.py:257``). Round loop parity:

  * continue/stop flag broadcast  (reference ``server.py:74,105``)
  * server weight fan-out          (``server.py:76-77``) — one pytree
    broadcast over DCN, not per-tensor gloo broadcasts + TCP files
  * local training epochs          (``client.py:284``)
  * participation-weighted gather  (``server.py:80-103``) — clients that
    miss a round simply contribute weight 0 instead of killing the job
    (fixes Final_Report.pdf VII.a)

Runs standalone too (single process): degrades to local FedAvg.
"""

from __future__ import annotations

import os
import sys
from pathlib import Path

import numpy as np

from fedrec_tpu.cli.run import build_parser

# EX_TEMPFAIL: a supervised worker's "world broken, relaunch me" status —
# the supervisor respawns the FULL distributed invocation, which
# re-rendezvouses and resumes from local snapshots (the elastic path)
RESPAWN_EXIT = 75


def _argv_value(tokens: list[str], flag: str) -> str | None:
    """The value of ``--flag X`` / ``--flag=X`` in an argv slice, or None."""
    for i, tok in enumerate(tokens):
        if tok == flag and i + 1 < len(tokens):
            return tokens[i + 1]
        if tok.startswith(flag + "="):
            return tok.split("=", 1)[1]
    return None


def _membership_status(address: str) -> dict | None:
    """Best-effort status query against the membership service (the
    supervisor's handshake source); None when unreachable."""
    try:
        from fedrec_tpu.parallel.membership import MembershipClient

        return MembershipClient(
            address, worker_id="_supervisor", rpc_timeout_s=5.0
        ).status()
    except Exception:  # noqa: BLE001 — a down service must not stop respawns
        return None


def _supervise(argv: list[str]) -> int:
    """``--supervise``: wrap the worker in an auto-respawn loop.

    The worker runs as a child process; whenever it dies abnormally — a
    crash/kill (negative returncode), or the deliberate
    :data:`RESPAWN_EXIT` a supervised worker uses when its world breaks —
    the supervisor relaunches the identical invocation after a jittered
    backoff. Every relaunch re-rendezvouses at the same coordinator
    address and resumes from the local snapshots (counter negotiation +
    ``sync_from_server`` integrate even a worker that never saved), so a
    killed peer turns test_elastic's manual stop-the-world restart story
    into zero operator actions: run every host with ``--supervise`` and
    the run finishes.

    The first respawn waits about the worker's ``--collective-timeout``:
    the surviving peers need that long to notice the broken world, exit
    with :data:`RESPAWN_EXIT` themselves, and free the coordination
    service address for the new world. ``FEDREC_SUPERVISE_MAX`` (default
    20) bounds the respawn budget; ``FEDREC_WORKER_PIDFILE`` (if set)
    receives the live worker's pid, so chaos tooling can kill it.

    Elastic handshake (``--membership``): before every (re)spawn the
    supervisor queries the membership service and hands the child the
    CURRENT epoch via ``FEDREC_MEMBERSHIP_EPOCH`` — and when the service
    shows a reformation already in progress (epoch advanced since the
    child started, joiners parked, or reform pending) the backoff is cut
    to ~1s: the rc-75 exit IS the reformation protocol, so making the
    child wait out a crash-grade backoff would stall the forming epoch
    for every other member. Without the handshake a respawned child
    re-execs into whatever rendezvous it last knew — the dead world —
    and loops.
    """
    import random
    import subprocess
    import time

    keep = [t for t in argv if t != "--supervise"]
    env = dict(os.environ, FEDREC_SUPERVISED="1")
    pidfile = os.environ.get("FEDREC_WORKER_PIDFILE")
    membership_addr = _argv_value(keep, "--membership")
    last_epoch: int | None = None
    base_delay = 5.0
    for i, tok in enumerate(keep):
        val = None
        if tok == "--collective-timeout" and i + 1 < len(keep):
            val = keep[i + 1]
        elif tok.startswith("--collective-timeout="):
            val = tok.split("=", 1)[1]
        if val is not None:
            try:
                base_delay = max(2.0, min(float(val), 30.0))
            except ValueError:
                pass
    max_respawns = int(os.environ.get("FEDREC_SUPERVISE_MAX", "20"))
    rng = random.Random(os.getpid())
    attempt = 0
    while True:
        if membership_addr:
            st = _membership_status(membership_addr)
            if st is not None:
                env["FEDREC_MEMBERSHIP_EPOCH"] = str(st["epoch"])
                last_epoch = int(st["epoch"])
        proc = subprocess.Popen(
            [sys.executable, "-m", "fedrec_tpu.cli.coordinator", *keep],
            env=env,
        )
        if pidfile:
            try:
                Path(pidfile).write_text(str(proc.pid))
            except OSError:
                pass
        rc = proc.wait()
        if rc == 0:
            if attempt:
                print(f"[supervisor] worker finished after {attempt} respawn(s)")
            return 0
        # only RETRYABLE statuses respawn: a signal/crash (rc < 0), the
        # deliberate RESPAWN_EXIT a supervised worker uses for a broken
        # world (which also covers rendezvous races — see main()), or the
        # chaos kill's os._exit(137). A deterministic failure (config
        # error rc=1, argparse rc=2) would fail identically 20 times —
        # surface it immediately instead.
        if rc > 0 and rc not in (RESPAWN_EXIT, 137):
            print(
                f"[supervisor] worker exited rc={rc} (non-retryable); "
                "not respawning",
                flush=True,
            )
            return rc
        attempt += 1
        if attempt > max_respawns:
            print(
                f"[supervisor] giving up after {max_respawns} respawns "
                f"(last rc={rc})",
                flush=True,
            )
            return rc if rc > 0 else 1
        delay = min(base_delay * (1.5 ** min(attempt - 1, 6)), 60.0)
        delay *= 0.5 + rng.random()  # jitter: desynchronize peer supervisors
        if membership_addr:
            st = _membership_status(membership_addr)
            reforming = st is not None and (
                st.get("reform_pending")
                or st.get("pending")
                or (last_epoch is not None and int(st["epoch"]) != last_epoch)
            )
            if reforming:
                # the exit was the reformation protocol, not a crash: the
                # forming epoch is waiting on this worker's join
                delay = 0.5 + rng.random()
        print(
            f"[supervisor] worker exited rc={rc}; respawn "
            f"{attempt}/{max_respawns} in {delay:.1f}s",
            flush=True,
        )
        time.sleep(delay)


def apply_process_sharding(cfg, rt, server_trains: bool) -> None:
    """Default ``data.num_shards``/``data.shard_index`` from the runtime so
    each process trains a DISJOINT slice of the corpus — the reference's
    per-rank ``DistributedSampler`` (reference ``main.py:166``,
    ``client.py:243-249``). Explicit ``--set data.num_shards=...`` wins —
    including ``data.num_shards=1``, which opts OUT (every host trains the
    full corpus, the pre-sharding behavior).

    With a non-training server (the reference deployment), shards are dealt
    across the ``N-1`` training clients only; the reference shards across
    the whole world, stranding the server's slice.
    """
    if rt.num_processes <= 1 or cfg.data.num_shards != 0:
        return
    if server_trains:
        cfg.data.num_shards = rt.num_processes
        cfg.data.shard_index = rt.process_id
    else:
        cfg.data.num_shards = max(rt.num_processes - 1, 1)
        # the server (process 0) holds shard 0 but never trains on it
        cfg.data.shard_index = max(rt.process_id - 1, 0)


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    parser.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                        help="rendezvous address (omit for single-process)")
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument("--server-trains", action="store_true",
                        help="process 0 also trains (reference server does not)")
    parser.add_argument("--collective-timeout", type=float, default=300.0,
                        help="seconds before a hung DCN collective marks the "
                             "world broken and this host finishes standalone "
                             "(0 = wait forever, the reference's behavior)")
    parser.add_argument("--resume-local-state", default=None, metavar="PATH",
                        help="internal: resume standalone from a per-process "
                             "msgpack state (degraded-mode respawn)")
    parser.add_argument("--supervise", action="store_true",
                        help="run the worker under an auto-respawn "
                             "supervisor: a died/killed worker (or a broken "
                             "world) relaunches and rejoins through the "
                             "elastic resume path without operator action")
    parser.add_argument("--membership", default=None, metavar="HOST:PORT",
                        help="elastic membership service "
                             "(fedrec_tpu.parallel.membership): the world "
                             "size becomes a membership EPOCH — peer loss "
                             "shrinks-and-continues, a respawned peer "
                             "rejoins at the next epoch boundary. "
                             "--process-id is then the stable worker "
                             "identity; requires --supervise")
    original_argv = list(sys.argv[1:] if argv is None else argv)
    args = parser.parse_args(argv)
    if args.supervise:
        return _supervise(original_argv)
    supervised = os.environ.get("FEDREC_SUPERVISED") == "1"

    from fedrec_tpu.parallel.multihost import (
        REFORM_SIGNAL,
        CoordinatorRuntime,
        initialize_distributed,
    )

    membership = None
    assignment = None
    if args.membership is not None:
        if args.process_id is None:
            parser.error("--membership requires --process-id (the stable "
                         "worker identity snapshots are keyed by)")
        if not supervised:
            parser.error(
                "--membership requires --supervise: reforming an epoch "
                "LEAVES the process (rc 75) and only the supervisor can "
                "rejoin it at the next epoch"
            )
        from fedrec_tpu.config import ExperimentConfig as _PreCfg
        from fedrec_tpu.fed.chaos import rejoin_holdoff
        from fedrec_tpu.parallel.membership import (
            MembershipClient,
            MembershipError,
            elastic_policy,
            publish_membership_metrics,
        )

        # elastic + chaos knobs are needed BEFORE the full config build
        # (which touches jax and must wait for the rendezvous); config
        # parsing itself is jax-free
        pre_cfg = _PreCfg()
        pre_cfg.apply_overrides(args.overrides)
        el = pre_cfg.fed.elastic
        holdoff = rejoin_holdoff(
            pre_cfg.chaos, args.process_id,
            Path(pre_cfg.train.snapshot_dir or "snapshots"),
        )
        if holdoff > 0:
            import time as _time

            print(
                f"[chaos] worker {args.process_id} holding off its rejoin "
                f"{holdoff:.0f}s (chaos.rejoin_delay_s) so the survivors' "
                "shrunk epoch forms first",
                flush=True,
            )
            _time.sleep(holdoff)
        membership = MembershipClient(
            args.membership, worker_id=str(args.process_id),
            join_timeout_s=el.join_timeout_s,
        )
        handed = os.environ.get("FEDREC_MEMBERSHIP_EPOCH")
        try:
            assignment = membership.join(policy=elastic_policy(el))
        except (OSError, MembershipError, ValueError) as e:
            # a join that cannot complete (service briefly down, formation
            # waiting on a member that has not reached its boundary yet)
            # is retryable by definition under supervision
            print(
                f"[membership] worker {args.process_id} join failed "
                f"({type(e).__name__}: {e}); exiting for retry "
                f"(rc {RESPAWN_EXIT})",
                flush=True,
            )
            sys.exit(RESPAWN_EXIT)
        print(
            f"[membership] worker {args.process_id} joined epoch "
            f"{assignment.epoch} as rank {assignment.rank}/"
            f"{assignment.world} (coordinator {assignment.coordinator}"
            + (f"; supervisor handed epoch {handed}" if handed else "")
            + ")",
            flush=True,
        )
        # heartbeats start BEFORE the rendezvous: leases began ticking at
        # formation, and bring-up (transport probe included) can outlast
        # lease_ms — a late first renewal would read as a death and
        # reform the world that just formed
        membership.start_heartbeat()
        publish_membership_metrics(assignment=assignment, client=membership)

    coordinator_address = args.coordinator
    world_processes = args.num_processes
    world_rank = args.process_id
    if assignment is not None:
        coordinator_address = assignment.coordinator
        world_processes = assignment.world
        world_rank = assignment.rank

    if coordinator_address is not None:
        # supervised relaunches get a BOUNDED rendezvous: a respawn racing
        # the old (dying) world must fail fast and let the supervisor retry
        init_timeout = None
        if supervised and args.collective_timeout:
            init_timeout = max(30.0, min(args.collective_timeout * 2, 120.0))
        try:
            initialize_distributed(
                coordinator_address, world_processes, world_rank,
                initialization_timeout=init_timeout,
            )
        except Exception as e:  # noqa: BLE001 — supervised rendezvous
            # failures are RETRYABLE by definition (a respawn racing the
            # dying world); exit with the retryable status so the
            # supervisor relaunches, instead of rc=1 (non-retryable)
            if not supervised:
                raise
            print(
                f"[coordinator] supervised rendezvous failed "
                f"({type(e).__name__}: {e}); exiting for retry "
                f"(rc {RESPAWN_EXIT})",
                flush=True,
            )
            sys.exit(RESPAWN_EXIT)

    import jax

    from fedrec_tpu.config import ExperimentConfig
    from fedrec_tpu.data import load_mind_artifacts
    from fedrec_tpu.privacy import calibrate_from_config
    from fedrec_tpu.train.trainer import Trainer

    cfg = ExperimentConfig()
    cfg.fed.rounds = args.total_epochs
    cfg.data.batch_size = args.batch_size
    cfg.train.save_every = args.save_every
    # local aggregation within each host's mesh stays param_avg; cross-host
    # aggregation goes through the coordinator runtime
    cfg.fed.strategy = "param_avg"
    cfg.fed.local_epochs = args.local_epochs
    cfg.fed.num_clients = args.clients or len(jax.local_devices())
    # record the data source IN the config (config.json provenance);
    # --set data.* overrides below still win over the CLI flags
    cfg.data.data_dir = args.data_dir
    if args.synthetic:
        cfg.data.dataset = "synthetic"
    cfg.apply_overrides(args.overrides)

    if membership is not None:
        cfg.fed.elastic.enabled = True  # config.json provenance
    elif cfg.fed.elastic.enabled:
        raise ValueError(
            "fed.elastic.enabled is set but no membership service was "
            "given: pass --membership HOST:PORT (and run under "
            "--supervise) — the epoch layer cannot form without the "
            "lease service"
        )

    if cfg.fed.dcn_compress == "auto":
        # the adaptive per-leaf map is pinned from the Trainer's in-graph
        # warmup telemetry; the coordinator wire path has no warmup window
        # yet, so a concrete codec must be named per deployment
        raise ValueError(
            "fed.dcn_compress='auto' needs the trainer's warmup telemetry "
            "and is not available on the coordinator path; pin a concrete "
            "codec (int8/sign1bit/topk/countsketch/randproj) per deployment"
        )
    if cfg.fed.robust.method != "mean" and cfg.fed.dcn_compress != "none":
        # robust x compress is LEGAL for every per-contribution codec: the
        # gather decodes each contribution per process BEFORE any reduction
        # (decode-before-reduce, fedrec_tpu.comms), so trimmed-mean/median
        # judge clients, not quantization noise. The fail-fast survives for
        # the LINEAR sketches, whose contributions only exist pre-aggregated
        # (capability table: decodes_per_contribution=False) — checked HERE
        # (same policy as validate_compress): raised lazily inside the
        # aggregation collective, it would be misread by the watchdog as a
        # peer failure and silently degrade every host to standalone
        # training.
        from fedrec_tpu.comms import codec_caps

        if not codec_caps(cfg.fed.dcn_compress).decodes_per_contribution:
            raise ValueError(
                f"fed.robust.method={cfg.fed.robust.method!r} needs "
                "per-contribution decode, which codec "
                f"{cfg.fed.dcn_compress!r} cannot provide (order statistics "
                "judge CLIENTS, and sketch collisions mix every client's "
                "coordinates before any decode exists); use one of the "
                "decodable codecs (int8/sign1bit/topk) or "
                "fed.robust.method='mean'"
            )
    rt = CoordinatorRuntime(
        collective_timeout_s=args.collective_timeout or None,
        compress=cfg.fed.dcn_compress,
        robust=cfg.fed.robust,
        topk_ratio=cfg.fed.dcn_topk_ratio,
        error_feedback=cfg.fed.dcn_error_feedback,
        sketch_width=cfg.fed.dcn_sketch_width,
        sketch_seed=cfg.fed.dcn_sketch_seed,
        # cross-device round deadline: bound the round-end report gather
        # (fed.population.round_deadline_ms) so a straggling peer costs a
        # bounded wait, never a wedged run. NOTE this is a REAL wall-clock
        # bound on the DCN all-gather (a miss degrades this host to
        # standalone for the remaining rounds — collectives are ordered
        # and a partial gather cannot be resumed), so on a coordinator
        # deployment size it to real gather time, not to the simulated
        # straggle scale the in-process deadline cuts against
        round_deadline_s=(
            cfg.fed.population.round_deadline_ms / 1e3
            if cfg.fed.population.round_deadline_ms > 0 else None
        ),
        membership=membership,
        epoch=assignment.epoch if assignment is not None else 0,
        # agg.mode=hierarchical: per-host robust pre-aggregate + tiered
        # cross-host reduce (mean deliberately lowers to the flat
        # collective — see aggregate_from_hosts)
        agg=cfg.agg,
    )
    apply_process_sharding(cfg, rt, args.server_trains)

    if cfg.data.dataset == "synthetic":
        from fedrec_tpu.cli.run import make_synthetic_from_args

        data = make_synthetic_from_args(args, cfg)
    else:
        # "mind" and "adressa" share the artifact schema, one loader both
        data = load_mind_artifacts(cfg.data.data_dir)

    token_path = args.token_states or str(Path(cfg.data.data_dir) / "token_states.npy")
    if Path(token_path).exists():
        token_states = np.load(token_path)
    else:
        token_states = None
        if membership is not None and cfg.shard.table:
            # sharded-catalog recovery: a (re)joined worker whose token
            # source is gone reloads the frozen rows from the last table
            # checkpoint (save cadence below) instead of losing them —
            # the no-rows-lost half of shrink-and-continue
            from fedrec_tpu.train.checkpoint import load_table_checkpoint

            token_states = load_table_checkpoint(
                Path(cfg.train.snapshot_dir or "snapshots")
            )
            if token_states is not None:
                from fedrec_tpu.obs import get_registry

                get_registry().counter(
                    "shard.reshard_rows_recovered_total",
                    "catalog rows reloaded from the table checkpoint "
                    "across membership epoch changes",
                ).inc(float(token_states.shape[0]))
                print(
                    f"[membership] worker {args.process_id} recovered "
                    f"{token_states.shape[0]} catalog rows from the table "
                    "checkpoint"
                )
        if token_states is None:
            token_states = np.random.default_rng(0).standard_normal(
                (data.num_news, data.title_len, cfg.model.bert_hidden)
            ).astype(np.float32)

    if args.dp_epsilon > 0:
        cfg.privacy.enabled = True
        cfg.privacy.epsilon = args.dp_epsilon
        # calibrate against this HOST's actual training-set size: process
        # sharding shrinks the local data, and a global-count calibration
        # would underestimate the sample rate q and under-noise every round
        # (privacy loss would exceed the configured epsilon)
        n_local = len(data.train_samples)
        if cfg.data.num_shards > 1:
            # shard length by arithmetic: process_shard_indices deals
            # perm[shard_index::num_shards] over n rows (index_samples is
            # 1:1 with train_samples), so the count is independent of the
            # permutation — no need to materialize it here
            n_local = -(-(n_local - cfg.data.shard_index) // cfg.data.num_shards)
        cfg.privacy.sigma = calibrate_from_config(cfg, n_local)

    # ---- fleet observability (fedrec_tpu.obs.fleet): stamp this
    # worker's stable id + per-epoch rank/epoch into every span,
    # snapshot and JSONL record; give each worker its OWN obs subdir
    # (the worker_* layout `fedrec-obs fleet` merges); and re-seed the
    # registry's counters from the persisted baseline so a respawned
    # worker's totals resume instead of resetting
    from fedrec_tpu.obs.fleet import (
        restore_counter_baseline,
        set_fleet_identity,
    )

    # snapshot/artifact identity: under elastic membership the STABLE
    # worker id (ranks are re-dealt every epoch, so rank-keyed files
    # would adopt a different worker's state after a reshuffle); the
    # rank otherwise — THE one definition, shared by the obs worker dir,
    # the state_suffix snapshot naming and the chaos-kill target below
    ident = int(args.process_id) if membership is not None else rt.process_id
    set_fleet_identity(
        worker=str(ident),
        rank=rt.process_id,
        epoch=assignment.epoch if assignment is not None else None,
    )
    if cfg.obs.dir and (rt.num_processes > 1 or membership is not None):
        cfg.obs.dir = str(Path(cfg.obs.dir) / f"worker_{ident}")
    if cfg.obs.dir and membership is not None:
        restore_counter_baseline(Path(cfg.obs.dir))
    if assignment is not None:
        from fedrec_tpu.obs import get_tracer

        get_tracer().instant(
            "membership_join", epoch=assignment.epoch,
            rank=assignment.rank, world=assignment.world,
        )

    trains = args.server_trains or not rt.is_server or rt.num_processes == 1
    local_snap = None
    # a degraded-mode respawn is a standalone process that must keep the
    # multi-process msgpack snapshot flavor (it continues ITS shard's run);
    # so must an elastic world shrunk to 1 — the next epoch may grow back
    msgpack_snapshots = (
        rt.num_processes > 1 or args.resume_local_state
        or membership is not None
    )
    # state files key on the same stable identity (`ident`, defined with
    # the fleet-observability block above)
    state_suffix = (
        f"w{args.process_id}" if membership is not None
        else f"p{rt.process_id}"
    )
    if msgpack_snapshots:
        # orbax snapshots assume whole-world coordination; in the coordinator
        # deployment each process instead flax-serializes its FULL local
        # state (params + opt state + PRNG) per save cadence, and the server
        # additionally persists the global model per round (the reference's
        # model.pt / received_model_{i}.pt artifacts, client.py:288 /
        # server.py:27 — which lose client opt state on restart; ours don't)
        snapshot_dir = Path(cfg.train.snapshot_dir or "snapshots")
        cfg.train.snapshot_dir = ""
    trainer = Trainer(cfg, data, token_states)
    if rt.num_processes > 1 and rt.is_server:
        # resolved config next to the snapshots for serving (fedrec-recommend
        # reads it back — same contract as Trainer's orbax path; ADVICE r2).
        # Server-only + atomic: per-process configs differ (shard_index,
        # sigma) and concurrent non-atomic writes to a shared dir could tear
        # the JSON a concurrently-running fedrec-recommend reads; serving
        # always restores the SERVER's globals, so its config is the truth
        from fedrec_tpu.train.checkpoint import atomic_write_bytes

        snapshot_dir.mkdir(parents=True, exist_ok=True)
        atomic_write_bytes(
            snapshot_dir / "config.json", cfg.to_json().encode()
        )
    if cfg.data.num_shards > 1:
        print(
            f"[coordinator] process {rt.process_id} data shard "
            f"{cfg.data.shard_index + 1}/{cfg.data.num_shards}: "
            f"{trainer.num_local_samples} samples"
        )

    codec_snap = None
    if msgpack_snapshots and rt.codec_state is not None:
        # biased-codec (sign1bit/topk) error-feedback residual: THIS
        # process's wire-endpoint EF state, persisted at save cadence so a
        # resumed run keeps carrying the mass its encodes dropped. A
        # missing/corrupt sidecar just starts the residual from zero — the
        # same bounded-staleness contract as a fresh logical client.
        codec_snap = snapshot_dir / f"codec_state_{state_suffix}.npz"
        if cfg.train.resume and codec_snap.exists():
            from fedrec_tpu.comms import load_codec_state

            try:
                rt.codec_state, ef_round = load_codec_state(
                    codec_snap.read_bytes(), trainer._client0_params()
                )
                print(
                    f"[coordinator] process {rt.process_id} resumed codec "
                    f"residual from round {ef_round}"
                )
            except Exception as e:  # noqa: BLE001 — a torn sidecar must
                # not kill the resume; dropping a residual only costs the
                # one round's banked encode error
                print(
                    f"[coordinator] process {rt.process_id} codec residual "
                    f"sidecar unreadable ({type(e).__name__}: {e}); "
                    "starting the residual from zero"
                )

    server_optimizer = None
    if msgpack_snapshots:
        from flax import serialization

        local_snap = (
            Path(args.resume_local_state)
            if args.resume_local_state
            else snapshot_dir / f"local_state_{state_suffix}.msgpack"
        )
        if cfg.train.resume and local_snap.exists():
            import time as _time

            reshard_t0 = _time.perf_counter()
            template = {"state": trainer.state, "round": 0}
            try:
                restored = serialization.from_bytes(
                    template, local_snap.read_bytes()
                )
                from fedrec_tpu.train.checkpoint import verify_state_tree

                verify_state_tree(restored["state"])
            except Exception as e:  # noqa: BLE001 — a torn/corrupt snapshot
                # must not kill the resume: this shard restarts fresh and is
                # re-integrated by the server's round negotiation + fan-out
                # (the same path a brand-new elastic host takes)
                print(
                    f"[coordinator] process {rt.process_id} local snapshot "
                    f"{local_snap.name} is corrupt/torn "
                    f"({type(e).__name__}: {e}); starting this shard fresh — "
                    "the server's fan-out re-integrates it next round"
                )
                restored = None
            if restored is not None:
                trainer.adopt_state(restored["state"])
                trainer.start_round = int(restored["round"]) + 1
                print(
                    f"[coordinator] process {rt.process_id} resumed local state "
                    f"at round {trainer.start_round - 1}"
                )
            if membership is not None:
                # epoch-boundary reshard: the restore above re-committed
                # the hand-off state to THIS epoch's mesh/world layout
                # (Trainer._place_state re-derives placement, the data
                # shards re-dealt at apply_process_sharding) — publish how
                # long the hand-off cost
                from fedrec_tpu.obs import get_registry

                get_registry().gauge(
                    "shard.reshard_seconds",
                    "wall seconds the last membership-epoch state "
                    "hand-off took (restore + re-placement)",
                ).set(_time.perf_counter() - reshard_t0)
        if membership is not None and cfg.train.resume:
            # participation-ledger continuity across epochs: the per-worker
            # population sidecar re-adopts with resize tolerance (the
            # re-formed world may deal different local data)
            pop_snap = snapshot_dir / f"population_state_{state_suffix}.msgpack"
            if pop_snap.exists() and trainer._pop_engine:
                try:
                    pop_round = trainer.adopt_population_sidecar(
                        pop_snap.read_bytes(), resize=True
                    )
                    print(
                        f"[membership] worker {args.process_id} carried its "
                        f"participation ledger from round {pop_round}"
                    )
                except Exception as e:  # noqa: BLE001 — a torn sidecar
                    # costs history, never the resume
                    print(
                        f"[membership] population sidecar unreadable "
                        f"({type(e).__name__}: {e}); ledger restarts fresh"
                    )
        if cfg.fed.server_opt != "none":
            # cross-host FedOpt is hub-and-spoke: ONLY the server holds and
            # steps the optimizer (the FedOpt paper's topology); clients
            # adopt the plain mean this round and receive the server's
            # post-opt global at the next round's fan-out. Optimizer state
            # therefore never needs to agree across hosts — a client
            # resuming from a stale snapshot cannot desync it. The per-host
            # trainer must not also step its own server optimizer on the
            # in-process mean (double application). A degraded-mode respawn
            # (single process, resume_local_state) is still a CLIENT: it
            # must not start stepping FedOpt locally either.
            trainer.server_opt = None
            if rt.is_server and rt.num_processes > 1:
                from fedrec_tpu.fed.strategies import ServerOptimizer

                server_optimizer = ServerOptimizer(
                    cfg.fed.server_opt, cfg.fed.server_lr, cfg.fed.server_momentum
                )
                opt_snap = snapshot_dir / "server_opt_state.msgpack"
                if cfg.train.resume and opt_snap.exists():
                    loaded_round = server_optimizer.load_state(
                        opt_snap.read_bytes(), trainer._client0_params()
                    )
                    if loaded_round != trainer.start_round - 1:
                        print(
                            f"[coordinator] server_opt sidecar is from round "
                            f"{loaded_round}, local snapshot from round "
                            f"{trainer.start_round - 1} — momentum may be "
                            "skewed for the first resumed round"
                        )

    def respawn_standalone() -> None:
        """Degraded CLIENT: leave the broken distributed runtime entirely.

        A degraded client cannot keep living inside the old process. Two
        failure modes were observed on a 4-process peer-kill run: (1) the
        XLA coordination client's error poller fatally terminates the
        process the moment the service (hosted by process 0, itself
        degraded and exiting) goes away; (2) the watchdog's abandoned
        collective thread stays blocked inside the runtime and holds its
        execution lock, so ANY further device op — even serializing state
        for a snapshot — deadlocks until the broken collective errors
        out. The only safe move is device-free: exec a standalone
        continuation of the same command (fresh process, no distributed
        runtime) that resumes this shard from the last SAVED snapshot.
        The round in flight when the world broke is simply re-trained
        standalone. The SERVER owns the coordination service and finishes
        degraded in-process (finalize's os._exit skips broken teardown).

        Under a supervisor (``--supervise``) the policy changes: every
        degraded process — server included — exits device-free with
        RESPAWN_EXIT so its supervisor relaunches the full distributed
        invocation; the relaunched world re-rendezvouses and resumes from
        local snapshots. The server's exit is what frees the coordination
        service address for the new world.
        """
        if rt.num_processes == 1:
            return
        if supervised:
            print(
                f"[coordinator] process {rt.process_id} world degraded "
                f"under supervision — exiting for re-rendezvous "
                f"(rc {RESPAWN_EXIT})",
                flush=True,
            )
            # obs flush is DEVICE-FREE (registry/tracer are host JSON),
            # so it is safe on the degraded path — without it, every
            # span this incarnation recorded before the world broke
            # would vanish from the fleet merge
            if trainer.fleet_pusher is not None:
                trainer.fleet_pusher.push(final=True)
            _dump_obs_artifacts()
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(RESPAWN_EXIT)
        if rt.is_server or local_snap is None:
            return
        world_flags = {"--coordinator", "--num-processes", "--process-id",
                       "--collective-timeout", "--resume-local-state"}
        keep: list[str] = []
        skip_value = False
        for tok in original_argv:
            if skip_value:
                skip_value = False
                continue
            base = tok.split("=", 1)[0]
            if base in world_flags:
                skip_value = "=" not in tok
                continue
            if base == "--server-trains":
                continue
            keep.append(tok)
        cmd = [
            sys.executable, "-m", "fedrec_tpu.cli.coordinator", *keep,
            "--resume-local-state", str(local_snap),
            "--set", f"data.num_shards={cfg.data.num_shards}",
            "--set", f"data.shard_index={cfg.data.shard_index}",
        ]
        print(
            f"[coordinator] process {rt.process_id} world degraded — "
            f"respawning standalone, resuming from "
            f"{local_snap.name if local_snap.exists() else 'scratch'}",
            flush=True,
        )
        sys.stdout.flush()
        sys.stderr.flush()
        os.execv(sys.executable, cmd)

    def _dump_obs_artifacts() -> None:
        """Flush the registry/trace into this worker's obs dir on the
        coordinator CLI's exit paths (reform + finish): unlike
        Trainer.run, this loop never writes registry snapshots itself,
        so without a final dump the membership/reshard gauges would
        never reach the artifacts `fedrec-obs report` reads.  Elastic
        workers tag the trace with their membership epoch
        (``trace_e<N>.json``) so each incarnation's spans survive the
        respawn that overwrites ``trace.json``, and persist the counter
        baseline the next incarnation resumes from."""
        if not cfg.obs.dir:
            return
        from fedrec_tpu.obs import dump_artifacts, save_counter_baseline

        try:
            dump_artifacts(
                Path(cfg.obs.dir),
                trace_tag=f"e{rt.epoch}" if membership is not None else None,
            )
            if membership is not None:
                save_counter_baseline(Path(cfg.obs.dir), epoch=rt.epoch)
        except OSError as e:
            print(f"[coordinator] obs artifact dump failed: {e}")

    def save_elastic_sidecars(round_tag: int) -> None:
        """Membership-mode extras that ride every state save: the
        per-worker population sidecar (participation-ledger continuity
        across epochs) and the one-time table checkpoint (the sharded
        catalog's row-recovery source)."""
        if membership is None:
            return
        from fedrec_tpu.train.checkpoint import (
            NEWS_TABLE_CHECKPOINT,
            atomic_write_bytes,
            save_table_checkpoint,
        )

        pop_blob = trainer.population_sidecar_bytes(round_tag)
        if pop_blob is not None:
            atomic_write_bytes(
                snapshot_dir / f"population_state_{state_suffix}.msgpack",
                pop_blob,
            )
        if cfg.shard.table and not (
            snapshot_dir / NEWS_TABLE_CHECKPOINT
        ).exists():
            save_table_checkpoint(snapshot_dir, token_states)
        if cfg.obs.dir:
            # counter-baseline continuity rides the save cadence too: a
            # worker killed BETWEEN reformations (the chaos-kill path,
            # which never reaches a clean dump) still resumes its totals
            # from the last cadence save
            from fedrec_tpu.obs.fleet import save_counter_baseline

            try:
                save_counter_baseline(Path(cfg.obs.dir), epoch=rt.epoch)
            except OSError:
                pass

    def reform_handoff(next_round: int) -> None:
        """The reformation barrier's worker half: every member received
        :data:`REFORM_SIGNAL` in the SAME round broadcast, so the whole
        world executes this at one boundary — save the full local state
        (round-tagged hand-off snapshot the next epoch resumes from,
        bit-identical for the unchanged part of the world), tear the old
        runtime down while it is still healthy, and exit with the
        retryable status so the supervisor rejoins the forming epoch."""
        print(
            f"[membership] worker {args.process_id} leaving epoch "
            f"{rt.epoch} at round boundary {next_round} for reformation",
            flush=True,
        )
        trainer.tracer.instant(
            "membership_reform", epoch=rt.epoch, round=next_round
        )
        if local_snap is not None:
            from flax import serialization
            from fedrec_tpu.train.checkpoint import atomic_write_bytes

            snapshot_dir.mkdir(parents=True, exist_ok=True)
            atomic_write_bytes(
                local_snap,
                serialization.to_bytes(
                    {"state": trainer.state, "round": next_round - 1}
                ),
            )
            if server_optimizer is not None:
                atomic_write_bytes(
                    snapshot_dir / "server_opt_state.msgpack",
                    server_optimizer.state_bytes(next_round - 1),
                )
            if codec_snap is not None:
                from fedrec_tpu.comms import codec_state_bytes

                atomic_write_bytes(
                    codec_snap, codec_state_bytes(rt.codec_state, next_round - 1)
                )
            save_elastic_sidecars(next_round - 1)
        from fedrec_tpu.parallel.membership import publish_membership_metrics

        publish_membership_metrics(reforms=1, client=membership)
        if trainer.fleet_pusher is not None:
            trainer.fleet_pusher.push(final=True)
        _dump_obs_artifacts()
        trainer.logger.finish()
        # the world is HEALTHY here (the reform broadcast just completed),
        # so the synchronized teardown applies: coordination service and
        # gloo pairs close cleanly before every member leaves
        rt._synchronized_shutdown()
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(RESPAWN_EXIT)

    round_idx = trainer.start_round
    while True:
        # negotiate the round: everyone adopts the SERVER's counter (a host
        # resumed from a stale snapshot would otherwise desync batch seeds,
        # save cadence, and snapshot labels)
        server_round = rt.start_round(round_idx, cfg.fed.rounds)
        if rt.degraded:
            respawn_standalone()
        if server_round == REFORM_SIGNAL:
            reform_handoff(round_idx)
        if server_round < 0:
            break
        round_idx = server_round
        # host-level chaos fault: deterministic peer kill at round entry —
        # the surviving peers block in the next collective until their
        # watchdogs degrade them (supervised: the whole world relaunches).
        # Marker-guarded so the resumed/relaunched world doesn't re-die
        # when it re-reaches the same round.
        if (
            cfg.chaos.enabled
            and cfg.chaos.kill_round == round_idx
            # under elastic membership the kill targets the STABLE worker
            # identity (ranks re-deal every epoch)
            and cfg.chaos.kill_process == ident
        ):
            marker_dir = (
                snapshot_dir if msgpack_snapshots
                else Path(cfg.train.snapshot_dir or "snapshots")
            )
            marker_dir.mkdir(parents=True, exist_ok=True)
            marker = marker_dir / f"chaos_killed_p{ident}"
            if not marker.exists():
                marker.write_text(str(round_idx))
                print(
                    f"[chaos] process {rt.process_id} dying at round "
                    f"{round_idx} (chaos.kill_round)",
                    flush=True,
                )
                os._exit(137)
        # server fan-out: everyone adopts the global model
        u0, n0 = trainer._client0_params()
        u, n = rt.sync_from_server((u0, n0))
        if rt.degraded:
            respawn_standalone()
        trainer.set_global_params(u, n)
        round_start_global = (u, n)

        result = None
        if trains:
            # train_round_recovering: identical to train_round unless
            # fed.robust.recover, which quarantines/rolls back IN-host;
            # cross-host, a quarantined cohort still reports its (robust)
            # local aggregate — host-level exclusion is participation
            result = trainer.train_round_recovering(round_idx)

        # gather: participation weight 0 for a non-training server; with
        # fed.weight_by_samples each client counts by its shard size
        # (classic FedAvg) instead of the reference's unweighted key-wise
        # mean over unequal shards (server.py:37-55)
        u0, n0 = trainer._client0_params()
        # weigh by the TRUE local shard size (classic FedAvg n_k) — before
        # process sharding every host reported the identical global count,
        # which made the weighting degenerate
        w = float(trainer.num_local_samples) if cfg.fed.weight_by_samples else 1.0
        # round_start_global switches int8 compression to delta
        # quantization (every process holds the identical round-start
        # global from the fan-out above)
        u, n = rt.aggregate(
            (u0, n0), participated=trains, weight=w, base=round_start_global
        )
        if rt.degraded:
            # device-free exit NOW: the abandoned collective blocks any
            # further device op (incl. set_global_params below); the round
            # in flight is re-trained by the standalone continuation
            respawn_standalone()
        if server_optimizer is not None:
            # server-only (hub-and-spoke): clients adopt the plain mean this
            # round and receive the server's post-opt global at the next
            # round's fan-out
            u, n = server_optimizer.step(round_start_global, (u, n))
        trainer.set_global_params(u, n)

        # the coordinator loop completes rounds OUTSIDE Trainer.run, so
        # the rounds counter advances here — Trainer._after_round (its
        # only other inc site) never runs in this deployment, which left
        # coordinator workers' round totals frozen at zero
        trainer.registry.counter("train.rounds_total").inc()
        if result is not None:
            log = {"round": round_idx, "training_loss": result.train_loss}
            log.update(result.val_metrics)
            trainer.logger.log(round_idx, log)
        if (round_idx + 1) % cfg.train.save_every == 0:
            if trainer.snapshots is not None:
                # blocking under FedOpt so the sidecar never outruns the
                # orbax snapshot it pairs with (see Trainer.run)
                trainer.snapshots.save(
                    round_idx, trainer.state, wait=trainer.server_opt is not None
                )
                if trainer.server_opt is not None:
                    from fedrec_tpu.train.checkpoint import atomic_write_bytes

                    atomic_write_bytes(
                        trainer.snapshots.directory / "server_opt_state.msgpack",
                        trainer.server_opt.state_bytes(round_idx),
                    )
            elif local_snap is not None:
                from flax import serialization

                from fedrec_tpu.train.checkpoint import (
                    atomic_write_bytes,
                    coordinator_globals,
                )

                snapshot_dir.mkdir(parents=True, exist_ok=True)
                # atomic writes: a concurrently-running fedrec-recommend
                # must never read a torn snapshot
                atomic_write_bytes(
                    local_snap,
                    serialization.to_bytes(
                        {"state": trainer.state, "round": round_idx}
                    ),
                )
                if (
                    cfg.chaos.enabled
                    and cfg.chaos.torn_snapshot_round == round_idx
                ):
                    # host-level chaos fault: simulate a crash mid-write by
                    # truncating the snapshot we just wrote — the resume
                    # path must survive it (fresh shard + server fan-out)
                    blob = local_snap.read_bytes()
                    local_snap.write_bytes(blob[: max(len(blob) // 2, 1)])
                    print(
                        f"[chaos] process {rt.process_id} tore its local "
                        f"snapshot at round {round_idx}",
                        flush=True,
                    )
                if server_optimizer is not None:
                    # server-only state (hub-and-spoke FedOpt), round-tagged
                    atomic_write_bytes(
                        snapshot_dir / "server_opt_state.msgpack",
                        server_optimizer.state_bytes(round_idx),
                    )
                if codec_snap is not None:
                    # per-process EF residual rides the save cadence next
                    # to the local state it pairs with
                    from fedrec_tpu.comms import codec_state_bytes

                    atomic_write_bytes(
                        codec_snap,
                        codec_state_bytes(rt.codec_state, round_idx),
                    )
                save_elastic_sidecars(round_idx)
                if rt.is_server and rt.num_processes > 1:
                    # a degraded-mode respawn (single process) is a CLIENT
                    # continuation — its params are NOT the global model
                    atomic_write_bytes(
                        snapshot_dir / f"global_round_{round_idx}.msgpack",
                        serialization.to_bytes(
                            {"user": u, "news": n, "round": round_idx}
                        ),
                    )
                    # retention: mirror orbax's max_to_keep=3 — the reference
                    # leaves received_model_{i}.pt files piling up forever
                    # (server.py:27)
                    for old in coordinator_globals(snapshot_dir)[:-3]:
                        old.unlink(missing_ok=True)
        if trainer.fleet_pusher is not None:
            # the coordinator loop drives rounds itself (Trainer._after_round
            # never runs here), so the round-cadence telemetry push lands at
            # this boundary instead
            trainer.fleet_pusher.maybe_push(round_idx)
        round_idx += 1

    print(f"[coordinator] process {rt.process_id} done after {round_idx} rounds")
    if trainer.snapshots is not None:
        trainer.snapshots.wait()  # settle async saves before any exit path
    if membership is not None:
        # a finished run LEAVES (no lease to expire, no reform): the
        # service's final status must read completion, not death
        from fedrec_tpu.parallel.membership import publish_membership_metrics

        publish_membership_metrics(client=membership)
        membership.leave()
        membership.close()
    if trainer.fleet_pusher is not None:
        trainer.fleet_pusher.push(final=True)
    _dump_obs_artifacts()
    trainer.logger.finish()  # before finalize: os._exit skips teardown
    rt.finalize(0)  # no-op unless the world broke mid-run (then exits here)
    return 0


if __name__ == "__main__":
    sys.exit(main())
