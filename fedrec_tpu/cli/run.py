"""Unified CLI driver — replaces the reference's four entry scripts.

Reference drivers and their equivalents here (positional args kept
compatible with ``torchrun ... <script> epochs batch save_every``,
reference ``main.py:178-184``):

  * ``main.py`` (DDP simulation)            -> ``--strategy grad_avg``
  * ``Gradient_Averaging_main.py``          -> ``--strategy grad_avg``
  * ``Parameter_Averaging_main.py``         -> ``--strategy param_avg``
  * ``client.py``/``server.py`` coordinator -> ``--strategy coordinator``
    (multi-host; see fedrec_tpu.parallel.multihost)

Usage:
  python -m fedrec_tpu.cli.run EPOCHS BATCH SAVE_EVERY \
      [--strategy param_avg] [--clients 8] [--data-dir UserData] \
      [--dp-epsilon 10] [--set section.key=value ...]

Unlike the reference there is no torchrun/c10d rendezvous to stand up: the
clients are mesh slots of one SPMD program (single host) or
``jax.distributed``-initialized processes (multi-host).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("total_epochs", type=int, help="global rounds (reference argv 1)")
    p.add_argument("batch_size", type=int, help="per-client batch size (argv 2)")
    p.add_argument("save_every", type=int, help="snapshot cadence in rounds (argv 3)")
    p.add_argument("--strategy", default="param_avg",
                   choices=["local", "grad_avg", "param_avg", "coordinator"])
    p.add_argument("--clients", type=int, default=None,
                   help="default: all visible devices")
    p.add_argument("--data-dir", default="/root/reference/UserData",
                   help="directory with bert_news_index.npy etc.")
    p.add_argument("--token-states", default=None,
                   help="path to cached (N, L, H) trunk token states .npy; "
                        "default <data-dir>/token_states.npy if present, else "
                        "random states (smoke mode)")
    p.add_argument("--dp-epsilon", type=float, default=0.0,
                   help="enable LDP with this epsilon (reference argv 4; 0 = off)")
    p.add_argument("--local-epochs", type=int, default=1)
    p.add_argument("--participation", type=float, default=1.0)
    p.add_argument("--mode", default=None, choices=[None, "joint", "decoupled"])
    p.add_argument("--synthetic", action="store_true",
                   help="use synthetic data instead of --data-dir artifacts")
    p.add_argument("--synthetic-train", type=int, default=2048,
                   help="synthetic corpus size (train samples)")
    p.add_argument("--synthetic-news", type=int, default=512,
                   help="synthetic corpus size (distinct news)")
    p.add_argument("--obs-dir", default=None,
                   help="write observability artifacts here (shorthand for "
                        "--set obs.dir=...); render with fedrec-obs report")
    p.add_argument("--agg-server", default=None, metavar="HOST:PORT",
                   help="async federation (agg.mode=async across processes): "
                        "drive rounds against this fedrec_tpu.agg.server "
                        "commit authority instead of a collective world")
    p.add_argument("--worker-id", default=None,
                   help="this worker's name on the agg server / in the "
                        "fleet report (required with --agg-server)")
    p.add_argument("--set", dest="overrides", action="append", default=[],
                   metavar="SECTION.KEY=VALUE")
    return p


def make_synthetic_from_args(args, cfg):
    """Shared synthetic-corpus construction for the run and coordinator
    drivers (one definition of the valid-set sizing)."""
    from fedrec_tpu.data import make_synthetic_mind

    return make_synthetic_mind(
        num_news=args.synthetic_news, num_train=args.synthetic_train,
        num_valid=max(args.synthetic_train // 8, 32),
        title_len=cfg.data.max_title_len, popular_frac=0.2,
    )


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    import jax

    from fedrec_tpu.config import ExperimentConfig
    from fedrec_tpu.data import load_mind_artifacts
    from fedrec_tpu.privacy import calibrate_from_config
    from fedrec_tpu.train.trainer import Trainer

    cfg = ExperimentConfig()
    cfg.fed.rounds = args.total_epochs
    cfg.data.batch_size = args.batch_size
    cfg.train.save_every = args.save_every
    cfg.fed.strategy = args.strategy
    cfg.fed.local_epochs = args.local_epochs
    cfg.fed.participation = args.participation
    cfg.fed.num_clients = args.clients or len(jax.local_devices())
    if args.mode:
        cfg.model.text_encoder_mode = "table" if args.mode == "decoupled" else "head"
    if args.obs_dir:
        cfg.obs.dir = args.obs_dir
    # record the data source IN the config (snapshot config.json is the
    # provenance record of what a run trained on); --set data.* overrides
    # below still win over the CLI flags
    cfg.data.data_dir = args.data_dir
    if args.synthetic:
        cfg.data.dataset = "synthetic"
    cfg.apply_overrides(args.overrides)

    if cfg.data.dataset == "synthetic":
        data = make_synthetic_from_args(args, cfg)
    else:
        # "mind" and "adressa" share the artifact schema (the Adressa
        # preprocessor writes the exact UserData/ layout), so one loader
        # serves both dataset families
        data = load_mind_artifacts(cfg.data.data_dir)

    token_path = args.token_states or str(Path(cfg.data.data_dir) / "token_states.npy")
    if Path(token_path).exists():
        token_states = np.load(token_path)
    else:
        print(
            f"[run] no cached token states at {token_path}; using random states "
            "(smoke mode — precompute with fedrec_tpu.models.bert for real runs)",
            file=sys.stderr,
        )
        token_states = np.random.default_rng(0).standard_normal(
            (data.num_news, data.title_len, cfg.model.bert_hidden)
        ).astype(np.float32)

    if args.dp_epsilon > 0:
        cfg.privacy.enabled = True
        cfg.privacy.epsilon = args.dp_epsilon
        if cfg.model.text_encoder_mode == "table":
            # decoupled path: reference-parity noise-only mechanism (the
            # reference's sigma-from-Opacus + unclipped noise, client.py:87-89,
            # 271-281 — carries no rigorous epsilon; see fedrec_tpu.privacy)
            cfg.privacy.mechanism = "ldp_news"
            print(
                "[run] decoupled mode: using ldp_news (reference-parity, "
                "no rigorous epsilon); use --mode joint for real DP-SGD",
                file=sys.stderr,
            )
        cfg.privacy.sigma = calibrate_from_config(cfg, len(data.train_samples))
        print(
            f"[run] DP enabled: eps={cfg.privacy.epsilon} delta={cfg.privacy.delta} "
            f"sigma={cfg.privacy.sigma:.4f} clip={cfg.privacy.clip_norm}",
            file=sys.stderr,
        )

    if args.agg_server:
        if not args.worker_id:
            print("[run] --agg-server requires --worker-id", file=sys.stderr)
            return 2
        # async deployment: the round barrier is the agg server's quorum
        # commit, not a collective. The TRAINER stays in flat mode (its
        # local 1-client sync is the identity; the buffered commit lives
        # server-side) — agg.mode="async" is the IN-process simulation
        # knob for cohort deployments, not this wire path.
        from fedrec_tpu.obs.fleet import set_fleet_identity

        set_fleet_identity(worker=str(args.worker_id))
        if cfg.obs.dir:
            # the worker_* layout `fedrec-obs fleet` merges (same
            # discipline as the coordinator CLI)
            cfg.obs.dir = str(Path(cfg.obs.dir) / f"worker_{args.worker_id}")
        trainer = Trainer(cfg, data, token_states)

        from fedrec_tpu.agg.worker import run_async_worker
        from fedrec_tpu.parallel.rpc import AuthorityUnreachable

        # wire-level fault injection: a seeded chaos TCP proxy fronts
        # the authority and this worker dials THROUGH it, so torn
        # connections / duplicated pushes / partitions exercise the
        # resilient-RPC path on a real socket (scripts/async_smoke.sh's
        # fault leg). With the spec empty no proxy is built at all.
        proxy = None
        if cfg.chaos.wire_faults:
            if not cfg.chaos.enabled:
                raise ValueError(
                    "wire fault injection requires chaos.enabled=true "
                    "(chaos.wire_faults is part of the chaos plan)"
                )
            from fedrec_tpu.fed.chaos import ChaosProxy, WireFaultPlan

            up_host, up_port = args.agg_server.rsplit(":", 1)
            proxy = ChaosProxy(
                up_host, int(up_port),
                plan=WireFaultPlan(
                    cfg.chaos.wire_faults, seed=cfg.chaos.wire_seed
                ),
            )
            proxy.start()
            print(
                f"[run] chaos wire proxy {proxy.address} -> "
                f"{args.agg_server} ({cfg.chaos.wire_faults})",
                file=sys.stderr,
            )
        try:
            history = run_async_worker(
                trainer,
                proxy.address if proxy is not None else args.agg_server,
                args.worker_id,
            )
        except AuthorityUnreachable as e:
            # degrade, don't crash: rc-75 tells the PR-5 supervisor to
            # respawn this worker against the (re)started authority
            print(f"[run] {e}", file=sys.stderr)
            return e.returncode
        finally:
            if proxy is not None:
                proxy.stop()
    else:
        trainer = Trainer(cfg, data, token_states)
        history = trainer.run()
    if history and history[-1].val_metrics:
        m = history[-1].val_metrics
        print(
            f"final: loss={history[-1].train_loss:.4f} "
            f"auc={m.get('auc', float('nan')):.4f} "
            f"ndcg10={m.get('ndcg10', float('nan')):.4f}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
