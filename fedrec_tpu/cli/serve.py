"""Long-lived serving driver: ``fedrec-serve``.

Where ``fedrec-recommend`` is a one-shot batch job (restore -> encode ->
emit JSONL -> exit), this starts the online subsystem
(:mod:`fedrec_tpu.serving`): a TCP/JSON-lines server whose embedding
store can be hot-swapped from new training checkpoints while requests
are in flight (``{"cmd": "refresh", ...}`` on any connection).

Usage:
  # real artifacts (reference UserData layout + a training snapshot dir):
  fedrec-serve --data-dir UserData --snapshot-dir snapshots --port 7607

  # synthetic catalog, no artifacts needed (smoke / load testing):
  fedrec-serve --synthetic 65000 --port 7607

  # million-item mode: two-stage retrieval kicks in past --exact-threshold
  fedrec-serve --synthetic 1000000 --clusters 1024 --n-probe 64
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from pathlib import Path

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=7607)
    p.add_argument("--top-k", type=int, default=10)
    p.add_argument("--keep-history", action="store_true",
                   help="allow already-clicked news in responses")
    # ---- batching
    p.add_argument("--batch-sizes", default="1,8,32,128",
                   help="fixed padded batch buckets (comma-separated)")
    p.add_argument("--flush-ms", type=float, default=2.0,
                   help="max coalescing wait for the oldest pending request")
    p.add_argument("--max-queue", type=int, default=1024,
                   help="queue-depth backpressure threshold")
    # ---- retrieval
    p.add_argument("--clusters", type=int, default=0,
                   help="k-means coarse clusters (0 = exact full-catalog scoring)")
    p.add_argument("--n-probe", type=int, default=8)
    p.add_argument("--exact-threshold", type=int, default=4096,
                   help="catalogs at/below this size always use exact scoring")
    p.add_argument("--shard-store", action="store_true",
                   help="row-shard the embedding store across this "
                        "process's devices (fedrec_tpu.shard): per-device "
                        "HBM holds catalog/devices rows, the exact scorer "
                        "reads the sharded table transparently. Exact "
                        "retrieval only (incompatible with --clusters)")
    # ---- model / data sources
    p.add_argument("--synthetic", type=int, default=0, metavar="N",
                   help="serve a random N-item catalog with fresh-init params "
                        "(no artifacts needed; scores are meaningless)")
    p.add_argument("--data-dir", default="/root/reference/UserData")
    p.add_argument("--snapshot-dir", default=None)
    p.add_argument("--token-states", default=None,
                   help="(N, L, bert_hidden) .npy of cached trunk states")
    p.add_argument("--metrics-every", type=float, default=30.0,
                   help="seconds between metric JSON lines on stdout")
    p.add_argument("--obs-dir", default=None,
                   help="write observability artifacts here (metrics.jsonl "
                        "event log, trace.json host spans, prometheus.txt "
                        "exposition); render with fedrec-obs report")
    p.add_argument("--set", dest="overrides", action="append", default=[],
                   metavar="SECTION.KEY=VALUE")
    return p


def _synthetic_service(args, cfg):
    """Random catalog + fresh-init user params: every serving code path
    (batching, retrieval, swap) without any training artifact."""
    import jax
    import jax.numpy as jnp

    from fedrec_tpu.models import NewsRecommender
    from fedrec_tpu.serving import EmbeddingStore, ServingService

    model = NewsRecommender(cfg.model)
    rng = np.random.default_rng(0)
    table = jnp.asarray(
        rng.standard_normal((args.synthetic, cfg.model.news_dim)), jnp.float32
    )
    dummy = jnp.zeros((1, cfg.data.max_his_len, cfg.model.news_dim), jnp.float32)
    user_params = model.init(
        jax.random.PRNGKey(0), dummy, method=NewsRecommender.encode_user
    )["params"]["user_encoder"]
    store = EmbeddingStore()
    if args.shard_store:
        from fedrec_tpu.serving.store import publish_sharded

        publish_sharded(store, table, user_params, source="synthetic")
    else:
        store.publish(table, user_params, source="synthetic")
    return _service(args, cfg, model, store, id_map=None)


def _checkpoint_service(args, cfg):
    from fedrec_tpu.data import load_mind_artifacts
    from fedrec_tpu.models import NewsRecommender
    from fedrec_tpu.serving.store import EmbeddingStore, publish_from_checkpoint

    snap_dir = args.snapshot_dir or cfg.train.snapshot_dir
    data = load_mind_artifacts(args.data_dir)
    token_path = args.token_states or str(Path(args.data_dir) / "token_states.npy")
    if not Path(token_path).exists():
        print(f"[serve] ERROR: no token states at {token_path}; export them or "
              "pass --token-states (or use --synthetic for a smoke catalog)",
              file=sys.stderr)
        return None
    token_states = np.load(token_path)
    index2nid = {i: n for n, i in data.nid2index.items()}
    valid = np.zeros(data.num_news, bool)
    valid[[i for i in index2nid if 0 <= i < data.num_news]] = True
    model = NewsRecommender(cfg.model)
    store = EmbeddingStore()
    gen = publish_from_checkpoint(
        store, model, snap_dir, token_states, valid_mask=valid,
        dtype=cfg.model.dtype, shard=args.shard_store,
    )
    print(f"[serve] generation 0 from {gen.source} round {gen.round}",
          file=sys.stderr)
    return _service(args, cfg, model, store, id_map=index2nid)


def _service(args, cfg, model, store, id_map):
    from fedrec_tpu.serving import ServingService

    return ServingService(
        model,
        store,
        history_len=cfg.data.max_his_len,
        top_k=args.top_k,
        exclude_history=not args.keep_history,
        batch_sizes=tuple(int(b) for b in args.batch_sizes.split(",")),
        flush_ms=args.flush_ms,
        max_queue=args.max_queue,
        num_clusters=args.clusters,
        n_probe=args.n_probe,
        exact_threshold=args.exact_threshold,
        id_map=id_map,
    )


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    from fedrec_tpu.config import ExperimentConfig
    from fedrec_tpu.serving import serve_forever
    from fedrec_tpu.utils.logging import MetricLogger

    cfg = ExperimentConfig()
    cfg.apply_overrides(args.overrides)

    if args.shard_store and args.clusters:
        print(
            "[serve] ERROR: --shard-store pairs with exact retrieval only "
            "(the k-means member lists are host-built per cluster); drop "
            "--clusters or --shard-store",
            file=sys.stderr,
        )
        return 2
    service = (
        _synthetic_service(args, cfg) if args.synthetic
        else _checkpoint_service(args, cfg)
    )
    if service is None:
        return 2
    if cfg.obs.quality.enabled and cfg.obs.quality.probe_users > 0:
        # pre-swap drift probe: every {"cmd":"refresh"} hot-swap scores
        # the pinned probe set against both generations first, so a bad
        # table push surfaces serve.drift_* before it serves traffic
        service.store.enable_drift_probe(
            num_probes=cfg.obs.quality.probe_users,
            topk=cfg.obs.quality.probe_topk,
            seed=cfg.obs.quality.seed,
        )
    service.warmup()  # compile every bucket before accepting traffic
    import os as _os

    from fedrec_tpu.obs import ensure_fleet_identity, get_tracer

    # spans are only worth their memory when something will save them:
    # without --obs-dir this process never writes trace.json, so recording
    # per-request spans would just fill the bounded buffer with dead weight
    get_tracer().enabled = bool(args.obs_dir)
    # fleet correlation keys: serving spans/snapshots join the fleet's
    # training artifacts by worker id (FEDREC_WORKER_ID when the operator
    # co-locates a server with a training worker)
    ensure_fleet_identity(worker=_os.environ.get("FEDREC_WORKER_ID") or "serve")
    jsonl = None
    if args.obs_dir:
        from pathlib import Path as _Path

        _Path(args.obs_dir).mkdir(parents=True, exist_ok=True)
        jsonl = str(_Path(args.obs_dir) / "metrics.jsonl")
    logger = MetricLogger(jsonl_path=jsonl, jsonl_max_mb=cfg.obs.jsonl_max_mb)
    if cfg.obs.slo.enabled:
        # heartbeat-cadence watch: SLOs over the serve.* keys (p99, queue
        # depth, staleness) evaluate in serve_forever's beat; the admin
        # {"cmd":"alerts"} and fedrec-obs alerts read the same engine
        from fedrec_tpu.obs.watch import Watch

        service.watch = Watch(
            cfg.obs.slo, cfg.obs.watch,
            registry=service.registry,
            jsonl_path=jsonl,
            jsonl_max_mb=cfg.obs.jsonl_max_mb,
        )
    try:
        asyncio.run(serve_forever(
            service, host=args.host, port=args.port,
            metrics_every_s=args.metrics_every, logger=logger,
            obs_dir=args.obs_dir, jsonl_max_mb=cfg.obs.jsonl_max_mb,
        ))
    except KeyboardInterrupt:
        print("[serve] interrupted; shutting down", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
