"""Serving driver: trained snapshot -> top-k recommendations per user.

The reference framework ends at validation; an operator who trained a model
has no way to USE it. This driver closes that gap: restore the latest
snapshot, encode the news corpus once, and emit JSON-lines
``{"uid": ..., "news": [nid, ...], "scores": [...]}`` for every known user
(or a ``--uids`` subset), batched through the jitted full-catalog scorer
(:mod:`fedrec_tpu.serve`).

Each user's history is their LONGEST recorded click history across train +
valid samples (samples carry cumulative histories, so longest = latest).

Usage:
  python -m fedrec_tpu.cli.recommend --data-dir UserData \\
      --snapshot-dir snapshots [--top-k 10] [--out recs.jsonl] \\
      [--uids U123 U456] [--set section.key=value]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-dir", default="/root/reference/UserData",
                   help="reference UserData/ artifact layout")
    p.add_argument("--token-states", default=None,
                   help="(N, L, bert_hidden) .npy of cached trunk states")
    p.add_argument("--snapshot-dir", default=None,
                   help="orbax snapshot tree (default: train.snapshot_dir)")
    p.add_argument("--top-k", type=int, default=10)
    p.add_argument("--keep-history", action="store_true",
                   help="allow already-clicked news in the output")
    p.add_argument("--out", default="-", help="output JSONL path ('-' = stdout)")
    p.add_argument("--uids", nargs="*", default=None,
                   help="subset of user ids (default: every known user)")
    p.add_argument("--allow-random-states", action="store_true",
                   help="permit serving with RANDOM trunk token states when "
                        "token_states.npy is missing (smoke/testing only — "
                        "the scores are meaningless)")
    p.add_argument("--batch-users", type=int, default=256)
    p.add_argument("--set", dest="overrides", action="append", default=[],
                   metavar="SECTION.KEY=VALUE")
    return p


def collect_histories(data, max_his_len: int) -> dict[str, list[str]]:
    """uid -> longest recorded history (sample schema: [uidx, pos, negs,
    history, uid], reference ``dataset.py:81``)."""
    best: dict[str, list[str]] = {}
    for sample in list(data.train_samples) + list(data.valid_samples):
        _, _, _, his, uid = sample
        if len(his) >= len(best.get(uid, ())):
            best[uid] = list(his)
    return {u: h[-max_his_len:] for u, h in best.items()}


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    import jax
    import jax.numpy as jnp

    from fedrec_tpu.config import ExperimentConfig
    from fedrec_tpu.data import load_mind_artifacts
    from fedrec_tpu.models import NewsRecommender
    from fedrec_tpu.serve import build_recommend_fn
    from fedrec_tpu.train.step import encode_all_news, encode_corpus_tokens

    cfg = ExperimentConfig()
    cfg.apply_overrides(args.overrides)
    snap_dir = args.snapshot_dir or cfg.train.snapshot_dir

    # serve with the TRAINING run's resolved config when it was persisted
    # next to the snapshots (Trainer/coordinator write config.json): a
    # template-free restore otherwise trusts the operator to repeat every
    # --set, and a mismatch yields an opaque shape error — or, worse,
    # silently different scores for shape-compatible knobs like max_his_len
    # (ADVICE r2). Explicit CLI --set still wins on top.
    cfg_path = Path(snap_dir) / "config.json"
    if cfg_path.exists():
        try:
            cfg = ExperimentConfig.from_dict(json.loads(cfg_path.read_text()))
            cfg.apply_overrides(args.overrides)
            print(f"[recommend] using training config {cfg_path}",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001 — any malformed file degrades
            # to the unverified-defaults path instead of crashing serving
            print(f"[recommend] ignoring unreadable {cfg_path}: {e}",
                  file=sys.stderr)
    else:
        print("[recommend] no config.json next to the snapshot — model "
              "hyperparameters come from defaults + --set and are NOT "
              "verified against the training run", file=sys.stderr)

    # orbax trees (fedrec-run) and coordinator msgpack globals can coexist
    # in one directory; the shared restore policy (most recently WRITTEN
    # wins, host arrays, client-0 extraction) lives in
    # fedrec_tpu.serving.store so the one-shot CLI and the long-lived
    # server can never restore different checkpoints from the same dir
    from fedrec_tpu.serving.store import load_checkpoint_params

    try:
        user_params, news_params, round_, kind = load_checkpoint_params(
            snap_dir, log=lambda m: print(f"[recommend] {m}", file=sys.stderr)
        )
    except FileNotFoundError as e:
        print(f"[recommend] {e} — train first (fedrec-run / "
              "fedrec-coordinator) or pass --snapshot-dir", file=sys.stderr)
        return 2
    print(f"[recommend] serving {kind} snapshot"
          + (f" (round {round_})" if round_ is not None else ""),
          file=sys.stderr)

    data = load_mind_artifacts(args.data_dir)
    model = NewsRecommender(cfg.model)
    mode = cfg.model.text_encoder_mode
    if mode == "finetune":
        from fedrec_tpu.models.bert import make_text_encoder

        table = encode_corpus_tokens(
            make_text_encoder(cfg.model), news_params,
            jnp.asarray(data.news_tokens, jnp.int32),
        )
    else:
        token_path = args.token_states or str(
            Path(args.data_dir) / "token_states.npy"
        )
        if Path(token_path).exists():
            token_states = np.load(token_path)
        elif args.allow_random_states:
            print(f"[recommend] no token states at {token_path}; using RANDOM "
                  "states (--allow-random-states) — scores are meaningless",
                  file=sys.stderr)
            token_states = np.random.default_rng(0).standard_normal(
                (data.num_news, data.title_len, cfg.model.bert_hidden)
            ).astype(np.float32)
        else:
            # hard error (ADVICE r2): silently substituting random trunk
            # states produced normal-looking JSONL an operator could ship
            print(f"[recommend] ERROR: no token states at {token_path}. "
                  "Export them (fedrec_tpu.models.bert) or pass "
                  "--token-states; use --allow-random-states only for "
                  "smoke tests.", file=sys.stderr)
            return 2
        table = encode_all_news(
            model, news_params,
            jnp.asarray(token_states, jnp.dtype(cfg.model.dtype)),
        )

    histories = collect_histories(data, cfg.data.max_his_len)
    uids = sorted(histories) if args.uids is None else args.uids
    missing = [u for u in uids if u not in histories]
    if missing:
        print(f"[recommend] {len(missing)} unknown uid(s) skipped: "
              f"{missing[:5]}...", file=sys.stderr)
        uids = [u for u in uids if u in histories]
    if not uids:
        print("[recommend] no users to serve", file=sys.stderr)
        return 2

    index2nid = {i: n for n, i in data.nid2index.items()}
    # real artifacts can carry more token rows than mapped nids (the
    # reference demo shard: 225 rows, 139 ids) — never recommend the unmapped
    valid = np.zeros(data.num_news, bool)
    valid[[i for i in index2nid if 0 <= i < data.num_news]] = True
    if len(jax.devices()) > 1:
        # ride the mesh: catalog + score matrix sharded over every device,
        # local top-k + all_gather merge (serve.build_recommend_fn_sharded)
        from fedrec_tpu.parallel import client_mesh
        from fedrec_tpu.serve import build_recommend_fn_sharded

        mesh = client_mesh(len(jax.devices()))
        fn = build_recommend_fn_sharded(
            model, mesh, top_k=args.top_k,
            exclude_history=not args.keep_history, valid_mask=valid,
        )
        print(f"[recommend] catalog scoring sharded over {mesh.size} devices",
              file=sys.stderr)
    else:
        fn = build_recommend_fn(
            model, top_k=args.top_k,
            exclude_history=not args.keep_history, valid_mask=valid,
        )

    out_fh = sys.stdout if args.out == "-" else open(args.out, "w")
    h_len = cfg.data.max_his_len
    bu = args.batch_users
    for start in range(0, len(uids), bu):
        chunk = uids[start : start + bu]
        hist = np.zeros((bu, h_len), np.int32)  # static shape: one compile
        for r, uid in enumerate(chunk):
            ids = [data.nid2index.get(n, 0) for n in histories[uid]]
            hist[r, : len(ids)] = ids
        ids_out, scores_out = fn(user_params, table, hist)
        ids_out, scores_out = np.asarray(ids_out), np.asarray(scores_out)
        for r, uid in enumerate(chunk):
            keep = ids_out[r] >= 0
            out_fh.write(json.dumps({
                "uid": uid,
                "news": [index2nid[int(i)] for i in ids_out[r][keep]],
                "scores": [round(float(s), 5) for s in scores_out[r][keep]],
            }) + "\n")
    if out_fh is not sys.stdout:
        out_fh.close()
        print(f"[recommend] wrote {len(uids)} users to {args.out}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
