"""``fedrec-lint`` — the project-invariant static-analysis CLI.

Usage patterns (docs/ANALYSIS.md §2):

    fedrec-lint                          # lint the repo tree, exit 0/1
    fedrec-lint --list-codes             # every code + one-line meaning
    fedrec-lint --select TS,CC           # only these families
    fedrec-lint --ignore TS105           # drop a code everywhere
    fedrec-lint --format json            # machine-readable findings
    fedrec-lint --write-baseline         # accept current findings
    fedrec-lint --no-baseline            # report baselined findings too
    fedrec-lint --write-feature-table    # regen the docs compat table
    fedrec-lint --stats                  # scan/suppression counters

Exit codes: 0 clean (suppressed/baselined findings are clean), 1 new
findings, 2 usage/environment error — the same convention as fedrec-obs.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from fedrec_tpu.analysis import (
    DEFAULT_BASELINE,
    codes_table,
    run_lint,
    write_baseline,
    write_docs_table,
)
from fedrec_tpu.analysis.core import DEFAULT_SCAN_ROOTS


def _find_root(start: Path) -> Path | None:
    """Nearest ancestor that looks like the repo (has fedrec_tpu/config.py)."""
    cur = start.resolve()
    for cand in (cur, *cur.parents):
        if (cand / "fedrec_tpu" / "config.py").exists():
            return cand
    return None


def _split_codes(raw: str | None) -> list[str] | None:
    if raw is None:
        return None
    return [c.strip() for c in raw.split(",") if c.strip()]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fedrec-lint",
        description="project-invariant static analysis (docs/ANALYSIS.md)",
    )
    ap.add_argument(
        "paths", nargs="*",
        help="scan roots relative to the repo root "
             f"(default: {' '.join(DEFAULT_SCAN_ROOTS)})",
    )
    ap.add_argument("--root", default=None, help="repo root (default: auto-detect)")
    ap.add_argument("--select", default=None, metavar="CODES",
                    help="comma list of codes/prefixes to keep (TS,CC201)")
    ap.add_argument("--ignore", default=None, metavar="CODES",
                    help="comma list of codes/prefixes to drop")
    ap.add_argument("--analyzers", default=None, metavar="NAMES",
                    help="comma list of analyzers to run (default: all)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help=f"baseline file relative to root (default: {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into the baseline")
    ap.add_argument("--write-feature-table", action="store_true",
                    help="regenerate the docs feature-compatibility table "
                         "from analysis/feature_matrix.toml and exit")
    ap.add_argument("--list-codes", action="store_true")
    ap.add_argument("--stats", action="store_true",
                    help="print scan/suppression/baseline counters")
    args = ap.parse_args(argv)

    if args.list_codes:
        for code, analyzer, desc in codes_table():
            print(f"{code}  [{analyzer}]  {desc}")
        return 0

    root = Path(args.root) if args.root else _find_root(Path.cwd())
    if root is None or not (root / "fedrec_tpu" / "config.py").exists():
        print(
            "fedrec-lint: cannot find the repo root (no fedrec_tpu/config.py "
            "above the working directory); pass --root", file=sys.stderr,
        )
        return 2

    if args.write_feature_table:
        try:
            changed = write_docs_table(root)
        except FileNotFoundError as e:
            print(f"fedrec-lint: missing {e}", file=sys.stderr)
            return 2
        print(
            "feature table "
            + ("regenerated" if changed else "already up to date")
            + f" in {root / 'docs/ANALYSIS.md'}"
        )
        return 0

    # presence, not truthiness: --select "" would otherwise bypass the
    # filtered-run guards while deselecting EVERY code
    for flag, raw in (("--select", args.select), ("--ignore", args.ignore),
                      ("--analyzers", args.analyzers)):
        if raw is not None and not _split_codes(raw):
            print(f"fedrec-lint: {flag} got an empty code list", file=sys.stderr)
            return 2

    scan_roots = args.paths or DEFAULT_SCAN_ROOTS
    baseline = None if args.no_baseline else args.baseline
    try:
        result = run_lint(
            root,
            scan_roots=scan_roots,
            select=_split_codes(args.select),
            ignore=_split_codes(args.ignore) or (),
            baseline_path=baseline,
            analyzers=_split_codes(args.analyzers),
        )
    except ValueError as e:
        print(f"fedrec-lint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        # the engine's `filtered` flag is THE definition (normalized-root
        # aware: spelling out the default roots is NOT a filter); a
        # filtered run sees only a subset of findings, and writing it as
        # the baseline would silently delete every deselected entry
        if result.filtered:
            print(
                "fedrec-lint: --write-baseline requires an unfiltered run "
                "(no paths/--select/--ignore/--analyzers) — the baseline "
                "is the whole tree's accepted set, not a filtered view",
                file=sys.stderr,
            )
            return 2
        bp = root / args.baseline
        write_baseline(bp, result.all_fingerprints)
        print(
            f"baseline written: {len(set(result.all_fingerprints))} "
            f"fingerprints -> {bp}"
        )
        return 0

    if args.format == "json":
        payload = {
            "findings": [
                {
                    "path": f.path, "line": f.line, "col": f.col,
                    "code": f.code, "message": f.message,
                }
                for f in result.findings
            ],
            "suppressed": result.suppressed,
            "baselined": result.baselined,
            "files_scanned": result.files_scanned,
            "stale_baseline": result.stale_baseline,  # engine clears on filtered runs
        }
        print(json.dumps(payload, indent=2))
    else:
        for f in result.findings:
            print(f.format())
        if result.stale_baseline:  # engine clears this on filtered runs
            print(
                f"note: {len(result.stale_baseline)} baseline entries no "
                "longer match any finding — run --write-baseline to prune",
                file=sys.stderr,
            )
        if args.stats or result.findings:
            print(
                f"fedrec-lint: {len(result.findings)} finding(s), "
                f"{result.suppressed} suppressed, {result.baselined} "
                f"baselined, {result.files_scanned} files scanned",
                file=sys.stderr,
            )
    return result.exit_code


if __name__ == "__main__":
    raise SystemExit(main())
