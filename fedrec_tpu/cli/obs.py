"""``fedrec-obs`` — render and replay a run's observability artifacts.

Consumes the artifact trio every instrumented entry point writes
(Trainer with ``obs.dir``, ``fedrec-serve --obs-dir``,
``benchmarks/serve_load.py --obs-dir``):

* ``metrics.jsonl``   — MetricLogger records + registry snapshots
  (plus ``metrics.jsonl.1`` when ``obs.jsonl_max_mb`` rotated the log;
  rotated files are read first, in order)
* ``trace.json``      — Chrome-trace/Perfetto host spans
* ``prometheus.txt``  — final text exposition

plus the flight-recorder dump (``flightrec/``) the training-health
sentry writes on a non-finite/divergence trigger.

Subcommands:

  fedrec-obs report <dir | metrics.jsonl> [--trace trace.json] [--json]
      One-page run report: round throughput, loss trajectory, serve
      p50/p99, prefetch stalls, epsilon-spent trajectory, health +
      recompile counters, cap-overflow counts, host-span summary.

  fedrec-obs prom <dir | metrics.jsonl>
      Re-render the LAST registry snapshot in the event log as a
      Prometheus text exposition (for a run that predates, or lost, its
      prometheus.txt).

  fedrec-obs quality <dir | metrics.jsonl> [--json]
      Model-quality report off the last registry snapshot: every eval
      slice's AUC/MRR/NDCG + impression count (ascending AUC, so the
      worst stratum leads), the calibration reliability table + ECE,
      score separation, per-client AUC with the quality-outlier count,
      and the serving store's last pre-swap drift verdict.  Exit 2 when
      the run carried no quality telemetry (obs.quality.enabled=false).

  fedrec-obs perf <dir | metrics.jsonl> [--json]
      Performance report off the obs.perf telemetry: last-round
      throughput/MFU/HBM fraction, the per-round roofline-verdict
      counts (canonical verdict strings), the host phase table
      (batch_build/h2d/dispatch/aggregate/eval), the MFU trend over the
      last rounds, HBM bytes by component, the compile-cost
      (``cost_analysis``) table, and pointers to captured profiler
      traces.  Exit 2 when the run carried no perf telemetry
      (obs.perf.enabled=false).

  fedrec-obs replay <dir | flightrec dir> [--max-steps N] [--json]
      Re-execute the flight-recorder dump's recorded steps on CPU from
      the dumped chunk-entry state — deterministically confirming (and
      bisecting to) the step that went non-finite.  Exit 0 when the
      dump's trigger is reproduced, 1 when it is not.

  fedrec-obs fleet <dir> [--json]
      Fleet-wide report over a directory of ``worker_*`` obs dirs (the
      shared ``obs.dir`` of an elastic/coordinator run, or a collector's
      ``--telemetry-dir``): per-worker identity/epoch/rounds, the
      membership timeline, per-round straggler/critical-path attribution
      (which worker gated each round's barrier, and in which phase), and
      per-worker DCN bytes.  A single obs dir degrades to one worker.

  fedrec-obs fleet-trace <dir> [-o merged.json]
      ONE merged Chrome/Perfetto trace over every worker: a track per
      worker, clocks aligned via the shared round barrier (each
      ``fed_round`` N is a common event), membership epoch changes /
      lease expiries / joins / quarantines rendered as instants.

  fedrec-obs alerts <dir | metrics.jsonl> [--json]
      Alert timeline + active table off the ``{"kind":"alert"}``
      lifecycle records (one obs dir, or every ``worker_*`` log under a
      shared/collector dir — the fleet rules' ``worker_fleet`` included).
      Exit 1 while any alert is still firing at the end of the log(s),
      0 after everything resolved — scriptable as a gate.

  fedrec-obs tail <dir | metrics.jsonl> [--once] [--interval S]
      Live-follow the event log(s), printing each alert transition as it
      lands (rotation-aware).  ``--once`` prints the transitions already
      recorded and exits with the ``alerts`` exit-code contract.

``report``/``prom``/``fleet``/``fleet-trace`` import no JAX — usable on
any box the artifacts were copied to; ``replay`` imports JAX lazily (and
pins ``JAX_PLATFORMS=cpu`` unless the environment already chose a
platform).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

from fedrec_tpu.obs.registry import snapshot_to_prometheus
from fedrec_tpu.obs.report import (
    build_report,
    load_jsonl,
    load_trace,
    render_text,
)


def _fail(msg: str) -> int:
    print(f"fedrec-obs: {msg}", file=sys.stderr)
    return 2


def _resolve(path_arg: str) -> tuple[Path, Path | None]:
    """A directory (the obs.dir layout) or an explicit metrics.jsonl path
    -> (metrics_path, trace_path_or_None)."""
    p = Path(path_arg)
    if p.is_dir():
        metrics = p / "metrics.jsonl"
        trace = p / "trace.json"
        return metrics, (trace if trace.exists() else None)
    return p, None


def _load_event_log(metrics_path: Path):
    """load_jsonl with operator-grade failure messages instead of
    tracebacks; returns (records, snapshots) or an int exit code."""
    if not metrics_path.exists() and not Path(str(metrics_path) + ".1").exists():
        parent = metrics_path.parent
        hint = (
            " (the directory does not exist — check the obs dir path)"
            if not parent.exists()
            else " (directory exists but holds no event log — was the run "
                 "started with obs.dir / --obs-dir?)"
        )
        return _fail(f"no event log at {metrics_path}{hint}")
    try:
        return load_jsonl(metrics_path)
    except OSError as e:
        return _fail(f"cannot read {metrics_path}: {e}")


def _cmd_report(args) -> int:
    metrics_path, trace_path = _resolve(args.path)
    if args.trace:
        trace_path = Path(args.trace)
        if not trace_path.exists():
            return _fail(f"no trace file at {trace_path}")
    loaded = _load_event_log(metrics_path)
    if isinstance(loaded, int):
        return loaded
    records, snapshots = loaded
    trace_events = None
    if trace_path:
        try:
            trace_events = load_trace(trace_path)
        except (OSError, json.JSONDecodeError, KeyError) as e:
            print(f"fedrec-obs: skipping unreadable trace {trace_path}: {e}",
                  file=sys.stderr)
    report = build_report(records, snapshots, trace_events)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render_text(report))
    return 0


def _cmd_prom(args) -> int:
    metrics_path, _ = _resolve(args.path)
    loaded = _load_event_log(metrics_path)
    if isinstance(loaded, int):
        return loaded
    _, snapshots = loaded
    if not snapshots:
        return _fail(
            f"no registry snapshot in {metrics_path} (the run may have "
            "died before its first obs.snapshot_every round)"
        )
    # the SAME renderer the live {"cmd": "prometheus"} endpoint uses —
    # offline output cannot drift from the wire exposition
    print(snapshot_to_prometheus(snapshots[-1]), end="")
    return 0


# ----------------------------------------------------------------- quality
def _cmd_quality(args) -> int:
    from fedrec_tpu.obs.report import quality_detail_from_snapshot

    metrics_path, _ = _resolve(args.path)
    loaded = _load_event_log(metrics_path)
    if isinstance(loaded, int):
        return loaded
    _, snapshots = loaded
    if not snapshots:
        return _fail(
            f"no registry snapshot in {metrics_path} (the run may have "
            "died before its first obs.snapshot_every round)"
        )
    detail = quality_detail_from_snapshot(snapshots[-1])
    if not detail:
        return _fail(
            f"no quality telemetry in {metrics_path} — was the run "
            "started with obs.quality.enabled=1 (sliced eval; on "
            "fedrec-serve it also arms the drift probe, "
            "obs.quality.probe_users)?"
        )
    if args.json:
        print(json.dumps(detail, indent=2))
        return 0
    lines = ["# fedrec_tpu quality report", ""]
    slices = detail.get("slices")
    if slices:
        lines.append("## Eval slices (last eval, ascending AUC)")
        lines.append(
            f"{'slice':<20} {'auc':>8} {'mrr':>8} {'ndcg5':>8} "
            f"{'ndcg10':>8} {'count':>7}"
        )
        ordered = sorted(
            slices.items(), key=lambda kv: kv[1].get("auc", float("inf"))
        )
        for name, m in ordered:
            lines.append(
                f"{name:<20} {m.get('auc', float('nan')):>8.4f} "
                f"{m.get('mrr', float('nan')):>8.4f} "
                f"{m.get('ndcg5', float('nan')):>8.4f} "
                f"{m.get('ndcg10', float('nan')):>8.4f} "
                f"{int(m.get('count', 0)):>7}"
            )
        if detail.get("slices_skipped"):
            lines.append(
                f"(+ {int(detail['slices_skipped'])} slice evaluations "
                "skipped: empty/degenerate strata)"
            )
        lines.append("")
    if "ece" in detail or "score_separation" in detail:
        lines.append("## Scores & calibration")
        if "score_separation" in detail:
            dp = (
                f", d'={detail['score_dprime']:.3f}"
                if "score_dprime" in detail else ""
            )
            lines.append(
                f"separation: {detail['score_separation']:.4f}{dp}"
            )
        if "ece" in detail:
            lines.append(f"ece: {detail['ece']:.4f}")
        for row in detail.get("calibration", []):
            if row.get("count"):
                lines.append(
                    f"  bin {row['bin']}: conf="
                    f"{row.get('confidence', float('nan')):.3f} "
                    f"acc={row.get('accuracy', float('nan')):.3f} "
                    f"n={int(row['count'])}"
                )
        lines.append("")
    if "client_auc" in detail:
        lines.append("## Per-client AUC")
        lines.append(", ".join(
            f"c{c}={v:.4f}" for c, v in detail["client_auc"].items()
        ))
        if detail.get("quality_outlier_client_evals"):
            lines.append(
                "quality-outlier client-evals: "
                f"{int(detail['quality_outlier_client_evals'])}"
            )
        lines.append("")
    drift = detail.get("drift")
    if drift:
        lines.append("## Serving drift (last pre-swap probe)")
        if "score_shift_mean" in drift:
            lines.append(
                f"|Δscore| mean={drift['score_shift_mean']:.4g} "
                f"max={drift.get('score_shift_max', 0):.4g}"
            )
        if "topk_jaccard" in drift:
            lines.append(
                f"top-k jaccard={drift['topk_jaccard']:.3f} "
                f"(churn {drift.get('rank_churn', 0):.3f}) over "
                f"{int(drift.get('checks', 0))} check(s)"
            )
        lines.append("")
    print("\n".join(lines))
    return 0


# -------------------------------------------------------------------- perf
def _cmd_perf(args) -> int:
    from fedrec_tpu.obs.report import perf_detail_from_snapshot

    metrics_path, trace_path = _resolve(args.path)
    loaded = _load_event_log(metrics_path)
    if isinstance(loaded, int):
        return loaded
    records, snapshots = loaded
    if not snapshots:
        return _fail(
            f"no registry snapshot in {metrics_path} (the run may have "
            "died before its first obs.snapshot_every round)"
        )
    detail = perf_detail_from_snapshot(snapshots[-1])
    if not detail:
        return _fail(
            f"no perf telemetry in {metrics_path} — was the run started "
            "with obs.perf.enabled=1 (live MFU/roofline gauges, "
            "compile-cost telemetry, HBM attribution)?"
        )
    # the MFU/verdict trend rides the per-round MetricLogger records
    trend = [
        (r.get("round"), r.get("perf.mfu"), r.get("perf.samples_per_sec"),
         r.get("perf.verdict"))
        for r in records
        if "perf.samples_per_sec" in r and "round" in r
    ]
    captures = [
        r for r in records
        if r.get("kind") in ("perf_capture", "profile_trace")
    ]
    phases = None
    if trace_path:
        try:
            from fedrec_tpu.obs.fleet import ROUND_PHASES
            from fedrec_tpu.obs.report import span_summary

            # the same rollup build_report's span table uses, filtered to
            # the round phases — the two views cannot drift on one trace
            phases = span_summary(load_trace(trace_path), names=ROUND_PHASES)
        except (OSError, json.JSONDecodeError, KeyError) as e:
            print(f"fedrec-obs: skipping unreadable trace {trace_path}: {e}",
                  file=sys.stderr)
            phases = None
    if args.json:
        doc = dict(detail)
        if trend:
            doc["trend"] = [
                {"round": r, "mfu": m, "samples_per_sec": s, "verdict": v}
                for r, m, s, v in trend
            ]
        if captures:
            # NOT "captures": perf_detail_from_snapshot already uses that
            # key for the numeric counter — a consumer must never see the
            # key's type flip between runs
            doc["capture_records"] = captures
        if phases:
            doc["phases"] = phases
        print(json.dumps(doc, indent=2))
        return 0
    lines = ["# fedrec_tpu perf report", ""]
    head = []
    if "samples_per_sec" in detail:
        head.append(f"throughput: {detail['samples_per_sec']:.1f} samples/s")
    if "mfu" in detail:
        head.append(f"mfu: {detail['mfu']:.4f}")
    if "hbm_fraction" in detail:
        head.append(f"hbm: {detail['hbm_fraction']:.3f} of peak")
    if head:
        lines.append(", ".join(head) + " (last round)")
    if "verdict_rounds" in detail:
        from fedrec_tpu.obs.perf import ROOFLINE_VERDICTS

        lines.append("")
        lines.append("## Roofline verdicts")
        for key, n in sorted(detail["verdict_rounds"].items()):
            lines.append(
                f"  {int(n):>4} round(s)  {ROOFLINE_VERDICTS.get(key, key)}"
            )
    if phases:
        lines.append("")
        lines.append("## Phase table (host spans)")
        lines.append(f"{'phase':<14} {'count':>7} {'total_ms':>10} {'mean_ms':>9}")
        for name, p in phases.items():
            lines.append(
                f"{name:<14} {p['count']:>7} {p['total_ms']:>10.1f} "
                f"{p['mean_ms']:>9.2f}"
            )
    if trend:
        lines.append("")
        lines.append("## Trend (last 8 rounds)")
        for r, m, s, v in trend[-8:]:
            mfu_s = f" mfu={m:.4f}" if m is not None else ""
            lines.append(
                f"  r{int(r)}: {s:.1f} samples/s{mfu_s}"
                + (f" [{v}]" if v else "")
            )
    if "hbm_components" in detail:
        lines.append("")
        lines.append("## HBM by component (descending)")
        for name, v in sorted(
            detail["hbm_components"].items(), key=lambda kv: -kv[1]
        ):
            lines.append(f"  {name:<12} {v / (1024 * 1024):>10.1f} MB")
    if "compile_cost" in detail:
        lines.append("")
        lines.append("## Compile cost (xla cost_analysis)")
        lines.append(
            f"{'fn':<20} {'gflops':>10} {'MB_accessed':>12} {'intensity':>10}"
        )
        for fn, c in detail["compile_cost"].items():
            gf = c.get("flops")
            mb = c.get("bytes_accessed")
            ai = c.get("arithmetic_intensity")
            lines.append(
                f"{fn:<20} "
                f"{(gf / 1e9 if gf is not None else float('nan')):>10.2f} "
                f"{(mb / 1e6 if mb is not None else float('nan')):>12.2f} "
                f"{(ai if ai is not None else float('nan')):>10.1f}"
            )
    if captures:
        lines.append("")
        lines.append("## Captured traces")
        for c in captures:
            tag = c.get("kind")
            rnd = c.get("round")
            lines.append(
                f"  {tag}" + (f" r{int(rnd)}" if rnd is not None else "")
                + f": {c.get('logdir')}"
            )
    print("\n".join(lines))
    return 0


# ------------------------------------------------------------------- fleet
def _load_fleet(path_arg: str):
    from fedrec_tpu.obs.fleet import load_fleet_dir

    try:
        return load_fleet_dir(path_arg)
    except FileNotFoundError as e:
        return _fail(str(e))


def _cmd_fleet(args) -> int:
    from fedrec_tpu.obs.fleet import build_fleet_report, render_fleet_text

    workers = _load_fleet(args.path)
    if isinstance(workers, int):
        return workers
    report = build_fleet_report(workers)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render_fleet_text(report))
    return 0


def _cmd_fleet_trace(args) -> int:
    from fedrec_tpu.obs.fleet import build_fleet_trace

    workers = _load_fleet(args.path)
    if isinstance(workers, int):
        return workers
    doc = build_fleet_trace(workers)
    out = Path(args.out) if args.out else Path(args.path) / "fleet_trace.json"
    try:
        with open(out, "w") as f:
            json.dump(doc, f)
    except OSError as e:
        return _fail(f"cannot write merged trace to {out}: {e}")
    n_ev = sum(1 for e in doc["traceEvents"] if e.get("ph") != "M")
    print(
        f"merged {n_ev} events from {len(doc['otherData']['workers'])} "
        f"worker track(s) -> {out} (load in https://ui.perfetto.dev)"
    )
    return 0


# ------------------------------------------------------------------ alerts
def _alert_sources(path_arg: str) -> list[tuple[str | None, Path]]:
    """-> [(worker_or_None, metrics_path)]: every ``worker_*`` log under
    a shared/collector dir, or the single obs-dir / file log."""
    p = Path(path_arg)
    if p.is_dir():
        wdirs = sorted(d for d in p.glob("worker_*") if d.is_dir())
        if wdirs:
            return [
                (d.name[len("worker_"):], d / "metrics.jsonl")
                for d in wdirs
            ]
        return [(None, p / "metrics.jsonl")]
    return [(None, p)]


def _load_alert_logs(path_arg: str):
    """-> (timeline, active) across every source log, or an int exit
    code.  Alert keys are scoped per source so two workers' ``slo:x``
    lifecycles never collapse into one."""
    from fedrec_tpu.obs.watch import active_alerts, alert_records

    sources = _alert_sources(path_arg)
    timeline: list[dict] = []
    active: list[dict] = []
    found_log = False
    for worker, mp in sources:
        if not mp.exists() and not Path(str(mp) + ".1").exists():
            continue
        try:
            records, _ = load_jsonl(mp)
        except OSError as e:
            return _fail(f"cannot read {mp}: {e}")
        found_log = True
        recs = alert_records(records)
        if worker is not None:
            for r in recs:
                r.setdefault("labels", {}).setdefault("worker", worker)
        timeline.extend(recs)
        active.extend(active_alerts(recs))
    if not found_log:
        return _fail(
            f"no event log under {path_arg} (was the run started with "
            "obs.dir / --obs-dir, and obs.slo.enabled to record alerts?)"
        )
    timeline.sort(key=lambda r: r.get("ts", 0.0))
    return timeline, active


def _format_alert_line(rec: dict) -> str:
    import time as _time

    ts = _time.strftime("%H:%M:%S", _time.localtime(rec.get("ts", 0.0)))
    worker = (rec.get("labels") or {}).get("worker")
    wtxt = f" worker={worker}" if worker is not None else ""
    return (
        f"{ts} {rec.get('event', '?').upper():<8} "
        f"{rec.get('severity', '?'):<8} {rec.get('key', '?')}{wtxt}"
        f"  {rec.get('summary', '')}"
    )


def _cmd_alerts(args) -> int:
    loaded = _load_alert_logs(args.path)
    if isinstance(loaded, int):
        return loaded
    timeline, active = loaded
    if args.json:
        print(json.dumps({"timeline": timeline, "active": active}, indent=2))
        return 1 if active else 0
    print("# Alert timeline")
    if timeline:
        for rec in timeline:
            print(_format_alert_line(rec))
    else:
        print("(no alert transitions recorded)")
    print()
    print("# Active alerts")
    if active:
        for rec in sorted(active, key=lambda r: r.get("ts", 0.0)):
            print(_format_alert_line(rec))
    else:
        print("(none — everything resolved)")
    # the scriptable contract: firing -> 1, quiet -> 0 (errors exit 2)
    return 1 if active else 0


def _cmd_tail(args) -> int:
    import time as _time

    if args.once:
        loaded = _load_alert_logs(args.path)
        if isinstance(loaded, int):
            return loaded
        timeline, active = loaded
        for rec in timeline:
            print(_format_alert_line(rec))
        return 1 if active else 0
    sources = _alert_sources(args.path)
    offsets: dict[Path, int] = {}
    print(
        f"fedrec-obs: following {len(sources)} log(s) under {args.path} "
        "(ctrl-c to stop)",
        file=sys.stderr,
    )
    try:
        while True:
            for worker, mp in sources:
                try:
                    size = mp.stat().st_size
                except OSError:
                    continue
                pos = offsets.get(mp, 0)
                if size < pos:
                    pos = 0  # the log rotated under us: re-read from top
                if size == pos:
                    continue
                try:
                    with open(mp, "rb") as f:
                        f.seek(pos)
                        chunk = f.read()
                except OSError:
                    continue
                # consume only COMPLETE lines; a partially-flushed tail
                # stays unread until the writer finishes it
                nl = chunk.rfind(b"\n")
                if nl < 0:
                    continue
                offsets[mp] = pos + nl + 1
                for line in chunk[: nl + 1].splitlines():
                    try:
                        rec = json.loads(line)
                    except (json.JSONDecodeError, UnicodeDecodeError):
                        continue
                    if rec.get("kind") != "alert":
                        continue
                    if worker is not None:
                        rec.setdefault("labels", {}).setdefault(
                            "worker", worker
                        )
                    print(_format_alert_line(rec), flush=True)
            _time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


# ------------------------------------------------------------------ replay
def _resolve_flightrec(path_arg: str) -> Path | None:
    """obs dir / flightrec dir / manifest.json path -> flightrec dir."""
    p = Path(path_arg)
    if p.name == "manifest.json":
        p = p.parent
    if (p / "manifest.json").exists():
        return p
    if (p / "flightrec" / "manifest.json").exists():
        return p / "flightrec"
    return None


def _cmd_replay(args) -> int:
    flight_dir = _resolve_flightrec(args.path)
    if flight_dir is None:
        return _fail(
            f"no flight-recorder dump under {args.path} — expected "
            "<obs.dir>/flightrec/manifest.json (dumps are written when the "
            "health sentry trips with obs.dir set and "
            "obs.health.flight_recorder on)"
        )
    try:
        manifest = json.loads((flight_dir / "manifest.json").read_text())
    except (OSError, json.JSONDecodeError) as e:
        return _fail(f"unreadable manifest at {flight_dir}/manifest.json: {e}")
    if manifest.get("kind") != "flight_recorder_dump":
        return _fail(f"{flight_dir}/manifest.json is not a flight-recorder dump")
    if not manifest.get("records"):
        return _fail(
            "the dump holds no batch records (the trigger fired before any "
            "step was recorded); nothing to replay"
        )
    if manifest.get("state_file") is None:
        return _fail("the dump holds no state checkpoint; cannot replay")
    if manifest.get("table_file") is None:
        return _fail(
            "the dump omitted the feature table "
            f"(skipped at {manifest.get('table_skipped_mb', '?')} MB — raise "
            "obs.health.dump_table_max_mb); cannot replay"
        )

    # replay runs on CPU wherever the operator is, unless they chose
    # a platform explicitly — set BEFORE the first jax import
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import jax
    from flax import serialization

    from fedrec_tpu.config import ExperimentConfig
    from fedrec_tpu.fed.strategies import get_strategy
    from fedrec_tpu.models import NewsRecommender
    from fedrec_tpu.parallel.mesh import client_mesh, shard_fed_batch
    from fedrec_tpu.train.state import init_client_state, replicate_state
    from fedrec_tpu.train.step import build_fed_train_step, build_param_sync

    cfg = ExperimentConfig.from_dict(manifest["config"])
    if cfg.fed.seq_shards > 1:
        return _fail(
            "the dump was recorded with fed.seq_shards > 1; sequence-"
            "parallel steps need a multi-device mesh and cannot replay on "
            "one CPU device"
        )
    # replay is per-batch, host-driven, file-free — neutralize every knob
    # that would change dispatch shape or write artifacts
    cfg.train.rounds_per_scan = 1
    cfg.train.scan_steps = 1
    cfg.train.donate_batch = False
    cfg.data.prefetch_batches = 0
    cfg.obs.dir = ""
    cfg.obs.health.sentry = True  # the sentinel IS the replay's verdict

    # one CPU device hosting the whole client cohort: cohort vmapping makes
    # the collective math identical to the original packing (train.step)
    mesh = client_mesh(cfg.fed.num_clients, cfg.fed.mesh_axis, max_devices=1)
    model = NewsRecommender(cfg.model)
    strategy = get_strategy(cfg.fed.strategy)
    template = replicate_state(
        init_client_state(
            model, cfg, jax.random.PRNGKey(0),
            int(manifest["num_news"]), int(manifest["title_len"]),
        ),
        cfg.fed.num_clients,
        jax.random.PRNGKey(1),
    )
    try:
        state = serialization.from_bytes(
            template, (flight_dir / manifest["state_file"]).read_bytes()
        )
    except (OSError, ValueError) as e:
        return _fail(f"cannot restore the dumped state: {e}")
    table = np.load(flight_dir / manifest["table_file"])

    step = build_fed_train_step(
        model, cfg, strategy, mesh, mode=manifest.get("mode") or None
    )
    sync = (
        build_param_sync(cfg, mesh, strategy)
        if strategy.sync_params_every_round
        else None
    )
    from fedrec_tpu.train.step import compressed_sync_active

    # codec syncs (fed.dcn_compress != none) compress ROUND DELTAS: track
    # each round's entry params so a chunk-spanning dump replays the exact
    # compressed trajectory. Host copies — the step donates state buffers.
    sync_takes_entry = sync is not None and compressed_sync_active(cfg, strategy)

    def _entry_copy(st):
        return jax.tree_util.tree_map(
            np.asarray, (st.user_params, st.news_params)
        )

    entry = _entry_copy(state) if sync_takes_entry else None
    weights = {int(k): np.asarray(v) for k, v in manifest.get("weights", {}).items()}

    records = sorted(manifest["records"], key=lambda r: (r["round"], r["step"]))
    trigger = manifest.get("trigger", {})
    max_steps = args.max_steps or len(records)
    out_rows: list[dict] = []
    first_bad: dict | None = None
    prev_round = records[0]["round"]
    for i, rec in enumerate(records[:max_steps]):
        if rec["round"] != prev_round:
            if sync is not None and prev_round in weights:
                # re-apply the recorded round-end participation sync so a
                # chunk-spanning dump replays the exact trajectory
                if sync_takes_entry:
                    state = sync(state, np.asarray(weights[prev_round]), *entry)
                else:
                    state = sync(state, np.asarray(weights[prev_round]))
            if sync_takes_entry:
                entry = _entry_copy(state)
            prev_round = rec["round"]
        try:
            batch = dict(np.load(flight_dir / rec["file"]))
        except OSError as e:
            return _fail(f"cannot read batch record {rec['file']}: {e}")
        state, metrics = step(state, shard_fed_batch(mesh, batch, cfg), table)
        row = {
            "round": rec["round"],
            "step": rec["step"],
            "loss": float(np.asarray(metrics["mean_loss"]).reshape(-1)[0]),
            "grad_norm_max": float(np.max(np.asarray(metrics["health.grad_norm"]))),
            "update_norm_max": float(
                np.max(np.asarray(metrics["health.update_norm"]))
            ),
            "param_norm_max": float(
                np.max(np.asarray(metrics["health.param_norm"]))
            ),
            "nonfinite": int(np.asarray(metrics["health.nonfinite"]).sum()),
        }
        out_rows.append(row)
        if not args.json:
            print(
                f"round {row['round']} step {row['step']}: "
                f"loss={row['loss']:.6g} grad={row['grad_norm_max']:.4g} "
                f"update={row['update_norm_max']:.4g} "
                f"param={row['param_norm_max']:.4g} "
                f"nonfinite={row['nonfinite']}"
            )
        if row["nonfinite"] > 0:
            first_bad = row
            break

    reproduced = first_bad is not None
    verdict = {
        "trigger": trigger,
        "steps_replayed": len(out_rows),
        "reproduced_nonfinite": reproduced,
        "first_nonfinite": first_bad,
        "rows": out_rows,
    }
    if args.json:
        print(json.dumps(verdict, indent=2))
    elif reproduced:
        print(
            f"REPRODUCED: non-finite step at round {first_bad['round']} "
            f"step {first_bad['step']} (trigger was "
            f"{trigger.get('kind')} at round {trigger.get('round')} "
            f"step {trigger.get('step')})"
        )
    elif trigger.get("kind") == "nonfinite":
        print(
            "NOT REPRODUCED: no replayed step went non-finite — platform "
            "numerics may differ from the recording host, or the ring "
            "dropped the poisoning step (ring_complete="
            f"{manifest.get('ring_complete')})"
        )
    else:
        print(
            f"no non-finite step (trigger was {trigger.get('kind')!r}); "
            "the norm trajectory above is the evidence"
        )
    if trigger.get("kind") == "nonfinite":
        return 0 if reproduced else 1
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="fedrec-obs", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser("report", help="render the one-page run report")
    rep.add_argument("path", help="obs dir or metrics.jsonl path")
    rep.add_argument("--trace", default=None, help="explicit trace.json path")
    rep.add_argument("--json", action="store_true",
                     help="machine-readable report instead of text")
    rep.set_defaults(fn=_cmd_report)
    prom = sub.add_parser(
        "prom", help="Prometheus exposition from the last registry snapshot"
    )
    prom.add_argument("path", help="obs dir or metrics.jsonl path")
    prom.set_defaults(fn=_cmd_prom)
    qu = sub.add_parser(
        "quality",
        help="model-quality report: per-slice eval metrics, calibration, "
             "per-client AUC, serving drift (obs.quality telemetry)",
    )
    qu.add_argument("path", help="obs dir or metrics.jsonl path")
    qu.add_argument("--json", action="store_true",
                    help="machine-readable detail instead of text")
    qu.set_defaults(fn=_cmd_quality)
    pf = sub.add_parser(
        "perf",
        help="performance report: MFU trend + roofline verdicts, phase "
             "table, HBM attribution, compile-cost table (obs.perf "
             "telemetry)",
    )
    pf.add_argument("path", help="obs dir or metrics.jsonl path")
    pf.add_argument("--json", action="store_true",
                    help="machine-readable detail instead of text")
    pf.set_defaults(fn=_cmd_perf)
    rp = sub.add_parser(
        "replay",
        help="re-execute a flight-recorder dump on CPU to confirm/bisect",
    )
    rp.add_argument("path", help="obs dir, flightrec dir, or manifest.json")
    rp.add_argument("--max-steps", type=int, default=0,
                    help="replay at most N recorded steps (0 = all)")
    rp.add_argument("--json", action="store_true",
                    help="machine-readable verdict")
    rp.set_defaults(fn=_cmd_replay)
    fl = sub.add_parser(
        "fleet",
        help="fleet-wide report over worker_* obs dirs (straggler/"
             "critical-path attribution, membership timeline, DCN bytes)",
    )
    fl.add_argument("path", help="shared obs dir / collector dir / one "
                                 "worker's obs dir")
    fl.add_argument("--json", action="store_true",
                    help="machine-readable report instead of text")
    fl.set_defaults(fn=_cmd_fleet)
    ft = sub.add_parser(
        "fleet-trace",
        help="merge every worker's spans into ONE clock-aligned "
             "Chrome/Perfetto trace with per-worker tracks",
    )
    ft.add_argument("path", help="shared obs dir / collector dir / one "
                                 "worker's obs dir")
    ft.add_argument("-o", "--out", default=None,
                    help="output path (default <dir>/fleet_trace.json)")
    ft.set_defaults(fn=_cmd_fleet_trace)
    al = sub.add_parser(
        "alerts",
        help="alert timeline + active table off the {\"kind\":\"alert\"} "
             "records; exit 1 while any alert is still firing",
    )
    al.add_argument("path", help="obs dir, collector/shared dir, or "
                                 "metrics.jsonl path")
    al.add_argument("--json", action="store_true",
                    help="machine-readable {timeline, active} instead of "
                         "text (same exit-code contract)")
    al.set_defaults(fn=_cmd_alerts)
    tl = sub.add_parser(
        "tail",
        help="live-follow the event log(s), printing alert transitions "
             "as they land",
    )
    tl.add_argument("path", help="obs dir, collector/shared dir, or "
                                 "metrics.jsonl path")
    tl.add_argument("--once", action="store_true",
                    help="print the recorded transitions and exit with "
                         "the alerts exit-code contract")
    tl.add_argument("--interval", type=float, default=1.0,
                    help="poll interval seconds (default 1.0)")
    tl.set_defaults(fn=_cmd_tail)
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
