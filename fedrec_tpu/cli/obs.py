"""``fedrec-obs`` — render a run's observability artifacts.

Consumes the artifact trio every instrumented entry point writes
(Trainer with ``obs.dir``, ``fedrec-serve --obs-dir``,
``benchmarks/serve_load.py --obs-dir``):

* ``metrics.jsonl``   — MetricLogger records + registry snapshots
* ``trace.json``      — Chrome-trace/Perfetto host spans
* ``prometheus.txt``  — final text exposition

Subcommands:

  fedrec-obs report <dir | metrics.jsonl> [--trace trace.json] [--json]
      One-page run report: round throughput, loss trajectory, serve
      p50/p99, prefetch stalls, epsilon-spent trajectory, cap-overflow
      counts, host-span summary.

  fedrec-obs prom <dir | metrics.jsonl>
      Re-render the LAST registry snapshot in the event log as a
      Prometheus text exposition (for a run that predates, or lost, its
      prometheus.txt).

Imports no JAX — usable on any box the artifacts were copied to.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from fedrec_tpu.obs.registry import snapshot_to_prometheus
from fedrec_tpu.obs.report import (
    build_report,
    load_jsonl,
    load_trace,
    render_text,
)


def _resolve(path_arg: str) -> tuple[Path, Path | None]:
    """A directory (the obs.dir layout) or an explicit metrics.jsonl path
    -> (metrics_path, trace_path_or_None)."""
    p = Path(path_arg)
    if p.is_dir():
        metrics = p / "metrics.jsonl"
        trace = p / "trace.json"
        return metrics, (trace if trace.exists() else None)
    return p, None


def _cmd_report(args) -> int:
    metrics_path, trace_path = _resolve(args.path)
    if args.trace:
        trace_path = Path(args.trace)
    if not metrics_path.exists():
        print(f"fedrec-obs: no event log at {metrics_path}", file=sys.stderr)
        return 2
    records, snapshots = load_jsonl(metrics_path)
    trace_events = load_trace(trace_path) if trace_path else None
    report = build_report(records, snapshots, trace_events)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render_text(report))
    return 0


def _cmd_prom(args) -> int:
    metrics_path, _ = _resolve(args.path)
    if not metrics_path.exists():
        print(f"fedrec-obs: no event log at {metrics_path}", file=sys.stderr)
        return 2
    _, snapshots = load_jsonl(metrics_path)
    if not snapshots:
        print(f"fedrec-obs: no registry snapshot in {metrics_path}",
              file=sys.stderr)
        return 2
    # the SAME renderer the live {"cmd": "prometheus"} endpoint uses —
    # offline output cannot drift from the wire exposition
    print(snapshot_to_prometheus(snapshots[-1]), end="")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="fedrec-obs", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser("report", help="render the one-page run report")
    rep.add_argument("path", help="obs dir or metrics.jsonl path")
    rep.add_argument("--trace", default=None, help="explicit trace.json path")
    rep.add_argument("--json", action="store_true",
                     help="machine-readable report instead of text")
    rep.set_defaults(fn=_cmd_report)
    prom = sub.add_parser(
        "prom", help="Prometheus exposition from the last registry snapshot"
    )
    prom.add_argument("path", help="obs dir or metrics.jsonl path")
    prom.set_defaults(fn=_cmd_prom)
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
