"""Federated aggregation strategies — one interface, three reference modes.

The reference implements federation three times with copy-paste drivers
(SURVEY.md section 1): DDP gradient sync (``Gradient_Averaging_main.py:119,146``),
explicit per-epoch parameter allreduce (``Parameter_Averaging_main.py:144-148``),
and a hub-and-spoke server that broadcasts weights and gathers full
state_dicts over TCP (``server.py:72-103``, ``client.py:256-291``). Here each
mode is a small strategy object whose hooks are called *inside* the jitted
SPMD train step, so the federation collectives compile into the same XLA
program as the model math and ride ICI:

  * ``GradAvg``  — ``sync_grads`` = ``lax.pmean`` each step (DDP parity)
  * ``ParamAvg`` — ``sync_params`` = ``lax.pmean`` at round end (FedAvg with
    equal weights, exactly ``all_reduce(param)/world_size``)
  * ``Local``    — no cross-client communication (single-client / debugging)

The coordinator deployment (server process + client processes) reuses
``weighted_param_avg``: per-round participation masks generalize the
equal-weight mean to client subsets, fixing the reference's "one client dies
=> whole training dies" limitation (Final_Report.pdf section VII.a; see
SURVEY.md section 5.3).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax


class FedStrategy:
    """Hooks called inside the jitted step / round sync. Default: no comms.

    ``sync_grads_every_step`` / ``sync_params_every_round`` are read by the
    Trainer to decide which collectives to schedule; ``sync_grads`` runs
    inside the per-batch step, ``sync_params`` inside the round-end sync
    (``fedrec_tpu.train.step.build_param_sync``).
    """

    name = "local"
    sync_grads_every_step = False
    sync_params_every_round = False

    def sync_grads(self, grads: Any, axis: str) -> Any:
        return grads

    def sync_params(self, params: Any, weight: jnp.ndarray, axis: str) -> Any:
        return params


class Local(FedStrategy):
    pass


class GradAvg(FedStrategy):
    """Per-step gradient averaging (DDP-parity: reference
    ``Gradient_Averaging_main.py:119`` — sync happens inside backward)."""

    name = "grad_avg"
    sync_grads_every_step = True

    def sync_grads(self, grads: Any, axis: str) -> Any:
        return lax.pmean(grads, axis_name=axis)


class ParamAvg(FedStrategy):
    """Per-round parameter averaging (FedAvg): reference
    ``Parameter_Averaging_main.py:144-148`` — ``all_reduce(SUM)/world_size``.
    Participation-weighted: equal weights reproduce the reference exactly."""

    name = "param_avg"
    sync_params_every_round = True

    def sync_params(self, params: Any, weight: jnp.ndarray, axis: str) -> Any:
        return weighted_param_avg(params, weight, axis)


_STRATEGIES = {s.name: s for s in (Local, GradAvg, ParamAvg)}


def get_strategy(name: str) -> FedStrategy:
    # "coordinator" shares the device-side math with param_avg; its host-side
    # round loop lives in fedrec_tpu.fed.coordinator
    key = "param_avg" if name == "coordinator" else name
    if key not in _STRATEGIES:
        raise ValueError(f"unknown federation strategy {name!r}; have {sorted(_STRATEGIES)}")
    return _STRATEGIES[key]()


def participation_mask(
    rng: jax.Array, num_clients: int, fraction: float
) -> jnp.ndarray:
    """(num_clients,) float mask with at least one participant per round.

    Client dropout tolerance: rounds aggregate over the subset that reported
    (the reference instead dies if any client fails — Final_Report.pdf
    section VII.a).
    """
    if fraction >= 1.0:
        return jnp.ones((num_clients,), dtype=jnp.float32)
    scores = jax.random.uniform(rng, (num_clients,))
    k = max(1, int(round(fraction * num_clients)))
    threshold = jnp.sort(scores)[k - 1]
    return (scores <= threshold).astype(jnp.float32)


def weighted_param_avg(params: Any, weight: jnp.ndarray, axis: str) -> Any:
    """Participation-weighted FedAvg inside ``shard_map``.

    ``weight`` is this client's scalar round weight (0 = dropped out).
    Every client — including non-participants — adopts the aggregate,
    mirroring the coordinator broadcast (reference ``server.py:76-77``).
    A round where NO client reports keeps everyone's local parameters
    (rather than dividing by zero into NaN).
    """
    total = lax.psum(weight, axis_name=axis)
    safe_total = jnp.where(total > 0, total, 1.0)
    return jax.tree_util.tree_map(
        lambda p: jnp.where(
            total > 0, lax.psum(p * weight, axis_name=axis) / safe_total, p
        ),
        params,
    )
